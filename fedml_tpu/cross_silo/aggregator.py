"""Server-side aggregator for cross-silo FL.

Parity: reference ``cross_silo/horizontal/fedml_aggregator.py`` —
``add_local_trained_result``, ``check_whether_all_receive``, ``aggregate``,
``client_selection():134`` over real edge ids, ``data_silo_selection():103``.
Redesign: received pytrees are stacked and aggregated in one jitted weighted
mean (optionally through a ``RobustAggregator`` defense) instead of the
reference's per-key Python loop over state_dicts — the aggregation hot spot
SURVEY.md §3.2 calls out.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import telemetry
from ..core.robust import RobustAggregator
from ..simulation.fed_sim import reference_client_sampling

PyTree = Any


class FedMLAggregator:
    def __init__(
        self,
        test_global,
        train_global,
        all_train_data_num: int,
        client_num: int,
        args,
        model_params: PyTree,
        apply_fn=None,
        train_data_local_dict=None,
        test_data_local_dict=None,
        loss_kind: str = "ce",
    ):
        self.args = args
        self.test_global = test_global
        # per-client local splits: when present, eval rounds report the
        # reference MPI aggregator's weighted per-client train/test stats
        # (FedAVGAggregator.test_on_server_for_all_clients) instead of the
        # global-set accuracy alone
        self.train_data_local_dict = train_data_local_dict
        self.test_data_local_dict = test_data_local_dict
        self.loss_kind = loss_kind
        self._local_eval_fn = None
        self.all_train_data_num = all_train_data_num
        self.client_num = client_num
        self.apply_fn = apply_fn
        self.model_params = model_params
        self.model_dict: Dict[int, PyTree] = {}
        self.sample_num_dict: Dict[int, float] = {}
        self.flag_client_model_uploaded_dict = {i: False for i in range(client_num)}
        # cohort size of the current round; rounds may select fewer clients
        # than client_num (client_num_per_round < total), so the barrier
        # compares against this, not the full flag dict
        self.expected_this_round = client_num
        defense = getattr(args, "defense_type", None)
        # the divergence watchdog (server_manager) needs per-slot z-scores to
        # decide who to exclude on rollback, so it forces the sanitizer on
        self.detect = bool(getattr(args, "sanitize_updates", False)) or (
            float(getattr(args, "watchdog_factor", 0.0) or 0.0) > 0)
        self._robust = RobustAggregator(
            defense_type=defense,
            norm_bound=float(getattr(args, "norm_bound", 5.0)),
            stddev=float(getattr(args, "stddev", 0.0)),
            trim_ratio=float(getattr(args, "trim_ratio", 0.1)),
            byzantine_n=int(getattr(args, "byzantine_n", 0)),
            multi_krum_m=(
                None if getattr(args, "multi_krum_m", None) is None
                else int(args.multi_krum_m)
            ),
            sanitize=self.detect,
            z_thresh=float(getattr(args, "sanitize_z_thresh", 6.0)),
        ) if (defense or self.detect) else None
        # weak_dp noise key: fresh per aggregation via fold_in(seed key, call
        # counter) — the old code passed no rng at all, so enabling weak_dp
        # cross-silo raised ValueError on the first round
        self._dp_key = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        self._agg_calls = 0
        # per-aggregation detection report (slot-indexed; the server manager
        # maps slots back to real edge ids)
        self.last_quarantined_slots: List[int] = []
        self.last_z: Dict[int, float] = {}
        self._agg_fn = jax.jit(self._aggregate_stacked)
        # buffered-async plane: updates fold here as they arrive (tagged with
        # the model version they trained against); commit_async drains the
        # buffer into one staleness-weighted aggregate. Sender-keyed, not
        # slot-keyed — async has no per-round cohort slots.
        self._async_buffer: List[tuple] = []
        self.last_quarantined_senders: List[int] = []
        self._agg_fn_async = jax.jit(self._aggregate_async)

    # --- reference API ------------------------------------------------------

    def get_global_model_params(self) -> PyTree:
        return self.model_params

    def set_global_model_params(self, model_parameters: PyTree) -> None:
        self.model_params = model_parameters

    @staticmethod
    def _decode_upload(model_params: PyTree, tag: int) -> PyTree:
        from ..comm import codec as comm_codec
        from ..comm.message import decompress_tree, is_compressed

        if is_compressed(model_params):
            # decompress BEFORE sanitize/aggregate — the robust defenses (and
            # FaultyCommManager's decompress-then-corrupt byzantine path)
            # always see plain update trees
            t0 = time.perf_counter()
            with telemetry.get_tracer().span("codec.decode", slot=tag):
                frame_bytes = comm_codec.frame_nbytes(model_params)
                model_params = decompress_tree(model_params)
            comm_codec.record_codec(
                "decode", frame_bytes, comm_codec.tree_nbytes(model_params),
                time.perf_counter() - t0)
        return model_params

    def add_local_trained_result(self, index: int, model_params: PyTree, sample_num) -> None:
        logging.debug("add_model. index = %d", index)
        model_params = self._decode_upload(model_params, index)
        self.model_dict[index] = model_params
        self.sample_num_dict[index] = float(sample_num)
        self.flag_client_model_uploaded_dict[index] = True

    # --- buffered-async plane (FedBuff-style) -------------------------------

    def add_async_result(self, sender: int, model_params: PyTree, sample_num,
                         staleness: int) -> None:
        """Fold one free-running client's update into the commit buffer.
        ``staleness`` = committed model versions since the version this
        update trained against (0 = perfectly fresh)."""
        model_params = self._decode_upload(model_params, int(sender))
        self._async_buffer.append(
            (int(sender), model_params, float(sample_num), int(staleness)))

    @property
    def async_buffer_len(self) -> int:
        return len(self._async_buffer)

    def _aggregate_async(self, stacked: PyTree, weights: jax.Array,
                         sw: jax.Array, rng):
        """Staleness-weighted aggregate of a drained commit buffer: weights
        are sample counts × the staleness down-weight ``(1+s)^-α``; the
        sanitizer's robust z judges norms on the same post-weighting scale
        (``staleness_scale``) so a stale honest client is not flagged for
        drift the down-weight already absorbs."""
        wf = weights * sw
        if self._robust is not None:
            agg, info = self._robust.aggregate_with_info(
                stacked, wf, rng, staleness_scale=sw)
            return agg, info["quarantine"], info["z"]
        w = wf / jnp.maximum(wf.sum(), 1e-12)
        agg = jax.tree.map(
            lambda x: jnp.tensordot(
                w.astype(jnp.float32), x.astype(jnp.float32),
                axes=(0, 0)).astype(x.dtype),
            stacked,
        )
        return agg, None, None

    def commit_async(self, alpha: float, cohort: int) -> PyTree:
        """Drain the buffer into one commit: staleness-weighted robust
        aggregate, scaled by the buffer fraction ``n/cohort`` so a full
        cycle of commits applies the same total server step a synchronous
        round would (a full-cohort buffer — the lockstep fallback — hits
        ``frac == 1.0`` and skips the scale entirely)."""
        buf = self._async_buffer
        self._async_buffer = []
        self.last_quarantined_slots = []
        self.last_z = {}
        self.last_quarantined_senders = []
        if not buf:
            return self.model_params
        senders = [b[0] for b in buf]
        stacked = jax.tree.map(
            lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
            *[b[1] for b in buf],
        )
        weights = jnp.asarray([b[2] for b in buf], jnp.float32)
        sw = jnp.asarray([(1.0 + b[3]) ** (-alpha) for b in buf], jnp.float32)
        self._agg_calls += 1
        rng = (jax.random.fold_in(self._dp_key, self._agg_calls)
               if self._robust is not None else None)
        agg_delta, quarantine, z = self._agg_fn_async(stacked, weights, sw, rng)
        frac = len(buf) / float(max(int(cohort), 1))
        if frac != 1.0:
            agg_delta = jax.tree.map(
                lambda a: (a * frac).astype(a.dtype), agg_delta)
        if quarantine is not None:
            # sync by design: the verdict feeds the commit record the server
            # writes before replying to the uploader
            qn = np.asarray(quarantine)  # graftcheck: disable=host-sync
            zn = np.asarray(z)  # graftcheck: disable=host-sync
            self.last_quarantined_senders = sorted(
                {senders[i] for i in np.nonzero(qn)[0]})
            self.last_z = {senders[i]: float(zn[i])
                           for i in range(len(senders))}
            if self.last_quarantined_senders:
                reg = telemetry.get_registry()
                if reg.enabled:
                    reg.counter("fedml_quarantined_total").inc(
                        len(self.last_quarantined_senders))
        self.model_params = jax.tree.map(
            lambda p, d: (jnp.asarray(p) + d.astype(p.dtype)),
            self.model_params, agg_delta,
        )
        return self.model_params

    def set_expected_this_round(self, n: int) -> None:
        self.expected_this_round = int(n)

    def check_whether_all_receive(self) -> bool:
        """True once every client *selected this round* has uploaded (the
        reference checks the full flag dict, which deadlocks whenever
        client_num_per_round < client_num)."""
        if self.received_count >= self.expected_this_round:
            self.reset_flags()
            return True
        return False

    def reset_flags(self) -> None:
        """Clear the per-round receive barrier (also used by the straggler
        timeout path, which aggregates a partial cohort)."""
        for i in range(self.client_num):
            self.flag_client_model_uploaded_dict[i] = False

    @property
    def received_count(self) -> int:
        return len(self.model_dict)

    def has_upload_from(self, index: int) -> bool:
        """Whether the given cohort slot already uploaded this round (the
        server's rejoin path uses this to avoid re-training a client whose
        result is already in)."""
        return index in self.model_dict

    def _aggregate_stacked(self, stacked: PyTree, weights: jax.Array, rng):
        if self._robust is not None:
            agg, info = self._robust.aggregate_with_info(stacked, weights, rng)
            return agg, info["quarantine"], info["z"]
        w = weights / jnp.maximum(weights.sum(), 1.0)
        agg = jax.tree.map(
            lambda x: jnp.tensordot(w.astype(jnp.float32), x.astype(jnp.float32), axes=(0, 0)).astype(x.dtype),
            stacked,
        )
        return agg, None, None

    def aggregate(self) -> PyTree:
        """Clients upload *deltas* (local - global); the new global model is
        params + weighted-mean(delta) — algebraically the reference's weighted
        param mean, with defenses applied to the deltas (where clipping is
        actually meaningful)."""
        idx = sorted(self.model_dict)
        self.last_quarantined_slots = []
        self.last_z = {}
        if not idx:
            # zero uploads (a fully-dead round closed by the straggler
            # timeout with min_clients=0): keep the global model unchanged
            return self.model_params
        stacked = jax.tree.map(
            lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
            *[self.model_dict[i] for i in idx],
        )
        weights = jnp.asarray([self.sample_num_dict[i] for i in idx], jnp.float32)
        self._agg_calls += 1
        rng = (jax.random.fold_in(self._dp_key, self._agg_calls)
               if self._robust is not None else None)
        agg_delta, quarantine, z = self._agg_fn(stacked, weights, rng)
        if quarantine is not None:
            # sync by design: the quarantine verdict decides which slots the
            # server manager excludes BEFORE it broadcasts the next round
            qn = np.asarray(quarantine)  # graftcheck: disable=host-sync
            zn = np.asarray(z)  # graftcheck: disable=host-sync
            self.last_quarantined_slots = [idx[i] for i in np.nonzero(qn)[0]]
            self.last_z = {idx[i]: float(zn[i]) for i in range(len(idx))}
            if self.last_quarantined_slots:
                reg = telemetry.get_registry()
                if reg.enabled:
                    reg.counter("fedml_quarantined_total").inc(
                        len(self.last_quarantined_slots))
        self.model_params = jax.tree.map(
            lambda p, d: (jnp.asarray(p) + d.astype(p.dtype)), self.model_params, agg_delta
        )
        self.model_dict.clear()
        self.sample_num_dict.clear()
        return self.model_params

    def client_selection(
        self, round_idx: int, client_id_list_in_total: List[int], client_num_per_round: int
    ) -> List[int]:
        """Select real edge ids (reference ``client_selection:134`` — same
        round-seeded np.random.choice)."""
        if client_num_per_round == len(client_id_list_in_total):
            return list(client_id_list_in_total)
        # reference parity: fedavg_api.py seeds the global stream per round,
        # and RoundStateStore resume snapshots exactly this MT19937 state —
        # graftcheck: disable=determinism
        np.random.seed(round_idx)
        return list(
            np.random.choice(client_id_list_in_total, client_num_per_round, replace=False)
        )

    def data_silo_selection(
        self, round_idx: int, client_num_in_total: int, client_num_per_round: int
    ) -> List[int]:
        """Map selected edges -> data partition indices (reference
        ``data_silo_selection:103``)."""
        if client_num_in_total == client_num_per_round:
            return list(range(client_num_per_round))
        return list(
            reference_client_sampling(round_idx, client_num_in_total, client_num_per_round)
        )

    def test_on_server_for_all_clients(self, round_idx: int) -> Optional[Dict[str, float]]:
        if self.apply_fn is None:
            return None
        out: Dict[str, float] = {}
        if self.test_data_local_dict is not None:
            out.update(self._local_test_on_all_clients())
        if self.test_global is not None and len(self.test_global.x):
            logits = self.apply_fn(
                self.model_params, jnp.asarray(self.test_global.x), train=False)
            acc = float((jnp.argmax(logits, -1)
                         == jnp.asarray(self.test_global.y)).mean())
            logging.info("round %d server test_acc=%.4f", round_idx, acc)
            out["test_acc"] = acc
        return out or None

    def _local_test_on_all_clients(self) -> Dict[str, float]:
        """Reference MPI ``test_on_server_for_all_clients``
        (simulation/mpi/fedavg/FedAVGAggregator.py:128-180): evaluate the
        CURRENT global params on every client's local train and test split;
        report sample-weighted aggregates. Clients without local test data
        are excluded from both sides (the reference's ``continue``).
        Cross-silo cohorts are small (a handful of silos), so a per-client
        padded-batch loop over one jitted eval is the right shape here —
        the simulation engine's segmented single-program pass exists for
        the 100+-client regime (simulation/fed_sim.py)."""
        from ..algorithms.local_sgd import make_eval_fn
        from ..simulation.fed_sim import FedSimulator

        if self._local_eval_fn is None:
            self._local_eval_fn = jax.jit(
                lambda p, xs, ys, ms: jax.lax.scan(
                    lambda c, b: (tuple(
                        a + v for a, v in zip(
                            c, make_eval_fn(self.apply_fn, self.loss_kind)(
                                p, *b))), None),
                    (0.0, 0.0, 0.0), (xs, ys, ms))[0])
        keys = sorted(set((self.train_data_local_dict or {}).keys())
                      | set((self.test_data_local_dict or {}).keys()))
        out: Dict[str, float] = {}
        bs = int(getattr(self.args, "eval_batch_size", 256))

        def eligible(k, d):
            tpair = (self.test_data_local_dict or {}).get(k)
            if tpair is None or len(tpair) == 0:
                return None  # reference: skip the client on BOTH sides
            pair = d.get(k)
            return pair if pair is not None and len(pair) else None

        split_pairs = {
            split: [p for p in (eligible(k, d) for k in keys) if p is not None]
            for split, d in (("train", self.train_data_local_dict),
                             ("test", self.test_data_local_dict))
            if d is not None
        }
        # every client on every split padded to the SAME (all-splits-max)
        # row count: masked rows are exact, and one shape means ONE XLA
        # compile for the whole evaluation instead of one per split
        longest = max((len(p) for ps in split_pairs.values() for p in ps),
                      default=0)
        total = -(-max(longest, 1) // bs) * bs
        for split, prefix in (("train", "local_train"),
                              ("test", "local_test")):
            pairs = split_pairs.get(split)
            if not pairs:
                continue
            loss_sum = correct = valid = 0.0
            for pair in pairs:
                xs, ys, ms = FedSimulator._pad_and_batch(
                    pair.x, pair.y, bs, total=total)
                ls, c, v = self._local_eval_fn(self.model_params, xs, ys, ms)
                loss_sum += float(ls)
                correct += float(c)
                valid += float(v)
            if valid > 0:
                # no keys at all when nothing was evaluated — 0.0/0.0
                # would be indistinguishable from a perfect-loss model
                out[f"{prefix}_loss"] = loss_sum / valid
                out[f"{prefix}_acc"] = correct / valid
        return out
