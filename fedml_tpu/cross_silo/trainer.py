"""Silo-side trainer: the compiled local update, optionally data-parallel
over the silo's own device mesh.

Parity: reference ``cross_silo/horizontal/fedml_trainer.py`` (``FedMLTrainer``
swap-dataset wrapper) + the hierarchical silo's DDP adapter
(``trainer_dist_adapter.py:40`` wrapping the model in
``torch.nn.parallel.DistributedDataParallel``). Redesign: intra-silo data
parallelism needs no process group, no DDP, no master/slave broadcast — the
jitted ``local_update`` runs with its batch axis sharded over the silo's
``data`` mesh axis and XLA inserts the gradient all-reduce (psum over ICI).
The reference's ``ProcessGroupManager`` + pdsh/torchrun launcher collapse
into a Mesh constructor.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import numpy as np

from ..data.federated import FederatedData
from ..parallel.mesh import AXIS_DATA
from ..parallel.sharding import replicated, shard_along

PyTree = Any


class FedMLTrainer:
    """Holds the silo's local shard; ``train(round_idx)`` runs one compiled
    local update and returns (update, num_samples)."""

    def __init__(
        self,
        client_index: int,
        fed_data: FederatedData,
        model_params: PyTree,
        local_update: Callable,
        args,
        mesh=None,
    ):
        self.fed = fed_data
        self.client_index = int(client_index)
        self.model_params = model_params
        self.args = args
        self.mesh = mesh
        self.batch_size = int(getattr(args, "batch_size", 32))
        self._batch_sh = None
        if mesh is not None:
            batch_sh = shard_along(mesh, AXIS_DATA, 1)  # (NB, BS, ...) -> shard BS
            self._batch_sh = batch_sh
            rep = replicated(mesh)
            self._local_update = jax.jit(
                local_update,
                in_shardings=(rep, rep, {"x": batch_sh, "y": batch_sh,
                                         "mask": batch_sh, "num_samples": rep}, rep),
                out_shardings=rep,
            )
        else:
            self._local_update = jax.jit(local_update)
        self._rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        self._pack_rng = np.random.default_rng(int(getattr(args, "random_seed", 0)))

    def update_model(self, weights: PyTree) -> None:
        self.model_params = weights

    def update_dataset(self, client_index: int) -> None:
        """Reference swap-dataset semantics: silo trains partition
        ``client_index`` this round (data_silo_selection output)."""
        self.client_index = int(client_index)

    def train(self, round_idx: int):
        bs = self.batch_size
        if self.mesh is not None:
            # batch must divide the data axis; pad up via packing width
            data_axis = self.mesh.shape[AXIS_DATA]
            bs = -(-bs // data_axis) * data_axis
        batches = self.fed.pack_clients(
            [self.client_index], bs, num_batches=None, rng=self._pack_rng
        )
        data = {
            "x": np.asarray(batches.x[0]),
            "y": np.asarray(batches.y[0]),
            "mask": np.asarray(batches.mask[0]),
            "num_samples": np.asarray(batches.num_samples[0]),
        }
        if self.mesh is not None and jax.process_count() > 1:
            # multi-process silo: every process packs the identical global
            # batch (same files, same rng), so assemble sharded jax.Arrays
            # from it — jit rejects plain numpy for cross-process shardings
            sh = self._batch_sh
            for key in ("x", "y", "mask"):
                arr = data[key]
                data[key] = jax.make_array_from_callback(
                    arr.shape, sh, lambda idx, a=arr: a[idx]
                )
        self._rng, step_rng = jax.random.split(self._rng)
        out = self._local_update(self.model_params, (), data, step_rng)
        weights_np = jax.tree.map(np.asarray, out.update)
        return weights_np, int(batches.num_samples[0])
