"""Cross-silo FL server: online-handshake + round FSM.

Parity: reference ``cross_silo/horizontal/fedml_server_manager.py:11`` —
on CONNECTION_READY select clients and probe status
(``handle_messag_connection_ready:87``); once every selected client reports
ONLINE (``handle_message_client_status_update:108``) send INIT
(``send_init_msg:51``); each round collect models, aggregate, test, select the
next cohort and SYNC (``handle_message_receive_model_from_client:133``).
Redesign: adds the round-timeout + FINISH message the reference lacks (its
barrier stalls forever on a dead client — SURVEY.md §5.3), and model payloads
ride the binary codec instead of pickle/S3 URLs.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from typing import Dict, List, Optional, Set

import numpy as np

from ..comm import Message, ServerManager
from ..comm import codec as comm_codec
from ..comm.resilience import SendFailure
from ..comm.utils import log_round_end, log_round_start
from ..core import telemetry, trace_plane
from ..utils.checkpoint import (DEFAULT_KEEP_VERSIONS, RoundStateStore,
                                trim_version_log)
from .message_define import MyMessage


class FedMLServerManager(ServerManager):
    def __init__(
        self,
        args,
        aggregator,
        comm=None,
        rank: int = 0,
        client_num: int = 0,
        backend: str = "LOOPBACK",
        **kw,
    ):
        super().__init__(args, comm=comm, rank=rank, size=client_num + 1, backend=backend, **kw)
        self.aggregator = aggregator
        self.round_num = int(getattr(args, "comm_round", 1))
        self.round_idx = 0
        self.client_num = client_num
        self.client_real_ids: List[int] = list(
            getattr(args, "client_id_list", None) or range(1, client_num + 1)
        )
        self.client_online_mapping: Dict[int, bool] = {}
        self.client_id_list_in_this_round: List[int] = []
        self.data_silo_index_list: List[int] = []
        self.is_initialized = False
        self.start_running_time = 0.0
        self.history: List[Dict[str, float]] = []
        # straggler tolerance (ours; the reference barrier waits forever —
        # SURVEY.md §5.3): if set, a round closes round_timeout seconds after
        # it STARTS (init/sync broadcast) with whatever subset arrived
        # (>= min_clients) — so size it to cover full local training, not
        # just the straggler spread
        self.round_timeout: Optional[float] = (
            float(getattr(args, "round_timeout", 0)) or None
        )
        self.min_clients = int(getattr(args, "min_clients_per_round", 1))
        # handshake deadline (ours; the reference all-online barrier waits
        # forever): after this many seconds the cohort is re-selected without
        # the clients that never reported ONLINE. 0/unset = wait forever.
        self.handshake_timeout: Optional[float] = (
            float(getattr(args, "handshake_timeout", 0)) or None
        )
        # on a round-timeout *extension* (uploads < min_clients), re-send the
        # current round's model to clients that have neither uploaded nor
        # been marked dead — a client that restarted mid-round re-enters the
        # round instead of idling until FINISH
        self.round_retry_resend = bool(
            getattr(args, "round_retry_resend", True))
        # clients whose send terminally failed this round: out of the upload
        # barrier until they re-announce ONLINE (rejoin path)
        self._dead_clients: Set[int] = set()
        self._round_lock = threading.Lock()
        self._round_gen = 0  # increments at each round completion
        self._timer: Optional[threading.Timer] = None
        self._handshake_timer: Optional[threading.Timer] = None
        # buffered-async mode (FedBuff-style): no round barrier — each upload
        # folds into the aggregator's commit buffer under the FSM lock and
        # every async_buffer_size folds commit a new model version; the
        # uploader gets the freshest committed model back immediately and
        # keeps free-running. comm_round counts COMMITS here, not rounds.
        self.async_mode = bool(getattr(args, "async_mode", False))
        self.model_version = 0
        self.committed_updates = 0
        self.shed_updates = 0
        self._client_seq: Dict[int, int] = {}
        # model-version log: one ``[version, n_updates, senders]`` entry per
        # commit, bounded to the last ``round_store_keep_versions`` entries
        # (<= 0 = unbounded) — resume only ever consults the tail, so the
        # checkpoint blob stays O(keep), not O(run length)
        self._version_log: List[list] = []
        self._pending_senders: List[int] = []
        self.keep_versions = int(
            getattr(args, "round_store_keep_versions",
                    DEFAULT_KEEP_VERSIONS) or 0)
        if self.async_mode:
            if float(getattr(args, "watchdog_factor", 0.0) or 0.0) > 0:
                raise ValueError(
                    "async_mode is incompatible with the divergence watchdog "
                    "(rollback assumes a round barrier to re-run); rely on "
                    "the staleness-aware sanitizer instead")
            k = getattr(args, "async_buffer_size", None)
            cohort = int(getattr(args, "client_num_per_round", client_num)
                         or client_num)
            self.async_buffer_size = int(k) if k is not None else cohort
            if not (1 <= self.async_buffer_size <= cohort):
                raise ValueError(
                    f"async_buffer_size must be in [1, {cohort}], got {k}")
            self.async_staleness_alpha = float(
                getattr(args, "async_staleness_alpha", 0.5))
            # no barrier → nothing for the straggler timer to close
            self.round_timeout = None
            from ..core.tenancy import (CheckinQueue,
                                        DeficitRoundRobinScheduler)

            # admission edge: uploads check in here before folding; a full
            # queue sheds (the client still gets a fresh model back, only
            # the update is dropped) and the DRR deficit keeps a fast
            # client from monopolizing commit slots
            self._checkin = CheckinQueue(maxsize=max(64, 4 * cohort))
            self._adrr = DeficitRoundRobinScheduler()
            self._adrr_tenants: Set[str] = set()
        # round-state checkpointing: global params + next round + np RNG,
        # saved every ckpt_every_rounds completions; a restarted server
        # process resumes mid-run instead of starting from round 0
        self.ckpt_every_rounds = int(getattr(args, "ckpt_every_rounds", 1))
        ckpt_path = getattr(args, "round_ckpt_path", None)
        self.round_store = RoundStateStore(ckpt_path) if ckpt_path else None
        if self.round_store is not None and self.round_store.exists():
            state = self.round_store.load()
            self.round_idx = int(state["round_idx"])
            self.aggregator.set_global_model_params(state["params"])
            extra = state.get("extra") or {}
            if self.async_mode and extra:
                # model-version log: a restarted server resumes the commit
                # counters and each client's upload sequence — a client
                # re-sending an already-committed update is deduped by its
                # stale sequence number instead of double-committed
                self.model_version = int(extra.get("model_version", 0))
                self.committed_updates = int(
                    extra.get("committed_updates", 0))
                self._client_seq = {
                    int(c): int(s)
                    for c, s in (extra.get("client_seq") or {}).items()}
                self._version_log = [
                    list(e) for e in (extra.get("version_log") or [])]
                self.round_idx = self.model_version
            logging.warning(
                "server: resumed round state from %s — continuing at round "
                "%d/%d", ckpt_path, self.round_idx, self.round_num)
        # divergence watchdog (self-healing rounds): after each aggregation,
        # compare the round's eval loss against a windowed baseline and check
        # the global params for non-finite leaves; a bad round is rolled back
        # to its pre-aggregate params and re-run (same round_idx) without the
        # clients the sanitizer's z-scores implicate, at most max_rollbacks
        # times per round. 0 disables.
        self.watchdog_factor = float(getattr(args, "watchdog_factor", 0.0) or 0.0)
        self.watchdog_window = int(getattr(args, "watchdog_window", 5))
        self.max_rollbacks = int(getattr(args, "max_rollbacks", 2))
        self.rollback_z_thresh = float(getattr(args, "rollback_z_thresh", 3.0))
        self._loss_window: List[float] = []
        self._rollbacks_this_round = 0
        self._excluded_this_round: Set[int] = set()  # real edge ids
        self._finite_fn = None
        # telemetry: one root trace context per round (init/sync messages are
        # stamped with it, clients inherit it on receive and their replies
        # carry it back) + per-client round-trip timing from broadcast to
        # model receipt — the straggler-tail histogram
        self._round_ctx: Optional[telemetry.TraceContext] = None
        self.round_trace_ids: Dict[int, str] = {}
        self._client_send_ts: Dict[int, float] = {}
        # event spans around the round FSM (reference wraps server.wait /
        # server.agg_and_eval the same way, fedml_server_manager.py:66-69)
        self.mlops_event = None
        if getattr(args, "enable_tracking", False):
            from ..core.mlops import MetricsSink, MLOpsProfilerEvent

            sink = MetricsSink(path=getattr(args, "tracking_path", None))
            self.mlops_event = MLOpsProfilerEvent(args, sink=sink)
        # downlink codec: broadcasts keep only the stateless quantization
        # stage of the configured spec (delta/topk residual state cannot
        # survive a fan-out path with drops/rejoins). Encoded once per params
        # object — the one-slot identity cache covers every per-client
        # add_params of the same round's broadcast.
        dspec = comm_codec.resolve_downlink_spec(
            args, comm_codec.resolve_codec_spec(args, backend))
        self._bcast_codec = comm_codec.UpdateCodec(dspec) if dspec else None
        self._bcast_cache = (None, None)
        self._codec_seed = int(getattr(args, "random_seed", 0))

    # --- round protocol -----------------------------------------------------

    def start(self) -> None:
        """Kick the handshake (the reference's MQTT broker emits
        CONNECTION_READY; loopback/gRPC deployments call start())."""
        self._on_connection_ready(None)

    def _encode_broadcast(self, params):
        """Encode global params for a broadcast (no-op when no downlink
        codec). Cached by params identity so one round's fan-out encodes
        once regardless of cohort size or re-send paths."""
        if self._bcast_codec is None or params is None:
            return params
        cached, frame = self._bcast_cache
        if cached is params:
            return frame
        t0 = time.perf_counter()
        with telemetry.get_tracer().span("codec.encode",
                                         round_idx=self.round_idx):
            frame = self._bcast_codec.encode(
                params, seed=self._codec_seed, round_idx=self.round_idx,
                client_id=0)
        comm_codec.record_codec(
            "encode", comm_codec.tree_nbytes(params),
            comm_codec.frame_nbytes(frame), time.perf_counter() - t0,
            plane="downlink")
        self._bcast_cache = (params, frame)
        return frame

    def send_init_msg(self) -> None:
        log_round_start(self.rank, self.round_idx)
        self.start_running_time = time.time()
        with self._round_lock:
            self._dead_clients.clear()  # fresh round, fresh barrier
            self.aggregator.set_expected_this_round(
                len(self.client_id_list_in_this_round))
            round_gen = self._round_gen
        global_model_params = self._encode_broadcast(
            self.aggregator.get_global_model_params())
        self._round_ctx = telemetry.new_round_context(self.round_idx)
        if self._round_ctx is not None:
            self.round_trace_ids[self.round_idx] = self._round_ctx.trace_id
        msgs = []
        for idx, client_id in enumerate(self.client_id_list_in_this_round):
            msg = Message(MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self.rank, client_id)
            msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, global_model_params)
            msg.add_params(
                MyMessage.MSG_ARG_KEY_CLIENT_INDEX, int(self.data_silo_index_list[idx])
            )
            if self.async_mode:
                # per-client upload sequence (resumes non-zero after a server
                # restart) + the committed version this model carries, so the
                # upload's staleness echo starts correct from the first round
                seq = self._client_seq.get(client_id, 0)
                if seq > 0:
                    msg.add_params(MyMessage.MSG_ARG_KEY_ROUND_INDEX, seq)
                msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_VERSION,
                               int(self.model_version))
            elif self.round_idx > 0:
                # resume-from-checkpoint: tell clients which round this is.
                # A fresh run's INIT stays byte-identical to before.
                msg.add_params(MyMessage.MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
            msgs.append(msg)
        # the dispatch loop sends (marking terminally-unreachable clients
        # dead) then arms the straggler timer — a round where every client
        # dies before its first upload must still time out
        self._dispatch_round_end((msgs, False, round_gen, self._round_ctx))

    def _in_round_ctx(self, ctx: Optional[telemetry.TraceContext] = None):
        ctx = ctx or self._round_ctx
        return telemetry.use_context(ctx) if ctx is not None \
            else contextlib.nullcontext()

    def _arm_round_timer(self, expected_gen: int) -> None:
        """Arm the straggler timer for the round that started at generation
        ``expected_gen``. If the round already completed (or the run finished)
        by the time we get here, skip — arming then would create a phantom
        timer no completion will ever cancel."""
        if not self.round_timeout:
            return
        with self._round_lock:
            if expected_gen != self._round_gen:
                return
            if self._timer is not None:
                self._timer.cancel()
            self._timer = threading.Timer(
                self.round_timeout, self._on_round_timeout, args=(expected_gen,)
            )
            self._timer.daemon = True
            self._timer.start()

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_CONNECTION_IS_READY, self._on_connection_ready
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self._on_client_status
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self._on_model_from_client
        )

    def _on_connection_ready(self, _msg: Optional[Message]) -> None:
        if self.is_initialized:
            return
        self.client_id_list_in_this_round = self.aggregator.client_selection(
            self.round_idx, self.client_real_ids,
            int(getattr(self.args, "client_num_per_round", self.client_num)),
        )
        self.data_silo_index_list = self.aggregator.data_silo_selection(
            self.round_idx,
            int(getattr(self.args, "client_num_in_total", self.client_num)),
            len(self.client_id_list_in_this_round),
        )
        for client_id in self.client_id_list_in_this_round:
            self._send_probe(client_id)
        self._arm_handshake_timer()

    def _send_probe(self, client_id: int) -> None:
        msg = Message(MyMessage.MSG_TYPE_S2C_CHECK_CLIENT_STATUS, self.rank, client_id)
        try:
            self.send_message(msg)
        except SendFailure as exc:
            # an unreachable client simply never reports ONLINE; the
            # handshake deadline (if armed) drops it from the cohort
            logging.warning("server: status probe to client %d failed (%s)",
                            client_id, exc)

    def _arm_handshake_timer(self) -> None:
        if not self.handshake_timeout:
            return
        with self._round_lock:
            if self.is_initialized:
                return
            if self._handshake_timer is not None:
                self._handshake_timer.cancel()
            self._handshake_timer = threading.Timer(
                self.handshake_timeout, self._on_handshake_timeout)
            self._handshake_timer.daemon = True
            self._handshake_timer.start()

    def _on_handshake_timeout(self) -> None:
        """All-online barrier deadline: proceed with the online subset
        (keeping each survivor's silo-index pairing) if it meets
        ``min_clients``, else re-probe the missing clients and re-arm."""
        start_init = False
        probes: List[int] = []
        with self._round_lock:
            self._handshake_timer = None
            if self.is_initialized:
                return
            cohort = self.client_id_list_in_this_round
            online = [c for c in cohort
                      if self.client_online_mapping.get(c, False)]
            if len(online) >= max(self.min_clients, 1):
                pairing = dict(zip(cohort, self.data_silo_index_list))
                dropped = [c for c in cohort if c not in online]
                self.client_id_list_in_this_round = online
                self.data_silo_index_list = [pairing[c] for c in online]
                logging.warning(
                    "server: handshake deadline (%.1fs) — starting with %d/%d"
                    " clients online (dropped: %s)", self.handshake_timeout,
                    len(online), len(cohort), dropped)
                self.is_initialized = True
                start_init = True
            else:
                probes = [c for c in cohort
                          if not self.client_online_mapping.get(c, False)]
                logging.error(
                    "server: handshake deadline with %d/%d online (< min %d)"
                    " — re-probing %s", len(online), len(cohort),
                    self.min_clients, probes)
        if start_init:
            self.send_init_msg()
            return
        for client_id in probes:
            self._send_probe(client_id)
        self._arm_handshake_timer()

    def _on_client_status(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        # the status reply doubles as the clock-skew exchange: the client
        # stamped its wall clock when span shipping is on
        trace_plane.note_client_clock(sender, msg.get(trace_plane.CLOCK_KEY))
        if msg.get(MyMessage.MSG_ARG_KEY_CLIENT_STATUS) == MyMessage.MSG_CLIENT_STATUS_IDLE:
            self.client_online_mapping[sender] = True
        start_init = False
        rejoin: Optional[Message] = None
        with self._round_lock:
            if not self.is_initialized:
                all_online = all(
                    self.client_online_mapping.get(cid, False)
                    for cid in self.client_id_list_in_this_round
                )
                logging.info("server: client %d online; all_online=%s",
                             sender, all_online)
                if all_online:
                    self.is_initialized = True
                    if self._handshake_timer is not None:
                        self._handshake_timer.cancel()
                        self._handshake_timer = None
                    start_init = True
            else:
                rejoin = self._rejoin_locked(sender)
                rejoin_gen = self._round_gen
        if start_init:
            if self.round_idx >= self.round_num:
                # resumed from a checkpoint written after the final round:
                # nothing left to train — just release the clients
                msgs = [
                    Message(MyMessage.MSG_TYPE_S2C_FINISH, self.rank, cid)
                    for cid in self.client_real_ids
                ]
                self._dispatch_round_end((msgs, True, self._round_gen, None))
            else:
                self.send_init_msg()
        elif rejoin is not None:
            self._dispatch_round_end(
                ([rejoin], False, rejoin_gen, self._round_ctx))

    def _rejoin_locked(self, sender: int) -> Optional[Message]:
        """Mid-run ONLINE report = a client that restarted and lost its
        round state. If it belongs to the current cohort and hasn't uploaded
        yet, un-mark it dead and hand back the current round's model so it
        re-enters the round. Caller holds the round lock."""
        if sender not in self.client_id_list_in_this_round:
            return None
        if self.async_mode:
            # free-running regime: a rejoiner just gets the freshest
            # committed model and its current upload sequence
            logging.warning(
                "server: client %d rejoined async run — resending version %d",
                sender, self.model_version)
            return self._async_sync_msg_locked(sender)
        slot = self.client_id_list_in_this_round.index(sender)
        if self.aggregator.has_upload_from(slot):
            return None  # its result is already in — nothing to redo
        if sender in self._dead_clients:
            self._dead_clients.discard(sender)
            alive = [c for c in self.client_id_list_in_this_round
                     if c not in self._dead_clients]
            self.aggregator.set_expected_this_round(len(alive))
        logging.warning(
            "server: client %d rejoined mid-round %d — resending sync",
            sender, self.round_idx)
        sync = Message(
            MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self.rank, sender)
        sync.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                        self._encode_broadcast(
                            self.aggregator.get_global_model_params()))
        sync.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX,
                        int(self.data_silo_index_list[slot]))
        sync.add_params(MyMessage.MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
        return sync

    def _on_model_from_client(self, msg: Message) -> None:
        model_params = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
        local_sample_num = msg.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES)
        shipped_spans = msg.get(trace_plane.SPANS_KEY)
        if shipped_spans is not None:
            # fold the client's round spans into the assembled timeline
            # before the FSM lock — decode never belongs under it
            trace_plane.ingest_shipped(shipped_spans, msg.get_sender_id())
        sent_at = self._client_send_ts.get(msg.get_sender_id())
        if sent_at is not None:
            # broadcast -> model receipt: wire + local training + wire, per
            # client — the tail of this histogram IS the straggler tail
            telemetry.get_registry().histogram(
                "fedml_client_round_trip_seconds",
                client=str(msg.get_sender_id()),
            ).observe(time.perf_counter() - sent_at)
        if self.async_mode:
            self._on_model_async(msg, model_params, local_sample_num)
            return
        outcome = None
        with self._round_lock:
            msg_round = msg.get(MyMessage.MSG_ARG_KEY_ROUND_INDEX)
            stale = msg_round is not None and int(msg_round) != self.round_idx
            if stale or msg.get_sender_id() not in self.client_id_list_in_this_round:
                logging.warning(
                    "server: stale/late upload from %d (round %s, now %d) ignored",
                    msg.get_sender_id(), msg_round, self.round_idx,
                )
                return
            if msg.get_sender_id() in self._dead_clients:
                # presumed dead but its upload made it through — implicit
                # rejoin; fold it back into the barrier
                self._dead_clients.discard(msg.get_sender_id())
                self.aggregator.set_expected_this_round(len(
                    [c for c in self.client_id_list_in_this_round
                     if c not in self._dead_clients]))
            # map real edge id -> dense slot index for the barrier bookkeeping
            slot = self.client_id_list_in_this_round.index(msg.get_sender_id())
            self.aggregator.add_local_trained_result(slot, model_params, local_sample_num)
            if self.aggregator.check_whether_all_receive():
                outcome = self._complete_round_locked()
        self._dispatch_round_end(outcome)

    # --- buffered-async plane (FedBuff-style) ------------------------------

    def _on_model_async(self, msg, model_params, local_sample_num) -> None:
        """Async upload path: dedup by per-sender sequence, admit through the
        checkin queue, fold into the aggregator's commit buffer, commit every
        ``async_buffer_size`` folds, and immediately hand the uploader the
        freshest committed model — no barrier, no cohort wait."""
        sender = msg.get_sender_id()
        outcome = None
        reply = None
        with self._round_lock:
            if self.model_version >= self.round_num:
                return  # run finished; a late upload changes nothing
            seq = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_INDEX, 0) or 0)
            expected = self._client_seq.get(sender, 0)
            if seq != expected:
                # seq < expected: a duplicate (e.g. the client re-sent after
                # a server restart whose fold was already committed and
                # persisted in the version log) — drop the update but
                # re-sync the client so it keeps free-running. seq >
                # expected cannot happen with an honest client; drop it too.
                logging.warning(
                    "server: async upload from %d with seq %d (expected %d)"
                    " — deduped", sender, seq, expected)
                if seq < expected:
                    reply = self._async_sync_msg_locked(sender)
            else:
                base_version = int(
                    msg.get(MyMessage.MSG_ARG_KEY_MODEL_VERSION, 0) or 0)
                staleness = max(0, self.model_version - base_version)
                self._client_seq[sender] = seq + 1
                tenant = str(sender)
                if tenant not in self._adrr_tenants:
                    self._adrr.register(tenant, round_cost=1.0)
                    self._adrr_tenants.add(tenant)
                if not self._checkin.offer((sender, seq), tenant=tenant):
                    # admission queue full: shed the update (never the
                    # client — it still gets a fresh model back)
                    self.shed_updates += 1
                    reg = telemetry.get_registry()
                    if reg.enabled:
                        reg.counter("fedml_shed_updates_total").inc()
                else:
                    self._checkin.poll()
                    self._adrr.charge(tenant, 1.0)
                    self.aggregator.add_async_result(
                        sender, model_params, local_sample_num, staleness)
                    self._pending_senders.append(sender)
                    reg = telemetry.get_registry()
                    if reg.enabled:
                        reg.histogram(
                            "fedml_update_staleness").observe(
                                float(staleness))
                    if (self.aggregator.async_buffer_len
                            >= self.async_buffer_size):
                        if self._commit_async_locked():
                            outcome = (
                                [Message(MyMessage.MSG_TYPE_S2C_FINISH,
                                         self.rank, cid)
                                 for cid in self.client_real_ids],
                                True, self._round_gen, self._round_ctx)
                if outcome is None:
                    reply = self._async_sync_msg_locked(sender)
        if outcome is not None:
            self._dispatch_round_end(outcome)
        elif reply is not None:
            self._client_send_ts[sender] = time.perf_counter()
            try:
                with self._in_round_ctx():
                    self.send_message(reply)
            except SendFailure as exc:
                # an unreachable free-running client simply stops running;
                # it rejoins by re-announcing ONLINE
                logging.error(
                    "server: async sync to client %d failed (%s)",
                    sender, exc)

    def _async_sync_msg_locked(self, sender: int):
        """Fresh-model SYNC for one free-running client: current committed
        params, that client's next upload sequence, and the version being
        handed out (the staleness echo). Caller holds the round lock."""
        if sender not in self.client_id_list_in_this_round:
            return None
        slot = self.client_id_list_in_this_round.index(sender)
        sync = Message(
            MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self.rank, sender)
        sync.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                        self._encode_broadcast(
                            self.aggregator.get_global_model_params()))
        sync.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX,
                        int(self.data_silo_index_list[slot]))
        sync.add_params(MyMessage.MSG_ARG_KEY_ROUND_INDEX,
                        int(self._client_seq.get(sender, 0)))
        sync.add_params(MyMessage.MSG_ARG_KEY_MODEL_VERSION,
                        int(self.model_version))
        return sync

    def _commit_async_locked(self) -> bool:
        """Drain the commit buffer into one model version. Returns True when
        this commit finishes the run (``comm_round`` commits). Caller holds
        the round lock."""
        n = self.aggregator.async_buffer_len
        cohort = int(getattr(self.args, "client_num_per_round",
                             self.client_num) or self.client_num)
        with self._in_round_ctx():
            with telemetry.get_tracer().span(
                    "server.commit", round_idx=self.model_version):
                self.aggregator.commit_async(
                    self.async_staleness_alpha, cohort)
                metrics = self.aggregator.test_on_server_for_all_clients(
                    self.model_version) or {}
        self.model_version += 1
        self.committed_updates += n
        self._version_log.append([int(self.model_version), int(n),
                                  sorted(self._pending_senders)])
        self._pending_senders = []
        self._version_log = trim_version_log(
            self._version_log, self.keep_versions)
        # round_idx mirrors the version so FINISH checks, resumed-INIT
        # short-circuits, and log lines all stay meaningful
        self.round_idx = self.model_version
        record = {"round": self.model_version - 1,
                  "model_version": self.model_version,
                  "n_updates": n, **metrics}
        if getattr(self.aggregator, "detect", False):
            record["quarantined"] = sorted(
                getattr(self.aggregator, "last_quarantined_senders", []))
        self.history.append(record)
        trace_plane.record_instant(
            "commit", round_idx=self.model_version - 1, rank=self.rank,
            attrs={"n": n, "version": self.model_version})
        trace_plane.on_round_record(record, rank=self.rank)
        reg = telemetry.get_registry()
        if reg.enabled:
            reg.counter("fedml_commits_total").inc()
            elapsed = time.time() - self.start_running_time
            if elapsed > 0:
                reg.gauge("fedml_goodput_updates_per_s").set(
                    self.committed_updates / elapsed)
        log_round_end(self.rank, self.model_version - 1)
        if self.round_store is not None and self.ckpt_every_rounds > 0 and (
                self.model_version % self.ckpt_every_rounds == 0
                or self.model_version >= self.round_num):
            # model-version log: commit counters + per-client sequences ride
            # the same atomic blob as the params, so a restarted server
            # neither loses nor double-commits a committed update
            self.round_store.save(
                self.model_version,
                self.aggregator.get_global_model_params(),
                extra={
                    "model_version": int(self.model_version),
                    "committed_updates": int(self.committed_updates),
                    "client_seq": {str(c): int(s)
                                   for c, s in self._client_seq.items()},
                    "version_log": self._version_log,
                })
        return self.model_version >= self.round_num

    def _on_round_timeout(self, gen: int) -> None:
        outcome = None
        resend: List[Message] = []
        with self._round_lock:
            if gen != self._round_gen:
                return  # round already completed normally
            n = self.aggregator.received_count
            if n < self.min_clients:
                logging.error(
                    "server: round %d timed out with %d/%d uploads (< min %d) — "
                    "extending wait", self.round_idx, n,
                    len(self.client_id_list_in_this_round), self.min_clients,
                )
                self._timer = threading.Timer(
                    self.round_timeout, self._on_round_timeout, args=(gen,)
                )
                self._timer.daemon = True
                self._timer.start()
                if self.round_retry_resend:
                    resend = self._missing_sync_msgs_locked()
            else:
                missing = [
                    cid for i, cid in enumerate(self.client_id_list_in_this_round)
                    if i not in self.aggregator.model_dict
                ]
                logging.warning(
                    "server: round %d closing on timeout with %d/%d uploads "
                    "(stragglers: %s)", self.round_idx, n,
                    len(self.client_id_list_in_this_round), missing,
                )
                self.aggregator.reset_flags()
                outcome = self._complete_round_locked()
        if outcome is not None:
            self._dispatch_round_end(outcome)
            return
        # extend path: re-offer the current round's model to clients that
        # have neither uploaded nor died — one that restarted and missed the
        # broadcast re-enters the round (duplicate uploads are slot-keyed,
        # so a merely-slow client re-training is wasteful but harmless)
        for m in resend:
            try:
                with self._in_round_ctx():
                    self.send_message(m)
            except SendFailure as exc:
                nxt = self._mark_client_dead(m.get_receiver_id(), gen, exc)
                if nxt is not None:
                    self._dispatch_round_end(nxt)
                    return

    def _missing_sync_msgs_locked(self) -> List[Message]:
        """SYNC re-sends for cohort members with no upload and no death mark
        this round. Caller holds the round lock."""
        global_model_params = self._encode_broadcast(
            self.aggregator.get_global_model_params())
        msgs = []
        for idx, cid in enumerate(self.client_id_list_in_this_round):
            if self.aggregator.has_upload_from(idx) or cid in self._dead_clients:
                continue
            sync = Message(
                MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self.rank, cid)
            sync.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, global_model_params)
            sync.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX,
                            int(self.data_silo_index_list[idx]))
            sync.add_params(MyMessage.MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
            msgs.append(sync)
        if msgs:
            logging.warning("server: round %d extend — re-sending model to %s",
                            self.round_idx, [m.get_receiver_id() for m in msgs])
        return msgs

    def _mark_client_dead(self, client_id: int, gen: int, exc: SendFailure):
        """A send to ``client_id`` exhausted its retry budget: drop it from
        this round's upload barrier (it rejoins by re-announcing ONLINE, or
        implicitly if an upload still arrives). Returns a round-end outcome
        when removing it completes the round, else None."""
        trace_plane.flight_dump("send_failure")
        with self._round_lock:
            if gen != self._round_gen or client_id in self._dead_clients:
                return None
            self._dead_clients.add(client_id)
            # it must re-announce before a future handshake counts it online
            self.client_online_mapping.pop(client_id, None)
            logging.error(
                "server: client %d unreachable after %d attempts — marked "
                "dead for round %d (%s)", client_id, exc.attempts,
                self.round_idx, exc)
            if client_id not in self.client_id_list_in_this_round:
                return None
            alive = [c for c in self.client_id_list_in_this_round
                     if c not in self._dead_clients]
            self.aggregator.set_expected_this_round(len(alive))
            if self.aggregator.check_whether_all_receive():
                return self._complete_round_locked()
        return None

    def _complete_round_locked(self):
        """Aggregate the round's uploads and prepare the next round's
        messages. Caller holds the round lock; returns (messages, finished)
        for the caller to send *outside* the lock — a blocking send to a dead
        client must not freeze the round FSM."""
        self._round_gen += 1
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if self.mlops_event:
            self.mlops_event.log_event_started("server.agg_and_eval",
                                               event_value=str(self.round_idx))
        # the round's pre-aggregate params double as the rollback restore
        # point: cross-silo eval runs on the post-aggregate params, so any
        # state that survived last round's watchdog check is validated-good
        pre_params = self.aggregator.get_global_model_params()
        # span under the completed round's trace context (the timeout path
        # arrives on a timer thread with no inherited context)
        with self._in_round_ctx():
            with telemetry.get_tracer().span("server.agg_and_eval",
                                             round_idx=self.round_idx):
                self.aggregator.aggregate()
                metrics = self.aggregator.test_on_server_for_all_clients(
                    self.round_idx) or {}
        if self.mlops_event:
            self.mlops_event.log_event_ended("server.agg_and_eval",
                                             event_value=str(self.round_idx))
        if self.watchdog_factor > 0:
            retry = self._watchdog_verdict_locked(pre_params, metrics)
            if retry is not None:
                return retry
        record = {"round": self.round_idx, **metrics}
        if self.watchdog_factor > 0 or getattr(self.aggregator, "detect", False):
            cohort = self.client_id_list_in_this_round
            record["quarantined"] = sorted(
                {cohort[s] for s in
                 getattr(self.aggregator, "last_quarantined_slots", [])
                 if s < len(cohort)}
                | self._excluded_this_round)
            record["rollbacks"] = self._rollbacks_this_round
        self._rollbacks_this_round = 0
        self._excluded_this_round = set()
        self.history.append(record)
        if record.get("quarantined"):
            trace_plane.record_instant(
                "quarantine", round_idx=self.round_idx,
                attrs={"clients": record["quarantined"]})
        trace_plane.on_round_record(record, rank=self.rank)
        log_round_end(self.rank, self.round_idx)

        self.round_idx += 1
        if self.round_store is not None and self.ckpt_every_rounds > 0 and (
                self.round_idx % self.ckpt_every_rounds == 0
                or self.round_idx >= self.round_num):
            # crash-safe resume point: aggregated params + the round a
            # restarted server should broadcast next
            self.round_store.save(
                self.round_idx, self.aggregator.get_global_model_params())
        if self.round_idx >= self.round_num:
            msgs = [
                Message(MyMessage.MSG_TYPE_S2C_FINISH, self.rank, client_id)
                for client_id in self.client_real_ids
            ]
            return msgs, True, self._round_gen, self._round_ctx
        # next cohort — dead marks do not carry over: a client that was
        # unreachable last round gets fresh sends (and a fresh chance to
        # fail) this round
        self._dead_clients.clear()
        self.client_id_list_in_this_round = self.aggregator.client_selection(
            self.round_idx, self.client_real_ids,
            int(getattr(self.args, "client_num_per_round", self.client_num)),
        )
        self.data_silo_index_list = self.aggregator.data_silo_selection(
            self.round_idx,
            int(getattr(self.args, "client_num_in_total", self.client_num)),
            len(self.client_id_list_in_this_round),
        )
        self.aggregator.set_expected_this_round(len(self.client_id_list_in_this_round))
        log_round_start(self.rank, self.round_idx)
        # fresh root trace for the round that starts with these SYNC messages
        self._round_ctx = telemetry.new_round_context(self.round_idx)
        if self._round_ctx is not None:
            self.round_trace_ids[self.round_idx] = self._round_ctx.trace_id
        global_model_params = self._encode_broadcast(
            self.aggregator.get_global_model_params())
        msgs = []
        for idx, client_id in enumerate(self.client_id_list_in_this_round):
            sync = Message(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self.rank, client_id)
            sync.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, global_model_params)
            sync.add_params(
                MyMessage.MSG_ARG_KEY_CLIENT_INDEX, int(self.data_silo_index_list[idx])
            )
            sync.add_params(MyMessage.MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
            msgs.append(sync)
        return msgs, False, self._round_gen, self._round_ctx

    def _watchdog_verdict_locked(self, pre_params, metrics):
        """Judge the just-aggregated round. Healthy → fold its loss into the
        baseline window and return None (accept). Bad (non-finite loss or
        global params, or loss > watchdog_factor × windowed median) → restore
        ``pre_params`` and return a re-SYNC outcome for the SAME round_idx
        minus the clients the sanitizer's z-scores implicate; once
        ``max_rollbacks`` is spent (or nobody is excludable) the round is
        accepted degraded. Caller holds the round lock.

        No RoundStateStore rewrite is needed on restore: checkpoints are
        written only when a round is *accepted*, so the store never holds a
        rolled-back aggregate."""
        loss = metrics.get("local_train_loss", metrics.get("local_test_loss"))
        if self._finite_fn is None:
            import jax
            import jax.numpy as jnp

            self._finite_fn = jax.jit(lambda p: jax.tree_util.tree_reduce(
                lambda a, x: jnp.logical_and(a, jnp.all(jnp.isfinite(x))),
                p, jnp.bool_(True)))
        spike = bool(
            loss is not None and np.isfinite(loss) and self._loss_window
            and loss > self.watchdog_factor * float(np.median(self._loss_window)))
        bad = ((loss is not None and not np.isfinite(loss)) or spike
               or not bool(self._finite_fn(
                   self.aggregator.get_global_model_params())))
        if not bad:
            if loss is not None:
                self._loss_window.append(float(loss))
                del self._loss_window[:-max(1, self.watchdog_window)]
            return None
        if self._rollbacks_this_round >= self.max_rollbacks:
            logging.error(
                "watchdog: round %d still bad after %d rollbacks — accepting "
                "degraded state", self.round_idx, self._rollbacks_this_round)
            return None
        cohort = self.client_id_list_in_this_round
        zmap = dict(getattr(self.aggregator, "last_z", {}) or {})
        cand = {cohort[s] for s, zv in zmap.items()
                if zv >= self.rollback_z_thresh and s < len(cohort)}
        if not cand and zmap:
            # nobody crossed the threshold: exclude the single worst z so a
            # just-under-threshold attacker cannot stall every retry
            worst = max(zmap, key=zmap.get)
            if worst < len(cohort):
                cand = {cohort[worst]}
        survivors = [c for c in cohort if c not in cand]
        if not cand or not survivors:
            logging.error(
                "watchdog: round %d bad but no excludable client — accepting "
                "degraded state", self.round_idx)
            return None
        self.aggregator.set_global_model_params(pre_params)
        self._rollbacks_this_round += 1
        self._excluded_this_round |= cand
        reg = telemetry.get_registry()
        if reg.enabled:
            reg.counter("fedml_rollbacks_total").inc()
        trace_plane.record_instant(
            "rollback", round_idx=self.round_idx, rank=self.rank,
            attrs={"excluded": sorted(cand),
                   "cause": "loss_spike" if spike else "non_finite"})
        trace_plane.flight_dump("watchdog_rollback")
        pairing = dict(zip(cohort, self.data_silo_index_list))
        self.client_id_list_in_this_round = survivors
        self.data_silo_index_list = [pairing[c] for c in survivors]
        self.aggregator.set_expected_this_round(len(survivors))
        logging.warning(
            "watchdog: round %d rollback #%d (%s) — re-running without "
            "clients %s", self.round_idx, self._rollbacks_this_round,
            "loss spike" if spike else "non-finite state", sorted(cand))
        pre_frame = self._encode_broadcast(pre_params)
        msgs = []
        for idx, cid in enumerate(self.client_id_list_in_this_round):
            sync = Message(
                MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self.rank, cid)
            sync.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, pre_frame)
            sync.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX,
                            int(self.data_silo_index_list[idx]))
            sync.add_params(MyMessage.MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
            msgs.append(sync)
        return msgs, False, self._round_gen, self._round_ctx

    def _dispatch_round_end(self, outcome) -> None:
        """Send the round-start/round-end messages prepared under the lock,
        then either finish or arm the round's straggler timer. A send that
        exhausts its retry budget marks that client dead instead of letting
        the transport exception escape the FSM thread; if dead-marking
        completes the round (every still-alive client had already uploaded),
        the loop rolls straight into dispatching the NEXT round — iterative,
        so cascading failures walk through rounds without recursion."""
        while outcome is not None:
            msgs, finished, gen, ctx = outcome
            outcome = None
            if finished:
                for m in msgs:
                    try:
                        self.send_message(m)
                    except SendFailure as exc:
                        # undeliverable FINISH changes nothing — the run is
                        # over; that client dies with its transport
                        logging.warning(
                            "server: FINISH to client %d undeliverable (%s)",
                            m.get_receiver_id(), exc)
                logging.info(
                    "server: training finished in %.1fs",
                    time.time() - self.start_running_time,
                )
                self.finish()
                return
            with self._in_round_ctx(ctx):
                for m in msgs:
                    self._client_send_ts[m.get_receiver_id()] = time.perf_counter()
                    try:
                        self.send_message(m)
                    except SendFailure as exc:
                        outcome = self._mark_client_dead(
                            m.get_receiver_id(), gen, exc)
                        if outcome is not None:
                            break  # round rolled over; the rest are stale
            if outcome is None:
                self._arm_round_timer(gen)
