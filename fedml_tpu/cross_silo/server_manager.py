"""Cross-silo FL server: online-handshake + round FSM.

Parity: reference ``cross_silo/horizontal/fedml_server_manager.py:11`` —
on CONNECTION_READY select clients and probe status
(``handle_messag_connection_ready:87``); once every selected client reports
ONLINE (``handle_message_client_status_update:108``) send INIT
(``send_init_msg:51``); each round collect models, aggregate, test, select the
next cohort and SYNC (``handle_message_receive_model_from_client:133``).
Redesign: adds the round-timeout + FINISH message the reference lacks (its
barrier stalls forever on a dead client — SURVEY.md §5.3), and model payloads
ride the binary codec instead of pickle/S3 URLs.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from typing import Dict, List, Optional

from ..comm import Message, ServerManager
from ..comm.utils import log_round_end, log_round_start
from ..core import telemetry
from .message_define import MyMessage


class FedMLServerManager(ServerManager):
    def __init__(
        self,
        args,
        aggregator,
        comm=None,
        rank: int = 0,
        client_num: int = 0,
        backend: str = "LOOPBACK",
        **kw,
    ):
        super().__init__(args, comm=comm, rank=rank, size=client_num + 1, backend=backend, **kw)
        self.aggregator = aggregator
        self.round_num = int(getattr(args, "comm_round", 1))
        self.round_idx = 0
        self.client_num = client_num
        self.client_real_ids: List[int] = list(
            getattr(args, "client_id_list", None) or range(1, client_num + 1)
        )
        self.client_online_mapping: Dict[int, bool] = {}
        self.client_id_list_in_this_round: List[int] = []
        self.data_silo_index_list: List[int] = []
        self.is_initialized = False
        self.start_running_time = 0.0
        self.history: List[Dict[str, float]] = []
        # straggler tolerance (ours; the reference barrier waits forever —
        # SURVEY.md §5.3): if set, a round closes round_timeout seconds after
        # it STARTS (init/sync broadcast) with whatever subset arrived
        # (>= min_clients) — so size it to cover full local training, not
        # just the straggler spread
        self.round_timeout: Optional[float] = (
            float(getattr(args, "round_timeout", 0)) or None
        )
        self.min_clients = int(getattr(args, "min_clients_per_round", 1))
        self._round_lock = threading.Lock()
        self._round_gen = 0  # increments at each round completion
        self._timer: Optional[threading.Timer] = None
        # telemetry: one root trace context per round (init/sync messages are
        # stamped with it, clients inherit it on receive and their replies
        # carry it back) + per-client round-trip timing from broadcast to
        # model receipt — the straggler-tail histogram
        self._round_ctx: Optional[telemetry.TraceContext] = None
        self.round_trace_ids: Dict[int, str] = {}
        self._client_send_ts: Dict[int, float] = {}
        # event spans around the round FSM (reference wraps server.wait /
        # server.agg_and_eval the same way, fedml_server_manager.py:66-69)
        self.mlops_event = None
        if getattr(args, "enable_tracking", False):
            from ..core.mlops import MetricsSink, MLOpsProfilerEvent

            sink = MetricsSink(path=getattr(args, "tracking_path", None))
            self.mlops_event = MLOpsProfilerEvent(args, sink=sink)

    # --- round protocol -----------------------------------------------------

    def start(self) -> None:
        """Kick the handshake (the reference's MQTT broker emits
        CONNECTION_READY; loopback/gRPC deployments call start())."""
        self._on_connection_ready(None)

    def send_init_msg(self) -> None:
        log_round_start(self.rank, self.round_idx)
        self.start_running_time = time.time()
        self.aggregator.set_expected_this_round(len(self.client_id_list_in_this_round))
        global_model_params = self.aggregator.get_global_model_params()
        round_gen = self._round_gen
        self._round_ctx = telemetry.new_round_context(self.round_idx)
        if self._round_ctx is not None:
            self.round_trace_ids[self.round_idx] = self._round_ctx.trace_id
        with self._in_round_ctx():
            for idx, client_id in enumerate(self.client_id_list_in_this_round):
                msg = Message(MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self.rank, client_id)
                msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, global_model_params)
                msg.add_params(
                    MyMessage.MSG_ARG_KEY_CLIENT_INDEX, int(self.data_silo_index_list[idx])
                )
                self._client_send_ts[client_id] = time.perf_counter()
                self.send_message(msg)
        # arm at round start: a round where every client dies before its first
        # upload must still time out
        self._arm_round_timer(round_gen)

    def _in_round_ctx(self, ctx: Optional[telemetry.TraceContext] = None):
        ctx = ctx or self._round_ctx
        return telemetry.use_context(ctx) if ctx is not None \
            else contextlib.nullcontext()

    def _arm_round_timer(self, expected_gen: int) -> None:
        """Arm the straggler timer for the round that started at generation
        ``expected_gen``. If the round already completed (or the run finished)
        by the time we get here, skip — arming then would create a phantom
        timer no completion will ever cancel."""
        if not self.round_timeout:
            return
        with self._round_lock:
            if expected_gen != self._round_gen:
                return
            if self._timer is not None:
                self._timer.cancel()
            self._timer = threading.Timer(
                self.round_timeout, self._on_round_timeout, args=(expected_gen,)
            )
            self._timer.daemon = True
            self._timer.start()

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_CONNECTION_IS_READY, self._on_connection_ready
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self._on_client_status
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self._on_model_from_client
        )

    def _on_connection_ready(self, _msg: Optional[Message]) -> None:
        if self.is_initialized:
            return
        self.client_id_list_in_this_round = self.aggregator.client_selection(
            self.round_idx, self.client_real_ids,
            int(getattr(self.args, "client_num_per_round", self.client_num)),
        )
        self.data_silo_index_list = self.aggregator.data_silo_selection(
            self.round_idx,
            int(getattr(self.args, "client_num_in_total", self.client_num)),
            len(self.client_id_list_in_this_round),
        )
        for client_id in self.client_id_list_in_this_round:
            msg = Message(MyMessage.MSG_TYPE_S2C_CHECK_CLIENT_STATUS, self.rank, client_id)
            self.send_message(msg)

    def _on_client_status(self, msg: Message) -> None:
        if msg.get(MyMessage.MSG_ARG_KEY_CLIENT_STATUS) == MyMessage.MSG_CLIENT_STATUS_IDLE:
            self.client_online_mapping[msg.get_sender_id()] = True
        all_online = all(
            self.client_online_mapping.get(cid, False)
            for cid in self.client_id_list_in_this_round
        )
        logging.info("server: client %d online; all_online=%s", msg.get_sender_id(), all_online)
        if all_online and not self.is_initialized:
            self.is_initialized = True
            self.send_init_msg()

    def _on_model_from_client(self, msg: Message) -> None:
        model_params = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
        local_sample_num = msg.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES)
        sent_at = self._client_send_ts.get(msg.get_sender_id())
        if sent_at is not None:
            # broadcast -> model receipt: wire + local training + wire, per
            # client — the tail of this histogram IS the straggler tail
            telemetry.get_registry().histogram(
                "fedml_client_round_trip_seconds",
                client=str(msg.get_sender_id()),
            ).observe(time.perf_counter() - sent_at)
        outcome = None
        with self._round_lock:
            msg_round = msg.get(MyMessage.MSG_ARG_KEY_ROUND_INDEX)
            stale = msg_round is not None and int(msg_round) != self.round_idx
            if stale or msg.get_sender_id() not in self.client_id_list_in_this_round:
                logging.warning(
                    "server: stale/late upload from %d (round %s, now %d) ignored",
                    msg.get_sender_id(), msg_round, self.round_idx,
                )
                return
            # map real edge id -> dense slot index for the barrier bookkeeping
            slot = self.client_id_list_in_this_round.index(msg.get_sender_id())
            self.aggregator.add_local_trained_result(slot, model_params, local_sample_num)
            if self.aggregator.check_whether_all_receive():
                outcome = self._complete_round_locked()
        self._dispatch_round_end(outcome)

    def _on_round_timeout(self, gen: int) -> None:
        outcome = None
        with self._round_lock:
            if gen != self._round_gen:
                return  # round already completed normally
            n = self.aggregator.received_count
            if n < self.min_clients:
                logging.error(
                    "server: round %d timed out with %d/%d uploads (< min %d) — "
                    "extending wait", self.round_idx, n,
                    len(self.client_id_list_in_this_round), self.min_clients,
                )
                self._timer = threading.Timer(
                    self.round_timeout, self._on_round_timeout, args=(gen,)
                )
                self._timer.daemon = True
                self._timer.start()
                return
            missing = [
                cid for i, cid in enumerate(self.client_id_list_in_this_round)
                if i not in self.aggregator.model_dict
            ]
            logging.warning(
                "server: round %d closing on timeout with %d/%d uploads "
                "(stragglers: %s)", self.round_idx, n,
                len(self.client_id_list_in_this_round), missing,
            )
            self.aggregator.reset_flags()
            outcome = self._complete_round_locked()
        self._dispatch_round_end(outcome)

    def _complete_round_locked(self):
        """Aggregate the round's uploads and prepare the next round's
        messages. Caller holds the round lock; returns (messages, finished)
        for the caller to send *outside* the lock — a blocking send to a dead
        client must not freeze the round FSM."""
        self._round_gen += 1
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if self.mlops_event:
            self.mlops_event.log_event_started("server.agg_and_eval",
                                               event_value=str(self.round_idx))
        # span under the completed round's trace context (the timeout path
        # arrives on a timer thread with no inherited context)
        with self._in_round_ctx():
            with telemetry.get_tracer().span("server.agg_and_eval",
                                             round_idx=self.round_idx):
                self.aggregator.aggregate()
                metrics = self.aggregator.test_on_server_for_all_clients(
                    self.round_idx) or {}
        if self.mlops_event:
            self.mlops_event.log_event_ended("server.agg_and_eval",
                                             event_value=str(self.round_idx))
        self.history.append({"round": self.round_idx, **metrics})
        log_round_end(self.rank, self.round_idx)

        self.round_idx += 1
        if self.round_idx >= self.round_num:
            msgs = [
                Message(MyMessage.MSG_TYPE_S2C_FINISH, self.rank, client_id)
                for client_id in self.client_real_ids
            ]
            return msgs, True, self._round_gen, self._round_ctx
        # next cohort
        self.client_id_list_in_this_round = self.aggregator.client_selection(
            self.round_idx, self.client_real_ids,
            int(getattr(self.args, "client_num_per_round", self.client_num)),
        )
        self.data_silo_index_list = self.aggregator.data_silo_selection(
            self.round_idx,
            int(getattr(self.args, "client_num_in_total", self.client_num)),
            len(self.client_id_list_in_this_round),
        )
        self.aggregator.set_expected_this_round(len(self.client_id_list_in_this_round))
        log_round_start(self.rank, self.round_idx)
        # fresh root trace for the round that starts with these SYNC messages
        self._round_ctx = telemetry.new_round_context(self.round_idx)
        if self._round_ctx is not None:
            self.round_trace_ids[self.round_idx] = self._round_ctx.trace_id
        global_model_params = self.aggregator.get_global_model_params()
        msgs = []
        for idx, client_id in enumerate(self.client_id_list_in_this_round):
            sync = Message(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self.rank, client_id)
            sync.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, global_model_params)
            sync.add_params(
                MyMessage.MSG_ARG_KEY_CLIENT_INDEX, int(self.data_silo_index_list[idx])
            )
            sync.add_params(MyMessage.MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
            msgs.append(sync)
        return msgs, False, self._round_gen, self._round_ctx

    def _dispatch_round_end(self, outcome) -> None:
        """Send the round-end messages prepared under the lock, then either
        finish or arm the next round's straggler timer."""
        if outcome is None:
            return
        msgs, finished, gen, ctx = outcome
        with self._in_round_ctx(ctx):
            for m in msgs:
                self._client_send_ts[m.get_receiver_id()] = time.perf_counter()
                self.send_message(m)
        if finished:
            logging.info(
                "server: training finished in %.1fs",
                time.time() - self.start_running_time,
            )
            self.finish()
        else:
            self._arm_round_timer(gen)
