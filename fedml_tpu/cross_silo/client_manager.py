"""Cross-silo FL client actor.

Parity: reference ``cross_silo/horizontal/fedml_client_manager.py:14`` —
report ONLINE on status probe, train on INIT (``handle_message_init:73``),
retrain + upload each SYNC (``__train:171``). The model delta (not full
params) is uploaded; the server adds the aggregated delta — algebraically the
reference's weighted param mean but half the numerical drift in bf16.
"""

from __future__ import annotations

import logging
import time
from typing import Dict

import numpy as np

from ..comm import Message, ClientManager
from ..comm import codec as comm_codec
from ..comm.message import decompress_tree, is_compressed
from ..comm.resilience import ClientDelayPlan, SendFailure
from ..comm.utils import log_communication_tick, log_communication_tock
from ..core import telemetry, trace_plane
from .message_define import MyMessage


class FedMLClientManager(ClientManager):
    def __init__(self, args, trainer, comm=None, rank: int = 0, size: int = 0,
                 backend: str = "LOOPBACK", **kw):
        super().__init__(args, comm=comm, rank=rank, size=size, backend=backend, **kw)
        self.trainer = trainer
        self.num_rounds = int(getattr(args, "comm_round", 1))
        self.round_idx = 0
        # trace ids observed per round (restored from the server's stamped
        # init/sync messages) — the client half of round-trace parity
        self.round_trace_ids: Dict[int, str] = {}
        # uplink codec: this manager owns the per-client error-feedback
        # residuals (path -> flat f32), keyed to the stable rank — they never
        # travel on the wire, and stochastic rounding is deterministic per
        # (random_seed, round_idx, rank)
        spec = comm_codec.resolve_codec_spec(args, backend)
        self._codec = comm_codec.UpdateCodec(spec) if spec else None
        self._codec_residuals: Dict[str, np.ndarray] = {}
        self._codec_seed = int(getattr(args, "random_seed", 0))
        # straggler drill hook: when a seeded delay plan is configured
        # (straggler_skew > 0), this client sleeps its deterministic per-round
        # delay before each upload — a replayable 10× speed skew without
        # touching the training path. None in normal runs.
        self._delay_plan = ClientDelayPlan.from_args(args)
        # committed model version last received from a buffered-async server
        # (echoed on upload so the server can compute this update's
        # staleness); None when the server never sent one (sync runs)
        self._model_version = None

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_CHECK_CLIENT_STATUS, self._on_check_status
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self._on_init
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self._on_sync
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_FINISH, lambda m: self.finish()
        )

    def _on_check_status(self, msg: Message) -> None:
        reply = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.rank, msg.get_sender_id())
        reply.add_params(MyMessage.MSG_ARG_KEY_CLIENT_STATUS, MyMessage.MSG_CLIENT_STATUS_IDLE)
        # wall-clock stamp so the server can skew-correct this rank's spans
        trace_plane.attach_clock(reply)
        self.send_message(reply)

    def announce(self) -> None:
        """Spontaneous ONLINE report (no probe preceded it): a client that
        (re)started mid-run calls this after ``run()`` is entered — the
        server's rejoin path answers with the current round's model so this
        client re-enters the round instead of idling until FINISH."""
        reply = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.rank, 0)
        reply.add_params(MyMessage.MSG_ARG_KEY_CLIENT_STATUS, MyMessage.MSG_CLIENT_STATUS_IDLE)
        trace_plane.attach_clock(reply)
        self.send_message(reply)

    def _maybe_decode(self, params):
        """Decode a compressed server broadcast (context-free: downlink
        frames are quantization-only, see codec.resolve_downlink_spec).
        Dispatch is on the frame itself so a client without ``comm_codec``
        configured still understands a compressing server."""
        if params is None or not is_compressed(params):
            return params
        t0 = time.perf_counter()
        tree = decompress_tree(params)
        comm_codec.record_codec(
            "decode", comm_codec.frame_nbytes(params),
            comm_codec.tree_nbytes(tree), time.perf_counter() - t0,
            plane="downlink")
        return tree

    def _on_init(self, msg: Message) -> None:
        global_model_params = self._maybe_decode(
            msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS))
        client_index = msg.get(MyMessage.MSG_ARG_KEY_CLIENT_INDEX)
        self.trainer.update_model(global_model_params)
        self.trainer.update_dataset(int(client_index))
        # a resumed server's INIT names the round it restarts from; a fresh
        # run's INIT carries no round param and starts at 0 as before
        self.round_idx = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_INDEX, 0))
        self._note_model_version(msg)
        self._train()

    def _on_sync(self, msg: Message) -> None:
        global_model_params = self._maybe_decode(
            msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS))
        client_index = msg.get(MyMessage.MSG_ARG_KEY_CLIENT_INDEX)
        self.round_idx = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_INDEX, self.round_idx + 1))
        self._note_model_version(msg)
        self.trainer.update_model(global_model_params)
        self.trainer.update_dataset(int(client_index))
        self._train()

    def _note_model_version(self, msg: Message) -> None:
        v = msg.get(MyMessage.MSG_ARG_KEY_MODEL_VERSION)
        if v is not None:
            self._model_version = int(v)

    def _train(self) -> None:
        logging.info("client %d: round %d train start", self.rank, self.round_idx)
        # handler dispatch restored the server's round trace context before
        # calling us — record it (parity check hook) and span the local train;
        # the upload below then inherits the same trace via inject_trace.
        ctx = telemetry.current_context()
        if ctx is not None:
            self.round_trace_ids[self.round_idx] = ctx.trace_id
        with telemetry.get_tracer().span(
            "client.train", round_idx=self.round_idx, client=self.rank
        ):
            update, local_sample_num = self.trainer.train(self.round_idx)
        if self._codec is not None:
            t0 = time.perf_counter()
            raw_nbytes = comm_codec.tree_nbytes(update)
            with telemetry.get_tracer().span(
                "codec.encode", round_idx=self.round_idx, client=self.rank
            ):
                update = self._codec.encode(
                    update, seed=self._codec_seed, round_idx=self.round_idx,
                    client_id=self.rank, residuals=self._codec_residuals)
            comm_codec.record_codec(
                "encode", raw_nbytes, comm_codec.frame_nbytes(update),
                time.perf_counter() - t0)
        msg = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank, 0)
        msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, update)
        msg.add_params(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, local_sample_num)
        msg.add_params(MyMessage.MSG_ARG_KEY_ROUND_INDEX, self.round_idx)
        if self._model_version is not None:
            # buffered-async echo: which committed version this update
            # trained against (a sync server never set it → key absent, wire
            # bytes unchanged)
            msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_VERSION,
                           int(self._model_version))
        if self._delay_plan is not None:
            # seeded straggler injection: deterministic per-(client, round)
            # heavy-tail delay, applied at the upload edge
            time.sleep(self._delay_plan.sleep_s(self.rank, self.round_idx))
        # ship this rank's finished spans for the round with the upload —
        # the server assembles the cross-rank round timeline from them
        trace_plane.attach_spans(msg, self.round_idx, self.rank)
        # greppable comm benchmark markers around the model upload
        # (reference communication/utils.py tick/tock role)
        log_communication_tick(self.rank, 0)
        try:
            self.send_message(msg)
        except SendFailure as exc:
            # server unreachable after the retry budget: the round's work is
            # lost but the client survives — the server's straggler timeout
            # closes the round without us, and the next sync (or a rejoin
            # probe) pulls this client back in
            logging.error("client %d: round %d upload failed terminally (%s)",
                          self.rank, self.round_idx, exc)
            trace_plane.flight_dump("send_failure")
        log_communication_tock(self.rank, 0)
