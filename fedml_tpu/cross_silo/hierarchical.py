"""Hierarchical cross-silo: a silo spanning multiple processes/hosts.

Parity: reference ``cross_silo/hierarchical/`` — ``ClientMasterManager``
(process 0 of the silo talks to the FL server and broadcasts
``[round_idx, model, client_index]`` to silo slaves via
``dist.broadcast_object_list``, ``client_slave_manager.py:39
await_sync_process_group``), ``ProcessGroupManager`` (``dist.init_process_group``)
and the pdsh/torchrun launcher (``dist_trainer_launcher.py:23``).

Redesign: the process group is ``jax.distributed`` (coordinator service, see
``parallel/mesh.py:maybe_initialize_distributed`` + ``scripts/
launch_multihost.sh``); the per-round master→slave sync is
``multihost_utils.broadcast_one_to_all`` (an XLA collective, riding ICI/DCN
instead of a gloo TCP ring); and DDP dissolves entirely — every silo process
enters the same jitted ``local_update`` whose batch axis is sharded over a
``Mesh`` that spans the processes, so the gradient all-reduce is a psum XLA
inserts, not a DDP hook.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
import numpy as np

from .client_manager import FedMLClientManager

FINISH_SENTINEL = -1


class SlaveSync:
    """Master→slave round synchronization over the jax.distributed world.

    The broadcast payload is ``(round_idx, client_index, params)`` — exactly
    the ``[round_idx, model_params, client_index]`` object list the reference
    broadcasts into the silo process group (``client_slave_manager.py:39``).
    All processes must construct this with the same pytree structure
    (slaves pass their own init params as the template).
    """

    def __init__(self, params_template):
        self._template = params_template

    def broadcast_round(self, round_idx: int, client_index: int, params):
        from jax.experimental import multihost_utils

        payload = (np.int64(round_idx), np.int64(client_index), params)
        return multihost_utils.broadcast_one_to_all(payload)

    def await_round(self):
        """Slave side: blocks until the master reaches its broadcast."""
        from jax.experimental import multihost_utils

        payload = (np.int64(0), np.int64(0), self._template)
        round_idx, client_index, params = multihost_utils.broadcast_one_to_all(
            payload
        )
        return int(round_idx), int(client_index), params

    def broadcast_finish(self):
        self.broadcast_round(FINISH_SENTINEL, 0, self._template)


class ClientMasterManager(FedMLClientManager):
    """Process 0 of a multi-process silo: speaks the WAN FL protocol AND
    leads the silo's collective training (reference
    ``client_master_manager.py``)."""

    def __init__(self, *a, slave_sync: Optional[SlaveSync] = None, **kw):
        super().__init__(*a, **kw)
        self.slave_sync = slave_sync

    def _train(self) -> None:
        if self.slave_sync is not None:
            self.slave_sync.broadcast_round(
                self.round_idx, self.trainer.client_index,
                self.trainer.model_params,
            )
        super()._train()

    def finish(self) -> None:
        if self.slave_sync is not None:
            self.slave_sync.broadcast_finish()
        super().finish()


class ClientSlaveManager:
    """Silo processes 1..P-1: no WAN connection — they follow the master's
    broadcasts and co-execute the collective local update (reference
    ``client_slave_manager.py``: ``await_sync_process_group`` then train)."""

    def __init__(self, trainer):
        self.trainer = trainer
        self._sync = SlaveSync(trainer.model_params)

    @property
    def slave_sync(self) -> SlaveSync:
        return self._sync

    def run(self) -> None:
        while True:
            round_idx, client_index, params = self._sync.await_round()
            if round_idx == FINISH_SENTINEL:
                logging.info("silo slave %d: finish", jax.process_index())
                return
            self.trainer.update_model(params)
            self.trainer.update_dataset(client_index)
            # same jitted program as the master — the batch axis is sharded
            # over the silo mesh, so this call IS the collective step
            self.trainer.train(round_idx)
