"""Hierarchical cross-silo: a silo spanning multiple processes/hosts.

Parity: reference ``cross_silo/hierarchical/`` — ``ClientMasterManager``
(process 0 of the silo talks to the FL server and broadcasts
``[round_idx, model, client_index]`` to silo slaves via
``dist.broadcast_object_list``, ``client_slave_manager.py:39
await_sync_process_group``), ``ProcessGroupManager`` (``dist.init_process_group``)
and the pdsh/torchrun launcher (``dist_trainer_launcher.py:23``).

Redesign: the process group is ``jax.distributed`` (coordinator service, see
``parallel/mesh.py:maybe_initialize_distributed`` + ``scripts/
launch_multihost.sh``); the per-round master→slave sync is
``multihost_utils.broadcast_one_to_all`` (an XLA collective, riding ICI/DCN
instead of a gloo TCP ring); and DDP dissolves entirely — every silo process
enters the same jitted ``local_update`` whose batch axis is sharded over a
``Mesh`` that spans the processes, so the gradient all-reduce is a psum XLA
inserts, not a DDP hook.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

import jax
import numpy as np

from ..comm.message import Message
from ..comm.resilience import SendFailure
from .client_manager import FedMLClientManager

FINISH_SENTINEL = -1


class TierMsg:
    """Message vocabulary of the tiered (root <-> leaf-aggregator) plane.

    A separate namespace from :class:`.message_define.MyMessage` — tier
    traffic rides the same transports but is a different protocol (leaf
    aggregators are *processes*, not clients), and keeping the vocabularies
    disjoint means a flat cross-silo deployment's wire format is untouched
    by the tier plane existing (failover off ⇒ byte-identical frames).
    """

    MSG_TYPE_DISPATCH = "tier_dispatch"      # root -> leaf: round work order
    MSG_TYPE_PARTIAL = "tier_partial"        # leaf -> root: partial aggregate
    MSG_TYPE_HEARTBEAT = "tier_heartbeat"    # leaf -> root: lease renewal
    MSG_TYPE_JOIN = "tier_join"              # leaf -> root: (re)join request
    MSG_TYPE_SYNC = "tier_sync"              # root -> leaf: adoption/re-sync
    MSG_TYPE_FINISH = "tier_finish"          # root -> leaf: run over

    # the round index rides the same param key the resilience plane reads
    # (comm.resilience.ROUND_IDX_PARAM), so round-windowed fault rules and
    # crash plans see tier traffic exactly like flat cross-silo traffic
    ARG_ROUND_IDX = "round_idx"
    ARG_MODEL_PARAMS = "model_params"
    ARG_MODEL_VERSION = "model_version"
    ARG_COHORT_SIZE = "cohort_size"
    ARG_CHUNKS = "chunks"                    # list of {lo, client_ids}
    ARG_PARTIALS = "partials"                # list of partial records
    ARG_LEAF_RANK = "leaf_rank"


class HeartbeatSender:
    """Daemon thread renewing a leaf's lease at the root.

    Sends one :data:`TierMsg.MSG_TYPE_HEARTBEAT` every ``interval_s``,
    stamped with the leaf's current round (``round_fn``) so round-windowed
    chaos (partitions, leaf crashes) applies to heartbeats the same way it
    applies to protocol traffic. Send failures are swallowed — a heartbeat
    that cannot reach the root IS the failure signal (the lease lapses)."""

    def __init__(self, send_fn: Callable[[Message], None], rank: int,
                 root_rank: int = 0, interval_s: float = 0.5,
                 round_fn: Callable[[], int] = lambda: 0):
        self._send = send_fn
        self.rank = int(rank)
        self.root_rank = int(root_rank)
        self.interval_s = float(interval_s)
        self._round_fn = round_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            msg = Message(TierMsg.MSG_TYPE_HEARTBEAT, self.rank,
                          self.root_rank)
            msg.add_params(TierMsg.ARG_ROUND_IDX, int(self._round_fn()))
            try:
                self._send(msg)
            except SendFailure:
                logging.debug("leaf %d: heartbeat to root undeliverable",
                              self.rank)

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"tier-heartbeat-{self.rank}")
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval_s + 1.0)
            self._thread = None


class SlaveSync:
    """Master→slave round synchronization over the jax.distributed world.

    The broadcast payload is ``(round_idx, client_index, params)`` — exactly
    the ``[round_idx, model_params, client_index]`` object list the reference
    broadcasts into the silo process group (``client_slave_manager.py:39``).
    All processes must construct this with the same pytree structure
    (slaves pass their own init params as the template).
    """

    def __init__(self, params_template):
        self._template = params_template

    def broadcast_round(self, round_idx: int, client_index: int, params):
        from jax.experimental import multihost_utils

        payload = (np.int64(round_idx), np.int64(client_index), params)
        return multihost_utils.broadcast_one_to_all(payload)

    def await_round(self):
        """Slave side: blocks until the master reaches its broadcast."""
        from jax.experimental import multihost_utils

        payload = (np.int64(0), np.int64(0), self._template)
        round_idx, client_index, params = multihost_utils.broadcast_one_to_all(
            payload
        )
        return int(round_idx), int(client_index), params

    def broadcast_finish(self):
        self.broadcast_round(FINISH_SENTINEL, 0, self._template)


class ClientMasterManager(FedMLClientManager):
    """Process 0 of a multi-process silo: speaks the WAN FL protocol AND
    leads the silo's collective training (reference
    ``client_master_manager.py``)."""

    def __init__(self, *a, slave_sync: Optional[SlaveSync] = None, **kw):
        super().__init__(*a, **kw)
        self.slave_sync = slave_sync

    def _train(self) -> None:
        if self.slave_sync is not None:
            self.slave_sync.broadcast_round(
                self.round_idx, self.trainer.client_index,
                self.trainer.model_params,
            )
        super()._train()

    def finish(self) -> None:
        if self.slave_sync is not None:
            self.slave_sync.broadcast_finish()
        super().finish()


class ClientSlaveManager:
    """Silo processes 1..P-1: no WAN connection — they follow the master's
    broadcasts and co-execute the collective local update (reference
    ``client_slave_manager.py``: ``await_sync_process_group`` then train)."""

    def __init__(self, trainer):
        self.trainer = trainer
        self._sync = SlaveSync(trainer.model_params)

    @property
    def slave_sync(self) -> SlaveSync:
        return self._sync

    def run(self) -> None:
        while True:
            round_idx, client_index, params = self._sync.await_round()
            if round_idx == FINISH_SENTINEL:
                logging.info("silo slave %d: finish", jax.process_index())
                return
            self.trainer.update_model(params)
            self.trainer.update_dataset(client_index)
            # same jitted program as the master — the batch axis is sharded
            # over the silo mesh, so this call IS the collective step
            self.trainer.train(round_idx)
