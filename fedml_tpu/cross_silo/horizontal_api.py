"""Octopus horizontal API: assemble server/client actors by rank.

Parity: reference ``cross_silo/horizontal/fedml_horizontal_api.py``
(``FedML_Horizontal:10`` + the ``Client``/``Server`` wrappers in
``cross_silo/__init__.py``). Hierarchical cross-silo reuses the same actors —
the silo-internal tier is a ``data``-axis mesh inside ``FedMLTrainer`` rather
than a separate DDP process group (see trainer.py docstring), so the
"hierarchical" API differs only by passing that mesh.
"""

from __future__ import annotations

from typing import Optional

import jax

from .. import data as data_mod
from .. import models as models_mod
from ..algorithms import LocalTrainConfig, make_local_update
from ..parallel.mesh import AXIS_DATA, MeshConfig, create_mesh
from .aggregator import FedMLAggregator
from .client_manager import FedMLClientManager
from .server_manager import FedMLServerManager
from .trainer import FedMLTrainer


def assemble_silo(args, mesh=None):
    """Load data, build the model + compiled local_update for one silo.
    Public so multi-process workers can assemble once and wire the pieces
    into both server and trainer actors themselves."""
    return _assemble(args, mesh)


def _assemble(args, mesh=None):
    fed_data, output_dim = data_mod.load(args)
    model = models_mod.create(args, output_dim)
    sample = models_mod.sample_input_for(args, fed_data)
    rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
    variables = models_mod.init_params(model, rng, sample)

    def apply_fn(vars_, x, train=False, rngs=None, mutable=False):
        return model.apply(vars_, x, train=train, rngs=rngs, mutable=mutable)

    from ..algorithms.local_sgd import infer_loss_kind

    cfg = LocalTrainConfig(
        lr=float(getattr(args, "learning_rate", 0.03)),
        epochs=int(getattr(args, "epochs", 1)),
        client_optimizer=str(getattr(args, "client_optimizer", "sgd")),
        momentum=float(getattr(args, "momentum", 0.0)),
        weight_decay=float(getattr(args, "weight_decay", 0.0)),
        loss_kind=infer_loss_kind(args, fed_data),
    )
    local_update = make_local_update(
        apply_fn, cfg, has_batch_stats="batch_stats" in variables
    )
    return fed_data, variables, apply_fn, local_update


def FedML_Horizontal(args, client_rank: int, client_num: int, comm=None,
                     backend: str = "LOOPBACK", mesh=None, **kw):
    """rank 0 = server, 1..N = silo clients. Returns the (not yet running)
    manager so callers control the thread/process it runs on."""
    fed_data, variables, apply_fn, local_update = _assemble(args, mesh)
    if client_rank == 0:
        from ..algorithms.local_sgd import infer_loss_kind

        local_eval = bool(getattr(args, "local_test_on_all_clients", False))
        aggregator = FedMLAggregator(
            fed_data.test_data_global,
            fed_data.train_data_global,
            fed_data.train_data_num,
            client_num,
            args,
            variables,
            apply_fn=apply_fn,
            # per-client local-test evaluation at eval rounds (reference
            # MPI FedAVGAggregator semantics) — opt-in, like the engine;
            # the eval loss family must match what training used
            train_data_local_dict=(
                fed_data.train_data_local_dict if local_eval else None),
            test_data_local_dict=(
                fed_data.test_data_local_dict if local_eval else None),
            loss_kind=infer_loss_kind(args, fed_data),
        )
        return FedMLServerManager(
            args, aggregator, comm=comm, rank=0, client_num=client_num,
            backend=backend, **kw,
        )
    trainer = FedMLTrainer(
        client_index=client_rank - 1,
        fed_data=fed_data,
        model_params=variables,
        local_update=local_update,
        args=args,
        mesh=mesh,
    )
    return FedMLClientManager(
        args, trainer, comm=comm, rank=client_rank, size=client_num + 1,
        backend=backend, **kw,
    )


class Server:
    """Reference ``fedml.run_cross_silo_server()`` target."""

    def __init__(self, args, mesh=None, backend: Optional[str] = None, **kw):
        backend = backend or str(getattr(args, "backend", "LOOPBACK"))
        # client_num = connected silos (ranks 1..N); per-round selection may
        # pick a subset — the round barrier tracks the cohort, not N
        self.manager = FedML_Horizontal(
            args, 0, int(getattr(args, "client_num_in_total",
                                 getattr(args, "client_num_per_round", 1))),
            backend=backend, mesh=mesh, **kw,
        )

    def run(self):
        self.manager.start()
        self.manager.run()
        return self.manager.history


class Client:
    """Reference ``fedml.run_cross_silo_client()`` target."""

    def __init__(self, args, mesh=None, backend: Optional[str] = None, **kw):
        backend = backend or str(getattr(args, "backend", "LOOPBACK"))
        # a Client is never rank 0 (that's the server), so the role implies a
        # different default than mlops' global one; graftcheck: disable=config-drift
        rank = int(getattr(args, "rank", 1))
        self.manager = FedML_Horizontal(
            args, rank, int(getattr(args, "client_num_in_total",
                                    getattr(args, "client_num_per_round", 1))),
            backend=backend, mesh=mesh, **kw,
        )

    def run(self):
        self.manager.run()


class HierarchicalServer(Server):
    """Hierarchical cross-silo server — identical FSM; silos differ."""


class HierarchicalClient(Client):
    """Silo client with an internal data-parallel mesh (replaces the
    reference's in-silo DDP, ``trainer_dist_adapter.py:66-68``)."""

    def __init__(self, args, mesh=None, **kw):
        if mesh is None:
            n = int(getattr(args, "n_proc_in_silo", 0)) or len(jax.devices())
            n = min(n, len(jax.devices()))
            mesh = create_mesh(
                MeshConfig(axes=((AXIS_DATA, n),)), devices=jax.devices()[:n]
            )
        super().__init__(args, mesh=mesh, **kw)
