"""Chaos drill: a full cross-silo FL run under a seeded fault plan.

One entry point — :func:`run_chaos_drill` — stands up a complete loopback
deployment (server + N silo clients, real message codec, real round FSM),
switches on the requested ``fault_*`` plan, runs it to completion, and
reports whether every round closed plus what the resilience plane did along
the way (faults injected, sends retried, sends declared dead).

Shared by the ``fedml-tpu chaos-drill`` CLI command, ``bench.py --chaos``,
and the ``tests/test_chaos.py`` suite — one implementation, three front
doors, so the drill the CI gate runs is exactly the drill an operator can
run by hand against a proposed config change.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Dict, List, Optional

PHASE_DEFAULTS = dict(
    dataset="mnist",
    model="lr",
    debug_small_data=True,
    client_num_in_total=3,
    client_num_per_round=3,
    comm_round=3,
    learning_rate=0.1,
    epochs=1,
    batch_size=8,
    frequency_of_the_test=1,
    random_seed=0,
    # recovery knobs: a drill must terminate even when messages vanish, so
    # rounds close on a short straggler timeout with whatever arrived
    round_timeout=2.0,
    min_clients_per_round=1,
    handshake_timeout=2.0,
    # the default plan: WAN-grade packet loss on every message type
    fault_seed=7,
    fault_drop_rate=0.2,
)


@dataclasses.dataclass
class ChaosDrillResult:
    rounds_completed: int
    rounds_expected: int
    elapsed_s: float
    faults_injected: Dict[str, float]
    send_retries: float
    send_failures: float
    history: List[dict]
    # self-healing plane (PR 4): sanitizer quarantine hits and watchdog
    # rollbacks observed during the drill (0 unless defenses are on)
    quarantined: float = 0.0
    rollbacks: float = 0.0
    # compressed update plane: raw/wire byte deltas keyed by plane
    # (uplink/downlink); empty unless comm_codec was active in the drill
    codec_bytes_raw: Dict[str, float] = dataclasses.field(default_factory=dict)
    codec_bytes_wire: Dict[str, float] = dataclasses.field(default_factory=dict)
    # tenant whose scoped registry the drill accounted against (None = global)
    tenant: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.rounds_completed >= self.rounds_expected

    def codec_ratio(self, plane: str = "uplink") -> float:
        """Raw/wire compression ratio observed on one plane (1.0 when the
        codec was off or produced no traffic there)."""
        raw = self.codec_bytes_raw.get(plane, 0.0)
        wire = self.codec_bytes_wire.get(plane, 0.0)
        return raw / wire if raw > 0 and wire > 0 else 1.0

    def summary(self) -> str:
        faults = ", ".join(f"{k}={int(v)}"
                           for k, v in sorted(self.faults_injected.items()))
        healing = ""
        if self.quarantined or self.rollbacks:
            healing = (f" | quarantined={int(self.quarantined)} "
                       f"rollbacks={int(self.rollbacks)}")
        codec = ""
        if self.codec_bytes_wire:
            codec = (f" | codec uplink {self.codec_ratio('uplink'):.1f}x "
                     f"({int(self.codec_bytes_wire.get('uplink', 0))}B wire)")
        return (
            f"chaos drill: {'PASS' if self.ok else 'FAIL'} — "
            f"{self.rounds_completed}/{self.rounds_expected} rounds in "
            f"{self.elapsed_s:.1f}s | faults injected: {faults or 'none'} | "
            f"sends retried={int(self.send_retries)} "
            f"declared-dead={int(self.send_failures)}" + healing + codec
        )

    def json_record(self) -> dict:
        """The drill outcome as one JSON-able dict — the single reporter
        behind ``bench.py --chaos`` and ``fedml-tpu chaos-drill --json``
        (callers add their own ``metric``/``unit`` framing on top)."""
        rec = {
            "rounds_completed": self.rounds_completed,
            "rounds_expected": self.rounds_expected,
            "elapsed_s": round(self.elapsed_s, 3),
            "faults_injected": {k: int(v)
                                for k, v in sorted(self.faults_injected.items())},
            "send_retries": int(self.send_retries),
            "send_failures": int(self.send_failures),
            "quarantined": int(self.quarantined),
            "rollbacks": int(self.rollbacks),
            "ok": self.ok,
        }
        if self.tenant is not None:
            rec["tenant"] = self.tenant
        if self.codec_bytes_wire:
            rec["codec_bytes_raw"] = {
                k: int(v) for k, v in sorted(self.codec_bytes_raw.items())}
            rec["codec_bytes_wire"] = {
                k: int(v) for k, v in sorted(self.codec_bytes_wire.items())}
            rec["codec_uplink_ratio"] = round(self.codec_ratio("uplink"), 3)
        return rec


STRAGGLER_DEFAULTS = dict(
    dataset="digits",
    model="lr",
    partition_method="homo",
    client_num_in_total=8,
    client_num_per_round=8,
    comm_round=6,
    learning_rate=0.3,
    epochs=1,
    batch_size=32,
    frequency_of_the_test=3,
    random_seed=0,
    # the straggler plan: deterministic heavy-tail speed skew — the slowest
    # client runs async_delay_skew× slower than the fastest, per-round jitter
    # on top, all hash-seeded so every drill replays bit-for-bit
    async_buffer_size=2,
    async_staleness_alpha=0.5,
    async_delay_base_s=1.0,
    async_delay_skew=10.0,
    async_delay_jitter=0.2,
)


@dataclasses.dataclass
class StragglerDrillResult:
    """Sync-vs-async outcome under one seeded straggler plan. Goodput is
    measured on the shared virtual clock (committed updates per virtual
    second), so the comparison is deterministic — a wall-clock drill would
    gate CI on scheduler noise."""

    commits: int
    committed_updates: int
    shed_updates: int
    staleness_max: int
    sync_round_rate: float   # sync rounds per virtual second (barrier pace)
    async_goodput_ups: float  # async committed updates per virtual second
    sync_final_acc: float
    async_final_acc: float
    elapsed_s: float
    min_goodput_ratio: float = 3.0
    max_acc_delta: float = 0.02
    history: List[dict] = dataclasses.field(default_factory=list)

    @property
    def goodput_ratio(self) -> float:
        """Committed-update goodput over the synchronous round rate — the
        acceptance metric: a sync round folds its whole cohort but lands only
        at the barrier pace the slowest client sets, while async keeps
        committing off the fast clients the barrier would have idled."""
        return (self.async_goodput_ups / self.sync_round_rate
                if self.sync_round_rate > 0 else 0.0)

    @property
    def acc_delta(self) -> float:
        return self.sync_final_acc - self.async_final_acc

    @property
    def ok(self) -> bool:
        return (self.goodput_ratio >= self.min_goodput_ratio
                and self.acc_delta <= self.max_acc_delta)

    def summary(self) -> str:
        return (
            f"straggler drill: {'PASS' if self.ok else 'FAIL'} — "
            f"async {self.async_goodput_ups:.2f} upd/vs vs sync "
            f"{self.sync_round_rate:.2f} rounds/vs "
            f"({self.goodput_ratio:.1f}x, gate >={self.min_goodput_ratio:.1f}x)"
            f" | acc async {self.async_final_acc:.4f} vs sync "
            f"{self.sync_final_acc:.4f} (delta {self.acc_delta:+.4f}, gate "
            f"<={self.max_acc_delta:.2f}) | {self.commits} commits, "
            f"{self.committed_updates} updates, max staleness "
            f"{self.staleness_max}, shed {self.shed_updates}"
        )

    def json_record(self) -> dict:
        """Same single-reporter contract as :meth:`ChaosDrillResult.
        json_record` — one JSON-able dict behind ``bench.py --async-sweep``
        and ``fedml-tpu chaos-drill --straggler --json``."""
        return {
            "commits": self.commits,
            "committed_updates": self.committed_updates,
            "shed_updates": self.shed_updates,
            "staleness_max": self.staleness_max,
            "sync_rounds_per_vs": round(self.sync_round_rate, 4),
            "async_goodput_updates_per_vs": round(self.async_goodput_ups, 4),
            "goodput_ratio": round(self.goodput_ratio, 3),
            "sync_final_acc": round(self.sync_final_acc, 6),
            "async_final_acc": round(self.async_final_acc, 6),
            "acc_delta": round(self.acc_delta, 6),
            "elapsed_s": round(self.elapsed_s, 3),
            "ok": self.ok,
        }


def _final_acc(history: List[dict]) -> float:
    accs = [r["test_acc"] for r in history if "test_acc" in r]
    return float(accs[-1]) if accs else float("nan")


def run_straggler_drill(min_goodput_ratio: float = 3.0,
                        max_acc_delta: float = 0.02,
                        **overrides) -> StragglerDrillResult:
    """Run the sync and buffered-async simulation engines over the SAME
    seeded heavy-tail delay plan and compare goodput + final accuracy.

    The sync side barriers every round on the slowest sampled client
    (:func:`~fedml_tpu.simulation.async_engine.sync_virtual_seconds`), the
    async side commits every ``async_buffer_size`` arrivals — both on the
    identical hash-seeded virtual clock, so the reported ratio is a property
    of the plan, not of the machine running the drill."""
    import time as _time

    import fedml_tpu
    from ..comm.resilience import ClientDelayPlan
    from ..simulation import build_simulator
    from ..simulation.async_engine import sync_virtual_seconds

    cfg = dict(STRAGGLER_DEFAULTS)
    cfg.update(overrides)
    t0 = _time.perf_counter()

    def _run(extra):
        args = fedml_tpu.init(config=dict(cfg, **extra))
        sim, apply_fn = build_simulator(args)
        history = sim.run(apply_fn, log_fn=None)
        return sim, history

    sync_sim, sync_hist = _run({"async_mode": False})
    async_sim, async_hist = _run({"async_mode": True})

    plan = ClientDelayPlan(
        seed=int(cfg["random_seed"]), base_s=float(cfg["async_delay_base_s"]),
        skew=float(cfg["async_delay_skew"]),
        jitter=float(cfg["async_delay_jitter"]))
    n_rounds = int(cfg["comm_round"])
    cohort = int(cfg["client_num_per_round"])
    sync_vs = sync_virtual_seconds(
        plan, float(cfg["async_delay_base_s"]), range(cohort), n_rounds)
    stats = async_sim.async_stats()
    return StragglerDrillResult(
        commits=int(stats["version"]),
        committed_updates=int(stats["committed_updates"]),
        shed_updates=int(stats["shed_updates"]),
        staleness_max=max(
            (int(r.get("staleness_max", 0)) for r in async_hist), default=0),
        sync_round_rate=n_rounds / sync_vs if sync_vs > 0 else 0.0,
        async_goodput_ups=float(stats["goodput_updates_per_s"]),
        sync_final_acc=_final_acc(sync_hist),
        async_final_acc=_final_acc(async_hist),
        elapsed_s=_time.perf_counter() - t0,
        min_goodput_ratio=float(min_goodput_ratio),
        max_acc_delta=float(max_acc_delta),
        history=list(async_hist),
    )


TIER_DEFAULTS = dict(
    dataset="mnist",
    model="lr",
    debug_small_data=True,
    client_num_in_total=6,
    client_num_per_round=4,
    comm_round=3,
    learning_rate=0.1,
    epochs=1,
    batch_size=8,
    frequency_of_the_test=1,
    random_seed=0,
    # the tier plane: 1 root + 2 leaf aggregators over loopback, aggressive
    # lease cadence so a killed leaf is detected within the drill's budget
    hier_num_leaves=2,
    group_comm_round=2,
    lease_ttl_s=0.5,
    lease_heartbeat_s=0.1,
    hier_round_timeout_s=30.0,
    hier_join_timeout_s=20.0,
)


@dataclasses.dataclass
class TierDrillResult:
    """Outcome of one hierarchical-federation drill (leaf crash or
    partition): did the run survive the fault, was every surviving client's
    update committed exactly once, and did the final model stay within the
    accuracy gate of the fault-free reference?"""

    scenario: str                 # "leaf_crash" | "partition"
    rounds_completed: int
    rounds_expected: int
    failovers: int                # lease expiries that triggered reassignment
    rehydrations: int             # chunks recovered from a dead leaf's shard
    committed_updates: int        # client updates folded, across all rounds
    expected_updates: int         # rounds x cohort — what exactly-once means
    duplicate_commits: int        # ledger-caught double-folds (must be 0)
    faults_injected: Dict[str, float]
    fault_free_acc: float
    faulted_acc: float
    elapsed_s: float
    max_acc_delta: float = 0.02
    history: List[dict] = dataclasses.field(default_factory=list)

    @property
    def acc_delta(self) -> float:
        return self.fault_free_acc - self.faulted_acc

    @property
    def ok(self) -> bool:
        return (self.rounds_completed >= self.rounds_expected
                and self.failovers >= 1          # the fault actually fired
                and self.duplicate_commits == 0
                and self.committed_updates == self.expected_updates
                and self.acc_delta <= self.max_acc_delta)

    def summary(self) -> str:
        return (
            f"tier drill [{self.scenario}]: {'PASS' if self.ok else 'FAIL'}"
            f" — {self.rounds_completed}/{self.rounds_expected} rounds in "
            f"{self.elapsed_s:.1f}s | failovers={self.failovers} "
            f"rehydrations={self.rehydrations} | committed "
            f"{self.committed_updates}/{self.expected_updates} updates, "
            f"{self.duplicate_commits} duplicates | acc faulted "
            f"{self.faulted_acc:.4f} vs fault-free {self.fault_free_acc:.4f}"
            f" (delta {self.acc_delta:+.4f}, gate <={self.max_acc_delta:.2f})"
        )

    def json_record(self) -> dict:
        """Same single-reporter contract as :meth:`ChaosDrillResult.
        json_record` — one JSON-able dict behind ``bench.py --chaos`` and
        ``fedml-tpu chaos-drill --leaf-crash/--partition --json``."""
        return {
            "scenario": self.scenario,
            "rounds_completed": self.rounds_completed,
            "rounds_expected": self.rounds_expected,
            "failovers": self.failovers,
            "rehydrations": self.rehydrations,
            "committed_updates": self.committed_updates,
            "expected_updates": self.expected_updates,
            "duplicate_commits": self.duplicate_commits,
            "faults_injected": {k: int(v)
                                for k, v in sorted(self.faults_injected.items())},
            "fault_free_acc": round(self.fault_free_acc, 6),
            "faulted_acc": round(self.faulted_acc, 6),
            "acc_delta": round(self.acc_delta, 6),
            "elapsed_s": round(self.elapsed_s, 3),
            "ok": self.ok,
        }


def run_tier_drill(scenario: str = "leaf_crash",
                   max_acc_delta: float = 0.02,
                   **overrides) -> TierDrillResult:
    """Run one hierarchical-federation failure drill over loopback.

    ``leaf_crash`` kills leaf aggregator 1 mid-generation (it computes and
    persists its shard, then dies uploading — the rehydration path's exact
    cut point); ``partition`` cuts root<->leaf-1 for one round window and
    lets the cut heal. Both run a fault-free single-process reference over
    the same seed first, so the accuracy gate — and in practice bit-identical
    params — pins that failover loses no client update and commits none
    twice."""
    import tempfile
    import time as _time

    import fedml_tpu
    from ..core import telemetry
    from ..simulation.federation import (build_tiered_simulator,
                                         run_tiered_federation)

    if scenario not in ("leaf_crash", "partition"):
        raise ValueError(f"unknown tier drill scenario: {scenario!r}")
    cfg = dict(TIER_DEFAULTS)
    cfg.update(overrides)
    rounds = int(cfg["comm_round"])
    cohort = int(cfg["client_num_per_round"])
    t0 = _time.perf_counter()

    # fault-free reference: the single-process driver (same chunks, same
    # leaf program, same fold — minus the wire and minus the fault plan)
    ref_sim, ref_apply = build_tiered_simulator(fedml_tpu.init(config=cfg))
    ref_hist = ref_sim.run(ref_apply, log_fn=None)

    faulted = dict(cfg)
    if scenario == "leaf_crash":
        faulted.setdefault("hier_shard_dir", tempfile.mkdtemp(
            prefix="tier_drill_shards_"))
        faulted.update(fault_leaf_crash_rank=1, fault_leaf_crash_at_round=1)
    else:
        faulted.update(fault_partition_ranks_a=[0],
                       fault_partition_ranks_b=[1],
                       fault_partition_rounds=(1, 2))

    registry = telemetry.get_registry()
    before = registry.snapshot()["counters"] if telemetry.enabled() else {}
    root = run_tiered_federation(fedml_tpu.init(config=faulted))
    after = registry.snapshot()["counters"] if telemetry.enabled() else {}

    def delta(name, label=None):
        a = _label_totals(after, name, label)
        b = _label_totals(before, name, label)
        return {k: v - b.get(k, 0.0) for k, v in a.items()}

    ledger = root.state.ledger
    return TierDrillResult(
        scenario=scenario,
        rounds_completed=len(root.history),
        rounds_expected=rounds,
        failovers=int(root.failovers),
        rehydrations=int(root.rehydrations),
        committed_updates=int(ledger.total_commits),
        expected_updates=rounds * cohort,
        duplicate_commits=int(ledger.duplicates),
        faults_injected=delta("fedml_faults_injected_total", "action"),
        fault_free_acc=_final_acc(ref_hist),
        faulted_acc=_final_acc(root.history),
        elapsed_s=_time.perf_counter() - t0,
        max_acc_delta=float(max_acc_delta),
        history=list(root.history),
    )


def _label_totals(counters: Dict[str, float], name: str,
                  label: Optional[str] = None,
                  where: Optional[Dict[str, str]] = None) -> Dict[str, float]:
    """Collect ``name{...}`` counters from a registry snapshot; with
    ``label``, key the result by that label's value; ``where`` keeps only
    series whose labels match every given key=value pair."""
    out: Dict[str, float] = {}
    for key, value in counters.items():
        if not (key == name or key.startswith(name + "{")):
            continue
        inner = key[len(name):].strip("{}")
        labels = dict(kv.split("=", 1) for kv in inner.split(",") if "=" in kv)
        if where and any(labels.get(k) != v for k, v in where.items()):
            continue
        if label is None:
            out["total"] = out.get("total", 0.0) + value
            continue
        k = labels.get(label, "?")
        out[k] = out.get(k, 0.0) + value
    return out


def run_chaos_drill(args=None, n_clients: Optional[int] = None,
                    join_timeout_s: float = 120.0,
                    tenant: Optional[str] = None, registry=None,
                    **overrides) -> ChaosDrillResult:
    """Run one seeded chaos deployment over loopback and report the outcome.

    ``overrides`` lands on top of :data:`PHASE_DEFAULTS` (so e.g.
    ``fault_crash_rank=1`` or ``fault_drop_rate=0.4`` tweak the plan);
    passing a pre-built ``args`` skips the defaults entirely.

    ``tenant``/``registry`` scope the drill's accounting to one tenant: every
    server/client thread runs inside :func:`telemetry.tenant_scope`, so the
    resilience counters land tenant-labeled, and the before/after deltas are
    filtered to that tenant's series. Passing a
    :class:`~fedml_tpu.core.telemetry.TenantRegistry` (from
    :func:`telemetry.scoped_registry`) implies its tenant.
    """
    import fedml_tpu
    from ..comm import LoopbackHub
    from ..core import telemetry
    from .horizontal_api import FedML_Horizontal

    if args is None:
        cfg = dict(PHASE_DEFAULTS)
        cfg.update(overrides)
        args = fedml_tpu.init(config=cfg)
    # PHASE_DEFAULTS is the single source for drill defaults — a pre-built
    # args missing a key falls back to the same values the cfg path uses
    n = int(n_clients if n_clients is not None
            else getattr(args, "client_num_in_total",
                         PHASE_DEFAULTS["client_num_in_total"]))
    rounds = int(getattr(args, "comm_round", PHASE_DEFAULTS["comm_round"]))

    if registry is None:
        registry = telemetry.get_registry()
    if tenant is None:
        tenant = getattr(registry, "tenant", None)
    before = registry.snapshot()["counters"] if telemetry.enabled() else {}

    def scoped(fn):
        # contextvars do not inherit into threads: each drill thread must
        # enter the tenant scope inside its own body
        def runner():
            with telemetry.tenant_scope(tenant):
                fn()
        return runner

    hub = LoopbackHub()
    server = FedML_Horizontal(args, 0, n, backend="LOOPBACK", hub=hub)
    clients = [FedML_Horizontal(args, rank, n, backend="LOOPBACK", hub=hub)
               for rank in range(1, n + 1)]
    threads = [threading.Thread(target=scoped(c.run), daemon=True,
                                name=f"chaos-c{i+1}")
               for i, c in enumerate(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    with telemetry.tenant_scope(tenant):
        server.start()  # caller-thread sends must carry the label too
    server_thread = threading.Thread(target=scoped(server.run), daemon=True,
                                     name="chaos-server")
    server_thread.start()
    server_thread.join(timeout=join_timeout_s)
    hung = server_thread.is_alive()
    if hung:
        logging.error("chaos drill: server did not finish within %.0fs — "
                      "forcing shutdown", join_timeout_s)
        server.finish()
    for c in clients:
        c.com_manager.stop_receive_message()
    for t in threads:
        t.join(timeout=10.0)
    elapsed = time.perf_counter() - t0

    after = registry.snapshot()["counters"] if telemetry.enabled() else {}
    twhere = {"tenant": tenant} if tenant is not None else {}

    def delta(name, label=None, where=None):
        w = dict(where or {}, **twhere) or None
        a = _label_totals(after, name, label, w)
        b = _label_totals(before, name, label, w)
        return {k: v - b.get(k, 0.0) for k, v in a.items()}

    # codec accounting from the ENCODE side only: the drill hosts server and
    # clients in one process, so summing encode+decode would double-count
    # every frame. encode's in=raw / out=wire on both planes.
    enc = {"direction": "encode"}
    return ChaosDrillResult(
        rounds_completed=len(server.history) if not hung else
        min(len(server.history), rounds - 1),  # a hung run never passes
        rounds_expected=rounds,
        elapsed_s=elapsed,
        faults_injected=delta("fedml_faults_injected_total", "action"),
        send_retries=sum(delta("fedml_send_retries_total").values()),
        send_failures=sum(delta("fedml_send_failures_total").values()),
        history=list(server.history),
        quarantined=sum(delta("fedml_quarantined_total").values()),
        rollbacks=sum(delta("fedml_rollbacks_total").values()),
        codec_bytes_raw=delta("fedml_codec_bytes_in", "plane", enc),
        codec_bytes_wire=delta("fedml_codec_bytes_out", "plane", enc),
        tenant=tenant,
    )


# --- poisoned-rollout drill (serving plane) ----------------------------------

ROLLOUT_DEFAULTS = dict(
    dataset="mnist",
    model="lr",
    debug_small_data=True,
    client_num_in_total=6,
    client_num_per_round=4,
    comm_round=6,
    learning_rate=0.1,
    epochs=1,
    batch_size=8,
    # every round commits AND evaluates synchronously, so publish order is
    # deterministic and each version number pairs with its exact round
    frequency_of_the_test=1,
    random_seed=0,
    prefetch=False,
    # serving plane: canary on, inline verdicts (no worker thread — the
    # drill wants the promote/rollback decision before publish returns)
    serve_enabled=True,
    canary_batches=4,
    canary_batch_size=64,
    canary_regression_threshold=0.02,
    canary_seed=0,
    # the poison: the publish artifact of this version is corrupted the way
    # a compromised rollout pipeline would corrupt it — training itself is
    # untouched, so fault-free and faulted runs train identically
    rollout_poison_version=5,
    rollout_poison_kind="sign_flip",
    rollout_poison_scale=10.0,
)


@dataclasses.dataclass
class RolloutDrillResult:
    """Outcome of one poisoned-rollout drill: did the canary block the
    poisoned promotion, did serving roll back to last-good, did served
    accuracy hold, and is the poisoned version pinned unre-promotable?"""

    poison_version: int
    poison_kind: str
    publishes: int
    promoted: int                 # hot-swaps in the faulted run
    rollbacks: int                # store rollbacks (>= 1: the fault fired)
    rollbacks_counter: float      # fedml_rollbacks_served_total delta
    poison_status: str            # publish() return for the poisoned version
    poison_verdict: str           # version-log verdict for that version
    repub_status: str             # re-publishing the CLEAN params afterwards
    served_acc_gap: float         # max over versions: ref served acc - faulted
    fault_free_acc: float         # final served accuracy, fault-free run
    faulted_acc: float            # final served accuracy, faulted run
    trajectory: List[dict]        # per publish: version/status/served acc
    elapsed_s: float
    max_acc_delta: float = 0.02

    @property
    def ok(self) -> bool:
        return (self.rollbacks >= 1
                and self.rollbacks_counter >= 1
                and self.poison_status == "rolled_back"
                and self.poison_verdict == "rolled_back"
                and self.repub_status == "pinned"
                and self.served_acc_gap <= self.max_acc_delta)

    def summary(self) -> str:
        return (
            f"rollout drill [{self.poison_kind} @ v{self.poison_version}]: "
            f"{'PASS' if self.ok else 'FAIL'} — {self.publishes} publishes, "
            f"{self.promoted} promoted, {self.rollbacks} rolled back in "
            f"{self.elapsed_s:.1f}s | poison {self.poison_status}/"
            f"{self.poison_verdict}, re-publish {self.repub_status} | "
            f"served acc gap {self.served_acc_gap:+.4f} "
            f"(gate <= {self.max_acc_delta:.2f}; final faulted "
            f"{self.faulted_acc:.4f} vs fault-free {self.fault_free_acc:.4f})"
        )

    def json_record(self) -> dict:
        return {
            "scenario": "rollout",
            "poison_version": self.poison_version,
            "poison_kind": self.poison_kind,
            "publishes": self.publishes,
            "promoted": self.promoted,
            "rollbacks": self.rollbacks,
            "rollbacks_counter": int(self.rollbacks_counter),
            "poison_status": self.poison_status,
            "poison_verdict": self.poison_verdict,
            "repub_status": self.repub_status,
            "served_acc_gap": round(self.served_acc_gap, 6),
            "fault_free_acc": round(self.fault_free_acc, 6),
            "faulted_acc": round(self.faulted_acc, 6),
            "trajectory": self.trajectory,
            "elapsed_s": round(self.elapsed_s, 3),
            "ok": self.ok,
        }


def run_rollout_drill(max_acc_delta: float = 0.02,
                      **overrides) -> RolloutDrillResult:
    """Poisoned-rollout drill: train a real simulator twice over the same
    seed, publishing every committed version through the canary-gated
    serving plane. The faulted run corrupts ONE version's published
    artifact (``rollout_poison_kind``, a byzantine kind from
    comm/resilience.py — training itself is untouched, modeling a
    compromised rollout pipeline, not a poisoned cohort). The canary must
    refuse the promotion, serving must keep answering from last-good within
    the accuracy gate, and the poisoned version must stay pinned: a later
    re-publish — even of CLEAN params under that version number — is
    refused, because a version number that shipped poison can never be
    trusted to mean one thing again."""
    import numpy as np

    import fedml_tpu
    from ..comm.resilience import corrupt_update_tree
    from ..core import telemetry
    from ..serving import (CanaryEvaluator, InferenceServer, ServeConfig,
                           held_out_batches)
    from ..simulation import build_simulator

    cfg = dict(ROLLOUT_DEFAULTS)
    cfg.update(overrides)
    poison_v = int(cfg["rollout_poison_version"])
    kind = str(cfg["rollout_poison_kind"])
    t0 = time.perf_counter()

    def _run(poison: bool):
        args = fedml_tpu.init(config=cfg)
        sim, apply_fn = build_simulator(args)
        scfg = ServeConfig.from_args(args)

        def predict(params, x):
            return np.asarray(apply_fn(params, np.asarray(x), train=False))

        test = sim.fed.test_data_global
        batches = held_out_batches(test.x, test.y, scfg.canary)
        evaluator = CanaryEvaluator(predict, batches, scfg.canary)
        server = InferenceServer(predict, scfg, eval_batches=batches)
        traj: List[dict] = []
        clean: Dict[int, object] = {}

        def publish(version, params):
            clean[int(version)] = params
            if poison and int(version) == poison_v:
                params = corrupt_update_tree(
                    params, kind, scale=float(cfg["rollout_poison_scale"]),
                    seed=int(cfg["random_seed"]))
            status = server.publish(version, params)
            act = server.store.active()
            served_acc = evaluator.score(act[1])[0] if act else 0.0
            traj.append({"version": int(version), "status": status,
                         "served_acc": round(served_acc, 6)})
            return status

        sim.attach_publisher(publish)
        sim.run(apply_fn, log_fn=None)
        return server, traj, clean

    # fault-free reference: same seed, same publishes, no poison
    _, ref_traj, _ = _run(poison=False)

    registry = telemetry.get_registry()
    before = registry.snapshot()["counters"] if telemetry.enabled() else {}
    server, traj, clean = _run(poison=True)
    after = registry.snapshot()["counters"] if telemetry.enabled() else {}

    def delta(name):
        a = _label_totals(after, name)
        b = _label_totals(before, name)
        return sum(a.values()) - sum(b.values())

    poison_recs = [r for r in traj if r["version"] == poison_v]
    poison_status = poison_recs[0]["status"] if poison_recs else "missing"
    verdicts = server.store.versions()
    # the pin: re-publishing the poisoned version number with the CLEAN
    # params must still be refused
    repub_status = server.publish(poison_v, clean[poison_v])
    gap = max((ref["served_acc"] - fau["served_acc"]
               for ref, fau in zip(ref_traj, traj)), default=float("nan"))
    store = server.store.stats()
    return RolloutDrillResult(
        poison_version=poison_v,
        poison_kind=kind,
        publishes=len(traj),
        promoted=store["swaps"],
        rollbacks=store["rollbacks"],
        rollbacks_counter=delta("fedml_rollbacks_served_total"),
        poison_status=poison_status,
        poison_verdict=str(verdicts.get(poison_v, "missing")),
        repub_status=repub_status,
        served_acc_gap=float(gap),
        fault_free_acc=ref_traj[-1]["served_acc"] if ref_traj else 0.0,
        faulted_acc=traj[-1]["served_acc"] if traj else 0.0,
        trajectory=traj,
        elapsed_s=time.perf_counter() - t0,
        max_acc_delta=float(max_acc_delta),
    )
