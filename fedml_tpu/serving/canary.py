"""Seeded canary evaluation for version promotion.

A candidate version earns its hot-swap by scoring against a fixed set of
held-out batches drawn once with the canary seed — the SAME batches every
round and every process, so a canary verdict is reproducible and two
replicas never disagree about whether a rollout regressed. Two gates:

- **finiteness** — any non-finite output (or non-finite params; see
  :func:`fedml_tpu.core.robust.tree_finite`, the watchdog's shared gate)
  fails immediately: a NaN model would serve NaN scores to every request;
- **regression** — candidate accuracy more than ``regression_threshold``
  below the serving baseline fails (baseline = the currently-promoted
  version scored on the same batches).

The evaluator is deliberately tiny and host-side: a few small batches per
verdict, cheap enough to ride the publish path or the serve worker's drain
loop without denting throughput.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Sequence, Tuple

import numpy as np

PyTree = Any


@dataclasses.dataclass(frozen=True)
class CanaryConfig:
    # fraction of live traffic routed to an undecided candidate while the
    # evaluator scores it (0 = shadow-only canary, no live exposure)
    fraction: float = 0.1
    # held-out batches per verdict; more batches = lower-variance verdict
    batches: int = 4
    batch_size: int = 64
    # max accuracy drop vs the serving baseline before rollback fires
    regression_threshold: float = 0.02
    seed: int = 0


def held_out_batches(x, y, cfg: CanaryConfig
                     ) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Draw the canary's held-out batches from a global test split,
    deterministically in the canary seed (NOT the run seed — the canary
    must score identically across runs that train differently)."""
    x = np.asarray(x)
    y = np.asarray(y)
    n = int(x.shape[0])
    if n == 0:
        return []
    rng = np.random.default_rng(int(cfg.seed))
    out = []
    for _ in range(max(int(cfg.batches), 1)):
        idx = rng.choice(n, size=min(int(cfg.batch_size), n), replace=False)
        out.append((x[idx], y[idx]))
    return out


class CanaryEvaluator:
    """Scores params against the fixed held-out batches.

    ``predict_fn(params, x) -> outputs`` — class scores ``(B, C)`` or a
    scalar-per-sample vector ``(B,)`` (thresholded at 0.5, the bce
    convention used by the eval plane).
    """

    def __init__(self, predict_fn: Callable[[PyTree, np.ndarray], Any],
                 batches: Sequence[Tuple[np.ndarray, np.ndarray]],
                 cfg: CanaryConfig = CanaryConfig()):
        self.cfg = cfg
        self._predict = predict_fn
        self._batches = list(batches)
        if not self._batches:
            raise ValueError("canary evaluator needs >= 1 held-out batch")

    def __len__(self) -> int:
        return len(self._batches)

    def score_batch(self, params: PyTree, i: int
                    ) -> Tuple[float, bool, int]:
        """One batch: ``(accuracy, finite, n_samples)``. ``i`` wraps, so an
        incremental scorer can just feed its running batch counter."""
        x, y = self._batches[i % len(self._batches)]
        out = np.asarray(self._predict(params, x))
        finite = bool(np.all(np.isfinite(out)))
        if not finite:
            return 0.0, False, int(x.shape[0])
        if out.ndim > 1:
            pred = np.argmax(out, axis=-1)
        else:
            pred = (out > 0.5).astype(np.int64)
        acc = float(np.mean(pred.reshape(-1) == np.asarray(y).reshape(-1)))
        return acc, True, int(x.shape[0])

    def score(self, params: PyTree) -> Tuple[float, bool]:
        """All batches: sample-weighted accuracy + finiteness. Short-circuits
        on the first non-finite batch (the verdict is already decided)."""
        acc_sum = 0.0
        n_sum = 0
        for i in range(len(self._batches)):
            acc, finite, n = self.score_batch(params, i)
            if not finite:
                return 0.0, False
            acc_sum += acc * n
            n_sum += n
        return acc_sum / max(n_sum, 1), True

    def verdict(self, baseline_acc: float, cand_acc: float,
                cand_finite: bool) -> bool:
        """True = promote. The epsilon absorbs float summation noise so a
        bit-identical re-publish of the baseline always passes."""
        if not cand_finite:
            return False
        return (float(baseline_acc) - float(cand_acc)
                <= float(self.cfg.regression_threshold) + 1e-12)
