"""In-process batched inference server with canary-gated hot-swap.

The serving half of the train/serve plane: training commits a model version
(``FedSimulator.attach_publisher`` → :meth:`InferenceServer.publish`),
the canary scores it against seeded held-out batches, and only a passing
version is promoted into the request path — a regressing or non-finite
rollout is rolled back to last-good automatically and pinned so it can
never be re-promoted (the verdict rides the version log; see
serving/store.py).

Admission reuses the multi-tenant edge the cross-silo server and the async
engine already share: requests enter through a bounded
:class:`~fedml_tpu.core.tenancy.CheckinQueue` (overload sheds with a
counter instead of an unbounded backlog) and, when a
:class:`~fedml_tpu.core.tenancy.DeficitRoundRobinScheduler` is attached,
drain in deficit-round-robin order across tenants — mixed train/serve
traffic shares one queue without starvation.

Hot-swap contract: a batch reads the store's active ``(version, params)``
tuple ONCE and serves the whole batch from that reference; a promote
landing mid-batch swaps the tuple for the NEXT batch. No request is ever
dropped by a swap — drops happen only at the admission edge, and only
under overload.

Threading: ``pump`` drains on the caller's thread (deterministic drills);
``start`` runs it on a worker thread (throughput benches). Every mutable
server attribute is touched only under ``self._lock``; metric writes and
store calls happen outside it (graftcheck lock-order/thread-hazard scope
covers this package).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core import telemetry
from ..core.robust import tree_finite_host
from ..core.tenancy import CheckinQueue, DeficitRoundRobinScheduler
from ..utils.checkpoint import DEFAULT_KEEP_VERSIONS
from .canary import CanaryConfig, CanaryEvaluator
from .store import VersionedModelStore

PyTree = Any


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Serving knobs (``serve_*``/``canary_*`` in the flat args namespace).
    ``enabled`` is the master gate: False (the default) means no server is
    built anywhere — the training path stays byte-identical."""

    enabled: bool = False
    batch_max: int = 64
    queue_maxsize: int = 4096
    tenant: str = "serve"
    keep_versions: int = DEFAULT_KEEP_VERSIONS
    canary: CanaryConfig = dataclasses.field(default_factory=CanaryConfig)

    @staticmethod
    def from_args(args) -> "ServeConfig":
        return ServeConfig(
            enabled=bool(getattr(args, "serve_enabled", False)),
            batch_max=int(getattr(args, "serve_batch_max", 64)),
            queue_maxsize=int(getattr(args, "serve_queue_maxsize", 4096)),
            tenant=str(getattr(args, "serve_tenant", "serve")),
            # shared retention default with the round-store / federation log
            keep_versions=int(
                getattr(args, "round_store_keep_versions",
                        DEFAULT_KEEP_VERSIONS) or 0),
            canary=CanaryConfig(
                fraction=float(getattr(args, "canary_fraction", 0.1)),
                batches=int(getattr(args, "canary_batches", 4)),
                batch_size=int(getattr(args, "canary_batch_size", 64)),
                regression_threshold=float(
                    getattr(args, "canary_regression_threshold", 0.02)),
                seed=int(getattr(args, "canary_seed", 0)),
            ),
        )


class InferenceServer:
    """Batched request server over a :class:`VersionedModelStore`.

    ``predict_fn(params, x) -> outputs`` must accept a stacked feature
    batch. ``eval_batches`` (held-out ``(x, y)`` pairs) arm the canary;
    without them every publish promotes immediately (trust-on-publish).
    ``handler`` consumes non-inference queue items (mixed-traffic mode:
    training check-in frames share the admission queue). ``on_result``
    (optional) receives ``(request_id, served_version, output_row)`` per
    request — for correctness tests, not the throughput path.
    ``on_verdict`` (optional) receives ``(version, status)`` when a
    version reaches a terminal state (``promoted`` / ``rolled_back`` /
    ``superseded``) — fired outside every lock, so a trainer can block
    on a real Event for the canary verdict instead of GIL-starved
    polling.
    """

    def __init__(self, predict_fn: Callable[[PyTree, np.ndarray], Any],
                 cfg: Optional[ServeConfig] = None,
                 eval_batches=(),
                 queue: Optional[CheckinQueue] = None,
                 drr: Optional[DeficitRoundRobinScheduler] = None,
                 handler: Optional[Callable[[Any], Any]] = None,
                 on_result: Optional[Callable[[Any, int, Any], Any]] = None,
                 on_verdict: Optional[Callable[[int, str], Any]] = None):
        self.cfg = cfg or ServeConfig(enabled=True)
        self._predict = predict_fn
        self.store = VersionedModelStore(self.cfg.keep_versions)
        self._canary = (
            CanaryEvaluator(predict_fn, eval_batches, self.cfg.canary)
            if eval_batches else None)
        self.queue = queue or CheckinQueue(maxsize=self.cfg.queue_maxsize)
        self._drr = drr
        if drr is not None:
            try:
                drr.register(self.cfg.tenant, round_cost=1.0)
            except ValueError:
                pass  # shared scheduler: tenant registered by the caller
        self._handler = handler
        self._on_result = on_result
        self._on_verdict = on_verdict
        self._lock = threading.Lock()
        self._run = threading.Event()
        self._worker: Optional[threading.Thread] = None
        # mutable serving state — every access below goes through self._lock
        self._submitted = 0
        self._admitted = 0
        self._served = 0
        self._handled = 0
        self._canary_served = 0
        self._seq = 0
        self._by_version: Dict[int, int] = {}
        self._pend: List[tuple] = []  # admitted before any version exists
        self._baseline: Optional[Tuple[int, float]] = None
        self._cand: Optional[dict] = None  # in-flight canary bookkeeping

    # --- publish side (training thread) ------------------------------------

    def publish(self, version: int, params: PyTree) -> str:
        """The commit→publish hook. Returns the final status: ``promoted``,
        ``candidate`` (worker mode: verdict lands asynchronously after the
        canary window), ``rolled_back``, ``pinned`` or ``duplicate``."""
        status = self.store.publish(version, params)
        if status != "candidate":
            return status
        if self._canary is None:
            self.store.promote(version)
            return "promoted"
        # shared servability gate with the divergence watchdog (host-side
        # variant — the publish path must not boot the XLA backend): a
        # version with non-finite params never reaches the request path
        if not tree_finite_host(params):
            self.store.rollback(version, reason="non_finite_params")
            self._notify(version, "rolled_back")
            return "rolled_back"
        base = self._baseline_acc()
        worker_live = self._worker is not None and self._run.is_set()
        if worker_live:
            with self._lock:
                prev, self._cand = self._cand, {
                    "version": int(version), "acc_sum": 0.0, "n_sum": 0,
                    "steps": 0, "finite": True, "base": base}
            if prev is not None:
                # a newer publish closes the previous canary window
                self.store.retire(prev["version"])
                self._notify(prev["version"], "superseded")
            return "candidate"
        # no worker: score the whole window inline — the deterministic
        # drill path (verdict before publish returns)
        acc, finite = self._canary.score(params)
        if self._canary.verdict(base, acc, finite):
            self.store.promote(version)
            self._notify(version, "promoted")
            return "promoted"
        self.store.rollback(
            version,
            reason="canary_regression" if finite else "non_finite_outputs")
        self._notify(version, "rolled_back")
        return "rolled_back"

    def _notify(self, version: int, status: str) -> None:
        if self._on_verdict is not None:
            self._on_verdict(int(version), status)

    def _baseline_acc(self) -> float:
        """Serving baseline = the active version's score on the canary
        batches, cached per version (one re-score per promote)."""
        act = self.store.active()
        if act is None:
            return 0.0
        version, params = act
        with self._lock:
            b = self._baseline
        if b is not None and b[0] == version:
            return b[1]
        acc, _ = self._canary.score(params)
        with self._lock:
            self._baseline = (version, acc)
        return acc

    # --- request side -------------------------------------------------------

    def submit(self, features, request_id=None,
               tenant: Optional[str] = None) -> bool:
        """Offer one request at the admission edge. False = shed (queue
        full) — the only way the serving plane ever drops a request."""
        t = str(tenant or self.cfg.tenant)
        ok = self.queue.offer(("infer", request_id, features, t), tenant=t)
        with self._lock:
            self._submitted += 1
            if ok:
                self._admitted += 1
        return ok

    def pump(self, max_items: Optional[int] = None) -> int:
        """Drain up to ``max_items`` queue entries on the caller's thread.
        Returns the number drained (0 = queue empty). Non-inference items
        go to ``handler``; inference items are DRR-ordered across tenants
        (when a scheduler is attached) and served in batches of
        ``batch_max``, each batch on ONE store read."""
        if self.store.active() is None:
            # nothing published yet: leave traffic parked in the BOUNDED
            # queue (the edge keeps shedding) instead of pulling it into an
            # unbounded host list — admitted requests still serve once the
            # first version lands
            return 0
        limit = (int(max_items) if max_items is not None
                 else 4 * self.cfg.batch_max)
        infer: List[tuple] = []
        other: List[Any] = []
        n = 0
        while n < limit:
            item = self.queue.poll()
            if item is None:
                break
            n += 1
            if isinstance(item, tuple) and item and item[0] == "infer":
                infer.append(item)
            else:
                other.append(item)
        if other and self._handler is not None:
            for it in other:
                self._handler(it)
            with self._lock:
                self._handled += len(other)
        with self._lock:
            if self._pend:
                infer = self._pend + infer
                self._pend = []
        if infer and self._drr is not None:
            infer = self._drr_order(infer)
        for start in range(0, len(infer), self.cfg.batch_max):
            self._process_batch(infer[start:start + self.cfg.batch_max])
        self._canary_step()
        return n

    def _drr_order(self, items: List[tuple]) -> List[tuple]:
        by_t: Dict[str, List[tuple]] = {}
        for it in items:
            by_t.setdefault(str(it[3]), []).append(it)
        if len(by_t) == 1:
            return items
        ordered: List[tuple] = []
        ready = set(by_t)
        while ready:
            t = self._drr.next_tenant(ready=ready)
            if t is None:
                break
            lst = by_t[t]
            ordered.append(lst.pop(0))
            self._drr.charge(t, 1.0)
            if not lst:
                ready.discard(t)
        for lst in by_t.values():  # tenants the scheduler doesn't know
            ordered.extend(lst)
        return ordered

    def _process_batch(self, items: List[tuple]) -> None:
        act = self.store.active()
        if act is None:
            # admitted before the first publish: park, retry next pump —
            # an admitted request is never dropped
            with self._lock:
                self._pend.extend(items)
            return
        version, params = act  # ONE read; the batch serves this version
        cand = None
        frac = self.cfg.canary.fraction
        with self._lock:
            cand_v = (self._cand["version"]
                      if self._cand is not None else None)
            seq0 = self._seq
            self._seq += len(items)
        if cand_v is not None and frac > 0:
            cand = self.store.get(cand_v)
        stride = max(1, int(round(1.0 / frac))) if frac > 0 else 0
        idx_c = ([i for i in range(len(items))
                  if (seq0 + i) % stride == 0]
                 if cand is not None else [])
        idx_m = [i for i in range(len(items)) if i not in set(idx_c)]
        outs: List[Any] = [None] * len(items)
        vers: List[int] = [version] * len(items)
        for idx, p, v in ((idx_m, params, version),
                          (idx_c, cand, cand_v)):
            if not idx:
                continue
            x = np.stack([np.asarray(items[i][2]) for i in idx])
            out = np.asarray(self._predict(p, x))
            for j, i in enumerate(idx):
                outs[i] = out[j]
                vers[i] = v
        if self._on_result is not None:
            for i, it in enumerate(items):
                self._on_result(it[1], vers[i], outs[i])
        with self._lock:
            self._served += len(items)
            self._canary_served += len(idx_c)
            self._by_version[version] = (
                self._by_version.get(version, 0) + len(idx_m))
            if idx_c:
                self._by_version[cand_v] = (
                    self._by_version.get(cand_v, 0) + len(idx_c))
        reg = telemetry.get_registry()
        if reg.enabled:
            reg.counter("fedml_inference_requests_total").inc(len(items))

    def _canary_step(self) -> None:
        """One held-out batch of canary scoring per drain iteration; the
        verdict fires once the window is full (or on the first non-finite
        batch). Runs on whichever thread pumps."""
        if self._canary is None:
            return
        with self._lock:
            c = self._cand
        if c is None:
            return
        params = self.store.get(c["version"])
        if params is None:  # rolled back / retired underneath us
            with self._lock:
                if self._cand is c:
                    self._cand = None
            return
        acc, finite, nb = self._canary.score_batch(params, c["steps"])
        done = False
        with self._lock:
            if self._cand is not c:
                return
            c["acc_sum"] += acc * nb
            c["n_sum"] += nb
            c["steps"] += 1
            c["finite"] = c["finite"] and finite
            done = ((not c["finite"])
                    or c["steps"] >= self._canary.cfg.batches)
            if done:
                self._cand = None
        if not done:
            return
        cand_acc = c["acc_sum"] / max(c["n_sum"], 1)
        if self._canary.verdict(c["base"], cand_acc, c["finite"]):
            self.store.promote(c["version"])
            self._notify(c["version"], "promoted")
        else:
            self.store.rollback(
                c["version"],
                reason=("canary_regression" if c["finite"]
                        else "non_finite_outputs"))
            self._notify(c["version"], "rolled_back")

    # --- worker -------------------------------------------------------------

    def start(self) -> None:
        if self._worker is not None:
            return
        self._run.set()
        self._worker = threading.Thread(
            target=self._serve_loop, name="fedml-serve", daemon=True)
        self._worker.start()

    def stop(self, drain: bool = True) -> None:
        self._run.clear()
        w = self._worker
        if w is not None:
            w.join(timeout=30.0)
        self._worker = None
        if not drain:
            return
        while self.pump() > 0:
            pass
        # land the verdict of a candidate still mid-window so no version
        # exits the run undecided
        for _ in range(self.cfg.canary.batches + 1):
            with self._lock:
                pending = self._cand is not None
            if not pending:
                break
            self._canary_step()

    def _serve_loop(self) -> None:
        while self._run.is_set():
            if self.pump() == 0:
                time.sleep(0.0005)

    # --- accounting ---------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {
                "submitted": self._submitted,
                "admitted": self._admitted,
                "served": self._served,
                "handled": self._handled,
                "canary_served": self._canary_served,
                "pending": len(self._pend),
                "served_by_version": dict(self._by_version),
            }
        out["dropped"] = out["submitted"] - out["admitted"]
        out["queue"] = self.queue.stats()
        out["store"] = self.store.stats()
        return out
