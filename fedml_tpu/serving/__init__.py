"""Canary-gated serving plane: versioned hot-swap inference.

Training commits a model version after every round / async commit; this
package turns those commits into a live request path with three guarantees:

1. **Zero-drop hot-swap** — a promote is an RCU pointer swap in the
   :class:`~fedml_tpu.serving.store.VersionedModelStore`; in-flight batches
   finish on the version they started with, the next batch serves the new
   one. Requests drop only at the bounded admission edge, under overload.
2. **Canary-gated promotion** — a new version serves a configurable traffic
   fraction while a seeded evaluator scores it against fixed held-out
   batches (:mod:`~fedml_tpu.serving.canary`); a regression beyond the
   threshold or any non-finite output rolls the rollout back to last-good.
3. **Rollback pins** — the verdict is recorded in the version log; a
   rolled-back version is refused on re-publish forever, across trims and
   restarts (``export_state``/``import_state``).

Everything is off by default: no ``serve_*`` knob set means no server is
constructed and the training path is byte-identical to builds without this
package.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from .canary import CanaryConfig, CanaryEvaluator, held_out_batches
from .server import InferenceServer, ServeConfig
from .store import VersionedModelStore

__all__ = [
    "CanaryConfig",
    "CanaryEvaluator",
    "InferenceServer",
    "ServeConfig",
    "VersionedModelStore",
    "build_inference_server",
    "held_out_batches",
]


def build_inference_server(args, sim, apply_fn,
                           queue=None, drr=None, handler=None,
                           on_result=None) -> Optional[InferenceServer]:
    """Wire a server to a built simulator: the canary's held-out batches
    come from the global test split (seeded by ``canary_seed``, not the run
    seed) and ``predict_fn`` is the model's apply under the committed
    variables. Returns None when serving is disabled — the caller attaches
    nothing and the run is unchanged."""
    cfg = ServeConfig.from_args(args)
    if not cfg.enabled:
        return None

    def predict(params: Any, x: np.ndarray):
        return apply_fn(params, np.asarray(x), train=False)

    test = sim.fed.test_data_global
    batches = (held_out_batches(test.x, test.y, cfg.canary)
               if len(test.x) else [])
    server = InferenceServer(
        predict, cfg, eval_batches=batches, queue=queue, drr=drr,
        handler=handler, on_result=on_result)
    sim.attach_publisher(server.publish)
    return server
