"""Versioned model store: the RCU core of the serving plane.

One committed model version = one immutable params pytree. Readers never
block writers and writers never block readers for more than a pointer swap:
``active()`` returns the current ``(version, params)`` tuple and the caller
keeps serving from that reference for as long as it likes — a concurrent
promote just swaps the tuple, so in-flight batches finish on the version
they started with and the next batch picks up the new one (zero dropped
requests across a hot-swap, by construction).

Promotion is two-phase. ``publish`` lands a commit as a *candidate*; only
``promote`` swaps it live (the canary gate in serving/server.py sits between
the two). ``rollback`` pins a version as permanently unservable — the
verdict lives in a dict that survives log trimming, so a rolled-back
version is refused on re-publish even after its params were dropped (the
"never re-promote a poisoned rollout" invariant). The version log itself is
bounded with the shared :data:`~fedml_tpu.utils.checkpoint.DEFAULT_KEEP_VERSIONS`
retention window; entries whose params fell out of the window are freed
unless a reader holds a lease (``acquire``/``release``) or they are the
active / last-good / candidate version.

Concurrency discipline (enforced by graftcheck on this package): every
mutable attribute is touched only under ``self._lock``; metric and trace
writes happen strictly AFTER the lock is released (the registry has its own
lock and the lock-order checker forbids nesting the two).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..core import telemetry, trace_plane
from ..utils.checkpoint import DEFAULT_KEEP_VERSIONS, trim_version_log

PyTree = Any

STATUS_CANDIDATE = "candidate"
STATUS_PROMOTED = "promoted"
STATUS_ROLLED_BACK = "rolled_back"
STATUS_SUPERSEDED = "superseded"


class VersionedModelStore:
    """Thread-safe versioned params store with candidate/promote/rollback
    lifecycle, reader leases, and a bounded version log."""

    def __init__(self, keep_versions: int = DEFAULT_KEEP_VERSIONS):
        # <= 0 = unbounded, same convention as trim_version_log
        self.keep_versions = int(keep_versions or 0)
        self._lock = threading.Lock()
        # version -> {"params", "status", "refs"}; params freed at trim
        self._entries: Dict[int, dict] = {}
        self._published: List[int] = []  # publish order (trim window basis)
        # decided versions, NEVER trimmed (a few bytes per version): the
        # rollback pin and the duplicate-publish guard both live here
        self._verdicts: Dict[int, str] = {}
        self._log: List[list] = []  # [version, event] pairs, trimmed
        self._active: Optional[Tuple[int, PyTree]] = None  # the RCU tuple
        self._last_good: Optional[int] = None
        self._swaps = 0
        self._rollbacks = 0

    # --- write side ---------------------------------------------------------

    def publish(self, version: int, params: PyTree) -> str:
        """Land a committed version. Returns the entry's status:
        ``"promoted"`` (very first version — nothing to canary against),
        ``"candidate"`` (awaiting a promote/rollback verdict), ``"pinned"``
        (version was rolled back earlier; refused), or ``"duplicate"``
        (version already decided or currently held; refused)."""
        version = int(version)
        with self._lock:
            if self._verdicts.get(version) == STATUS_ROLLED_BACK:
                outcome = "pinned"
            elif version in self._verdicts or version in self._entries:
                outcome = "duplicate"
            else:
                first = self._active is None
                status = STATUS_PROMOTED if first else STATUS_CANDIDATE
                self._entries[version] = {
                    "params": params, "status": status, "refs": 0}
                self._published.append(version)
                self._log.append([version, "publish"])
                if first:
                    self._active = (version, params)
                    self._last_good = version
                    self._verdicts[version] = STATUS_PROMOTED
                    self._log.append([version, "promote"])
                self._trim_locked()
                outcome = status
        if outcome in ("pinned", "duplicate"):
            reg = telemetry.get_registry()
            if reg.enabled:
                reg.counter("fedml_publish_refused_total",
                            reason=outcome).inc()
            if trace_plane.active():
                trace_plane.record_instant(
                    "publish_refused",
                    attrs={"version": version, "reason": outcome})
        return outcome

    def promote(self, version: int) -> bool:
        """Swap a candidate live (the hot-swap). O(1) under the lock — the
        swap is one tuple store; latency lands in
        ``fedml_serving_swap_seconds``."""
        version = int(version)
        t0 = time.perf_counter()
        with self._lock:
            e = self._entries.get(version)
            if e is None or e["status"] != STATUS_CANDIDATE:
                return False
            prev = self._active[0] if self._active is not None else None
            e["status"] = STATUS_PROMOTED
            self._active = (version, e["params"])
            self._last_good = version
            self._verdicts[version] = STATUS_PROMOTED
            self._log.append([version, "promote"])
            self._swaps += 1
            self._trim_locked()
        dt = time.perf_counter() - t0
        reg = telemetry.get_registry()
        if reg.enabled:
            reg.counter("fedml_versions_promoted_total").inc()
            reg.histogram("fedml_serving_swap_seconds").observe(dt)
        if trace_plane.active():
            trace_plane.record_instant(
                "promote", attrs={"version": version, "previous": prev,
                                  "swap_s": round(dt, 9)})
        return True

    def rollback(self, version: int, reason: str = "canary") -> Optional[int]:
        """Pin ``version`` as permanently unservable and, if it was live,
        swap back to the newest promoted version. Returns the version now
        active (None if nothing promotable remains)."""
        version = int(version)
        with self._lock:
            e = self._entries.get(version)
            if e is not None:
                e["status"] = STATUS_ROLLED_BACK
            self._verdicts[version] = STATUS_ROLLED_BACK
            self._log.append([version, "rollback"])
            if self._active is not None and self._active[0] == version:
                fallback = max(
                    (v for v, en in self._entries.items()
                     if en["status"] == STATUS_PROMOTED and v != version),
                    default=None)
                if fallback is not None:
                    self._active = (
                        fallback, self._entries[fallback]["params"])
                else:
                    self._active = None
                self._last_good = fallback
            elif self._last_good == version:
                self._last_good = (
                    self._active[0] if self._active is not None else None)
            self._rollbacks += 1
            active_v = self._active[0] if self._active is not None else None
            self._trim_locked()
        reg = telemetry.get_registry()
        if reg.enabled:
            reg.counter("fedml_rollbacks_served_total").inc()
        if trace_plane.active():
            trace_plane.record_instant(
                "rollback_served",
                attrs={"version": version, "reason": reason,
                       "active": active_v})
            trace_plane.flight_dump("serving_rollback")
        return active_v

    def retire(self, version: int) -> None:
        """Close out a candidate that lost its canary window to a newer
        publish. Unlike ``rollback`` this carries no fault verdict — no
        rollback counter, no pin against which the fault drills assert —
        but the version is decided (re-publish refused as duplicate)."""
        version = int(version)
        with self._lock:
            e = self._entries.get(version)
            if e is None or e["status"] != STATUS_CANDIDATE:
                return
            e["status"] = STATUS_SUPERSEDED
            self._verdicts[version] = STATUS_SUPERSEDED
            self._log.append([version, "supersede"])
            self._trim_locked()

    # --- read side ----------------------------------------------------------

    def active(self) -> Optional[Tuple[int, PyTree]]:
        """The live ``(version, params)`` tuple. The caller may keep the
        reference across a concurrent promote — that IS the RCU contract."""
        with self._lock:
            return self._active

    def candidate(self) -> Optional[Tuple[int, PyTree]]:
        """The newest undecided candidate (canary traffic target), if any."""
        with self._lock:
            for v in reversed(self._published):
                e = self._entries.get(v)
                if e is not None and e["status"] == STATUS_CANDIDATE:
                    return v, e["params"]
        return None

    def get(self, version: int) -> Optional[PyTree]:
        with self._lock:
            e = self._entries.get(int(version))
            return None if e is None else e["params"]

    def acquire(self, version: Optional[int] = None
                ) -> Optional[Tuple[int, PyTree]]:
        """Lease a version: its params survive trimming until ``release``.
        ``None`` leases whatever is active."""
        with self._lock:
            if version is None:
                if self._active is None:
                    return None
                version = self._active[0]
            e = self._entries.get(int(version))
            if e is None:
                return None
            e["refs"] += 1
            return int(version), e["params"]

    def release(self, version: int) -> None:
        with self._lock:
            e = self._entries.get(int(version))
            if e is not None and e["refs"] > 0:
                e["refs"] -= 1
            self._trim_locked()

    # --- retention / persistence -------------------------------------------

    def _trim_locked(self) -> None:
        # caller holds self._lock. Up to 3 log events per version
        # (publish/promote-or-supersede/rollback), so the event log keeps
        # 3x the version window to cover every retained version's history.
        keep = self.keep_versions
        if keep <= 0:
            return
        self._log = trim_version_log(self._log, keep * 3)
        retained = set(trim_version_log(self._published, keep))
        active_v = self._active[0] if self._active is not None else None
        for v in list(self._entries):
            e = self._entries[v]
            if v in retained or v == active_v or v == self._last_good:
                continue
            if e["refs"] > 0 or e["status"] == STATUS_CANDIDATE:
                continue
            del self._entries[v]
        self._published = [
            v for v in self._published if v in retained or v in self._entries]

    def versions(self) -> Dict[int, str]:
        """Status of every version the store still knows about — live
        entries overlay the (never-trimmed) verdict map."""
        with self._lock:
            out = dict(self._verdicts)
            for v, e in self._entries.items():
                out[v] = e["status"]
            return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "active_version": (
                    self._active[0] if self._active is not None else None),
                "last_good": self._last_good,
                "entries": len(self._entries),
                "swaps": self._swaps,
                "rollbacks": self._rollbacks,
                "log_len": len(self._log),
            }

    def export_state(self) -> dict:
        """Msgpack-friendly durable state: the event log and the verdict
        pins (params are NOT persisted — a restarted server re-fills from
        training commits, and the pins guarantee a poisoned version stays
        refused across the restart)."""
        with self._lock:
            return {
                "log": [list(e) for e in self._log],
                "verdicts": {int(k): str(v)
                             for k, v in self._verdicts.items()},
                "active_version": (
                    self._active[0] if self._active is not None else None),
                "last_good": self._last_good,
            }

    def import_state(self, state: dict) -> None:
        with self._lock:
            self._log = [list(e) for e in (state.get("log") or ())]
            self._verdicts = {
                int(k): str(v)
                for k, v in (state.get("verdicts") or {}).items()}
