"""Fleet-scale device registry: seeded availability + lifecycle for 1M clients.

Cross-device FL (PAPER.md's Beehive line) starts from a registry of
*devices*, not a list of silo ranks: millions of phones, each with its own
availability window (charging, idle, on wifi — the Google FL eligibility
criteria), each moving through a lifecycle per check-in::

    ELIGIBLE -> CHECKED_IN -> TRAINING -> (uploaded -> ELIGIBLE | DROPPED)

plus two churn transitions: DROPPED devices *rejoin* (back to ELIGIBLE,
possibly needing a model resync), and some depart permanently (DEPARTED —
the point where their spilled client state must be reclaimed, see
:meth:`fedml_tpu.simulation.client_store.ClientStateArena.discard`).

Everything here is vectorized numpy over the full fleet — a 1M-device
registry is ~15 MB of flat arrays, so "millions of users" fits tier-1 CPU
runs (FedJAX, PAPERS.md, makes the same bet). All randomness is drawn from
``np.random.default_rng([seed, ...])`` streams keyed by purpose, so a
simulated day replays bit-identically from the seed. Availability-aware
cohorting (only currently-awake devices are candidates) follows Parrot's
treatment of device heterogeneity as a scheduling input (PAPERS.md).
"""

from __future__ import annotations

from typing import Dict, Sequence

import numpy as np

# lifecycle states (int8 array values)
ELIGIBLE = 0
CHECKED_IN = 1
TRAINING = 2
DROPPED = 3
DEPARTED = 4

STATE_NAMES = ("eligible", "checked_in", "training", "dropped", "departed")


class DeviceRegistry:
    """Flat-array registry of ``size`` devices with seeded availability.

    - ``state``: lifecycle per device (``ELIGIBLE`` .. ``DEPARTED``).
    - availability: each device is awake for one seeded window per day
      (``awake_start`` offset, ``awake_len`` duration); :meth:`available`
      is a vectorized mask over the whole fleet.
    - ``device_class``: ``device_id % num_classes`` — the tenant key the
      admission edge's deficit-round-robin fairness runs over (a stand-in
      for device cohorts like hardware tier or geo).
    - ``last_version``: the model version a device last synced, consulted
      on rejoin to decide full vs incremental resync against the trimmed
      version log (the elastic-membership contract, PR 14).
    - ``held``: churn-wave hold — a held DROPPED device does not auto-
      recover; only an explicit rejoin wave releases it.
    """

    def __init__(self, size: int, *, num_classes: int = 4, seed: int = 0,
                 day_s: float = 86_400.0):
        if size <= 0:
            raise ValueError(f"registry size must be positive, got {size}")
        if num_classes <= 0:
            raise ValueError(f"num_classes must be positive, got {num_classes}")
        self.size = int(size)
        self.num_classes = int(num_classes)
        self.seed = int(seed)
        self.day_s = float(day_s)
        rng = np.random.default_rng([self.seed, 0x_DE5C])
        self.state = np.zeros(self.size, dtype=np.int8)
        self.awake_start = rng.uniform(
            0.0, self.day_s, size=self.size).astype(np.float32)
        self.awake_len = (rng.uniform(0.3, 0.9, size=self.size)
                          * self.day_s).astype(np.float32)
        self.last_version = np.zeros(self.size, dtype=np.int32)
        self.held = np.zeros(self.size, dtype=bool)
        self.counters: Dict[str, int] = {
            "checkins": 0, "uploads": 0, "dropouts": 0, "rejoins": 0,
            "departures": 0, "resync_full": 0, "resync_incremental": 0,
        }

    # ------------------------------------------------------- availability

    def available(self, t_s: float) -> np.ndarray:
        """Boolean mask: device is inside its awake window at time ``t_s``
        (wrapping across midnight) — independent of lifecycle state."""
        phase = (float(t_s) - self.awake_start) % self.day_s
        return phase < self.awake_len

    def eligible_available(self, t_s: float) -> np.ndarray:
        """Device ids that may check in at ``t_s``: awake AND eligible."""
        return np.flatnonzero(self.available(t_s)
                              & (self.state == ELIGIBLE))

    def device_class(self, ids) -> np.ndarray:
        return np.asarray(ids, dtype=np.int64) % self.num_classes

    def admissible(self, ids) -> np.ndarray:
        """Per-device admission verdict for an arrival wave: a device that
        dropped, departed, or already checked in since it decided to
        announce itself is refused (shed reason ``inadmissible``)."""
        return self.state[np.asarray(ids, dtype=np.int64)] == ELIGIBLE

    # ---------------------------------------------------------- lifecycle

    def mark_checked_in(self, ids) -> None:
        ids = np.asarray(ids, dtype=np.int64)
        self.state[ids] = CHECKED_IN
        self.counters["checkins"] += int(ids.size)

    def mark_training(self, ids) -> None:
        self.state[np.asarray(ids, dtype=np.int64)] = TRAINING

    def mark_uploaded(self, ids, version: int) -> None:
        ids = np.asarray(ids, dtype=np.int64)
        self.state[ids] = ELIGIBLE
        self.last_version[ids] = int(version)
        self.counters["uploads"] += int(ids.size)

    def release(self, ids) -> None:
        """Checked-in devices the round plane did not select this tick go
        back to ELIGIBLE (told to come back later) — not a dropout."""
        self.state[np.asarray(ids, dtype=np.int64)] = ELIGIBLE

    def mark_dropped(self, ids, *, held: bool = False) -> int:
        """Drop devices (mid-round failure or churn wave). Already-departed
        devices are unaffected. Returns how many actually transitioned."""
        ids = np.asarray(ids, dtype=np.int64)
        ids = ids[self.state[ids] != DEPARTED]
        self.state[ids] = DROPPED
        if held:
            self.held[ids] = True
        self.counters["dropouts"] += int(ids.size)
        return int(ids.size)

    def rejoin(self, ids, *, log_floor_version: int) -> Dict[str, int]:
        """Bring DROPPED devices back to ELIGIBLE. Each rejoiner resyncs:
        devices whose ``last_version`` predates the retained version log
        (``< log_floor_version``) need a *full* model resync, the rest an
        incremental one — mirroring the tier plane's elastic re-adoption
        against ``trim_version_log`` retention."""
        ids = np.asarray(ids, dtype=np.int64)
        ids = ids[self.state[ids] == DROPPED]
        full = int(np.sum(self.last_version[ids] < int(log_floor_version)))
        self.state[ids] = ELIGIBLE
        self.held[ids] = False
        self.counters["rejoins"] += int(ids.size)
        self.counters["resync_full"] += full
        self.counters["resync_incremental"] += int(ids.size) - full
        return {"rejoined": int(ids.size), "resync_full": full,
                "resync_incremental": int(ids.size) - full}

    def depart(self, ids) -> np.ndarray:
        """Permanent departures. Returns the ids that actually departed
        (for arena spill reclamation)."""
        ids = np.asarray(ids, dtype=np.int64)
        ids = ids[self.state[ids] != DEPARTED]
        self.state[ids] = DEPARTED
        self.held[ids] = False
        self.counters["departures"] += int(ids.size)
        return ids

    def recover(self, rate: float, rng) -> int:
        """Natural per-tick recovery: each non-held DROPPED device comes
        back to ELIGIBLE with probability ``rate`` (seeded by the caller's
        per-tick generator). Churn-held devices wait for their wave."""
        cand = np.flatnonzero((self.state == DROPPED) & ~self.held)
        if cand.size == 0 or rate <= 0:
            return 0
        back = cand[rng.random(cand.size) < float(rate)]
        self.state[back] = ELIGIBLE
        return int(back.size)

    # ----------------------------------------------------------- readouts

    def state_counts(self) -> Dict[str, int]:
        counts = np.bincount(self.state, minlength=len(STATE_NAMES))
        return {name: int(counts[i]) for i, name in enumerate(STATE_NAMES)}

    def summary(self) -> Dict[str, int]:
        out = dict(self.counters)
        out.update(self.state_counts())
        return out
