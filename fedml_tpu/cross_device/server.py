"""Cross-device (Beehive) FL server: aggregates serialized client payloads.

Parity: reference ``cross_device/server_mnn/`` — ``fedavg_cross_device:10``
(Python server only; phone clients are external), ``FedMLAggregator:15``
(model params read/written as serialized **.mnn files**,
``get_global_model_params_file:46``), ``FedMLServerManager:14`` (same
handshake FSM as Octopus over MQTT_S3_MNN). Redesign: the device payload is a
format-agnostic *blob* — bytes produced by any on-device codec. The default
codec is this framework's msgpack tensor format; an MNN-style file codec
would plug in the same two functions. The round FSM is inherited unchanged
from the cross-silo server manager (the reference duplicates it).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np

from ..comm.message import pack_payload, unpack_payload
from ..cross_silo.aggregator import FedMLAggregator
from ..cross_silo.server_manager import FedMLServerManager

PyTree = Any

# --- payload codec (device <-> server) --------------------------------------

def encode_model_blob(params: PyTree) -> bytes:
    """Serialize a param pytree to the on-wire device format."""
    flat = {
        "/".join(str(getattr(k, "key", k)) for k in path): np.asarray(leaf)
        for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]
    }
    return pack_payload(flat)


def decode_model_blob(blob: bytes, template: PyTree) -> PyTree:
    """Deserialize a device blob against the server's param structure."""
    flat = unpack_payload(blob)
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths:
        key = "/".join(str(getattr(k, "key", k)) for k in path)
        arr = np.asarray(flat[key]).reshape(np.shape(leaf))
        leaves.append(arr.astype(np.asarray(leaf).dtype))
    return jax.tree_util.tree_unflatten(treedef, leaves)


class FedMLCrossDeviceAggregator(FedMLAggregator):
    """Aggregates device blobs; persists the global model file each round
    (reference ``fedml_aggregator.py:46 get_global_model_params_file``)."""

    def __init__(self, *a, global_model_file_path: Optional[str] = None, **kw):
        super().__init__(*a, **kw)
        self.global_model_file_path = global_model_file_path

    def add_local_trained_result(self, index: int, model_params, sample_num) -> None:
        if isinstance(model_params, (bytes, bytearray)):
            model_params = decode_model_blob(bytes(model_params), self.model_params)
        super().add_local_trained_result(index, model_params, sample_num)

    def get_global_model_params_file(self) -> Optional[str]:
        if self.global_model_file_path is None:
            return None
        os.makedirs(os.path.dirname(self.global_model_file_path) or ".", exist_ok=True)
        with open(self.global_model_file_path, "wb") as f:
            f.write(encode_model_blob(self.model_params))
        return self.global_model_file_path

    def aggregate(self) -> PyTree:
        params = super().aggregate()
        self.get_global_model_params_file()
        return params


class ServerMNN:
    """Reference ``fedml.run_mnn_server()`` target (launch_cross_device.py:6):
    build the aggregator + server manager; devices connect over the chosen
    backend and upload blobs.

    Wire contract (conformance-tested by a protocol-only stand-in client in
    tests/test_cross_device_wire_protocol.py): downlink INIT/SYNC carry the
    FULL global params; uplink model_params is the DELTA (local - global),
    aggregated as params + weighted-mean(delta) (aggregator.py:108). Devices
    porting from the reference (which uploads full params) must subtract the
    received global before uploading."""

    def __init__(self, args, fed_data, variables, apply_fn=None,
                 backend: str = "LOOPBACK", **kw):
        n_clients = int(getattr(args, "client_num_in_total",
                                getattr(args, "client_num_per_round", 1)))
        self.aggregator = FedMLCrossDeviceAggregator(
            fed_data.test_data_global,
            fed_data.train_data_global,
            fed_data.train_data_num,
            n_clients,
            args,
            variables,
            apply_fn=apply_fn,
            global_model_file_path=getattr(args, "global_model_file_path", None),
        )
        self.manager = FedMLServerManager(
            args, self.aggregator, rank=0, client_num=n_clients,
            backend=backend, **kw,
        )

    def run(self):
        self.manager.start()
        self.manager.run()
        return self.manager.history
