"""A production device day: the 1M-client cross-device driver.

This module composes the pieces the repo already hardened into one
cross-device control plane and runs it over a full simulated day:

- :class:`~fedml_tpu.cross_device.registry.DeviceRegistry` — flat-array
  fleet with seeded availability windows and the device lifecycle
  (eligible → checked-in → training → uploaded | dropped);
- :class:`~fedml_tpu.cross_silo.loadgen.DiurnalCurve` — seeded diurnal
  arrival intensity; each tick's check-in count is a Poisson draw from the
  curve, so load swings through a realistic day/night cycle;
- the async engine's :class:`~fedml_tpu.simulation.async_engine.VirtualEventHeap`
  — arrivals land at seeded virtual times and drain in virtual-time order;
- the bounded :class:`~fedml_tpu.core.tenancy.CheckinQueue` + deficit-
  round-robin admission edge — overload sheds (``queue_full``) and stale
  arrivals are refused (``inadmissible``) instead of growing memory;
- :class:`~fedml_tpu.simulation.client_store.ClientStateArena` — per-device
  optimizer state tiered device → host → disk, so RSS stays bounded at
  1M-registry scale, and reclaimed on permanent departure;
- the tier plane's fan-in: cohorts split into leaf chunks
  (:func:`contiguous_group_split`), folded with :func:`fold_partials`, and
  committed exactly-once through a :class:`CommitLedger`, with
  ``trim_version_log`` retention driving rejoin resync decisions.

Everything is a pure function of the seed: two runs of the same config
produce byte-identical histories (the ``history_digest`` / ``params_digest``
in the result), which is what makes ``chaos-drill --device-churn`` a real
regression gate rather than a flaky demo. The churn drill drops 30% of the
fleet mid-day (with a permanent-departure subset and seeded rejoin waves),
cuts one device class off behind a :class:`NetworkPartition` window, and
asserts the day degrades instead of breaking: accuracy within tolerance of
the churn-free reference, sheds and drops fully accounted, no hangs.

Front doors: ``fedml-tpu chaos-drill --device-churn``, ``bench.py
--device-day``, ``scripts/device_day_smoke.py``, ``tests/test_device_day.py``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..comm.message import Message
from ..comm.resilience import FaultPlan, NetworkPartition
from ..core import telemetry
from ..core.tenancy import CheckinQueue, DeficitRoundRobinScheduler
from ..cross_silo.loadgen import MSG_TYPE_CHECKIN, DiurnalCurve
from ..simulation.async_engine import VirtualEventHeap
from ..simulation.client_store import ClientStateArena
from ..simulation.federation import CommitLedger
from ..simulation.hierarchical import contiguous_group_split, fold_partials
from ..utils.checkpoint import trim_version_log
from .registry import CHECKED_IN, DeviceRegistry

DEVICE_DAY_DEFAULTS = dict(
    device_registry_size=100_000,
    device_day_s=86_400.0,
    device_tick_s=300.0,
    device_classes=4,
    device_cohort=64,
    device_queue_maxsize=4096,
    device_peak_rate=2.0,          # check-ins/s at the diurnal peak
    device_trough_fraction=0.2,
    device_arrival_spread_ticks=1.5,  # announce latency, in ticks
    device_dropout_rate=0.02,      # per-cohort-member mid-round failure
    device_recovery_rate=0.25,     # per-tick natural DROPPED -> ELIGIBLE
    device_max_commits_per_tick=1,
    device_pool_max_factor=4,      # checked-in pool bound, in cohorts
    device_feature_dim=16,
    device_num_labels=8,
    device_local_batch=8,
    device_lr=0.5,
    device_momentum=0.9,
    device_arena_capacity=1024,
    device_host_capacity=8192,
    device_spill_dir="",           # "" = no disk tier
    device_keep_versions=32,
    device_leaves=4,
    device_eval_every_ticks=8,
    device_seed=0,
    # churn drill knobs (all inert at churn_fraction=0)
    churn_fraction=0.0,
    churn_dropout_tick=-1,         # -1 = day midpoint
    churn_rejoin_ticks=3,
    churn_permanent_fraction=0.1,
    churn_partition_classes=0,     # first N device classes get cut off
    churn_partition_ticks=0,       # window length from the dropout tick
)


@dataclasses.dataclass(frozen=True)
class DeviceDayConfig:
    """One simulated day's shape. All randomness keys off ``seed``."""

    registry_size: int = 100_000
    day_s: float = 86_400.0
    tick_s: float = 300.0
    num_classes: int = 4
    cohort: int = 64
    queue_maxsize: int = 4096
    peak_rate: float = 2.0
    trough_fraction: float = 0.2
    # a device decides to check in, but its announce lands up to this many
    # ticks later — arrivals straddle tick boundaries, so a churn wave (or
    # a duplicate announce) can land between decision and admission, which
    # is exactly what the `inadmissible` shed reason exists for
    arrival_spread_ticks: float = 1.5
    dropout_rate: float = 0.02
    recovery_rate: float = 0.25
    max_commits_per_tick: int = 1
    pool_max_factor: int = 4
    feature_dim: int = 16
    num_labels: int = 8
    local_batch: int = 8
    lr: float = 0.5
    momentum: float = 0.9
    arena_capacity: int = 1024
    host_capacity: int = 8192
    spill_dir: Optional[str] = None
    keep_versions: int = 32
    num_leaves: int = 4
    eval_every_ticks: int = 8
    seed: int = 0
    churn_fraction: float = 0.0
    churn_dropout_tick: int = -1
    churn_rejoin_ticks: int = 3
    churn_permanent_fraction: float = 0.1
    churn_partition_classes: int = 0
    churn_partition_ticks: int = 0

    @property
    def n_ticks(self) -> int:
        return max(1, int(round(self.day_s / self.tick_s)))

    def resolved_dropout_tick(self) -> int:
        t = int(self.churn_dropout_tick)
        return t if t >= 0 else self.n_ticks // 2


@dataclasses.dataclass
class DeviceDayResult:
    """One day's full accounting — every arrival ends up in exactly one of
    these buckets, and :attr:`ok` is the closure proof."""

    elapsed_s: float
    ticks: int
    registry_size: int
    arrivals: int                 # events popped off the virtual-time heap
    partition_blackholed: int     # never reached the edge (cut active)
    offered: int                  # reached the admission edge
    accepted: int
    shed_queue_full: int
    shed_inadmissible: int
    not_selected: int             # admitted but released unselected
    in_flight_eod: int            # announces still airborne at midnight
    commits: int
    zero_survivor_commits: int
    cohort_slots: int             # cohort memberships across all commits
    committed_updates: int        # survivors actually folded
    mid_round_drops: int
    dropouts: int                 # registry lifecycle dropouts (all causes)
    rejoins: int
    resync_full: int
    resync_incremental: int
    departures: int
    reclaimed_spill_files: int
    duplicates: int               # CommitLedger double-commits (must be 0)
    final_version: int
    final_acc: float
    admission_edge_s: float       # wall time inside offer/drain only
    max_queue_depth: int
    queue_maxsize: int
    arena_resident: int
    arena_spilled: int
    history_digest: str
    params_digest: str
    history: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list, repr=False)

    @property
    def offered_per_s(self) -> float:
        """Admission-edge throughput: offered check-ins per second of wall
        time spent at the edge itself (offer + DRR drain), not of the whole
        simulation loop."""
        return self.offered / self.admission_edge_s \
            if self.admission_edge_s > 0 else 0.0

    @property
    def ok(self) -> bool:
        """Accounting closes end to end: every arrival was blackholed or
        offered; every offered check-in was accepted or shed (by reason);
        every cohort slot committed or dropped mid-round; the queue bound
        held; and no client update was ever double-committed."""
        return (
            self.arrivals == self.offered + self.partition_blackholed
            and self.offered == (self.accepted + self.shed_queue_full
                                 + self.shed_inadmissible)
            and self.cohort_slots == self.committed_updates
            + self.mid_round_drops
            and self.max_queue_depth <= self.queue_maxsize
            and self.duplicates == 0
        )

    def summary(self) -> str:
        return (
            f"device-day: {'PASS' if self.ok else 'FAIL'} — "
            f"{self.registry_size:,} devices, {self.ticks} ticks in "
            f"{self.elapsed_s:.2f}s | {self.offered:,} offered "
            f"({self.offered_per_s:,.0f}/s at the edge), "
            f"{self.accepted:,} accepted, shed {self.shed_queue_full} full"
            f"/{self.shed_inadmissible} inadmissible, "
            f"{self.partition_blackholed} blackholed | "
            f"{self.commits} commits ({self.committed_updates} updates, "
            f"{self.mid_round_drops} mid-round drops), dup {self.duplicates}"
            f" | churn: {self.dropouts} drops, {self.rejoins} rejoins "
            f"({self.resync_full} full / {self.resync_incremental} incr "
            f"resync), {self.departures} departed, "
            f"{self.reclaimed_spill_files} spill files reclaimed | "
            f"acc {self.final_acc:.3f} @ v{self.final_version}"
        )

    def json_record(self) -> dict:
        rec = {f.name: getattr(self, f.name)
               for f in dataclasses.fields(self) if f.name != "history"}
        rec["elapsed_s"] = round(self.elapsed_s, 4)
        rec["admission_edge_s"] = round(self.admission_edge_s, 4)
        rec["final_acc"] = round(self.final_acc, 6)
        rec["offered_per_s"] = round(self.offered_per_s, 1)
        rec["ok"] = self.ok
        return rec


class _FleetModel:
    """Tiny synthetic FL task, fully vectorized and per-device stable.

    A hidden linear truth ``w_true`` labels every batch; device ``i`` sees
    rows of a fixed seeded pool (indexed ``i % pool``) shifted by its
    device-class offset (non-IID by class). A local step is one momentum-
    SGD softmax-cross-entropy gradient on the device's batch, with the
    momentum row living in the :class:`ClientStateArena`. Accuracy is
    agreement with ``w_true`` on a held-out set — it climbs as commits fold,
    which is what gives the churn drill a meaningful accuracy gate.
    """

    _POOL = 4096

    def __init__(self, cfg: DeviceDayConfig):
        self.cfg = cfg
        rng = np.random.default_rng([int(cfg.seed), 0x_7296])
        f, l, b = cfg.feature_dim, cfg.num_labels, cfg.local_batch
        self.w_true = rng.normal(size=(f, l)).astype(np.float32)
        self.pool = rng.normal(size=(self._POOL, b, f)).astype(np.float32)
        self.class_shift = (rng.normal(size=(cfg.num_classes, f))
                            .astype(np.float32) * 0.5)
        self.x_eval = rng.normal(size=(1024, f)).astype(np.float32)
        self.y_eval = np.argmax(self.x_eval @ self.w_true, axis=-1)
        self.params = np.zeros((f, l), dtype=np.float32)

    def _batches(self, ids: np.ndarray):
        x = (self.pool[ids % self._POOL]
             + self.class_shift[ids % self.cfg.num_classes][:, None, :])
        y = np.argmax(x @ self.w_true, axis=-1)
        return x, y

    def local_updates(self, ids: np.ndarray, momenta: np.ndarray):
        """Vectorized local step for ``ids``: returns the stacked update
        proposals ``(n, F, L)`` and the new momentum rows."""
        x, y = self._batches(ids)
        logits = x @ self.params                       # (n, B, L)
        z = logits - logits.max(axis=-1, keepdims=True)
        e = np.exp(z)
        probs = e / e.sum(axis=-1, keepdims=True)
        onehot = np.eye(self.cfg.num_labels,
                        dtype=np.float32)[y]           # (n, B, L)
        grad = np.einsum("nbf,nbl->nfl", x, probs - onehot,
                         dtype=np.float32) / self.cfg.local_batch
        m_new = self.cfg.momentum * momenta + grad
        return (-self.cfg.lr * m_new).astype(np.float32), \
            m_new.astype(np.float32)

    def accuracy(self) -> float:
        pred = np.argmax(self.x_eval @ self.params, axis=-1)
        return float(np.mean(pred == self.y_eval))


def _partition_plan(cfg: DeviceDayConfig) -> Optional[FaultPlan]:
    """PR 14 fault kinds drive the drill's cut: the gateway for device
    class ``c`` is rank ``c + 1``, the root is rank 0, and the first
    ``churn_partition_classes`` classes are cut off from the root for
    ``churn_partition_ticks`` ticks starting at the dropout tick."""
    if cfg.churn_partition_classes <= 0 or cfg.churn_partition_ticks <= 0:
        return None
    t0 = cfg.resolved_dropout_tick()
    cut = NetworkPartition(
        frozenset({0}),
        frozenset(c + 1 for c in range(min(cfg.churn_partition_classes,
                                           cfg.num_classes))),
        rounds=(t0, t0 + int(cfg.churn_partition_ticks)),
        rate=1.0)
    return FaultPlan(seed=int(cfg.seed), partition=cut)


def _cut_classes(plan: Optional[FaultPlan], cfg: DeviceDayConfig,
                 tick: int) -> frozenset:
    """Which device classes are behind the cut at this tick — one
    ``should_partition`` probe per class gateway edge, judged at the
    receiver (the root) exactly like the tier plane does."""
    if plan is None:
        return frozenset()
    cut = set()
    for c in range(cfg.num_classes):
        msg = Message(type=MSG_TYPE_CHECKIN, sender_id=c + 1, receiver_id=0)
        if plan.should_partition(msg, round_hint=tick):
            cut.add(c)
    if cut and telemetry.enabled():
        telemetry.record_fault("device_partition")
    return frozenset(cut)


def run_device_day(cfg: DeviceDayConfig) -> DeviceDayResult:
    """Run one simulated day over the fleet and return its accounting."""
    t_start = time.perf_counter()
    registry = DeviceRegistry(cfg.registry_size, num_classes=cfg.num_classes,
                              seed=cfg.seed, day_s=cfg.day_s)
    curve = DiurnalCurve(peak_rate=cfg.peak_rate,
                         trough_fraction=cfg.trough_fraction,
                         day_s=cfg.day_s, seed=cfg.seed)
    queue = CheckinQueue(maxsize=cfg.queue_maxsize)
    drr = DeficitRoundRobinScheduler()
    for c in range(cfg.num_classes):
        drr.register(str(c), round_cost=1.0)
    heap = VirtualEventHeap()
    model = _FleetModel(cfg)
    proto = np.zeros((cfg.feature_dim, cfg.num_labels), dtype=np.float32)
    arena = ClientStateArena(
        proto, cfg.arena_capacity,
        spill_dir=cfg.spill_dir or None,
        host_capacity=cfg.host_capacity if cfg.spill_dir else None)
    ledger = CommitLedger()
    plan = _partition_plan(cfg)

    version = 0
    version_log: List[List[int]] = []   # [version, n_survivors]
    pool: List[int] = []                # checked-in ids, DRR-drain order
    pending_rejoins: List[np.ndarray] = []
    history: List[Dict[str, Any]] = []

    arrivals = blackholed = offered = accepted = 0
    shed_full = shed_inad = not_selected = 0
    commits = zero_survivor = cohort_slots = committed = mid_drops = 0
    reclaimed = 0
    edge_s = 0.0
    commit_idx = 0
    seed = int(cfg.seed)

    # churn wave schedule (inert unless churn_fraction > 0)
    drop_tick = cfg.resolved_dropout_tick()
    churn_waves: Dict[int, np.ndarray] = {}
    departures_at: Dict[int, np.ndarray] = {}
    if cfg.churn_fraction > 0:
        wave_rng = np.random.default_rng([seed, 0x_C4])
        n_churn = int(cfg.registry_size * cfg.churn_fraction)
        churned = wave_rng.choice(cfg.registry_size, size=n_churn,
                                  replace=False)
        n_perm = int(n_churn * cfg.churn_permanent_fraction)
        departures_at[drop_tick] = churned[:n_perm]
        temp = churned[n_perm:]
        churn_waves[drop_tick] = temp
        rejoin_start = drop_tick + max(1, int(cfg.churn_partition_ticks)) + 1
        rejoin_parts = np.array_split(
            temp, max(1, int(cfg.churn_rejoin_ticks)))
        rejoin_at = {rejoin_start + j: part
                     for j, part in enumerate(rejoin_parts) if part.size}
    else:
        rejoin_at = {}

    for tick in range(cfg.n_ticks):
        t0, t1 = tick * cfg.tick_s, (tick + 1) * cfg.tick_s
        tick_rng = np.random.default_rng([seed, 0x_71C4, tick])
        tick_rec: Dict[str, Any] = {"tick": tick}

        # --- churn waves land at tick start ------------------------------
        if tick in departures_at:
            gone = registry.depart(departures_at[tick])
            reclaimed += arena.discard(gone)
            tick_rec["departed"] = int(gone.size)
        if tick in churn_waves:
            tick_rec["churn_dropped"] = registry.mark_dropped(
                churn_waves[tick], held=True)
            if telemetry.enabled():
                telemetry.record_fault("device_churn_wave")
        if tick in rejoin_at:
            floor = version_log[0][0] if version_log else 0
            tick_rec["rejoin"] = registry.rejoin(
                rejoin_at[tick], log_floor_version=floor)

        cut = _cut_classes(plan, cfg, tick)
        if cut:
            tick_rec["partitioned_classes"] = sorted(cut)

        # --- seeded diurnal arrivals into the virtual-time heap ----------
        n_arr = curve.arrivals(t0, t1, tick_rng)
        cands = registry.eligible_available(t0 + 0.5 * cfg.tick_s)
        n_arr = min(n_arr, int(cands.size))
        if n_arr:
            arr_ids = tick_rng.choice(cands, size=n_arr, replace=False)
            spread = cfg.tick_s * max(1.0, float(cfg.arrival_spread_ticks))
            arr_vts = t0 + np.sort(tick_rng.uniform(0, spread, size=n_arr))
            for dev, vt in zip(arr_ids.tolist(), arr_vts.tolist()):
                heap.push(vt, dev)

        # --- drain arrivals due this tick through the admission edge -----
        due: List[int] = []
        while heap and heap.peek_vt() < t1:
            _, batch = heap.pop_batch()
            due.extend(batch)
        arrivals += len(due)
        tick_rec["arrivals"] = len(due)
        if due:
            ids = np.asarray(due, dtype=np.int64)
            classes = registry.device_class(ids)
            if cut:
                cut_mask = np.isin(classes, list(cut))
                blackholed += int(cut_mask.sum())
                tick_rec["blackholed"] = int(cut_mask.sum())
                ids, classes = ids[~cut_mask], classes[~cut_mask]
            # a device whose first announce is still airborne can announce
            # again (it is still ELIGIBLE when the next tick samples) —
            # only the first copy in a wave is admissible, the rest are
            # duplicate announces and shed as `inadmissible`
            first_mask = np.zeros(ids.size, dtype=bool)
            first_mask[np.unique(ids, return_index=True)[1]] = True
            t_edge = time.perf_counter()
            for c in range(cfg.num_classes):
                cls_mask = classes == c
                sel = ids[cls_mask]
                if not sel.size:
                    continue
                adm = registry.admissible(sel) & first_mask[cls_mask]
                res = queue.offer_many(sel.tolist(), tenant=str(c),
                                       admissible=adm.tolist())
                offered += int(sel.size)
                shed_full += res["shed_queue_full"]
                shed_inad += res["shed_inadmissible"]
            # DRR-fair drain into the checked-in pool
            by_class: Dict[str, List[int]] = {}
            while True:
                item = queue.poll()
                if item is None:
                    break
                by_class.setdefault(
                    str(int(item) % cfg.num_classes), []).append(int(item))
            ready = {c for c, lst in by_class.items() if lst}
            while ready:
                tenant = drr.next_tenant(ready=ready)
                if tenant is None:
                    break
                lst = by_class[tenant]
                grant, by_class[tenant] = lst[:32], lst[32:]
                drr.charge(tenant, float(len(grant)))
                if not by_class[tenant]:
                    ready.discard(tenant)
                registry.mark_checked_in(grant)
                accepted += len(grant)
                pool.extend(grant)
            edge_s += time.perf_counter() - t_edge

        # --- commits: cohorts from the currently-available pool ----------
        tick_commits = 0
        while tick_commits < cfg.max_commits_per_tick:
            # pool members a churn wave evaporated since check-in drop out
            # here (already counted as dropouts by the wave)
            pool = [d for d in pool
                    if registry.state[d] == CHECKED_IN]
            if len(pool) < cfg.cohort:
                break
            cohort_ids = np.asarray(pool[:cfg.cohort], dtype=np.int64)
            pool = pool[cfg.cohort:]
            registry.mark_training(cohort_ids)
            cohort_slots += int(cohort_ids.size)
            commit_idx += 1
            tick_commits += 1
            commits += 1
            crng = np.random.default_rng([seed, 0x_D09, commit_idx])
            drop_mask = crng.random(cohort_ids.size) < cfg.dropout_rate
            if cut:
                # uploads from a cut-off class cannot cross the partition
                drop_mask |= np.isin(registry.device_class(cohort_ids),
                                     list(cut))
            drops = cohort_ids[drop_mask]
            survivors = cohort_ids[~drop_mask]
            if drops.size:
                registry.mark_dropped(drops)
                mid_drops += int(drops.size)
            if survivors.size == 0:
                zero_survivor += 1
                continue  # shrunken to nothing: skip the fold, never hang
            momenta = np.asarray(arena.gather(survivors))
            updates, m_new = model.local_updates(survivors, momenta)
            arena.scatter(survivors, m_new)
            # tier-plane fan-in: leaf chunks fold first, the root folds the
            # leaf partials (identical math to the hierarchical plane)
            parts, _ = contiguous_group_split(survivors, cfg.num_leaves)
            offsets = np.cumsum([0] + [len(p) for p in parts])
            leaf_us, leaf_ws = [], []
            for g, part in enumerate(parts):
                if not len(part):
                    continue
                rows = updates[offsets[g]:offsets[g + 1]]
                w = np.full(len(part), cfg.local_batch, np.float32)
                leaf_us.append(np.asarray(fold_partials(rows, w)))
                leaf_ws.append(float(len(part) * cfg.local_batch))
            delta = np.asarray(fold_partials(
                np.stack(leaf_us), np.asarray(leaf_ws, np.float32)))
            model.params = model.params + delta
            version += 1
            dups = ledger.record(commit_idx, survivors)
            assert not dups, f"double commit: {dups[:4]}"
            version_log.append([version, int(survivors.size)])
            version_log = trim_version_log(version_log, cfg.keep_versions)
            registry.mark_uploaded(survivors, version)
            committed += int(survivors.size)

        # --- end of tick: pool bound, natural recovery, eval -------------
        pool_max = cfg.pool_max_factor * cfg.cohort
        if len(pool) > pool_max:
            excess, pool = pool[pool_max:], pool[:pool_max]
            registry.release(excess)
            not_selected += len(excess)
        recovered = registry.recover(cfg.recovery_rate, tick_rng)
        tick_rec.update(
            offered=offered, accepted=accepted,
            shed_queue_full=shed_full, shed_inadmissible=shed_inad,
            commits=tick_commits, version=version, recovered=recovered,
            pool=len(pool))
        if (tick % max(1, cfg.eval_every_ticks)
                == max(1, cfg.eval_every_ticks) - 1):
            tick_rec["acc"] = round(model.accuracy(), 6)
        history.append(tick_rec)

    # unselected stragglers at end of day are released, not lost
    if pool:
        registry.release(pool)
        not_selected += len(pool)
    in_flight_eod = len(heap)   # announces that would land tomorrow

    final_acc = model.accuracy()
    stats = queue.stats()
    rc = registry.counters
    history_digest = hashlib.sha256(
        json.dumps(history, sort_keys=True).encode()).hexdigest()
    params_digest = hashlib.sha256(model.params.tobytes()).hexdigest()
    return DeviceDayResult(
        elapsed_s=time.perf_counter() - t_start,
        ticks=cfg.n_ticks,
        registry_size=cfg.registry_size,
        arrivals=arrivals,
        partition_blackholed=blackholed,
        offered=offered,
        accepted=accepted,
        shed_queue_full=shed_full,
        shed_inadmissible=shed_inad,
        not_selected=not_selected,
        in_flight_eod=in_flight_eod,
        commits=commits,
        zero_survivor_commits=zero_survivor,
        cohort_slots=cohort_slots,
        committed_updates=committed,
        mid_round_drops=mid_drops,
        dropouts=rc["dropouts"],
        rejoins=rc["rejoins"],
        resync_full=rc["resync_full"],
        resync_incremental=rc["resync_incremental"],
        departures=rc["departures"],
        reclaimed_spill_files=reclaimed,
        duplicates=ledger.duplicates,
        final_version=version,
        final_acc=final_acc,
        admission_edge_s=edge_s,
        max_queue_depth=stats["max_depth"],
        queue_maxsize=stats["maxsize"],
        arena_resident=arena.resident_count,
        arena_spilled=arena.spilled_count,
        history_digest=history_digest,
        params_digest=params_digest,
        history=history,
    )


# --- the churn drill ---------------------------------------------------------

CHURN_DRILL_DEFAULTS = dict(
    registry_size=20_000,
    day_s=7_200.0,
    tick_s=120.0,
    num_classes=4,
    cohort=48,
    queue_maxsize=512,   # tight enough that peak ticks shed (queue_full)
    peak_rate=6.0,
    max_commits_per_tick=2,
    arena_capacity=512,
    host_capacity=2048,
    eval_every_ticks=4,
    churn_fraction=0.3,
    churn_rejoin_ticks=3,
    churn_permanent_fraction=0.1,
    churn_partition_classes=1,
    churn_partition_ticks=4,
)


@dataclasses.dataclass
class DeviceChurnDrillResult:
    """Churn drill verdict: the churned day vs its churn-free reference."""

    reference: DeviceDayResult
    churned: DeviceDayResult
    replay_digest: str
    max_acc_delta: float

    @property
    def acc_delta(self) -> float:
        return abs(self.reference.final_acc - self.churned.final_acc)

    @property
    def replay_identical(self) -> bool:
        return self.replay_digest == self.churned.history_digest

    @property
    def ok(self) -> bool:
        return (self.reference.ok and self.churned.ok
                and self.acc_delta <= self.max_acc_delta
                and self.replay_identical
                and self.churned.dropouts > 0
                and self.churned.rejoins > 0
                and self.churned.departures > 0
                and self.churned.partition_blackholed > 0)

    def summary(self) -> str:
        c = self.churned
        return (
            f"device-churn drill: {'PASS' if self.ok else 'FAIL'} — "
            f"acc {c.final_acc:.3f} vs reference "
            f"{self.reference.final_acc:.3f} (delta {self.acc_delta:.3f} <= "
            f"{self.max_acc_delta}) | {c.dropouts} dropouts, {c.rejoins} "
            f"rejoins, {c.departures} departed "
            f"({c.reclaimed_spill_files} spill files reclaimed), "
            f"{c.partition_blackholed} blackholed | sheds "
            f"{c.shed_queue_full} full / {c.shed_inadmissible} inadmissible"
            f" | replay {'bit-identical' if self.replay_identical else 'DIVERGED'}"
        )

    def json_record(self) -> dict:
        return {
            "acc_reference": round(self.reference.final_acc, 6),
            "acc_churned": round(self.churned.final_acc, 6),
            "acc_delta": round(self.acc_delta, 6),
            "max_acc_delta": self.max_acc_delta,
            "replay_identical": self.replay_identical,
            "reference": self.reference.json_record(),
            "churned": self.churned.json_record(),
            "ok": self.ok,
        }


def run_device_churn_drill(cfg: Optional[DeviceDayConfig] = None,
                           max_acc_delta: float = 0.02,
                           spill_dir: Optional[str] = None
                           ) -> DeviceChurnDrillResult:
    """The robustness headline: run the churn-free reference day, then the
    same day with 30% fleet churn (dropout wave + seeded rejoin waves + a
    permanent-departure subset + one partition window), then replay the
    churned day and require a byte-identical history. Gates: accuracy
    within ``max_acc_delta`` of the reference, full shed/drop accounting,
    zero ledger duplicates, bit-identical replay."""
    if cfg is None:
        cfg = DeviceDayConfig(**CHURN_DRILL_DEFAULTS, spill_dir=spill_dir)

    def _isolated(run_cfg: DeviceDayConfig, name: str) -> DeviceDayConfig:
        # each run spills into its own subdirectory, so reclaim counts and
        # disk contents never leak between the churned run and its replay
        if not run_cfg.spill_dir:
            return run_cfg
        sub = os.path.join(run_cfg.spill_dir, name)
        os.makedirs(sub, exist_ok=True)
        return dataclasses.replace(run_cfg, spill_dir=sub)

    reference = run_device_day(dataclasses.replace(
        cfg, churn_fraction=0.0, churn_partition_classes=0,
        churn_partition_ticks=0, spill_dir=None))
    churned = run_device_day(_isolated(cfg, "churned"))
    replay = run_device_day(_isolated(cfg, "replay"))
    return DeviceChurnDrillResult(
        reference=reference, churned=churned,
        replay_digest=replay.history_digest,
        max_acc_delta=float(max_acc_delta))


# --- config plumbing ---------------------------------------------------------

def config_from_args(args) -> DeviceDayConfig:
    """Map the flat ``device_*`` / ``churn_*`` config keys onto a
    :class:`DeviceDayConfig` (the getattr sites feed the generated config
    reference)."""
    d = DEVICE_DAY_DEFAULTS
    return DeviceDayConfig(
        registry_size=int(getattr(args, "device_registry_size",
                                  d["device_registry_size"])),
        day_s=float(getattr(args, "device_day_s", d["device_day_s"])),
        tick_s=float(getattr(args, "device_tick_s", d["device_tick_s"])),
        num_classes=int(getattr(args, "device_classes",
                                d["device_classes"])),
        cohort=int(getattr(args, "device_cohort", d["device_cohort"])),
        queue_maxsize=int(getattr(args, "device_queue_maxsize",
                                  d["device_queue_maxsize"])),
        peak_rate=float(getattr(args, "device_peak_rate",
                                d["device_peak_rate"])),
        trough_fraction=float(getattr(args, "device_trough_fraction",
                                      d["device_trough_fraction"])),
        arrival_spread_ticks=float(
            getattr(args, "device_arrival_spread_ticks",
                    d["device_arrival_spread_ticks"])),
        dropout_rate=float(getattr(args, "device_dropout_rate",
                                   d["device_dropout_rate"])),
        recovery_rate=float(getattr(args, "device_recovery_rate",
                                    d["device_recovery_rate"])),
        max_commits_per_tick=int(getattr(args, "device_max_commits_per_tick",
                                         d["device_max_commits_per_tick"])),
        pool_max_factor=int(getattr(args, "device_pool_max_factor",
                                    d["device_pool_max_factor"])),
        feature_dim=int(getattr(args, "device_feature_dim",
                                d["device_feature_dim"])),
        num_labels=int(getattr(args, "device_num_labels",
                               d["device_num_labels"])),
        local_batch=int(getattr(args, "device_local_batch",
                                d["device_local_batch"])),
        lr=float(getattr(args, "device_lr", d["device_lr"])),
        momentum=float(getattr(args, "device_momentum",
                               d["device_momentum"])),
        arena_capacity=int(getattr(args, "device_arena_capacity",
                                   d["device_arena_capacity"])),
        host_capacity=int(getattr(args, "device_host_capacity",
                                  d["device_host_capacity"])),
        spill_dir=str(getattr(args, "device_spill_dir",
                              d["device_spill_dir"])) or None,
        keep_versions=int(getattr(args, "device_keep_versions",
                                  d["device_keep_versions"])),
        num_leaves=int(getattr(args, "device_leaves", d["device_leaves"])),
        eval_every_ticks=int(getattr(args, "device_eval_every_ticks",
                                     d["device_eval_every_ticks"])),
        seed=int(getattr(args, "device_seed", d["device_seed"])),
        churn_fraction=float(getattr(args, "churn_fraction",
                                     d["churn_fraction"])),
        churn_dropout_tick=int(getattr(args, "churn_dropout_tick",
                                       d["churn_dropout_tick"])),
        churn_rejoin_ticks=int(getattr(args, "churn_rejoin_ticks",
                                       d["churn_rejoin_ticks"])),
        churn_permanent_fraction=float(
            getattr(args, "churn_permanent_fraction",
                    d["churn_permanent_fraction"])),
        churn_partition_classes=int(
            getattr(args, "churn_partition_classes",
                    d["churn_partition_classes"])),
        churn_partition_ticks=int(getattr(args, "churn_partition_ticks",
                                          d["churn_partition_ticks"])),
    )


def run_device_day_from_args(args) -> DeviceDayResult:
    return run_device_day(config_from_args(args))
