"""Cross-device FL (Beehive): server-side aggregation of device payloads.

Parity: reference ``python/fedml/cross_device/`` (SURVEY.md §2.5). Phone-side
training is external in the reference too (Android/iOS SDK); this package is
the server plane: blob codec, FedAvg aggregator, LightSecAgg variant.
"""

from .device_day import (
    DEVICE_DAY_DEFAULTS,
    DeviceChurnDrillResult,
    DeviceDayConfig,
    DeviceDayResult,
    run_device_churn_drill,
    run_device_day,
    run_device_day_from_args,
)
from .registry import DeviceRegistry
from .server import (
    FedMLCrossDeviceAggregator,
    ServerMNN,
    decode_model_blob,
    encode_model_blob,
)
from .server_lsa import LSAAggregator

__all__ = [
    "FedMLCrossDeviceAggregator", "ServerMNN",
    "encode_model_blob", "decode_model_blob",
    "LSAAggregator",
    "DeviceRegistry", "DeviceDayConfig", "DeviceDayResult",
    "DeviceChurnDrillResult", "DEVICE_DAY_DEFAULTS",
    "run_device_day", "run_device_day_from_args", "run_device_churn_drill",
]
