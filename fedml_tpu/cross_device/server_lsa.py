"""LightSecAgg cross-device server: aggregate without seeing any update.

Parity: reference ``cross_device/server_mnn_lsa/fedml_aggregator.py:33-89``
(``add_local_aggregate_encoded_mask:67``,
``check_whether_all_aggregate_encoded_mask_receive:84``) — two extra
collection phases on top of the FedAvg round: (1) devices upload masked
updates, (2) surviving devices upload their summed mask *shares*; the server
LCC-reconstructs the aggregate mask and unmasks the sum. Field math is
host-side (``core/secure_agg.py``); only the unmasked aggregate touches the
TPU.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import numpy as np

from ..core.secure_agg import (
    LightSecAggConfig,
    LightSecAggServer,
    dequantize_tree,
)

PyTree = Any


class LSAAggregator:
    """Server-side LightSecAgg state machine for one round."""

    def __init__(self, cfg: LightSecAggConfig, model_params: PyTree):
        self.cfg = cfg
        self.model_params = model_params
        self._server = LightSecAggServer(cfg)
        self.masked_sum: Optional[np.ndarray] = None
        self.active_clients: List[int] = []
        self.agg_mask_shares: Dict[int, np.ndarray] = {}

    # phase 1: masked updates -------------------------------------------------
    def add_masked_update(self, client_id: int, masked: np.ndarray) -> None:
        masked = np.mod(np.asarray(masked, dtype=np.int64), self.cfg.prime)
        if self.masked_sum is None:
            self.masked_sum = masked.copy()
        else:
            self.masked_sum = np.mod(self.masked_sum + masked, self.cfg.prime)
        self.active_clients.append(int(client_id))

    def check_all_updates_received(self, expected: int) -> bool:
        return len(self.active_clients) >= expected

    # phase 2: aggregate-mask shares -----------------------------------------
    def add_local_aggregate_encoded_mask(self, client_id: int, share: np.ndarray) -> None:
        """Reference ``add_local_aggregate_encoded_mask:67``."""
        self.agg_mask_shares[int(client_id)] = np.asarray(share, dtype=np.int64)

    def check_whether_all_aggregate_encoded_mask_receive(self) -> bool:
        """Reference ``:84`` — need U surviving shares to decode."""
        return len(self.agg_mask_shares) >= self.cfg.target_active

    # finalize ----------------------------------------------------------------
    def aggregate(self) -> PyTree:
        assert self.masked_sum is not None, "no masked updates received"
        agg_mask = self._server.reconstruct_aggregate_mask(
            self.agg_mask_shares, self.active_clients
        )
        summed_update = self._server.unmask(
            self.masked_sum, agg_mask, self.model_params, len(self.active_clients)
        )
        # FedAvg: uniform mean of the securely-summed updates, applied to params
        n = max(len(self.active_clients), 1)
        self.model_params = jax.tree.map(
            lambda p, d: p + (np.asarray(d) / n).astype(np.asarray(p).dtype),
            self.model_params,
            summed_update,
        )
        return self.model_params
