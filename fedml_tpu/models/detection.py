"""Single-stage grid detector for federated object detection.

Parity: reference ``app/fedcv/object_detection`` — which vendors the whole
YOLOv5 torch tree (anchors, NMS, mosaic pipeline; ~10k LoC). The TPU-native
redesign is a compact anchor-free detector in the FCOS/YOLO-lite spirit:
a strided conv backbone maps the image to an S x S grid; each cell predicts
objectness, class logits, and a box (center offset within the cell + log
size), all with STATIC shapes — no NMS inside the compiled path (decoding +
greedy suppression are tiny host-side ops in ``decode_boxes``).
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


class GridDetector(nn.Module):
    """Conv backbone (stride 8) + per-cell detection head.

    Input (B, H, W, C_in); output (B, S, S, 5 + num_classes) with
    S = H // 8 and channels [obj_logit, dx, dy, logw, logh, class logits].
    dx/dy pass through a sigmoid (offset inside the cell); logw/logh are
    free (box size as a fraction of the image, exp-decoded).
    """

    num_classes: int = 2
    width: int = 32
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = x.astype(self.dtype)
        for i, mult in enumerate((1, 2, 4)):  # three stride-2 stages
            h = nn.Conv(self.width * mult, (3, 3), strides=(2, 2),
                        dtype=self.dtype, name=f"down{i}")(h)
            h = nn.relu(h)
            h = nn.Conv(self.width * mult, (3, 3), dtype=self.dtype,
                        name=f"conv{i}")(h)
            h = nn.relu(h)
        out = nn.Conv(5 + self.num_classes, (1, 1), dtype=self.dtype,
                      name="head")(h)
        obj = out[..., :1]
        dxdy = nn.sigmoid(out[..., 1:3])
        size = out[..., 3:5]
        cls = out[..., 5:]
        return jnp.concatenate([obj, dxdy, size, cls], axis=-1)


def rasterize_boxes(
    boxes: np.ndarray, classes: np.ndarray, grid: int, num_classes: int
) -> np.ndarray:
    """Boxes -> training target grid (the label format the loss consumes).

    ``boxes`` (N, 4) normalized [cx, cy, w, h]; ``classes`` (N,) ints.
    Returns (S, S, 6): [obj, class, dx, dy, w, h] — each box owns the cell
    containing its center (later boxes win collisions, as in YOLO).
    """
    if len(classes) and int(np.max(classes)) >= num_classes:
        raise ValueError(
            f"class id {int(np.max(classes))} >= num_classes {num_classes}")
    t = np.zeros((grid, grid, 6), np.float32)
    for (cx, cy, w, h), c in zip(boxes, classes):
        gx = min(int(cx * grid), grid - 1)
        gy = min(int(cy * grid), grid - 1)
        t[gy, gx, 0] = 1.0
        t[gy, gx, 1] = float(c)
        t[gy, gx, 2] = cx * grid - gx
        t[gy, gx, 3] = cy * grid - gy
        t[gy, gx, 4] = w
        t[gy, gx, 5] = h
    return t


def decode_boxes(
    pred: np.ndarray, obj_threshold: float = 0.5
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """One image's head output (S, S, 5+C) -> (boxes (M,4), classes, scores).

    Host-side (tiny): sigmoid objectness threshold, box decode back to
    normalized [cx, cy, w, h]. Greedy same-class IoU suppression is left to
    callers that need it — the synthetic eval uses center-cell ownership so
    duplicates don't arise.
    """
    S = pred.shape[0]
    obj = 1.0 / (1.0 + np.exp(-pred[..., 0]))
    ys, xs = np.nonzero(obj >= obj_threshold)
    boxes, classes, scores = [], [], []
    for y, x in zip(ys, xs):
        dx, dy = pred[y, x, 1], pred[y, x, 2]
        w, h = np.exp(pred[y, x, 3]) - 1.0, np.exp(pred[y, x, 4]) - 1.0
        boxes.append([(x + dx) / S, (y + dy) / S, max(w, 0.0), max(h, 0.0)])
        classes.append(int(np.argmax(pred[y, x, 5:])))
        scores.append(float(obj[y, x]))
    return (np.asarray(boxes, np.float32).reshape(-1, 4),
            np.asarray(classes, np.int32), np.asarray(scores, np.float32))


def box_iou(a: np.ndarray, b: np.ndarray) -> float:
    """IoU of two normalized [cx, cy, w, h] boxes."""
    ax0, ay0 = a[0] - a[2] / 2, a[1] - a[3] / 2
    ax1, ay1 = a[0] + a[2] / 2, a[1] + a[3] / 2
    bx0, by0 = b[0] - b[2] / 2, b[1] - b[3] / 2
    bx1, by1 = b[0] + b[2] / 2, b[1] + b[3] / 2
    ix = max(0.0, min(ax1, bx1) - max(ax0, bx0))
    iy = max(0.0, min(ay1, by1) - max(ay0, by0))
    inter = ix * iy
    union = a[2] * a[3] + b[2] * b[3] - inter
    return float(inter / union) if union > 0 else 0.0
