"""Multi-scale anchor-based detector (YOLO-family depth, TPU-native).

Parity: reference ``app/fedcv/object_detection`` vendors the full YOLOv5
torch tree — CSP backbone, PANet/FPN neck, 3-anchor heads at strides
8/16/32, CIoU box loss, NMS (~10k LoC of torch). This module is the
TPU-first rebuild of that *architecture class* (models/detection.py keeps
the compact anchor-free variant for the light path):

- conv backbone with three pyramid levels (strides 8/16/32),
- top-down FPN merge (nearest upsample + 1x1 lateral, YOLOv5 neck role),
- per-level heads predicting A anchors x (obj, dx, dy, dw, dh, classes),
- anchor-prior target assignment (host-side numpy, like the reference's
  build_targets) with best-IoU anchor matching,
- CIoU regression loss + BCE objectness + CE class (jax, static shapes),
- batched fixed-size NMS under jit (lax.fori_loop greedy suppression —
  no dynamic shapes, so it compiles onto the accelerator; the reference
  runs torchvision.ops.nms on host).

Everything jit-side is static-shape: per-level targets are packed into one
(sum(S_l^2 * A), 6) array per sample so the federated engine's rectangular
batch pipeline carries them like any label tensor.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

# normalized (w, h) anchor priors per pyramid level (stride 8 / 16 / 32) —
# small/medium/large, the YOLOv5 P3/P4/P5 split scaled to unit images
ANCHORS = (
    ((0.04, 0.05), (0.08, 0.06), (0.06, 0.12)),
    ((0.12, 0.16), (0.20, 0.14), (0.16, 0.28)),
    ((0.30, 0.35), (0.45, 0.30), (0.55, 0.60)),
)
A = 3  # anchors per level


def _conv_block(x, ch, dtype, name, stride=1):
    x = nn.Conv(ch, (3, 3), strides=(stride, stride), use_bias=False,
                dtype=dtype, name=f"{name}_conv")(x)
    x = nn.GroupNorm(num_groups=min(8, ch), dtype=dtype, name=f"{name}_gn")(x)
    return nn.silu(x)


class YoloLiteDetector(nn.Module):
    """Backbone -> FPN -> per-level anchor heads.

    Input (B, H, W, C); H must be divisible by 32. Returns a list of three
    tensors (B, S_l, S_l, A, 5 + num_classes) for strides 8/16/32, raw
    logits (decode applies sigmoid/exp).
    """

    num_classes: int = 2
    width: int = 32
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.shape[1] != x.shape[2] or x.shape[1] % 32:
            raise ValueError(
                f"YoloLiteDetector needs square input with H % 32 == 0, got "
                f"{x.shape[1]}x{x.shape[2]} (the grid/anchor plumbing — "
                "rasterize_multiscale, yolo_loss — is square-indexed)")
        w, dt = self.width, self.dtype
        h = x.astype(dt)
        h = _conv_block(h, w, dt, "stem", stride=2)          # /2
        h = _conv_block(h, w, dt, "s1", stride=2)            # /4
        h = _conv_block(h, w, dt, "s1b")
        p3 = _conv_block(h, 2 * w, dt, "s2", stride=2)       # /8
        p3 = _conv_block(p3, 2 * w, dt, "s2b")
        p4 = _conv_block(p3, 4 * w, dt, "s3", stride=2)      # /16
        p4 = _conv_block(p4, 4 * w, dt, "s3b")
        p5 = _conv_block(p4, 8 * w, dt, "s4", stride=2)      # /32
        p5 = _conv_block(p5, 8 * w, dt, "s4b")

        # top-down FPN: lateral 1x1 + nearest upsample + merge
        def up2(t):
            B, H, W, C = t.shape
            return jax.image.resize(t, (B, 2 * H, 2 * W, C), "nearest")

        l5 = nn.Conv(4 * w, (1, 1), dtype=dt, name="lat5")(p5)
        m4 = _conv_block(
            jnp.concatenate([nn.Conv(4 * w, (1, 1), dtype=dt, name="lat4")(p4),
                             up2(l5)], axis=-1), 4 * w, dt, "fpn4")
        m3 = _conv_block(
            jnp.concatenate([nn.Conv(2 * w, (1, 1), dtype=dt, name="lat3")(p3),
                             up2(nn.Conv(2 * w, (1, 1), dtype=dt,
                                         name="red4")(m4))], axis=-1),
            2 * w, dt, "fpn3")

        outs = []
        for name, feat in (("head3", m3), ("head4", m4), ("head5", l5)):
            o = nn.Conv(A * (5 + self.num_classes), (1, 1), dtype=dt,
                        name=name)(feat)
            B, S, _, _ = o.shape
            outs.append(o.reshape(B, S, S, A, 5 + self.num_classes))
        return outs


# --- target assignment (host-side, reference build_targets role) -----------

def _wh_iou(wh: Tuple[float, float], anchors: Sequence[Tuple[float, float]]):
    """IoU of a (w, h) box against anchor priors, both centered."""
    out = []
    for aw, ah in anchors:
        inter = min(wh[0], aw) * min(wh[1], ah)
        union = wh[0] * wh[1] + aw * ah - inter
        out.append(inter / max(union, 1e-12))
    return np.asarray(out)


def level_grids(image_size: int) -> Tuple[int, int, int]:
    return image_size // 8, image_size // 16, image_size // 32


def rasterize_multiscale(boxes: np.ndarray, classes: np.ndarray,
                         image_size: int, num_classes: int) -> np.ndarray:
    """Boxes (N,4 cxcywh, normalized) + classes (N,) -> packed target
    (sum_l S_l^2 * A, 6) rows [obj, class, dx, dy, w, h]. Each box is
    assigned to the globally best-IoU anchor prior (level, anchor), at the
    cell containing its center — the reference's best-anchor matching."""
    if len(classes) and int(np.max(classes)) >= num_classes:
        raise ValueError(
            f"class id {int(np.max(classes))} >= num_classes {num_classes}")
    grids = level_grids(image_size)
    levels = [np.zeros((S, S, A, 6), np.float32) for S in grids]
    for (cx, cy, w, h), c in zip(boxes, classes):
        ious = np.concatenate(
            [_wh_iou((w, h), ANCHORS[li]) for li in range(3)])
        best = int(np.argmax(ious))
        li, ai = divmod(best, A)
        S = grids[li]
        gx = max(0, min(int(cx * S), S - 1))  # clamp BOTH sides: negative
        gy = max(0, min(int(cy * S), S - 1))  # centers must not wrap to -1
        levels[li][gy, gx, ai] = (1.0, float(c), cx * S - gx, cy * S - gy,
                                  w, h)
    return np.concatenate([t.reshape(-1, 6) for t in levels], axis=0)


def unpack_targets(packed: jax.Array, image_size: int) -> List[jax.Array]:
    """(..., sum_l S_l^2*A, 6) -> per-level (..., S, S, A, 6)."""
    grids = level_grids(image_size)
    outs, off = [], 0
    for S in grids:
        n = S * S * A
        outs.append(packed[..., off:off + n, :].reshape(
            packed.shape[:-2] + (S, S, A, 6)))
        off += n
    return outs


# --- losses ----------------------------------------------------------------

def ciou(pred: jax.Array, target: jax.Array) -> jax.Array:
    """Complete-IoU (Zheng et al., the YOLOv5 box loss) on (..., 4) cxcywh
    boxes in unit coordinates. Returns (...,) CIoU in [-1.5, 1]."""
    px, py, pw, ph = (pred[..., i] for i in range(4))
    tx, ty, tw, th = (target[..., i] for i in range(4))
    pw, ph = jnp.maximum(pw, 1e-6), jnp.maximum(ph, 1e-6)
    tw, th = jnp.maximum(tw, 1e-6), jnp.maximum(th, 1e-6)
    # IoU
    x1 = jnp.maximum(px - pw / 2, tx - tw / 2)
    y1 = jnp.maximum(py - ph / 2, ty - th / 2)
    x2 = jnp.minimum(px + pw / 2, tx + tw / 2)
    y2 = jnp.minimum(py + ph / 2, ty + th / 2)
    inter = jnp.maximum(x2 - x1, 0.0) * jnp.maximum(y2 - y1, 0.0)
    union = pw * ph + tw * th - inter
    iou = inter / jnp.maximum(union, 1e-12)
    # center distance / enclosing diagonal
    cw = jnp.maximum(px + pw / 2, tx + tw / 2) - jnp.minimum(
        px - pw / 2, tx - tw / 2)
    chh = jnp.maximum(py + ph / 2, ty + th / 2) - jnp.minimum(
        py - ph / 2, ty - th / 2)
    c2 = cw ** 2 + chh ** 2
    rho2 = (px - tx) ** 2 + (py - ty) ** 2
    # aspect-ratio consistency
    v = (4 / jnp.pi ** 2) * (jnp.arctan(tw / th) - jnp.arctan(pw / ph)) ** 2
    alpha = v / jnp.maximum(1.0 - iou + v, 1e-12)
    return iou - rho2 / jnp.maximum(c2, 1e-12) - alpha * v


def decode_level(raw: jax.Array, level: int) -> jax.Array:
    """Raw head output (..., S, S, A, 5+C) -> boxes (..., S, S, A, 4)
    cxcywh: sigmoid cell offsets, anchor-scaled exp sizes."""
    S = raw.shape[-4]
    gy, gx = jnp.meshgrid(jnp.arange(S), jnp.arange(S), indexing="ij")
    anch = jnp.asarray(ANCHORS[level])  # (A, 2)
    cx = (jax.nn.sigmoid(raw[..., 1]) + gx[..., None]) / S
    cy = (jax.nn.sigmoid(raw[..., 2]) + gy[..., None]) / S
    w = anch[:, 0] * jnp.exp(jnp.clip(raw[..., 3], -6, 4))
    h = anch[:, 1] * jnp.exp(jnp.clip(raw[..., 4], -6, 4))
    return jnp.stack([cx, cy, w, h], axis=-1)


def yolo_loss(outs: List[jax.Array], packed_targets: jax.Array,
              image_size: int, num_classes: int,
              mask: jax.Array | None = None,
              box_weight: float = 5.0, noobj_weight: float = 0.5):
    """Multi-level detection loss (reference ``loss.py`` role): BCE
    objectness everywhere, CIoU + CE on object-owning anchors. ``mask``
    (B,) {0,1} drops padded samples (the engine's rectangle padding).
    Returns (loss, (correct, valid)) matching the engine's metric
    contract."""
    B = packed_targets.shape[0]
    m = jnp.ones((B,), jnp.float32) if mask is None else mask.astype(
        jnp.float32).reshape(B)
    m_live = jnp.maximum(m.sum(), 1.0)
    if outs[0].shape[-1] != 5 + num_classes:
        raise ValueError(
            f"head width {outs[0].shape[-1]} != 5 + num_classes "
            f"({5 + num_classes}) — model/num_classes mismatch")
    targets = unpack_targets(packed_targets, image_size)
    total = 0.0
    correct = 0.0
    valid = 0.0
    for li, (raw, tgt) in enumerate(zip(outs, targets)):
        obj_t = tgt[..., 0]
        obj_w = obj_t * m[:, None, None, None]  # padded samples own nothing
        obj_logit = raw[..., 0]
        bce = optax.sigmoid_binary_cross_entropy(obj_logit, obj_t)
        bce = jnp.where(obj_t > 0, bce, noobj_weight * bce)
        obj_loss = (bce.mean(axis=(1, 2, 3)) * m).sum() / m_live

        S = raw.shape[-4]
        gy, gx = jnp.meshgrid(jnp.arange(S), jnp.arange(S), indexing="ij")
        tboxes = jnp.stack([
            (tgt[..., 2] + gx[..., None]) / S,
            (tgt[..., 3] + gy[..., None]) / S,
            tgt[..., 4], tgt[..., 5]], axis=-1)
        pboxes = decode_level(raw, li)
        box_loss = (obj_w * (1.0 - ciou(pboxes, tboxes))).sum() / jnp.maximum(
            obj_w.sum(), 1.0)

        logits = raw[..., 5:]
        logp = jax.nn.log_softmax(logits, axis=-1)
        cls_t = tgt[..., 1].astype(jnp.int32)
        ce = -jnp.take_along_axis(logp, cls_t[..., None], axis=-1)[..., 0]
        cls_loss = (obj_w * ce).sum() / jnp.maximum(obj_w.sum(), 1.0)

        total = total + obj_loss + box_weight * box_loss + cls_loss
        pred_cls = jnp.argmax(logits, axis=-1)
        correct = correct + (obj_w * (pred_cls == cls_t)).sum()
        valid = valid + obj_w.sum()
    return total, (correct, valid)


# --- jit-side fixed-size NMS ----------------------------------------------

def batched_nms(boxes: jax.Array, scores: jax.Array, iou_threshold: float,
                max_out: int) -> Tuple[jax.Array, jax.Array]:
    """Greedy NMS with STATIC shapes (compiles on TPU; the reference runs
    torch NMS on host). boxes (N, 4) cxcywh, scores (N,). Returns
    (keep_idx (max_out,), keep_valid (max_out,) {0,1})."""
    n = boxes.shape[0]
    x1 = boxes[:, 0] - boxes[:, 2] / 2
    y1 = boxes[:, 1] - boxes[:, 3] / 2
    x2 = boxes[:, 0] + boxes[:, 2] / 2
    y2 = boxes[:, 1] + boxes[:, 3] / 2
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)

    def pair_iou(i):
        xx1 = jnp.maximum(x1[i], x1)
        yy1 = jnp.maximum(y1[i], y1)
        xx2 = jnp.minimum(x2[i], x2)
        yy2 = jnp.minimum(y2[i], y2)
        inter = jnp.maximum(xx2 - xx1, 0) * jnp.maximum(yy2 - yy1, 0)
        return inter / jnp.maximum(area[i] + area - inter, 1e-12)

    def body(k, carry):
        live, keep, kvalid = carry
        masked = jnp.where(live > 0, scores, -jnp.inf)
        i = jnp.argmax(masked)
        ok = (masked[i] > -jnp.inf).astype(jnp.float32)
        keep = keep.at[k].set(jnp.where(ok > 0, i, -1))
        kvalid = kvalid.at[k].set(ok)
        suppress = (pair_iou(i) > iou_threshold).astype(jnp.float32)
        live = jnp.where(ok > 0, live * (1.0 - suppress), live)
        live = live.at[i].set(0.0)
        return live, keep, kvalid

    live0 = jnp.ones((n,), jnp.float32)
    keep0 = jnp.full((max_out,), -1, jnp.int32)
    kv0 = jnp.zeros((max_out,), jnp.float32)
    _, keep, kvalid = jax.lax.fori_loop(0, max_out, body, (live0, keep0, kv0))
    return keep, kvalid


def detect(outs: List[jax.Array], image_size: int, score_threshold: float,
           iou_threshold: float = 0.5, max_out: int = 32):
    """Decode one image's head outputs (list of (S,S,A,5+C), no batch dim)
    into (boxes (max_out, 4), scores, classes, valid) via jit-side NMS."""
    all_boxes, all_scores, all_cls = [], [], []
    for li, raw in enumerate(outs):
        boxes = decode_level(raw, li).reshape(-1, 4)
        obj = jax.nn.sigmoid(raw[..., 0]).reshape(-1)
        cls_p = jax.nn.softmax(raw[..., 5:], axis=-1)
        cls = jnp.argmax(cls_p, axis=-1).reshape(-1)
        conf = obj * jnp.max(cls_p, axis=-1).reshape(-1)
        all_boxes.append(boxes)
        all_scores.append(conf)
        all_cls.append(cls)
    boxes = jnp.concatenate(all_boxes)
    scores = jnp.concatenate(all_scores)
    classes = jnp.concatenate(all_cls)
    scores = jnp.where(scores >= score_threshold, scores, 0.0)
    # class-aware NMS, YOLOv5-style: offset each class into its own
    # coordinate region so cross-class overlaps never suppress each other
    # offset must exceed the max decodable extent (w <= 0.55*e^4 ~ 30 plus
    # unit coords), or large cross-class boxes could still overlap
    offset_boxes = boxes.at[:, :2].add(classes[:, None].astype(boxes.dtype) * 64.0)
    keep, kvalid = batched_nms(offset_boxes, scores, iou_threshold, max_out)
    safe = jnp.maximum(keep, 0)
    kvalid = kvalid * (scores[safe] > 0)
    return boxes[safe], scores[safe], classes[safe], kvalid
