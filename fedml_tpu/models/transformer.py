"""Transformer LM + ViT.

Parity: the reference's transformer workloads live in ``app/fednlp`` (BERT
fine-tuning via HuggingFace) and FedCV; here transformers are first-class
in-tree models so the long-context / parallelism stack (ring attention over
the ``seq`` mesh axis, tensor parallel over ``model``) has a flagship to
drive. Attention routes through ``fedml_tpu.ops.attention`` so the same
module runs single-chip (fused softmax path) or sequence-sharded.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


def causal_mask(T: int, dtype=jnp.float32) -> jax.Array:
    return jnp.tril(jnp.ones((T, T), dtype=bool))


class MLPBlock(nn.Module):
    dim: int
    hidden_mult: int = 4
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.dim * self.hidden_mult, dtype=self.dtype)(x)
        h = nn.gelu(h)
        return nn.Dense(self.dim, dtype=self.dtype)(h)


class SelfAttention(nn.Module):
    dim: int
    num_heads: int
    causal: bool = True
    dtype: jnp.dtype = jnp.float32
    # sequence parallelism: when set (with ``mesh``), attention runs as ring
    # attention inside shard_map over this mesh axis — K/V blocks rotate via
    # ppermute, memory stays O(T/n) per device (ops/attention.py)
    seq_axis: Optional[str] = None
    mesh: Optional[object] = None

    @nn.compact
    def __call__(self, x):
        from ..ops.attention import multihead_attention, ring_attention

        B, T, D = x.shape
        H = self.num_heads
        qkv = nn.Dense(3 * self.dim, use_bias=False, dtype=self.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        reshape = lambda t: t.reshape(B, T, H, D // H)  # noqa: E731
        q, k, v = reshape(q), reshape(k), reshape(v)
        if self.seq_axis is not None:
            from jax import shard_map
            from jax.sharding import PartitionSpec as P

            spec = P(None, self.seq_axis, None, None)
            out = shard_map(
                lambda q, k, v: ring_attention(
                    q, k, v, self.seq_axis, causal=self.causal
                ),
                mesh=self.mesh,
                in_specs=(spec, spec, spec),
                out_specs=spec,
                check_vma=False,
            )(q, k, v)
        else:
            out = multihead_attention(q, k, v, causal=self.causal)
        out = out.reshape(B, T, D)
        return nn.Dense(self.dim, use_bias=False, dtype=self.dtype, name="proj")(out)


class Block(nn.Module):
    dim: int
    num_heads: int
    causal: bool = True
    dtype: jnp.dtype = jnp.float32
    seq_axis: Optional[str] = None
    mesh: Optional[object] = None

    @nn.compact
    def __call__(self, x):
        x = x + SelfAttention(
            self.dim, self.num_heads, self.causal, self.dtype,
            seq_axis=self.seq_axis, mesh=self.mesh,
        )(nn.LayerNorm(dtype=self.dtype)(x))
        x = x + MLPBlock(self.dim, dtype=self.dtype)(nn.LayerNorm(dtype=self.dtype)(x))
        return x


class TransformerLM(nn.Module):
    """Decoder-only causal LM."""

    vocab_size: int = 32000
    dim: int = 256
    num_heads: int = 8
    num_layers: int = 4
    max_len: int = 2048
    dtype: jnp.dtype = jnp.float32
    seq_axis: Optional[str] = None
    mesh: Optional[object] = None

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        B, T = tokens.shape
        h = nn.Embed(self.vocab_size, self.dim, dtype=self.dtype, name="wte")(tokens)
        pos = nn.Embed(self.max_len, self.dim, dtype=self.dtype, name="wpe")(
            jnp.arange(T)[None, :]
        )
        h = h + pos
        for i in range(self.num_layers):
            h = Block(self.dim, self.num_heads, causal=True, dtype=self.dtype,
                      seq_axis=self.seq_axis, mesh=self.mesh, name=f"block_{i}")(h)
        h = nn.LayerNorm(dtype=self.dtype, name="ln_f")(h)
        return nn.Dense(self.vocab_size, use_bias=False, dtype=self.dtype, name="head")(h)


class TransformerClassifier(nn.Module):
    """Encoder + CLS-pool classifier — the FedNLP text-classification model
    family (reference ``app/fednlp/text_classification/model/bert_model.py``
    wraps HuggingFace BERT; here a native encoder sized for federated
    fine-tuning experiments)."""

    num_classes: int = 20
    vocab_size: int = 30522
    dim: int = 256
    num_heads: int = 8
    num_layers: int = 4
    max_len: int = 512
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        B, T = tokens.shape
        h = nn.Embed(self.vocab_size, self.dim, dtype=self.dtype, name="wte")(tokens)
        pos = nn.Embed(self.max_len, self.dim, dtype=self.dtype, name="wpe")(
            jnp.arange(T)[None, :]
        )
        h = h + pos
        for i in range(self.num_layers):
            h = Block(self.dim, self.num_heads, causal=False, dtype=self.dtype,
                      name=f"block_{i}")(h)
        h = nn.LayerNorm(dtype=self.dtype, name="ln_f")(h)
        return nn.Dense(self.num_classes, dtype=self.dtype, name="cls")(h.mean(axis=1))


class ViT(nn.Module):
    """Small vision transformer (FedCV-parity family)."""

    num_classes: int = 10
    patch: int = 4
    dim: int = 192
    num_heads: int = 3
    num_layers: int = 6
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        B = x.shape[0]
        x = nn.Conv(self.dim, (self.patch, self.patch), (self.patch, self.patch),
                    dtype=self.dtype, name="patchify")(x.astype(self.dtype))
        x = x.reshape(B, -1, self.dim)
        cls = self.param("cls", nn.initializers.zeros, (1, 1, self.dim), self.dtype)
        x = jnp.concatenate([jnp.broadcast_to(cls, (B, 1, self.dim)), x], axis=1)
        pos = self.param("pos", nn.initializers.normal(0.02), (1, x.shape[1], self.dim), self.dtype)
        x = x + pos
        for i in range(self.num_layers):
            x = Block(self.dim, self.num_heads, causal=False, dtype=self.dtype, name=f"block_{i}")(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        return nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x[:, 0])
