"""Transformer LM + ViT.

Parity: the reference's transformer workloads live in ``app/fednlp`` (BERT
fine-tuning via HuggingFace) and FedCV; here transformers are first-class
in-tree models so the long-context / parallelism stack (ring attention over
the ``seq`` mesh axis, tensor parallel over ``model``) has a flagship to
drive. Attention routes through ``fedml_tpu.ops.attention`` so the same
module runs single-chip (fused softmax path) or sequence-sharded.
"""

from __future__ import annotations

from typing import Optional, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


def causal_mask(T: int, dtype=jnp.float32) -> jax.Array:
    return jnp.tril(jnp.ones((T, T), dtype=bool))


class MLPBlock(nn.Module):
    dim: int
    hidden_mult: int = 4
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.dim * self.hidden_mult, dtype=self.dtype)(x)
        h = nn.gelu(h)
        return nn.Dense(self.dim, dtype=self.dtype)(h)


class SelfAttention(nn.Module):
    dim: int
    num_heads: int
    causal: bool = True
    dtype: jnp.dtype = jnp.float32
    # sequence parallelism: when set (with ``mesh``), attention runs
    # sequence-sharded inside shard_map over this mesh axis.
    # ``sp_impl`` picks the collective pattern (ops/attention.py):
    #   "ring"    — K/V blocks rotate via ppermute, online softmax;
    #               O(T/n) memory per device (extreme context lengths).
    #   "ulysses" — two all-to-alls re-shard seq<->heads; full-sequence
    #               attention runs locally (flash-kernel eligible);
    #               needs num_heads % axis_size == 0.
    seq_axis: Optional[str] = None
    mesh: Optional[object] = None
    sp_impl: str = "ring"
    attn_impl: Optional[str] = None   # None = memory-aware auto (ops/attention)

    @nn.compact
    def __call__(self, x):
        from ..ops.attention import (
            multihead_attention,
            ring_attention,
            ulysses_attention,
        )

        B, T, D = x.shape
        H = self.num_heads
        qkv = nn.Dense(3 * self.dim, use_bias=False, dtype=self.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        reshape = lambda t: t.reshape(B, T, H, D // H)  # noqa: E731
        q, k, v = reshape(q), reshape(k), reshape(v)
        if self.seq_axis is not None:
            from jax import shard_map
            from jax.sharding import PartitionSpec as P

            if self.sp_impl == "ulysses":
                sp_fn = lambda q, k, v: ulysses_attention(  # noqa: E731
                    q, k, v, self.seq_axis, causal=self.causal)
            elif self.sp_impl == "ring":
                sp_fn = lambda q, k, v: ring_attention(  # noqa: E731
                    q, k, v, self.seq_axis, causal=self.causal)
            else:
                raise ValueError(f"unknown sp_impl '{self.sp_impl}'")
            spec = P(None, self.seq_axis, None, None)
            out = shard_map(
                sp_fn,
                mesh=self.mesh,
                in_specs=(spec, spec, spec),
                out_specs=spec,
                check_vma=False,
            )(q, k, v)
        else:
            out = multihead_attention(q, k, v, causal=self.causal,
                                      impl=self.attn_impl)
        out = out.reshape(B, T, D)
        return nn.Dense(self.dim, use_bias=False, dtype=self.dtype, name="proj")(out)


class Block(nn.Module):
    dim: int
    num_heads: int
    causal: bool = True
    dtype: jnp.dtype = jnp.float32
    seq_axis: Optional[str] = None
    mesh: Optional[object] = None
    sp_impl: str = "ring"
    attn_impl: Optional[str] = None

    @nn.compact
    def __call__(self, x):
        x = x + SelfAttention(
            self.dim, self.num_heads, self.causal, self.dtype,
            seq_axis=self.seq_axis, mesh=self.mesh, sp_impl=self.sp_impl,
            attn_impl=self.attn_impl,
        )(nn.LayerNorm(dtype=self.dtype)(x))
        x = x + MLPBlock(self.dim, dtype=self.dtype)(nn.LayerNorm(dtype=self.dtype)(x))
        return x


class TransformerLM(nn.Module):
    """Decoder-only causal LM."""

    vocab_size: int = 32000
    dim: int = 256
    num_heads: int = 8
    num_layers: int = 4
    max_len: int = 2048
    dtype: jnp.dtype = jnp.float32
    seq_axis: Optional[str] = None
    mesh: Optional[object] = None
    sp_impl: str = "ring"
    attn_impl: Optional[str] = None
    # rematerialize blocks in bwd (jax.checkpoint): False = save all
    # activations; True/"full" = recompute everything (O(1) activation HBM
    # per layer at ~1.3x fwd FLOPs); "dots" = checkpoint_dots policy —
    # matmul OUTPUTS are saved and only cheap elementwise/norm ops
    # recompute, trading some of full-remat's memory win to reclaim most
    # of its recompute FLOPs (the classic middle point on the
    # memory/compute curve; A/B'd by scripts/bench_lm_attribution_r5.py)
    remat: Union[bool, str] = False

    @nn.compact
    def __call__(self, tokens, train: bool = False,
                 return_hidden: bool = False):
        B, T = tokens.shape
        h = nn.Embed(self.vocab_size, self.dim, dtype=self.dtype, name="wte")(tokens)
        pos = nn.Embed(self.max_len, self.dim, dtype=self.dtype, name="wpe")(
            jnp.arange(T)[None, :]
        )
        h = h + pos
        if self.remat == "dots":
            block_cls = nn.remat(
                Block, policy=jax.checkpoint_policies.checkpoint_dots)
        elif self.remat in (True, "full"):
            block_cls = nn.remat(Block)
        elif not self.remat:
            block_cls = Block
        else:
            # a typo'd policy string must not silently run full remat —
            # every 'dots' conclusion would actually measure the wrong mode
            raise ValueError(
                f"unknown remat policy {self.remat!r}; use False, True, "
                "'full', or 'dots'")
        for i in range(self.num_layers):
            h = block_cls(self.dim, self.num_heads, causal=True, dtype=self.dtype,
                          seq_axis=self.seq_axis, mesh=self.mesh,
                          sp_impl=self.sp_impl, attn_impl=self.attn_impl,
                          name=f"block_{i}")(h)
        h = nn.LayerNorm(dtype=self.dtype, name="ln_f")(h)
        if return_hidden:
            # for chunked-CE training (ops/losses.chunked_lm_cross_entropy):
            # the caller applies the head per sequence chunk so the full
            # (B, T, V) logits never materialize
            return h
        return nn.Dense(self.vocab_size, use_bias=False, dtype=self.dtype, name="head")(h)


def _encode_tokens(mod: nn.Module, tokens) -> jax.Array:
    """Shared bidirectional token encoder: embed + pos + blocks + final LN.

    A plain function (not a submodule) called from each task model's
    ``@nn.compact`` body, so the layers bind to the CALLER's scope and every
    task model keeps the flat wte/wpe/block_i/ln_f param tree (checkpoint
    compatible with the pre-factoring layout)."""
    T = tokens.shape[1]
    h = nn.Embed(mod.vocab_size, mod.dim, dtype=mod.dtype, name="wte")(tokens)
    pos = nn.Embed(mod.max_len, mod.dim, dtype=mod.dtype, name="wpe")(
        jnp.arange(T)[None, :]
    )
    h = h + pos
    for i in range(mod.num_layers):
        h = Block(mod.dim, mod.num_heads, causal=False, dtype=mod.dtype,
                  name=f"block_{i}")(h)
    return nn.LayerNorm(dtype=mod.dtype, name="ln_f")(h)


class TransformerClassifier(nn.Module):
    """Encoder + CLS-pool classifier — the FedNLP text-classification model
    family (reference ``app/fednlp/text_classification/model/bert_model.py``
    wraps HuggingFace BERT; here a native encoder sized for federated
    fine-tuning experiments)."""

    num_classes: int = 20
    vocab_size: int = 30522
    dim: int = 256
    num_heads: int = 8
    num_layers: int = 4
    max_len: int = 512
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        h = _encode_tokens(self, tokens)
        return nn.Dense(self.num_classes, dtype=self.dtype, name="cls")(h.mean(axis=1))


class TransformerTagger(nn.Module):
    """Encoder + per-token head — the FedNLP sequence-tagging family
    (reference ``app/fednlp/seq_tagging``: BERT token classification for NER).
    Output (B, T, num_tags); per-token labels ride the shared masked CE
    (the mask broadcasts over the token dim)."""

    num_tags: int = 9
    vocab_size: int = 30522
    dim: int = 256
    num_heads: int = 8
    num_layers: int = 4
    max_len: int = 512
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        h = _encode_tokens(self, tokens)
        return nn.Dense(self.num_tags, dtype=self.dtype, name="tag_head")(h)


class TransformerSpanExtractor(nn.Module):
    """Encoder + start/end span heads — the FedNLP span-extraction family
    (reference ``app/fednlp/span_extraction``: SQuAD-style QA, BERT with
    start/end logits). Output (B, 2, T): two position-classification
    problems (class dim = sequence positions), so labels (B, 2) =
    (start_idx, end_idx) ride the shared masked CE unchanged."""

    vocab_size: int = 30522
    dim: int = 256
    num_heads: int = 8
    num_layers: int = 4
    max_len: int = 512
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        h = _encode_tokens(self, tokens)
        span = nn.Dense(2, dtype=self.dtype, name="span_head")(h)  # (B, T, 2)
        return jnp.swapaxes(span, 1, 2)  # (B, 2, T): classes = positions


class CrossAttention(nn.Module):
    """Decoder-side attention over encoder memory (no causal constraint)."""

    dim: int
    num_heads: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, memory):
        from ..ops.attention import multihead_attention

        B, T, D = x.shape
        S = memory.shape[1]
        H = self.num_heads
        q = nn.Dense(self.dim, use_bias=False, dtype=self.dtype, name="q")(x)
        kv = nn.Dense(2 * self.dim, use_bias=False, dtype=self.dtype, name="kv")(memory)
        k, v = jnp.split(kv, 2, axis=-1)
        q = q.reshape(B, T, H, D // H)
        k = k.reshape(B, S, H, D // H)
        v = v.reshape(B, S, H, D // H)
        # dense impl: the flash kernel assumes len(q) == len(kv); cross
        # attention has T != S and S is small in the seq2seq family
        out = multihead_attention(q, k, v, causal=False, impl="dense")
        out = out.reshape(B, T, D)
        return nn.Dense(self.dim, use_bias=False, dtype=self.dtype, name="proj")(out)


class DecoderBlock(nn.Module):
    dim: int
    num_heads: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, memory):
        x = x + SelfAttention(self.dim, self.num_heads, causal=True,
                              dtype=self.dtype)(nn.LayerNorm(dtype=self.dtype)(x))
        x = x + CrossAttention(self.dim, self.num_heads, dtype=self.dtype)(
            nn.LayerNorm(dtype=self.dtype)(x), memory)
        x = x + MLPBlock(self.dim, dtype=self.dtype)(nn.LayerNorm(dtype=self.dtype)(x))
        return x


class Seq2SeqTransformer(nn.Module):
    """Encoder-decoder with cross-attention — the FedNLP seq2seq family
    (reference ``app/fednlp/seq2seq``: BART-style summarization/generation).

    TPU-shaped I/O contract: the input is ONE rectangle ``(B, src_len +
    tgt_len)`` = ``[source tokens | shifted decoder-input tokens]`` (teacher
    forcing packed by the data pipeline — static shapes, no ragged pairs);
    labels are the (B, tgt_len) target tokens. Output (B, tgt_len, vocab)."""

    vocab_size: int = 30522
    src_len: int = 64
    tgt_len: int = 32
    dim: int = 256
    num_heads: int = 8
    num_layers: int = 3
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        if tokens.shape[1] != self.src_len + self.tgt_len:
            # fail fast: Embed silently clamps out-of-range positions, so a
            # config/data width mismatch would otherwise degrade invisibly
            raise ValueError(
                f"Seq2SeqTransformer expects width src_len+tgt_len = "
                f"{self.src_len}+{self.tgt_len}, got {tokens.shape[1]} — "
                f"align src_seq_len/tgt_seq_len with the dataset's packing")
        B = tokens.shape[0]
        src = tokens[:, : self.src_len]
        dec_in = tokens[:, self.src_len:]
        wte = nn.Embed(self.vocab_size, self.dim, dtype=self.dtype, name="wte")
        # encoder
        h = wte(src) + nn.Embed(self.src_len, self.dim, dtype=self.dtype,
                                name="enc_pos")(jnp.arange(src.shape[1])[None, :])
        for i in range(self.num_layers):
            h = Block(self.dim, self.num_heads, causal=False, dtype=self.dtype,
                      name=f"enc_{i}")(h)
        memory = nn.LayerNorm(dtype=self.dtype, name="enc_ln")(h)
        # decoder (causal self-attn + cross-attn into the encoder memory)
        d = wte(dec_in) + nn.Embed(self.tgt_len, self.dim, dtype=self.dtype,
                                   name="dec_pos")(jnp.arange(dec_in.shape[1])[None, :])
        for i in range(self.num_layers):
            d = DecoderBlock(self.dim, self.num_heads, dtype=self.dtype,
                             name=f"dec_{i}")(d, memory)
        d = nn.LayerNorm(dtype=self.dtype, name="dec_ln")(d)
        return nn.Dense(self.vocab_size, use_bias=False, dtype=self.dtype,
                        name="lm_head")(d)


class ViT(nn.Module):
    """Small vision transformer (FedCV-parity family)."""

    num_classes: int = 10
    patch: int = 4
    dim: int = 192
    num_heads: int = 3
    num_layers: int = 6
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        B = x.shape[0]
        x = nn.Conv(self.dim, (self.patch, self.patch), (self.patch, self.patch),
                    dtype=self.dtype, name="patchify")(x.astype(self.dtype))
        x = x.reshape(B, -1, self.dim)
        cls = self.param("cls", nn.initializers.zeros, (1, 1, self.dim), self.dtype)
        x = jnp.concatenate([jnp.broadcast_to(cls, (B, 1, self.dim)), x], axis=1)
        pos = self.param("pos", nn.initializers.normal(0.02), (1, x.shape[1], self.dim), self.dtype)
        x = x + pos
        for i in range(self.num_layers):
            x = Block(self.dim, self.num_heads, causal=False, dtype=self.dtype, name=f"block_{i}")(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        return nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x[:, 0])
