"""Model zoo + factory: ``fedml_tpu.models.create(args, output_dim)``.

Parity: reference ``python/fedml/model/model_hub.py:20-94`` — dispatch on
``(args.model, args.dataset)``. Returns an (un-initialized) Flax module;
``init_params(model, rng, sample_input)`` produces the param pytree.

Implemented: lr, cnn (CNN_DropOut), cnn_fedavg, resnet18_gn, resnet56/20,
rnn (per-dataset LSTM variants), rnn_fedavg, mobilenet (v1), mobilenet_v3,
efficientnet, vgg11, vit, transformer_lm, darts (FedNAS search net), unet
(FedSeg), GAN generator/discriminator, GKT client/server pair.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from .cnn import CNNDropOut, CNNOriginalFedAvg
from .linear import LogisticRegression
from .resnet import CifarResNet, ResNet18
from .rnn import RNNOriginalFedAvg, RNNStackOverFlow
from .mobilenet import MobileNetV1
from .mobilenet_v3 import EfficientNet, EfficientNetLite, MobileNetV3Small, VGG
from .transformer import (
    Seq2SeqTransformer,
    TransformerClassifier,
    TransformerLM,
    TransformerSpanExtractor,
    TransformerTagger,
    ViT,
)
from .gan import Discriminator, Generator
from .gkt import GKTClientNet, GKTServerNet
from .darts import DARTSSearchNet, derive_genotype
from .unet import UNetLite
from .yolo import YoloLiteDetector
from .gcn import (
    BipartiteGCNRecommender,
    GCNGraphClassifier,
    GCNGraphRegressor,
    GCNLinkPredictor,
    GCNNodeClassifier,
    RGCNRelationPredictor,
)
from .mobile import (
    MobileLeNet5,
    MobileResNet18,
    build_mobile_model_file,
    load_mobile_model_file,
)

__all__ = [
    "create", "init_params", "sample_input_for",
    "LogisticRegression", "CNNDropOut", "CNNOriginalFedAvg",
    "CifarResNet", "ResNet18", "RNNOriginalFedAvg", "RNNStackOverFlow",
    "MobileNetV1", "MobileNetV3Small", "EfficientNet", "EfficientNetLite", "VGG",
    "TransformerLM", "TransformerClassifier", "ViT",
    "TransformerTagger", "TransformerSpanExtractor", "Seq2SeqTransformer",
    "Generator", "Discriminator", "GKTClientNet", "GKTServerNet",
    "DARTSSearchNet", "derive_genotype", "UNetLite", "YoloLiteDetector", "GCNGraphClassifier",
    "GCNNodeClassifier", "GCNLinkPredictor", "GCNGraphRegressor",
    "MobileLeNet5", "MobileResNet18", "build_mobile_model_file",
    "load_mobile_model_file",
]


def create(args, output_dim: int):
    """Reference ``fedml.model.create`` (model_hub.py:20)."""
    model_name = getattr(args, "model", "lr")
    dataset = getattr(args, "dataset", "mnist")
    dtype = jnp.bfloat16 if getattr(args, "use_bf16", False) else jnp.float32

    if model_name == "lr":
        return LogisticRegression(num_classes=output_dim, dtype=dtype)
    if model_name == "cnn":
        return CNNDropOut(num_classes=output_dim, only_digits=(dataset == "mnist"), dtype=dtype)
    if model_name == "cnn_fedavg":
        return CNNOriginalFedAvg(num_classes=output_dim, dtype=dtype)
    if model_name == "resnet18_gn":
        return ResNet18(num_classes=output_dim, norm_kind="group", dtype=dtype)
    if model_name in ("resnet56", "resnet20", "resnet8"):
        # 6n+2 CIFAR family; resnet8 (n=1) exists for fast BN-path tests
        depth = int(model_name.replace("resnet", ""))
        # 'batch' matches the reference flagship resnet56 (model/cv/resnet.py:303);
        # batch_stats thread through training via make_local_update and are
        # federated-averaged like every other key (fedavg_api.py:163-170).
        norm = getattr(args, "norm", "group")
        # conv_impl: "xla" (default) | "im2col" | "pallas" — the multi-weight
        # conv paths (ops/conv.py) for per-lane-weight execution experiments;
        # measured on the v5e the XLA path wins at ResNet-56's shapes
        # (results/lane_sweep_r4.json), so it stays the default
        conv_impl = getattr(args, "conv_impl", None) or "xla"
        return CifarResNet(depth=depth, num_classes=output_dim,
                           norm_kind=norm, dtype=dtype, conv_impl=conv_impl)
    if model_name == "mobilenet":
        return MobileNetV1(num_classes=output_dim, dtype=dtype)
    if model_name == "mobilenet_v3":
        return MobileNetV3Small(num_classes=output_dim, dtype=dtype)
    if model_name == "efficientnet":
        return EfficientNetLite(num_classes=output_dim, dtype=dtype)
    if model_name.startswith("efficientnet-"):
        # compound-scaling family (reference model/cv/efficientnet)
        from .mobilenet_v3 import EFFICIENTNET_PARAMS

        variant = model_name.split("-", 1)[1]
        if variant not in EFFICIENTNET_PARAMS:
            raise ValueError(
                f"unknown efficientnet variant '{variant}' "
                f"(have {sorted(EFFICIENTNET_PARAMS)})")
        return EfficientNet(num_classes=output_dim, variant=variant,
                            dtype=dtype)
    if model_name == "vgg11":
        return VGG(num_classes=output_dim, dtype=dtype)
    if model_name in ("densenet", "densenet121"):
        # medical chest-x-ray backbone (reference app/fedcv/
        # medical_chest_xray_image_clf/model/densenet.py)
        from .densenet import DenseNet

        if model_name == "densenet121":
            return DenseNet(num_classes=output_dim, growth=32,
                            block_config=(6, 12, 24, 16), dtype=dtype)
        return DenseNet(num_classes=output_dim, dtype=dtype)
    if model_name == "darts":
        return DARTSSearchNet(num_classes=output_dim, dtype=dtype)
    if model_name == "unet":
        return UNetLite(num_classes=output_dim, dtype=dtype)
    if model_name in ("deeplabv3_plus", "deeplab"):
        # DeepLabV3+ (reference app/fedcv/image_segmentation/model/
        # deeplabV3_plus.py) — ASPP + low-level fusion decoder
        from .deeplab import DeepLabV3Plus

        return DeepLabV3Plus(num_classes=output_dim, dtype=dtype)
    if model_name == "transunet":
        # TransUNet (reference app/fedcv/image_segmentation/model/
        # transunet/transunet.py) — CNN encoder + ViT bottleneck + decoder
        from .transunet import TransUNet

        return TransUNet(num_classes=output_dim, dtype=dtype)
    if model_name == "yolo_lite":
        # multi-scale anchor detector (reference app/fedcv YOLOv5 class)
        return YoloLiteDetector(num_classes=output_dim, dtype=dtype)
    if model_name in ("gcn", "graph"):
        return GCNGraphClassifier(
            num_classes=output_dim,
            num_nodes=int(getattr(args, "graph_num_nodes", 16) or 16),
            dtype=dtype,
        )
    if model_name == "gcn_node":
        return GCNNodeClassifier(
            num_classes=output_dim,
            num_nodes=int(getattr(args, "graph_num_nodes", 16) or 16),
            dtype=dtype,
        )
    if model_name == "rgcn":
        # relation-type prediction over typed edges (reference
        # app/fedgraphnn/subgraph_relation_pred RGCN+DistMult); dataset
        # class_num = num_relations + 1 (class 0 = no relation)
        return RGCNRelationPredictor(
            num_relations=max(output_dim - 1, 1),
            num_nodes=int(getattr(args, "graph_num_nodes", 16) or 16),
            dtype=dtype,
        )
    if model_name in ("gcn_recsys", "recsys_link_pred"):
        # user-item rating completion (reference
        # app/fedgraphnn/recsys_subgraph_link_pred, MSE on rating logits)
        return BipartiteGCNRecommender(
            num_users=int(getattr(args, "graph_num_users", 8) or 8),
            num_items=int(getattr(args, "graph_num_items", 8) or 8),
            dtype=dtype,
        )
    if model_name == "gcn_link":
        return GCNLinkPredictor(
            num_nodes=int(getattr(args, "graph_num_nodes", 16) or 16),
            dtype=dtype,
        )
    if model_name == "gcn_reg":
        return GCNGraphRegressor(
            num_nodes=int(getattr(args, "graph_num_nodes", 16) or 16),
            dtype=dtype,
        )
    if model_name in ("rnn", "rnn_fedavg"):
        if "stackoverflow" in dataset:
            return RNNStackOverFlow(dtype=dtype)
        return RNNOriginalFedAvg(vocab_size=output_dim, dtype=dtype)
    if model_name == "transformer_lm":
        return TransformerLM(vocab_size=output_dim, dtype=dtype)
    if model_name in ("transformer_classifier", "bert_tiny"):
        vocab = int(getattr(args, "vocab_size", 2000) or 2000)
        return TransformerClassifier(
            num_classes=output_dim, vocab_size=vocab,
            max_len=int(getattr(args, "max_seq_len", 512) or 512), dtype=dtype,
        )
    if model_name == "vit":
        return ViT(num_classes=output_dim, dtype=dtype)
    dim = int(getattr(args, "model_dim", 256) or 256)
    layers = int(getattr(args, "model_layers", 4) or 4)
    heads = int(getattr(args, "model_heads", 8) or 8)
    if model_name in ("transformer_tagger", "bert_tagger"):
        vocab = int(getattr(args, "vocab_size", 2000) or 2000)
        return TransformerTagger(
            num_tags=output_dim, vocab_size=vocab, dim=dim,
            num_layers=layers, num_heads=heads,
            max_len=int(getattr(args, "max_seq_len", 512) or 512), dtype=dtype,
        )
    if model_name in ("span_extractor", "bert_qa"):
        vocab = int(getattr(args, "vocab_size", 2000) or 2000)
        return TransformerSpanExtractor(
            vocab_size=vocab, dim=dim, num_layers=layers, num_heads=heads,
            max_len=int(getattr(args, "max_seq_len", 512) or 512), dtype=dtype,
        )
    if model_name in ("seq2seq", "bart_tiny"):
        vocab = int(getattr(args, "vocab_size", 2000) or 2000)
        return Seq2SeqTransformer(
            vocab_size=vocab, dim=dim, num_heads=heads,
            # encoder+decoder stacks double the depth: seq2seq deliberately
            # defaults shallower; graftcheck: disable=config-drift
            num_layers=int(getattr(args, "model_layers", 3) or 3),
            src_len=int(getattr(args, "src_seq_len", 64) or 64),
            tgt_len=int(getattr(args, "tgt_seq_len", 32) or 32),
            dtype=dtype,
        )
    raise ValueError(f"unknown model '{model_name}'")


def sample_input_for(args, fed_or_shape: Any) -> jax.Array:
    """A (1, ...) sample batch for module init, derived from the dataset."""
    if hasattr(fed_or_shape, "train_data_global"):
        x = fed_or_shape.train_data_global.x[:1]
        return jnp.asarray(x)
    return jnp.zeros((1,) + tuple(fed_or_shape), jnp.float32)


def init_params(model, rng: jax.Array, sample_input: jax.Array):
    """Initialize a param pytree. Returns the full variables dict; for
    stateless models this is ``{'params': ...}``."""
    variables = model.init(rng, sample_input, train=False)
    return variables
