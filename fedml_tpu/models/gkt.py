"""FedGKT model pair: small client edge net + large server net.

Parity: reference split ResNet-56 for FedGKT
(``model/cv/resnet56/resnet_client.py`` / ``resnet_server.py``): the client
runs a shallow feature extractor + tiny head on-device; the server continues
from the client's feature maps with the deep trunk. Sized here for CIFAR-like
32x32 inputs.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class GKTClientNet(nn.Module):
    """Shallow extractor + local head. Returns (features, logits)."""

    num_classes: int = 10
    feature_dim: int = 32
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(16, (3, 3), dtype=self.dtype)(x)
        x = nn.GroupNorm(num_groups=8, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        h = nn.Conv(self.feature_dim, (3, 3), dtype=self.dtype)(x)
        h = nn.relu(h)  # (B, 16, 16, feature_dim) — shipped to the server
        pooled = h.mean(axis=(1, 2))
        logits = nn.Dense(self.num_classes, dtype=self.dtype)(pooled)
        return h, logits


class GKTServerNet(nn.Module):
    """Deep trunk continuing from client feature maps."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, h, train: bool = False):
        x = h.astype(self.dtype)
        for width in (64, 128):
            x = nn.Conv(width, (3, 3), dtype=self.dtype)(x)
            x = nn.GroupNorm(num_groups=16, dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.mean(axis=(1, 2))
        x = nn.Dense(256, dtype=self.dtype)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)
