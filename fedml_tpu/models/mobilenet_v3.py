"""MobileNetV3 (small) + EfficientNet-lite. Parity: reference
``model/cv/mobilenet_v3.py`` and ``model/cv/efficientnet/`` (model_hub.py
entries ``mobilenet_v3``, ``efficientnet``). Both are built from the same
inverted-residual (MBConv) block; GroupNorm replaces BatchNorm (FL-standard,
see resnet.py docstring) so no mutable batch stats cross client boundaries."""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp


def hard_swish(x):
    return x * nn.relu6(x + 3.0) / 6.0


class SqueezeExcite(nn.Module):
    channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        s = x.mean(axis=(1, 2))
        s = nn.relu(nn.Dense(max(8, self.channels // 4), dtype=self.dtype)(s))
        s = nn.hard_sigmoid(nn.Dense(self.channels, dtype=self.dtype)(s))
        return x * s[:, None, None, :]


class MBConv(nn.Module):
    """Inverted residual: expand (1x1) -> depthwise -> [SE] -> project (1x1)."""

    out_ch: int
    expand: int = 4
    stride: int = 1
    kernel: int = 3
    use_se: bool = True
    act: str = "hswish"  # hswish | relu
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        act = hard_swish if self.act == "hswish" else nn.relu
        in_ch = x.shape[-1]
        mid = in_ch * self.expand
        h = nn.Conv(mid, (1, 1), use_bias=False, dtype=self.dtype)(x)
        h = act(nn.GroupNorm(num_groups=min(8, mid), dtype=self.dtype)(h))
        h = nn.Conv(
            mid, (self.kernel, self.kernel), strides=(self.stride, self.stride),
            feature_group_count=mid, use_bias=False, dtype=self.dtype,
        )(h)
        h = act(nn.GroupNorm(num_groups=min(8, mid), dtype=self.dtype)(h))
        if self.use_se:
            h = SqueezeExcite(mid, dtype=self.dtype)(h)
        h = nn.Conv(self.out_ch, (1, 1), use_bias=False, dtype=self.dtype)(h)
        h = nn.GroupNorm(num_groups=min(8, self.out_ch), dtype=self.dtype)(h)
        if self.stride == 1 and in_ch == self.out_ch:
            h = h + x
        return h


class MobileNetV3Small(nn.Module):
    """Reference ``mobilenet_v3`` entry (small profile, GN variant)."""

    num_classes: int = 10
    width: float = 1.0
    dtype: jnp.dtype = jnp.float32
    # (out_ch, expand, stride, kernel, use_se, act)
    blocks: Sequence[Tuple[int, int, int, int, bool, str]] = (
        (16, 1, 2, 3, True, "relu"),
        (24, 4, 2, 3, False, "relu"),
        (24, 3, 1, 3, False, "relu"),
        (40, 3, 2, 5, True, "hswish"),
        (40, 3, 1, 5, True, "hswish"),
        (48, 3, 1, 5, True, "hswish"),
        (96, 6, 2, 5, True, "hswish"),
    )

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        c = int(16 * self.width)
        x = nn.Conv(c, (3, 3), strides=(2, 2), use_bias=False, dtype=self.dtype)(x)
        x = hard_swish(nn.GroupNorm(num_groups=8, dtype=self.dtype)(x))
        for out_ch, expand, stride, kernel, use_se, act in self.blocks:
            x = MBConv(
                int(out_ch * self.width), expand, stride, kernel, use_se, act,
                dtype=self.dtype,
            )(x)
        x = nn.Conv(int(288 * self.width), (1, 1), use_bias=False, dtype=self.dtype)(x)
        x = hard_swish(nn.GroupNorm(num_groups=8, dtype=self.dtype)(x))
        x = x.mean(axis=(1, 2))
        x = hard_swish(nn.Dense(int(512 * self.width), dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)


class EfficientNetLite(nn.Module):
    """Reference ``efficientnet`` entry (B0-lite profile: no SE in lite,
    relu6; depth/width at 1.0)."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32
    blocks: Sequence[Tuple[int, int, int, int]] = (
        # (out_ch, expand, stride, kernel)
        (16, 1, 1, 3),
        (24, 6, 2, 3),
        (40, 6, 2, 5),
        (80, 6, 2, 3),
        (112, 6, 1, 5),
        (192, 6, 2, 5),
        (320, 6, 1, 3),
    )

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), strides=(2, 2), use_bias=False, dtype=self.dtype)(x)
        x = nn.relu6(nn.GroupNorm(num_groups=8, dtype=self.dtype)(x))
        for out_ch, expand, stride, kernel in self.blocks:
            x = MBConv(out_ch, expand, stride, kernel, use_se=False, act="relu",
                       dtype=self.dtype)(x)
        x = nn.Conv(1280, (1, 1), use_bias=False, dtype=self.dtype)(x)
        x = nn.relu6(nn.GroupNorm(num_groups=8, dtype=self.dtype)(x))
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)


# EfficientNet compound scaling (reference ``model/cv/efficientnet/`` —
# the full b0-b7 family, not just the lite profile): width/depth/dropout
# per variant; resolution rides the caller's input size as in the reference.
EFFICIENTNET_PARAMS = {
    "b0": (1.0, 1.0, 0.2),
    "b1": (1.0, 1.1, 0.2),
    "b2": (1.1, 1.2, 0.3),
    "b3": (1.2, 1.4, 0.3),
    "b4": (1.4, 1.8, 0.4),
    "b5": (1.6, 2.2, 0.4),
    "b6": (1.8, 2.6, 0.5),
    "b7": (2.0, 3.1, 0.5),
}

# B0 base config: (out_ch, expand, stride, kernel, repeats)
_EFFNET_B0_BLOCKS = (
    (16, 1, 1, 3, 1),
    (24, 6, 2, 3, 2),
    (40, 6, 2, 5, 2),
    (80, 6, 2, 3, 3),
    (112, 6, 1, 5, 3),
    (192, 6, 2, 5, 4),
    (320, 6, 1, 3, 1),
)


def round_filters(ch: int, width: float, divisor: int = 8) -> int:
    """Reference ``efficientnet_utils.round_filters`` semantics."""
    ch *= width
    new = max(divisor, int(ch + divisor / 2) // divisor * divisor)
    if new < 0.9 * ch:  # never shrink >10%
        new += divisor
    return int(new)


def round_repeats(r: int, depth: float) -> int:
    import math

    return int(math.ceil(depth * r))


class EfficientNet(nn.Module):
    """Compound-scaled EfficientNet family (reference
    ``model/cv/efficientnet/``): SE blocks on, swish activations via the
    MBConv 'hswish' profile, GN in place of BN per the repo's FL norm
    policy (running-stat averaging pathologies — models/resnet.py note)."""

    num_classes: int = 10
    variant: str = "b0"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        width, depth, dropout = EFFICIENTNET_PARAMS[self.variant]
        x = x.astype(self.dtype)
        x = nn.Conv(round_filters(32, width), (3, 3), strides=(2, 2),
                    use_bias=False, dtype=self.dtype)(x)
        x = hard_swish(nn.GroupNorm(num_groups=8, dtype=self.dtype)(x))
        for out_ch, expand, stride, kernel, repeats in _EFFNET_B0_BLOCKS:
            out_ch = round_filters(out_ch, width)
            for i in range(round_repeats(repeats, depth)):
                x = MBConv(out_ch, expand, stride if i == 0 else 1, kernel,
                           use_se=True, dtype=self.dtype)(x)
        x = nn.Conv(round_filters(1280, width), (1, 1), use_bias=False,
                    dtype=self.dtype)(x)
        x = hard_swish(nn.GroupNorm(num_groups=8, dtype=self.dtype)(x))
        x = x.mean(axis=(1, 2))
        # per-variant head dropout (the third compound-scaling coefficient)
        x = nn.Dropout(dropout, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)


class VGG(nn.Module):
    """Reference ``model/cv/vgg.py`` (VGG-11 profile, GN)."""

    num_classes: int = 10
    cfg: Sequence = (64, "M", 128, "M", 256, 256, "M", 512, 512, "M")
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        for v in self.cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(int(v), (3, 3), use_bias=False, dtype=self.dtype)(x)
                x = nn.relu(nn.GroupNorm(num_groups=8, dtype=self.dtype)(x))
        x = x.mean(axis=(1, 2))
        x = nn.relu(nn.Dense(512, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)
