"""ResNets for CIFAR-class federated benchmarks.

Parity targets: reference ``model/cv/resnet.py:303`` (CIFAR ResNet-56, the
BENCHMARK_MPI.md flagship) and ``model/cv/resnet_gn.py:239`` (ResNet-18 with
GroupNorm, the fed_CIFAR100 baseline).

Normalization: GroupNorm by default (the standard FL fix for BN's
batch-statistics dependence — Hsieh et al.; the reference itself ships
resnet18_gn for this reason). ``norm='batch'`` matches the reference
flagship: its ResNet-56 uses BatchNorm and FedAvg averages the running stats
across clients (``fedavg_api.py:163-170`` iterates *all* state_dict keys) —
our training path threads the mutable ``batch_stats`` collection through the
local-update scan (``algorithms/local_sgd.py:_make_bn_local_update``) and the
shipped delta covers both collections, reproducing that behavior. Note: the
tail batch of a client is zero-padded, which slightly biases BN batch
statistics versus the reference's ragged final batch; running stats still
converge since most batches are full.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import flax.linen as nn
import jax.numpy as jnp

from ..ops.conv import Conv as MWConv

ModuleDef = Any


def _conv(conv_impl: str, dtype):
    """nn.Conv, or the multi-weight conv module (ops/conv.py) whose im2col/
    pallas paths avoid XLA's grouped-conv lowering under per-lane weight
    vmap (the packed-lane cohort executor). Both auto-name "Conv_i", so the
    param tree is identical either way."""
    if conv_impl == "xla":
        return partial(nn.Conv, use_bias=False, dtype=dtype)
    return partial(MWConv, use_bias=False, dtype=dtype, impl=conv_impl)


SYNC_BN_AXIS = "sync_bn"


def _norm(norm: str, dtype) -> Callable:
    if norm == "group":
        return partial(nn.GroupNorm, num_groups=None, group_size=16, dtype=dtype)
    if norm == "batch":
        return partial(nn.BatchNorm, use_running_average=None, momentum=0.9, dtype=dtype)
    if norm == "sync_batch":
        # SyncBN (reference model/cv/batchnorm_utils.py:488): batch stats
        # are all-reduced over the mapped device axis named SYNC_BN_AXIS —
        # TPU-first this is flax's axis_name hook riding an XLA psum, not a
        # NCCL allreduce; run the model under shard_map/pmap/vmap with that
        # axis name bound
        return partial(nn.BatchNorm, use_running_average=None, momentum=0.9,
                       axis_name=SYNC_BN_AXIS, dtype=dtype)
    raise ValueError(norm)


class BasicBlock(nn.Module):
    filters: int
    norm: ModuleDef
    strides: int = 1
    dtype: jnp.dtype = jnp.float32
    conv_impl: str = "xla"

    @nn.compact
    def __call__(self, x):
        conv = _conv(self.conv_impl, self.dtype)
        residual = x
        y = conv(self.filters, (3, 3), (self.strides, self.strides),
                 padding="SAME")(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), padding="SAME")(y)
        y = self.norm()(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1), (self.strides, self.strides),
                            name="proj")(residual)
            residual = self.norm(name="proj_norm")(residual)
        return nn.relu(y + residual)


class CifarResNet(nn.Module):
    """CIFAR-style 6n+2 ResNet: stages (16, 32, 64) x n blocks.

    depth 56 -> n=9 (reference resnet56); depth 20 -> n=3.
    """

    depth: int = 56
    num_classes: int = 10
    norm_kind: str = "group"
    dtype: jnp.dtype = jnp.float32
    conv_impl: str = "xla"

    @nn.compact
    def __call__(self, x, train: bool = False):
        n = (self.depth - 2) // 6
        norm = _norm(self.norm_kind, self.dtype)
        if self.norm_kind in ("batch", "sync_batch"):
            norm = partial(norm, use_running_average=not train)
        x = x.astype(self.dtype)
        x = _conv(self.conv_impl, self.dtype)(16, (3, 3), padding="SAME")(x)
        x = norm()(x)
        x = nn.relu(x)
        for i, filters in enumerate((16, 32, 64)):
            for j in range(n):
                strides = 2 if i > 0 and j == 0 else 1
                x = BasicBlock(filters, norm, strides, self.dtype,
                               self.conv_impl)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)


class ResNet18(nn.Module):
    """ImageNet-style ResNet-18 with GN (reference resnet18_gn for fed_CIFAR100;
    small-input mode uses a 3x3 stem as is standard for 32x32 data)."""

    num_classes: int = 100
    norm_kind: str = "group"
    small_input: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = _norm(self.norm_kind, self.dtype)
        if self.norm_kind in ("batch", "sync_batch"):
            norm = partial(norm, use_running_average=not train)
        x = x.astype(self.dtype)
        if self.small_input:
            x = nn.Conv(64, (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(x)
        else:
            x = nn.Conv(64, (7, 7), (2, 2), padding="SAME", use_bias=False, dtype=self.dtype)(x)
            x = norm()(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, filters in enumerate((64, 128, 256, 512)):
            for j in range(2):
                strides = 2 if i > 0 and j == 0 else 1
                x = BasicBlock(filters, norm, strides, self.dtype)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)
