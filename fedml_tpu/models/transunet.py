"""TransUNet for federated semantic segmentation (FedSeg).

Parity: reference ``app/fedcv/image_segmentation/model/transunet/
transunet.py`` — CNN encoder, ViT bottleneck over patch tokens, cascaded
upsampling decoder with encoder skip connections. Together with
``models/deeplab.py`` this covers both segmentation architecture classes
the reference ships.

TPU-first notes: the transformer bottleneck reuses ``models/transformer.
Block`` (bidirectional: ``causal=False``) so the attention stack shares
the flash/dense auto-dispatch and SP plumbing; token grid size is static
(H/8 x W/8), so the whole net is one fused XLA program. GroupNorm for the
conv stages (per-client stats; same reasoning as the other FL CV models).
Output (B, H*W, num_classes) token logits — rides the shared masked CE.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from .transformer import Block


from .deeplab import _gn


class _ConvStage(nn.Module):
    ch: int
    down: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        if self.down:
            x = nn.Conv(self.ch, (3, 3), (2, 2), padding="SAME",
                        use_bias=False, dtype=self.dtype)(x)
        else:
            x = nn.Conv(self.ch, (3, 3), padding="SAME", use_bias=False,
                        dtype=self.dtype)(x)
        x = nn.relu(_gn(self.ch, self.dtype)(x))
        x = nn.Conv(self.ch, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(x)
        return nn.relu(_gn(self.ch, self.dtype)(x))


class TransUNet(nn.Module):
    """Compact TransUNet: 3-stage CNN encoder (skips at H, H/2, H/4),
    transformer bottleneck on the H/8 token grid, cascaded decoder."""

    num_classes: int = 2
    base: int = 16
    trans_dim: int = 64
    trans_layers: int = 2
    trans_heads: int = 4
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False) -> jnp.ndarray:
        x = x.astype(self.dtype)
        B, H, W, _ = x.shape
        if H % 8 or W % 8:
            raise ValueError(
                f"TransUNet needs H and W divisible by 8 (3 stride-2 "
                f"stages + doubling decoder must realign with the skips); "
                f"got {H}x{W} — pad or resize the input")
        # encoder
        e0 = _ConvStage(self.base, down=False, dtype=self.dtype)(x)      # H
        e1 = _ConvStage(self.base * 2, dtype=self.dtype)(e0)             # H/2
        e2 = _ConvStage(self.base * 4, dtype=self.dtype)(e1)             # H/4
        y = _ConvStage(self.trans_dim, dtype=self.dtype)(e2)             # H/8
        # ViT bottleneck over the token grid
        h, w = y.shape[1], y.shape[2]
        tokens = y.reshape(B, h * w, self.trans_dim)
        # surface the resolution-bound contract BEFORE self.param, whose
        # ScopeParamShapeError on an apply-time mismatch is opaque
        existing = self.get_variable("params", "pos_embed")
        if existing is not None and existing.shape[1] != h * w:
            raise ValueError(
                f"TransUNet pos_embed was initialized for {existing.shape[1]} "
                f"tokens but this input yields {h * w} (input {H}x{W}): "
                "unlike the fully-convolutional DeepLabV3+, TransUNet "
                "params are resolution-bound — re-init or interpolate "
                "pos_embed for the new resolution")
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, h * w, self.trans_dim), jnp.float32)
        tokens = tokens + pos.astype(self.dtype)
        for i in range(self.trans_layers):
            tokens = Block(self.trans_dim, self.trans_heads, causal=False,
                           dtype=self.dtype, name=f"vit_{i}")(tokens)
        tokens = nn.LayerNorm(dtype=self.dtype, name="vit_ln")(tokens)
        y = tokens.reshape(B, h, w, self.trans_dim)

        # cascaded decoder with skips
        def up(y, skip, ch):
            B_, hh, ww, _ = y.shape
            y = jax.image.resize(y, (B_, hh * 2, ww * 2, y.shape[-1]),
                                 "bilinear")
            y = jnp.concatenate([y, skip], axis=-1)
            y = nn.Conv(ch, (3, 3), padding="SAME", use_bias=False,
                        dtype=self.dtype)(y)
            return nn.relu(_gn(ch, self.dtype)(y))

        y = up(y, e2, self.base * 4)                                     # H/4
        y = up(y, e1, self.base * 2)                                     # H/2
        y = up(y, e0, self.base)                                         # H
        logits = nn.Conv(self.num_classes, (1, 1), dtype=self.dtype)(y)
        return logits.reshape(B, H * W, self.num_classes)
