"""Character/word LSTM language models.

Parity: reference ``python/fedml/model/nlp/rnn.py:86`` —
``RNN_OriginalFedAvg`` (shakespeare: embed-8, 2xLSTM-256, vocab 90) and
``RNN_StackOverFlow`` (next-word-prediction: vocab 10k+special, embed-96,
LSTM-670, double dense head).

Implemented with ``nn.RNN`` over ``nn.OptimizedLSTMCell`` — the scan is
compiler-friendly (``lax.scan`` under the hood), static sequence length.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class RNNOriginalFedAvg(nn.Module):
    """2-layer LSTM char LM (reference ``RNN_OriginalFedAvg``)."""

    vocab_size: int = 90
    embedding_dim: int = 8
    hidden_size: int = 256
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        # x: (B, T) int tokens -> logits (B, T, vocab)
        h = nn.Embed(self.vocab_size, self.embedding_dim, dtype=self.dtype)(x)
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size, dtype=self.dtype))(h)
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden_size, dtype=self.dtype))(h)
        return nn.Dense(self.vocab_size, dtype=self.dtype)(h)


class RNNStackOverFlow(nn.Module):
    """Next-word LSTM (reference ``RNN_StackOverFlow``)."""

    vocab_size: int = 10000
    num_oov_buckets: int = 1
    embedding_size: int = 96
    latent_size: int = 670
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        extended_vocab = self.vocab_size + 3 + self.num_oov_buckets  # pad/bos/eos + oov
        h = nn.Embed(extended_vocab, self.embedding_size, dtype=self.dtype)(x)
        h = nn.RNN(nn.OptimizedLSTMCell(self.latent_size, dtype=self.dtype))(h)
        h = nn.Dense(self.embedding_size, dtype=self.dtype)(h)
        return nn.Dense(extended_vocab, dtype=self.dtype)(h)
