"""Autoencoder for FedIoT anomaly detection (reference ``app/fediot``:
a small symmetric AE over per-flow traffic feature vectors)."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class AnomalyAutoencoder(nn.Module):
    input_dim: int = 115   # the reference's N-BaIoT feature count
    hidden: Sequence[int] = (64, 32, 16)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = x.astype(self.dtype)
        for width in self.hidden:
            h = nn.relu(nn.Dense(width, dtype=self.dtype)(h))
        for width in list(self.hidden[-2::-1]):
            h = nn.relu(nn.Dense(width, dtype=self.dtype)(h))
        return nn.Dense(self.input_dim, dtype=self.dtype)(h)
