"""DenseNet-BC for federated medical imaging.

Parity: reference ``app/fedcv/medical_chest_xray_image_clf/model/
densenet.py`` (DenseNet-BC, the chest-x-ray classification backbone; the
trainer is plain CE — ``trainer/classification_trainer.py:22``).

TPU-first notes: dense connectivity is channel concatenation — pure data
movement XLA fuses into the next conv; the composite function is
norm->relu->1x1 bottleneck->norm->relu->3x3, all MXU matmul-shaped once
channels grow past the first block. GroupNorm replaces BatchNorm (per-client
batch stats don't transfer under FedAvg; same reasoning as
``models/resnet.py``). ``densenet121`` matches the reference config
(growth 32, blocks 6/12/24/16); the small default is test-sized.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


def _gn(ch: int, dtype):
    # dense-block channel counts are multiples of the growth rate, not of
    # 8 — pick the largest group count <=8 that divides ch
    g = next(g for g in range(min(8, ch), 0, -1) if ch % g == 0)
    return nn.GroupNorm(num_groups=g, dtype=dtype)


class _DenseLayer(nn.Module):
    growth: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        y = nn.relu(_gn(x.shape[-1], self.dtype)(x))
        y = nn.Conv(4 * self.growth, (1, 1), use_bias=False, dtype=self.dtype)(y)
        y = nn.relu(_gn(4 * self.growth, self.dtype)(y))
        y = nn.Conv(self.growth, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(y)
        return jnp.concatenate([x, y], axis=-1)


class _Transition(nn.Module):
    out_ch: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.relu(_gn(x.shape[-1], self.dtype)(x))
        x = nn.Conv(self.out_ch, (1, 1), use_bias=False, dtype=self.dtype)(x)
        return nn.avg_pool(x, (2, 2), strides=(2, 2))


class DenseNet(nn.Module):
    """DenseNet-BC (compression 0.5). Default sizing is compact for small
    federated imagery/tests; ``block_config=(6, 12, 24, 16), growth=32``
    reproduces the reference's DenseNet-121 layout."""

    num_classes: int = 4
    growth: int = 8
    block_config: Sequence[int] = (2, 4, 3)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        ch = 2 * self.growth
        x = nn.Conv(ch, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(x)
        for bi, n_layers in enumerate(self.block_config):
            for _ in range(n_layers):
                x = _DenseLayer(self.growth, self.dtype)(x)
            if bi != len(self.block_config) - 1:
                x = _Transition(x.shape[-1] // 2, self.dtype)(x)
        x = nn.relu(_gn(x.shape[-1], self.dtype)(x))
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)
