"""MNIST GAN. Parity: reference ``python/fedml/model/model_hub.py:88-94``
(MNIST GAN entry) + the FedGAN MPI aggregator's G/D pair
(``simulation/mpi/fedgan/``). DCGAN-style generator/discriminator sized for
28x28x1; kept bf16-friendly (transposed convs hit the MXU)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class Generator(nn.Module):
    """z (B, latent_dim) -> images (B, 28, 28, 1) in [-1, 1]."""

    latent_dim: int = 64
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, z, train: bool = False):
        z = z.astype(self.dtype)
        x = nn.Dense(7 * 7 * 64, dtype=self.dtype)(z)
        x = nn.relu(x)
        x = x.reshape((-1, 7, 7, 64))
        x = nn.ConvTranspose(32, (4, 4), strides=(2, 2), dtype=self.dtype)(x)  # 14x14
        x = nn.relu(x)
        x = nn.ConvTranspose(1, (4, 4), strides=(2, 2), dtype=self.dtype)(x)  # 28x28
        return jnp.tanh(x)


class Discriminator(nn.Module):
    """images (B, 28, 28, 1) -> real/fake logit (B,)."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (4, 4), strides=(2, 2), dtype=self.dtype)(x)  # 14x14
        x = nn.leaky_relu(x, 0.2)
        x = nn.Conv(64, (4, 4), strides=(2, 2), dtype=self.dtype)(x)  # 7x7
        x = nn.leaky_relu(x, 0.2)
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(1, dtype=self.dtype)(x)[:, 0]
