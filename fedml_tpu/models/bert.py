"""BERT encoder + classification head, HF-weight-compatible.

Parity: reference FedNLP fine-tunes HuggingFace BERT/DistilBERT
(``app/fednlp/text_classification/model/bert_model.py``). This module is a
Flax re-implementation of ``BertForSequenceClassification`` with *exact*
HF semantics — learned word/position/token-type embeddings, post-LayerNorm
residuals (eps 1e-12), erf-gelu intermediate, tanh pooler on [CLS] — so
weights imported from a torch checkpoint file produce identical logits
(``utils/torch_import.bert_state_dict_to_flax``), and federated fine-tuning
starts from the pretrained point exactly as the reference does.

Module names deliberately mirror the HF state_dict paths (word_embeddings,
attention_output_dense, ...) so the import mapping reads as a rename, not a
puzzle.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    num_labels: int = 2
    layer_norm_eps: float = 1e-12
    dropout_rate: float = 0.1


class BertSelfAttention(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, hidden, attn_bias, train: bool = False):
        c = self.cfg
        head_dim = c.hidden_size // c.num_attention_heads
        B, T, H = hidden.shape

        def heads(name):
            y = nn.Dense(c.hidden_size, name=name)(hidden)
            return y.reshape(B, T, c.num_attention_heads, head_dim)

        q, k, v = heads("query"), heads("key"), heads("value")
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(
            jnp.asarray(head_dim, hidden.dtype))
        scores = scores + attn_bias  # additive mask, HF-style
        probs = jax.nn.softmax(scores, axis=-1)
        if train and c.dropout_rate:
            probs = nn.Dropout(c.dropout_rate, deterministic=False)(probs)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, T, H)
        out = nn.Dense(c.hidden_size, name="output_dense")(ctx)
        if train and c.dropout_rate:
            out = nn.Dropout(c.dropout_rate, deterministic=False)(out)
        return nn.LayerNorm(epsilon=c.layer_norm_eps, name="output_norm")(
            out + hidden)


class BertLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, hidden, attn_bias, train: bool = False):
        c = self.cfg
        attn = BertSelfAttention(c, name="attention")(hidden, attn_bias, train)
        inter = nn.Dense(c.intermediate_size, name="intermediate_dense")(attn)
        inter = jax.nn.gelu(inter, approximate=False)  # HF "gelu" = erf form
        out = nn.Dense(c.hidden_size, name="output_dense")(inter)
        if train and c.dropout_rate:
            out = nn.Dropout(c.dropout_rate, deterministic=False)(out)
        return nn.LayerNorm(epsilon=c.layer_norm_eps, name="output_norm")(
            out + attn)


class BertForSequenceClassification(nn.Module):
    """HF ``BertForSequenceClassification`` forward, flax-native.

    ``__call__(x, ...)`` takes int32 token ids (B, T); ``attention_mask``
    (B, T) in {0,1} and ``token_type_ids`` default to all-ones/zeros like HF.
    """

    cfg: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask=None, token_type_ids=None,
                 train: bool = False):
        c = self.cfg
        B, T = x.shape
        if attention_mask is None:
            attention_mask = jnp.ones((B, T), jnp.float32)
        if token_type_ids is None:
            token_type_ids = jnp.zeros((B, T), jnp.int32)

        word = nn.Embed(c.vocab_size, c.hidden_size,
                        name="word_embeddings")(x)
        pos = nn.Embed(c.max_position_embeddings, c.hidden_size,
                       name="position_embeddings")(jnp.arange(T)[None, :])
        typ = nn.Embed(c.type_vocab_size, c.hidden_size,
                       name="token_type_embeddings")(token_type_ids)
        hidden = nn.LayerNorm(epsilon=c.layer_norm_eps,
                              name="embeddings_norm")(word + pos + typ)
        if train and c.dropout_rate:
            hidden = nn.Dropout(c.dropout_rate, deterministic=False)(hidden)

        # HF extended attention mask: (1 - m) * large_negative on key axis
        attn_bias = (1.0 - attention_mask[:, None, None, :]) * jnp.asarray(
            jnp.finfo(jnp.float32).min, hidden.dtype)
        for i in range(c.num_hidden_layers):
            hidden = BertLayer(c, name=f"layer_{i}")(hidden, attn_bias, train)

        pooled = jnp.tanh(
            nn.Dense(c.hidden_size, name="pooler_dense")(hidden[:, 0]))
        if train and c.dropout_rate:
            pooled = nn.Dropout(c.dropout_rate, deterministic=False)(pooled)
        return nn.Dense(c.num_labels, name="classifier")(pooled)
