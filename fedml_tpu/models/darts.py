"""DARTS-lite: differentiable architecture search net for FedNAS.

Parity: reference ``model/cv/darts/`` (``model_search.py:377`` mixed-op cells
with architecture parameters alpha) used by FedNAS
(``simulation/mpi/fednas/``). Redesign: a compact search space — each
``MixedOp`` is a softmax(alpha)-weighted sum of {conv3x3, conv5x5, avgpool,
identity}. The bilevel search itself lives in ``algorithms/fednas.py``
(alpha steps on a val split alternating with weight steps, compiled into one
scan); ``derive_genotype`` reads off argmax(alpha) after search and
``DerivedNet`` retrains the fixed architecture (reference ``train.py``).
"""

from __future__ import annotations

from typing import Any, Dict, List

import flax.linen as nn
import jax
import jax.numpy as jnp

OP_NAMES = ("conv3", "conv5", "avgpool", "identity")


class MixedOp(nn.Module):
    channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        alpha = self.param("alpha", nn.initializers.zeros, (len(OP_NAMES),))
        w = jax.nn.softmax(alpha)
        outs = [
            nn.Conv(self.channels, (3, 3), dtype=self.dtype)(x),
            nn.Conv(self.channels, (5, 5), dtype=self.dtype)(x),
            nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME"),
            x,
        ]
        return sum(w[i] * o for i, o in enumerate(outs))


class SearchCell(nn.Module):
    channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Conv(self.channels, (1, 1), dtype=self.dtype)(x)
        h = nn.relu(nn.GroupNorm(num_groups=8, dtype=self.dtype)(h))
        a = MixedOp(self.channels, dtype=self.dtype)(h, train)
        b = MixedOp(self.channels, dtype=self.dtype)(nn.relu(a), train)
        return nn.relu(a + b)


class DARTSSearchNet(nn.Module):
    """Stacked search cells + classifier (reference Network in model_search.py)."""

    num_classes: int = 10
    channels: int = 16
    n_cells: int = 2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(self.channels, (3, 3), dtype=self.dtype)(x)
        for i in range(self.n_cells):
            x = SearchCell(self.channels * (2 ** i), dtype=self.dtype)(x, train)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)


class FixedOp(nn.Module):
    """One op from the search space, selected by genotype (reference
    ``model.py`` builds cells from the derived genotype the same way)."""

    channels: int
    op: str
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.op == "conv3":
            return nn.Conv(self.channels, (3, 3), dtype=self.dtype)(x)
        if self.op == "conv5":
            return nn.Conv(self.channels, (5, 5), dtype=self.dtype)(x)
        if self.op == "avgpool":
            return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        if self.op == "identity":
            return x
        raise ValueError(f"unknown op '{self.op}'")


class DerivedCell(nn.Module):
    channels: int
    ops: tuple  # (op_a, op_b)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Conv(self.channels, (1, 1), dtype=self.dtype)(x)
        h = nn.relu(nn.GroupNorm(num_groups=8, dtype=self.dtype)(h))
        a = FixedOp(self.channels, self.ops[0], dtype=self.dtype)(h, train)
        b = FixedOp(self.channels, self.ops[1], dtype=self.dtype)(nn.relu(a), train)
        return nn.relu(a + b)


class DerivedNet(nn.Module):
    """Fixed net built from a derived genotype — the retrain phase
    (reference ``train.py`` retrains ``NetworkCIFAR(genotype)``)."""

    genotype: tuple  # ((op_a, op_b), ...) one pair per cell
    num_classes: int = 10
    channels: int = 16
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(self.channels, (3, 3), dtype=self.dtype)(x)
        for i, ops in enumerate(self.genotype):
            x = DerivedCell(self.channels * (2 ** i), tuple(ops),
                            dtype=self.dtype)(x, train)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)


def genotype_to_cells(genotype: List[Dict[str, str]],
                      n_cells: int) -> tuple:
    """Group the flat ``derive_genotype`` output into per-cell op pairs for
    ``DerivedNet`` (paths look like ``params/SearchCell_i/MixedOp_j``)."""
    import re

    cells = [["identity", "identity"] for _ in range(n_cells)]
    for entry in genotype:
        m = re.search(r"SearchCell_(\d+)/MixedOp_(\d+)", entry["path"])
        if m:
            cells[int(m.group(1))][int(m.group(2))] = entry["op"]
    return tuple(tuple(c) for c in cells)


def derive_genotype(variables: Any) -> List[Dict[str, str]]:
    """argmax(alpha) per MixedOp — the reference's genotype derivation
    (model_search.py genotype())."""
    genotype = []

    def visit(path, leaf):
        names = [str(getattr(p, "key", p)) for p in path]
        if names and names[-1] == "alpha":
            genotype.append({
                "op": OP_NAMES[int(jnp.argmax(leaf))],
                "path": "/".join(names[:-1]),
            })
        return leaf

    jax.tree_util.tree_map_with_path(visit, variables)
    return genotype
