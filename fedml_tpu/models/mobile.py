"""Mobile (cross-device / Beehive) model builders.

Parity: reference ``model/mobile/mnn_lenet.py:35`` (``create_mnn_lenet5_model``
builds a LeNet-5 and saves a ``.mnn`` file for Android/iOS clients) and
``model/mobile/mnn_resnet.py:137`` (``create_mnn_resnet18_model``). The
reference depends on the MNN C++ runtime's Python bindings to author the
on-device file; this rebuild is TPU-native, so the deployable artifact is the
framework's own format-agnostic device payload (``cross_device/server.py``
blob codec): a single msgpack container holding an architecture manifest plus
the serialized init params. A phone-side runtime (MNN, TFLite, ...) plugs in
by translating the manifest; the SERVER side — which is all the reference
ships in-repo — round-trips this format unchanged.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

PyTree = Any


class MobileLeNet5(nn.Module):
    """LeNet-5 for on-device MNIST training (reference mnn_lenet.py:35:
    conv5x5(20) -> pool -> conv5x5(50) -> pool -> fc500 -> fc10)."""

    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        if x.ndim == 3:
            x = x[..., None]
        x = nn.Conv(20, (5, 5), padding="VALID", dtype=self.dtype)(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(50, (5, 5), padding="VALID", dtype=self.dtype)(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(500, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)


class MobileResNet18(nn.Module):
    """ImageNet-style ResNet-18 for on-device training (reference
    mnn_resnet.py:137); GroupNorm instead of BatchNorm so federated
    averaging of statistics is a non-issue on-device."""

    num_classes: int = 1000
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(nn.GroupNorm(num_groups=32, dtype=self.dtype)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for stage, filters in enumerate((64, 128, 256, 512)):
            for block in range(2):
                strides = (2, 2) if stage > 0 and block == 0 else (1, 1)
                residual = x
                y = nn.Conv(filters, (3, 3), strides, padding="SAME",
                            use_bias=False, dtype=self.dtype)(x)
                y = nn.relu(nn.GroupNorm(num_groups=32, dtype=self.dtype)(y))
                y = nn.Conv(filters, (3, 3), padding="SAME",
                            use_bias=False, dtype=self.dtype)(y)
                y = nn.GroupNorm(num_groups=32, dtype=self.dtype)(y)
                if residual.shape != y.shape:
                    residual = nn.Conv(filters, (1, 1), strides,
                                       use_bias=False, dtype=self.dtype)(residual)
                x = nn.relu(residual + y)
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)


def build_mobile_model_file(
    model_name: str,
    path: str,
    num_classes: Optional[int] = None,
    seed: int = 0,
) -> bytes:
    """Author the deployable device model artifact (reference
    ``create_mnn_lenet5_model``/``create_mnn_resnet18_model`` write ``.mnn``
    files here). The artifact = msgpack{manifest, params-blob}; returns the
    bytes and writes them to ``path``."""
    from ..comm.message import pack_payload
    from ..cross_device.server import encode_model_blob

    if model_name in ("lenet", "lenet5", "mnn_lenet"):
        model = MobileLeNet5(num_classes=num_classes or 10)
        sample = jnp.zeros((1, 28, 28, 1), jnp.float32)
    elif model_name in ("resnet18", "mnn_resnet"):
        model = MobileResNet18(num_classes=num_classes or 1000)
        sample = jnp.zeros((1, 224, 224, 3), jnp.float32)
    else:
        raise ValueError(f"unknown mobile model '{model_name}'")
    variables = model.init(jax.random.PRNGKey(seed), sample)
    artifact = pack_payload({
        "manifest": {
            "format": "fedml_tpu.mobile.v1",
            "arch": model_name,
            "num_classes": int(num_classes or
                               (10 if "lenet" in model_name else 1000)),
            "input_shape": list(sample.shape[1:]),
        },
        "params": encode_model_blob(variables),
    })
    with open(path, "wb") as f:
        f.write(artifact)
    return artifact


def load_mobile_model_file(path: str):
    """Server-side load of a device artifact: returns (model, variables) —
    the counterpart the Beehive aggregator evaluates with (reference
    ``fedml_aggregator.py:171`` loads the .mnn into the MNN runtime)."""
    from ..comm.message import unpack_payload
    from ..cross_device.server import decode_model_blob

    with open(path, "rb") as f:
        art = unpack_payload(f.read())
    man = art["manifest"]
    if "lenet" in man["arch"]:
        model = MobileLeNet5(num_classes=int(man["num_classes"]))
        sample = jnp.zeros((1, *man["input_shape"]), jnp.float32)
    else:
        model = MobileResNet18(num_classes=int(man["num_classes"]))
        sample = jnp.zeros((1, *man["input_shape"]), jnp.float32)
    template = model.init(jax.random.PRNGKey(0), sample)
    variables = decode_model_blob(art["params"], template)
    return model, variables
