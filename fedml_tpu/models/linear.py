"""Linear models. Parity: reference ``python/fedml/model/linear/lr.py``."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class LogisticRegression(nn.Module):
    """LR over flattened input (reference ``LogisticRegression`` lr.py).

    The reference applies no final activation (CrossEntropyLoss takes logits);
    same here — callers use softmax-CE on the output.
    """

    num_classes: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        return nn.Dense(self.num_classes, dtype=self.dtype, name="linear")(x)
