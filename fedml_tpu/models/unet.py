"""UNet-lite for federated segmentation (FedSeg).

Parity: reference FedSeg (``simulation/mpi/fedseg/``, DeepLab/UNet family in
``app/fedcv``). Output is per-pixel logits flattened to (B, H*W, C) so the
per-token masked CE/accuracy path (ops/losses.py, shared with the LM models)
applies unchanged — segmentation labels ride the packing pipeline as (H*W,)
token targets.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class UNetLite(nn.Module):
    num_classes: int = 2
    base: int = 16
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)

        def block(h, ch):
            h = nn.Conv(ch, (3, 3), dtype=self.dtype)(h)
            h = nn.relu(nn.GroupNorm(num_groups=min(8, ch), dtype=self.dtype)(h))
            return h

        e1 = block(x, self.base)                                   # H
        e2 = block(nn.max_pool(e1, (2, 2), strides=(2, 2)), self.base * 2)  # H/2
        bott = block(nn.max_pool(e2, (2, 2), strides=(2, 2)), self.base * 4)  # H/4
        u2 = nn.ConvTranspose(self.base * 2, (2, 2), strides=(2, 2), dtype=self.dtype)(bott)
        d2 = block(jnp.concatenate([u2, e2], axis=-1), self.base * 2)
        u1 = nn.ConvTranspose(self.base, (2, 2), strides=(2, 2), dtype=self.dtype)(d2)
        d1 = block(jnp.concatenate([u1, e1], axis=-1), self.base)
        logits = nn.Conv(self.num_classes, (1, 1), dtype=self.dtype)(d1)
        B, H, W, C = logits.shape
        return logits.reshape(B, H * W, C)
