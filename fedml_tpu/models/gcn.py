"""Graph models for federated graph learning (FedGraphNN parity).

Parity: reference ``app/fedgraphnn`` (7 graph task families; molecule
property prediction is the flagship — MoleculeNet with GCN/GAT/GraphSAGE).
Redesign for TPU: graphs are batched to a fixed node count with dense
normalized adjacency — graph conv is then two batched matmuls (A_hat @ X @ W)
that tile straight onto the MXU, instead of scatter/gather message passing
(sparse ops are TPU-hostile). The data pipeline ships each graph as one
tensor ``[node_features | adjacency]`` of shape (N, F + N) so graph datasets
ride the standard rectangular packing.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


def split_graph_tensor(x: jnp.ndarray, num_nodes: int):
    """(B, N, F+N) -> (features (B, N, F), adj (B, N, N))."""
    feats = x[..., : x.shape[-1] - num_nodes]
    adj = x[..., x.shape[-1] - num_nodes:]
    return feats, adj


def normalize_adjacency(adj: jnp.ndarray) -> jnp.ndarray:
    """Symmetric GCN normalization D^-1/2 (A + I) D^-1/2 (Kipf & Welling)."""
    n = adj.shape[-1]
    a_hat = adj + jnp.eye(n, dtype=adj.dtype)
    deg = a_hat.sum(axis=-1)
    d_inv_sqrt = 1.0 / jnp.sqrt(jnp.maximum(deg, 1e-6))
    return a_hat * d_inv_sqrt[..., :, None] * d_inv_sqrt[..., None, :]


class GraphConv(nn.Module):
    features: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, h, a_hat):
        h = nn.Dense(self.features, use_bias=False, dtype=self.dtype)(h)
        return jnp.einsum("bij,bjf->bif", a_hat, h)


class GCNGraphClassifier(nn.Module):
    """Graph-level classifier: GCN layers -> mean pool -> dense head.

    Input: packed graph tensor (B, N, F+N) (see split_graph_tensor).
    """

    num_classes: int = 2
    num_nodes: int = 16
    hidden: int = 64
    n_layers: int = 2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        feats, adj = split_graph_tensor(x.astype(self.dtype), self.num_nodes)
        a_hat = normalize_adjacency(adj)
        h = feats
        for _ in range(self.n_layers):
            h = nn.relu(GraphConv(self.hidden, dtype=self.dtype)(h, a_hat))
        pooled = h.mean(axis=1)
        return nn.Dense(self.num_classes, dtype=self.dtype)(pooled)
