"""Graph models for federated graph learning (FedGraphNN parity).

Parity: reference ``app/fedgraphnn`` (7 graph task families; molecule
property prediction is the flagship — MoleculeNet with GCN/GAT/GraphSAGE).
Redesign for TPU: graphs are batched to a fixed node count with dense
normalized adjacency — graph conv is then two batched matmuls (A_hat @ X @ W)
that tile straight onto the MXU, instead of scatter/gather message passing
(sparse ops are TPU-hostile). The data pipeline ships each graph as one
tensor ``[node_features | adjacency]`` of shape (N, F + N) so graph datasets
ride the standard rectangular packing.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


def split_graph_tensor(x: jnp.ndarray, num_nodes: int):
    """(B, N, F+N) -> (features (B, N, F), adj (B, N, N))."""
    feats = x[..., : x.shape[-1] - num_nodes]
    adj = x[..., x.shape[-1] - num_nodes:]
    return feats, adj


def normalize_adjacency(adj: jnp.ndarray) -> jnp.ndarray:
    """Symmetric GCN normalization D^-1/2 (A + I) D^-1/2 (Kipf & Welling)."""
    n = adj.shape[-1]
    a_hat = adj + jnp.eye(n, dtype=adj.dtype)
    deg = a_hat.sum(axis=-1)
    d_inv_sqrt = 1.0 / jnp.sqrt(jnp.maximum(deg, 1e-6))
    return a_hat * d_inv_sqrt[..., :, None] * d_inv_sqrt[..., None, :]


class GraphConv(nn.Module):
    features: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, h, a_hat):
        h = nn.Dense(self.features, use_bias=False, dtype=self.dtype)(h)
        return jnp.einsum("bij,bjf->bif", a_hat, h)


def _gcn_encode(mod: nn.Module, x) -> jnp.ndarray:
    """Shared GCN encoder: unpack -> normalize -> n_layers of conv+relu.

    A plain function called from each task model's ``@nn.compact`` body so
    the GraphConv layers bind to the caller's scope (auto-named
    ``GraphConv_i`` exactly as before factoring)."""
    feats, adj = split_graph_tensor(x.astype(mod.dtype), mod.num_nodes)
    a_hat = normalize_adjacency(adj)
    h = feats
    for _ in range(mod.n_layers):
        h = nn.relu(GraphConv(mod.hidden, dtype=mod.dtype)(h, a_hat))
    return h


class GCNGraphClassifier(nn.Module):
    """Graph-level classifier: GCN layers -> mean pool -> dense head.

    Input: packed graph tensor (B, N, F+N) (see split_graph_tensor).
    """

    num_classes: int = 2
    num_nodes: int = 16
    hidden: int = 64
    n_layers: int = 2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        pooled = _gcn_encode(self, x).mean(axis=1)
        return nn.Dense(self.num_classes, dtype=self.dtype)(pooled)


class GCNNodeClassifier(nn.Module):
    """Per-node classifier — the FedGraphNN node-level task family
    (reference ``app/fedgraphnn/ego_networks_node_clf``). Output
    (B, N, num_classes); labels (B, N) ride the shared masked CE (the
    per-example mask broadcasts over the node dim)."""

    num_classes: int = 2
    num_nodes: int = 16
    hidden: int = 64
    n_layers: int = 2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = _gcn_encode(self, x)
        return nn.Dense(self.num_classes, dtype=self.dtype, name="node_head")(h)


class GCNLinkPredictor(nn.Module):
    """Link prediction — the FedGraphNN link-level task family (reference
    ``app/fedgraphnn/ego_networks_link_pred``, ``subgraph_link_pred``).

    Encodes nodes from the OBSERVED (partially-hidden) graph, scores every
    ordered pair with a bilinear decoder, and returns 2-class logits
    (no-link/link) shaped (B, N*N, 2) so pairwise labels (B, N*N) ride the
    shared masked CE."""

    num_nodes: int = 16
    hidden: int = 64
    n_layers: int = 2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = _gcn_encode(self, x)
        # bilinear pair scores z_i^T W z_j (one matmul chain, MXU-friendly)
        w = self.param("bilinear", nn.initializers.lecun_normal(),
                       (self.hidden, self.hidden), self.dtype)
        scores = jnp.einsum("bif,fg,bjg->bij", h, w, h)
        B = scores.shape[0]
        flat = scores.reshape(B, self.num_nodes * self.num_nodes, 1)
        bias = self.param("link_bias", nn.initializers.zeros, (1,), self.dtype)
        # [-(s+b), +(s+b)]: a 2-class head driven by one score
        return jnp.concatenate([-(flat + bias), flat + bias], axis=-1)


class GCNGraphRegressor(nn.Module):
    """Graph-level regression — the FedGraphNN regression family (reference
    ``app/fedgraphnn/moleculenet_graph_reg``: ESOL/FreeSolv/Lipophilicity).
    Output (B, 1) continuous; pairs with ``loss_kind='mse'``."""

    num_nodes: int = 16
    hidden: int = 64
    n_layers: int = 2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        pooled = _gcn_encode(self, x).mean(axis=1)
        return nn.Dense(1, dtype=self.dtype, name="reg_head")(pooled)
