"""Graph models for federated graph learning (FedGraphNN parity).

Parity: reference ``app/fedgraphnn`` (7 graph task families; molecule
property prediction is the flagship — MoleculeNet with GCN/GAT/GraphSAGE).
Redesign for TPU: graphs are batched to a fixed node count with dense
normalized adjacency — graph conv is then two batched matmuls (A_hat @ X @ W)
that tile straight onto the MXU, instead of scatter/gather message passing
(sparse ops are TPU-hostile). The data pipeline ships each graph as one
tensor ``[node_features | adjacency]`` of shape (N, F + N) so graph datasets
ride the standard rectangular packing.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


def split_graph_tensor(x: jnp.ndarray, num_nodes: int):
    """(B, N, F+N) -> (features (B, N, F), adj (B, N, N))."""
    feats = x[..., : x.shape[-1] - num_nodes]
    adj = x[..., x.shape[-1] - num_nodes:]
    return feats, adj


def normalize_adjacency(adj: jnp.ndarray) -> jnp.ndarray:
    """Symmetric GCN normalization D^-1/2 (A + I) D^-1/2 (Kipf & Welling)."""
    n = adj.shape[-1]
    a_hat = adj + jnp.eye(n, dtype=adj.dtype)
    deg = a_hat.sum(axis=-1)
    d_inv_sqrt = 1.0 / jnp.sqrt(jnp.maximum(deg, 1e-6))
    return a_hat * d_inv_sqrt[..., :, None] * d_inv_sqrt[..., None, :]


class GraphConv(nn.Module):
    features: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, h, a_hat):
        h = nn.Dense(self.features, use_bias=False, dtype=self.dtype)(h)
        return jnp.einsum("bij,bjf->bif", a_hat, h)


def _gcn_encode(mod: nn.Module, x) -> jnp.ndarray:
    """Shared GCN encoder: unpack -> normalize -> n_layers of conv+relu.

    A plain function called from each task model's ``@nn.compact`` body so
    the GraphConv layers bind to the caller's scope (auto-named
    ``GraphConv_i`` exactly as before factoring)."""
    feats, adj = split_graph_tensor(x.astype(mod.dtype), mod.num_nodes)
    a_hat = normalize_adjacency(adj)
    h = feats
    for _ in range(mod.n_layers):
        h = nn.relu(GraphConv(mod.hidden, dtype=mod.dtype)(h, a_hat))
    return h


class GCNGraphClassifier(nn.Module):
    """Graph-level classifier: GCN layers -> mean pool -> dense head.

    Input: packed graph tensor (B, N, F+N) (see split_graph_tensor).
    """

    num_classes: int = 2
    num_nodes: int = 16
    hidden: int = 64
    n_layers: int = 2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        pooled = _gcn_encode(self, x).mean(axis=1)
        return nn.Dense(self.num_classes, dtype=self.dtype)(pooled)


class GCNNodeClassifier(nn.Module):
    """Per-node classifier — the FedGraphNN node-level task family
    (reference ``app/fedgraphnn/ego_networks_node_clf``). Output
    (B, N, num_classes); labels (B, N) ride the shared masked CE (the
    per-example mask broadcasts over the node dim)."""

    num_classes: int = 2
    num_nodes: int = 16
    hidden: int = 64
    n_layers: int = 2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = _gcn_encode(self, x)
        return nn.Dense(self.num_classes, dtype=self.dtype, name="node_head")(h)


class GCNLinkPredictor(nn.Module):
    """Link prediction — the FedGraphNN link-level task family (reference
    ``app/fedgraphnn/ego_networks_link_pred``, ``subgraph_link_pred``).

    Encodes nodes from the OBSERVED (partially-hidden) graph, scores every
    ordered pair with a bilinear decoder, and returns 2-class logits
    (no-link/link) shaped (B, N*N, 2) so pairwise labels (B, N*N) ride the
    shared masked CE."""

    num_nodes: int = 16
    hidden: int = 64
    n_layers: int = 2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = _gcn_encode(self, x)
        # bilinear pair scores z_i^T W z_j (one matmul chain, MXU-friendly)
        w = self.param("bilinear", nn.initializers.lecun_normal(),
                       (self.hidden, self.hidden), self.dtype)
        scores = jnp.einsum("bif,fg,bjg->bij", h, w, h)
        B = scores.shape[0]
        flat = scores.reshape(B, self.num_nodes * self.num_nodes, 1)
        bias = self.param("link_bias", nn.initializers.zeros, (1,), self.dtype)
        # [-(s+b), +(s+b)]: a 2-class head driven by one score
        return jnp.concatenate([-(flat + bias), flat + bias], axis=-1)


class RGCNRelationPredictor(nn.Module):
    """Relation-type prediction over typed edges — the FedGraphNN
    subgraph-relation-prediction family (reference
    ``app/fedgraphnn/subgraph_relation_pred/model/rgcn.py``: RGCN encoder +
    DistMult decoder over (head, relation, tail) triples).

    TPU redesign: typed edges ship as R dense adjacency slabs packed after
    the features — input (B, N, F + R*N) — so the R-GCN layer is one einsum
    over [R, N, N] x [N, H] x per-relation weights (batched MXU matmuls,
    no scatter). The DistMult decoder scores every ordered pair against
    every relation embedding; a learned "no-relation" null class makes it a
    dense (R+1)-way classification over all pairs, (B, N*N, R+1), riding
    the shared masked CE exactly like link prediction."""

    num_relations: int = 4
    num_nodes: int = 16
    hidden: int = 64
    n_layers: int = 2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        n, r = self.num_nodes, self.num_relations
        f = x.shape[-1] - r * n
        feats = x[..., :f]
        adjs = x[..., f:].reshape(x.shape[0], n, r, n).transpose(0, 2, 1, 3)
        # per-relation row normalization (RGCN's 1/c_{i,r})
        deg = jnp.maximum(adjs.sum(axis=-1, keepdims=True), 1.0)
        adjs = adjs / deg
        h = nn.Dense(self.hidden, dtype=self.dtype, name="embed")(feats)
        for i in range(self.n_layers):
            w_self = nn.Dense(self.hidden, use_bias=False, dtype=self.dtype,
                              name=f"self_{i}")(h)
            w_rel = self.param(f"rel_w_{i}", nn.initializers.lecun_normal(),
                               (r, self.hidden, self.hidden), jnp.float32)
            # sum_r A_r @ h @ W_r : einsum keeps it one fused contraction
            msgs = jnp.einsum("brij,bjh,rhk->bik", adjs, h,
                              w_rel.astype(self.dtype))
            h = nn.relu(w_self + msgs)
        # DistMult: score(i, rel, j) = sum_h z_i * e_rel * z_j
        rel_emb = self.param("rel_emb", nn.initializers.lecun_normal(),
                             (r, self.hidden), jnp.float32)
        scores = jnp.einsum("bih,rh,bjh->bijr", h, rel_emb.astype(self.dtype), h)
        null = self.param("null_bias", nn.initializers.zeros, (1,), jnp.float32)
        b = scores.shape[0]
        null_col = jnp.broadcast_to(null.astype(self.dtype), (b, n, n, 1))
        logits = jnp.concatenate([null_col, scores], axis=-1)  # class 0 = none
        return logits.reshape(b, n * n, r + 1)


class BipartiteGCNRecommender(nn.Module):
    """Recsys subgraph link prediction — the FedGraphNN recommendation
    family (reference ``app/fedgraphnn/recsys_subgraph_link_pred``: GCN/GAT/
    SAGE encoders, MSE on user-item rating logits, MAE/RMSE metrics; data =
    per-client user-item subgraphs from ciao/epinions).

    TPU redesign: a fixed-size bipartite subgraph (U users + I items = N
    nodes) ships as the standard packed graph tensor (B, N, F+N); the GCN
    encoder runs on the symmetric interaction graph (edge weights = shown
    ratings) and a bilinear decoder predicts the dense U x I rating block,
    (B, U*I) float — rating-matrix completion with masked MSE (the
    reference's observed-edge MSE made rectangular: every cell carries its
    true rating and only a shown subset rides the adjacency)."""

    num_users: int = 8
    num_items: int = 8
    hidden: int = 64
    n_layers: int = 2
    dtype: jnp.dtype = jnp.float32

    @property
    def num_nodes(self) -> int:
        return self.num_users + self.num_items

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = _gcn_encode(self, x)
        # skip path from raw node features: graph convolution averages
        # neighborhoods, which dilutes each node's OWN latent factors —
        # exactly the signal a rating decoder needs
        feats, _ = split_graph_tensor(x.astype(self.dtype), self.num_nodes)
        h = h + nn.Dense(self.hidden, dtype=self.dtype, name="skip")(feats)
        zu = h[:, : self.num_users]                     # (B, U, H)
        zi = h[:, self.num_users:]                      # (B, I, H)
        w = self.param("rating_w", nn.initializers.lecun_normal(),
                       (self.hidden, self.hidden), self.dtype)
        scores = jnp.einsum("buf,fg,big->bui", zu, w, zi)
        bias = self.param("rating_bias", nn.initializers.zeros, (1,), self.dtype)
        b = scores.shape[0]
        return (scores + bias).reshape(b, self.num_users * self.num_items)


class GCNGraphRegressor(nn.Module):
    """Graph-level regression — the FedGraphNN regression family (reference
    ``app/fedgraphnn/moleculenet_graph_reg``: ESOL/FreeSolv/Lipophilicity).
    Output (B, 1) continuous; pairs with ``loss_kind='mse'``."""

    num_nodes: int = 16
    hidden: int = 64
    n_layers: int = 2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        pooled = _gcn_encode(self, x).mean(axis=1)
        return nn.Dense(1, dtype=self.dtype, name="reg_head")(pooled)
