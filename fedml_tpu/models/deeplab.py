"""DeepLabV3+ for federated semantic segmentation (FedSeg).

Parity: reference ``app/fedcv/image_segmentation/model/deeplabV3_plus.py``
(backbone with output-stride 16, ASPP with atrous rates (6, 12, 18) + image
pooling, and the V3+ decoder that fuses 4x-upsampled ASPP features with
1x1-reduced low-level backbone features). This is the architecture-class
upgrade over ``models/unet.py``'s UNetLite.

TPU-first design notes:
- atrous convs are ``nn.Conv(kernel_dilation=r)`` — XLA lowers dilated
  convs natively on the MXU; no im2col tricks needed at these channel
  widths (ASPP runs at 256 channels where the MXU is well fed).
- bilinear upsampling is ``jax.image.resize`` (static shapes, fuses fine);
  the reference uses ``F.interpolate(align_corners=True)``.
- GroupNorm everywhere (the standard FL norm fix — the reference uses
  SyncBN inside silos; our SyncBN variant is available via
  ``models/resnet.py`` but per-client GN is the right default for FedAvg).
- output is (B, H*W, num_classes) token logits like UNetLite, so the
  shared per-token masked CE path (``ops/losses.py``) and the packing
  pipeline apply unchanged.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


def _gn(ch: int, dtype) -> nn.Module:
    # largest group count <=8 that divides ch, so scaled-up base/aspp_ch
    # values that aren't multiples of 8 still construct
    g = next(g for g in range(min(8, ch), 0, -1) if ch % g == 0)
    return nn.GroupNorm(num_groups=g, dtype=dtype)


class _ResBlock(nn.Module):
    ch: int
    strides: int = 1
    dilation: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        r = x
        y = nn.Conv(self.ch, (3, 3), (self.strides, self.strides),
                    kernel_dilation=(self.dilation, self.dilation),
                    padding="SAME", use_bias=False, dtype=self.dtype)(x)
        y = nn.relu(_gn(self.ch, self.dtype)(y))
        y = nn.Conv(self.ch, (3, 3), kernel_dilation=(self.dilation, self.dilation),
                    padding="SAME", use_bias=False, dtype=self.dtype)(y)
        y = _gn(self.ch, self.dtype)(y)
        if r.shape != y.shape:
            r = nn.Conv(self.ch, (1, 1), (self.strides, self.strides),
                        use_bias=False, dtype=self.dtype)(r)
            r = _gn(self.ch, self.dtype)(r)
        return nn.relu(y + r)


class ASPP(nn.Module):
    """Atrous Spatial Pyramid Pooling (reference deeplabV3_plus.py ASPP:
    1x1 branch, three atrous 3x3 branches, global image pooling; concat +
    1x1 projection)."""

    ch: int = 64
    rates: Sequence[int] = (2, 4, 6)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h, w = x.shape[1], x.shape[2]
        branches = [nn.relu(_gn(self.ch, self.dtype)(
            nn.Conv(self.ch, (1, 1), use_bias=False, dtype=self.dtype)(x)))]
        for r in self.rates:
            b = nn.Conv(self.ch, (3, 3), kernel_dilation=(r, r),
                        padding="SAME", use_bias=False, dtype=self.dtype)(x)
            branches.append(nn.relu(_gn(self.ch, self.dtype)(b)))
        # image-level pooling branch
        gp = jnp.mean(x, axis=(1, 2), keepdims=True)
        gp = nn.relu(_gn(self.ch, self.dtype)(
            nn.Conv(self.ch, (1, 1), use_bias=False, dtype=self.dtype)(gp)))
        gp = jnp.broadcast_to(gp, (x.shape[0], h, w, self.ch))
        y = jnp.concatenate(branches + [gp], axis=-1)
        y = nn.Conv(self.ch, (1, 1), use_bias=False, dtype=self.dtype)(y)
        return nn.relu(_gn(self.ch, self.dtype)(y))


class DeepLabV3Plus(nn.Module):
    """Compact DeepLabV3+: GN-ResNet backbone at output stride 4 for small
    federated imagery, ASPP, and the V3+ low-level fusion decoder. The
    reference runs OS 16 with atrous rates (6, 12, 18) on 512px inputs;
    at 32-64px an 8x8 ASPP grid needs proportionally smaller rates —
    scale ``aspp_rates``/``base``/``aspp_ch`` up for real-resolution
    deployments."""

    num_classes: int = 2
    base: int = 16
    aspp_ch: int = 64
    aspp_rates: Sequence[int] = (2, 4, 6)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False) -> jnp.ndarray:
        x = x.astype(self.dtype)
        B, H, W, _ = x.shape
        # stem + stage 1 (stride 1): low-level features for the decoder
        y = nn.Conv(self.base, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(x)
        y = nn.relu(_gn(self.base, self.dtype)(y))
        y = _ResBlock(self.base, dtype=self.dtype)(y)
        low = y                                          # H x W x base
        # stage 2 (stride 2), stage 3 (stride 2) -> OS 4... OS 8 total
        y = _ResBlock(self.base * 2, strides=2, dtype=self.dtype)(y)
        y = _ResBlock(self.base * 2, dtype=self.dtype)(y)
        y = _ResBlock(self.base * 4, strides=2, dtype=self.dtype)(y)
        # dilated stage instead of further striding (atrous backbone tail)
        y = _ResBlock(self.base * 4, dilation=2, dtype=self.dtype)(y)
        y = ASPP(self.aspp_ch, rates=self.aspp_rates,
                 dtype=self.dtype)(y)                     # H/4 x W/4
        # decoder: upsample ASPP to low-level resolution, fuse, refine
        y = jax.image.resize(y, (B, H, W, y.shape[-1]), "bilinear")
        low = nn.relu(_gn(48, self.dtype)(
            nn.Conv(48, (1, 1), use_bias=False, dtype=self.dtype)(low)))
        y = jnp.concatenate([y, low], axis=-1)
        y = nn.Conv(self.aspp_ch, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(y)
        y = nn.relu(_gn(self.aspp_ch, self.dtype)(y))
        y = nn.Conv(self.aspp_ch, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(y)
        y = nn.relu(_gn(self.aspp_ch, self.dtype)(y))
        logits = nn.Conv(self.num_classes, (1, 1), dtype=self.dtype)(y)
        return logits.reshape(B, H * W, self.num_classes)
