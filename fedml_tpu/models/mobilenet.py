"""MobileNet v1. Parity: reference ``model/cv/mobilenet.py`` (the
BENCHMARK_MPI.md MobileNet rows). GroupNorm default for FL (see resnet.py)."""

from __future__ import annotations

from functools import partial

import flax.linen as nn
import jax.numpy as jnp


class DepthwiseSeparable(nn.Module):
    filters: int
    strides: int
    norm: object
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        in_ch = x.shape[-1]
        x = nn.Conv(in_ch, (3, 3), (self.strides, self.strides), padding="SAME",
                    feature_group_count=in_ch, use_bias=False, dtype=self.dtype)(x)
        x = self.norm()(x)
        x = nn.relu(x)
        x = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype)(x)
        x = self.norm()(x)
        return nn.relu(x)


class MobileNetV1(nn.Module):
    num_classes: int = 10
    width: float = 1.0
    small_input: bool = True  # CIFAR-style 32x32
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(nn.GroupNorm, num_groups=None, group_size=8, dtype=self.dtype)
        c = lambda ch: max(8, int(ch * self.width))  # noqa: E731
        x = x.astype(self.dtype)
        stem_stride = 1 if self.small_input else 2
        x = nn.Conv(c(32), (3, 3), (stem_stride, stem_stride), padding="SAME",
                    use_bias=False, dtype=self.dtype)(x)
        x = norm()(x)
        x = nn.relu(x)
        cfg = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
               (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2), (1024, 1)]
        for filters, strides in cfg:
            x = DepthwiseSeparable(c(filters), strides, norm, self.dtype)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)
