"""CNNs. Parity: reference ``python/fedml/model/cv/cnn.py:142`` —
``CNN_DropOut`` (the FedAvg-paper MNIST/FEMNIST CNN: 2x conv3x3 + maxpool +
dropout + 128-dense head) and ``CNN_OriginalFedAvg`` (conv5x5 pair, 512-dense,
used for MNIST/fed-EMNIST in the reference benchmark table)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class CNNDropOut(nn.Module):
    """FedAvg-paper CNN with dropout (reference ``CNN_DropOut``)."""

    num_classes: int = 62
    only_digits: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False, rngs=None):
        # x: (B, 28, 28, 1)
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), padding="VALID", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3), padding="VALID", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(0.25, deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(10 if self.only_digits else self.num_classes, dtype=self.dtype)(x)


class CNNOriginalFedAvg(nn.Module):
    """McMahan et al. CNN (reference ``CNN_OriginalFedAvg``): two 5x5 convs
    (32, 64) each followed by 2x2 maxpool, then 512-dense."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(512, dtype=self.dtype)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)
