"""Device-mesh construction and axis-name conventions.

TPU-native replacement for the reference's process-group setup
(``simulation/nccl/base_framework/common.py:114-133`` ``init_ddp`` and
``cross_silo/hierarchical/process_group_manager.py``): instead of ranks in a
process group, devices live in a named ``jax.sharding.Mesh`` and every
collective is expressed against a named axis.

Axis conventions (a mesh uses a subset):
  - ``client``: FL client shards — the Parrot-TPU simulator axis. The
    reference's "client parallelism" (workers each simulating a client subset,
    ``mpi/fedavg/FedAvgAPI.py:126``) maps here.
  - ``data``:   batch data parallelism (reference: DDP inside silos).
  - ``model``:  tensor parallelism (not in reference; first-class here).
  - ``pipe``:   pipeline stages (SplitNN's layer split maps here).
  - ``seq``:    sequence/context parallelism (ring attention).
  - ``expert``: expert parallelism (MoE).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_CLIENT = "client"
AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_PIPE = "pipe"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"

_DEFAULT_MESH: Optional[Mesh] = None


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh spec: axis name -> size; -1 means 'absorb the rest'."""

    axes: Tuple[Tuple[str, int], ...] = ((AXIS_CLIENT, -1),)

    @classmethod
    def from_dict(cls, d: Dict[str, int]) -> "MeshConfig":
        return cls(axes=tuple(d.items()))

    def resolve(self, n_devices: int) -> Tuple[Tuple[str, int], ...]:
        sizes = [s for _, s in self.axes]
        n_wild = sum(1 for s in sizes if s == -1)
        if n_wild > 1:
            raise ValueError("at most one mesh axis may be -1")
        fixed = math.prod(s for s in sizes if s != -1)
        if n_wild == 1:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes = [n_devices // fixed if s == -1 else s for s in sizes]
        elif fixed != n_devices:
            raise ValueError(f"mesh wants {fixed} devices, have {n_devices}")
        return tuple((name, size) for (name, _), size in zip(self.axes, sizes))


def create_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_names: Optional[Sequence[str]] = None,
    axis_sizes: Optional[Sequence[int]] = None,
) -> Mesh:
    """Build a named Mesh over the given (default: all) devices.

    Either pass a MeshConfig, or (axis_names, axis_sizes) directly. Device
    order follows ``jax.devices()``, which on TPU enumerates chips so that
    adjacent indices are ICI neighbors — keeping high-traffic axes innermost
    (last) rides the fastest links.
    """
    devices = list(devices if devices is not None else jax.devices())
    if config is None:
        if axis_names is None:
            config = MeshConfig()
        else:
            config = MeshConfig(axes=tuple(zip(axis_names, axis_sizes or [-1] * len(axis_names))))
    resolved = config.resolve(len(devices))
    names = tuple(n for n, _ in resolved)
    shape = tuple(s for _, s in resolved)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, names)


def set_default_mesh(mesh: Optional[Mesh]) -> None:
    global _DEFAULT_MESH
    _DEFAULT_MESH = mesh


def get_default_mesh() -> Mesh:
    """Return the process-wide default mesh, creating a 1-axis client mesh lazily."""
    global _DEFAULT_MESH
    if _DEFAULT_MESH is None:
        _DEFAULT_MESH = create_mesh()
    return _DEFAULT_MESH


def maybe_initialize_distributed(args=None) -> None:
    """Multi-host init: TPU replacement for the reference's MPI/torchrun world
    bootstrap (``fedml/__init__.py:90-99`` / ``dist_trainer_launcher.py``).

    On a pod slice each host calls ``jax.distributed.initialize()``; on a
    single host (or when env vars are absent) this is a no-op.
    """
    import os

    if os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get("COORDINATOR_ADDRESS"):
        coord = os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ["COORDINATOR_ADDRESS"]
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(os.environ.get("JAX_NUM_PROCESSES", os.environ.get("WORLD_SIZE", 1))),
            process_id=int(os.environ.get("JAX_PROCESS_ID", os.environ.get("RANK", 0))),
        )
