"""NamedSharding helpers for pytrees.

Replaces the reference's explicit tensor shipping (state_dict pickles over
MPI/gRPC, SURVEY.md §2.1) with sharding annotations: XLA inserts the
collectives; we only declare layouts.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_along(mesh: Mesh, axis_name: str, dim: int = 0) -> NamedSharding:
    """Sharding that splits array dimension ``dim`` across mesh axis ``axis_name``."""
    spec = [None] * (dim + 1)
    spec[dim] = axis_name
    return NamedSharding(mesh, P(*spec))


def shard_leading_axis(tree: Any, mesh: Mesh, axis_name: str) -> Any:
    """Place every leaf with its leading dim split across ``axis_name``.

    Used for stacked per-client state (leading client axis) — the TPU
    equivalent of the reference scattering client subsets to MPI workers
    (``nccl/base_framework/Server.py:109-122`` client_schedule + broadcast).
    """
    sharding = shard_along(mesh, axis_name, dim=0)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)


def replicate_tree(tree: Any, mesh: Optional[Mesh] = None) -> Any:
    """Replicate every leaf on all mesh devices (server/global state)."""
    if mesh is None:
        from .mesh import get_default_mesh

        mesh = get_default_mesh()
    sharding = replicated(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)
