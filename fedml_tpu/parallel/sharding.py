"""NamedSharding helpers for pytrees.

Replaces the reference's explicit tensor shipping (state_dict pickles over
MPI/gRPC, SURVEY.md §2.1) with sharding annotations: XLA inserts the
collectives; we only declare layouts.

This module is the single spec layer shared by the data-parallel trainer
(Megatron path rules, :func:`transformer_param_specs`) and the federated
simulator's 2-D ``client`` × ``model`` mesh (shape-driven inference,
:func:`auto_partition_specs`) — Cheetah-style training and federated rounds
place model state through the same helpers.
"""

from __future__ import annotations

import warnings
from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_along(mesh: Mesh, axis_name: str, dim: int = 0) -> NamedSharding:
    """Sharding that splits array dimension ``dim`` across mesh axis ``axis_name``.

    Validates against the mesh up front: an unknown axis name or a negative
    ``dim`` would otherwise produce a ``PartitionSpec`` that only fails (with
    an opaque GSPMD error, or silently out-of-range) once an array is placed.
    """
    if axis_name not in mesh.axis_names:
        raise ValueError(
            f"shard_along: mesh has no axis {axis_name!r} "
            f"(mesh axes: {tuple(mesh.axis_names)})")
    if not isinstance(dim, int) or dim < 0:
        raise ValueError(
            f"shard_along: dim must be a non-negative int (array dimension "
            f"to split), got {dim!r}")
    spec = [None] * (dim + 1)
    spec[dim] = axis_name
    return NamedSharding(mesh, P(*spec))


def shard_leading_axis(tree: Any, mesh: Mesh, axis_name: str) -> Any:
    """Place every leaf with its leading dim split across ``axis_name``.

    Used for stacked per-client state (leading client axis) — the TPU
    equivalent of the reference scattering client subsets to MPI workers
    (``nccl/base_framework/Server.py:109-122`` client_schedule + broadcast).
    """
    sharding = shard_along(mesh, axis_name, dim=0)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)


def replicate_tree(tree: Any, mesh: Optional[Mesh] = None) -> Any:
    """Replicate every leaf on all mesh devices (server/global state)."""
    if mesh is None:
        from .mesh import get_default_mesh

        mesh = get_default_mesh()
    sharding = replicated(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)


def _leaf_path_str(path) -> str:
    return jax.tree_util.keystr(path)


def auto_partition_specs(
    tree: Any,
    axis_name: str,
    axis_size: int,
    *,
    overrides: Optional[dict] = None,
    warn: bool = True,
) -> Any:
    """Shape-driven per-leaf ``PartitionSpec`` inference for one mesh axis.

    Largest-divisible-dim rule: each leaf shards the largest dimension whose
    extent is divisible by ``axis_size`` (ties broken toward the lowest dim
    index, so the rule is deterministic for equal extents). Leaves with no
    such dimension — or scalars — fall back to replicated (``P()``); all
    fallback paths are collected into ONE ``UserWarning`` rather than a
    per-leaf storm.

    ``overrides`` maps a path substring (matched against
    ``jax.tree_util.keystr`` of the leaf path; patterns tried in sorted order,
    first match wins) to either a dim index to shard or ``None`` to pin the
    leaf replicated. An override naming an out-of-range or indivisible dim
    raises — a silent bad layout would surface as a GSPMD error far from the
    config knob that caused it.

    Leaf order is the pytree's own deterministic flatten order; two calls on
    the same structure always yield identical spec trees (graftcheck's
    determinism fixture pins this).
    """
    if axis_size < 1:
        raise ValueError(f"auto_partition_specs: axis_size must be >= 1, "
                         f"got {axis_size}")
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    sorted_pats = sorted(overrides) if overrides else ()
    specs = []
    fallbacks = []
    for path, leaf in flat:
        pstr = _leaf_path_str(path)
        # .shape first: leaves may be ShapeDtypeStructs or tracers (the
        # simulator infers update-stack specs at trace time)
        shape = (tuple(leaf.shape) if hasattr(leaf, "shape")
                 else tuple(np.shape(leaf)))
        spec = None
        for pat in sorted_pats:
            if pat in pstr:
                dim = overrides[pat]
                if dim is None:
                    spec = P()
                    break
                if not isinstance(dim, int) or dim < 0 or dim >= len(shape):
                    raise ValueError(
                        f"auto_partition_specs: override {pat!r} names dim "
                        f"{dim!r} but leaf {pstr} has shape {shape}")
                if shape[dim] % axis_size != 0:
                    raise ValueError(
                        f"auto_partition_specs: override {pat!r} shards dim "
                        f"{dim} of leaf {pstr} (shape {shape}) but "
                        f"{shape[dim]} is not divisible by axis size "
                        f"{axis_size}")
                spec = P(*([None] * dim + [axis_name]))
                break
        if spec is None:
            cands = [d for d, s in enumerate(shape)
                     if s >= axis_size and s % axis_size == 0]
            if cands and axis_size > 1:
                best = max(cands, key=lambda d: (shape[d], -d))
                spec = P(*([None] * best + [axis_name]))
            else:
                spec = P()
                if axis_size > 1:
                    fallbacks.append(pstr or "<root>")
        specs.append(spec)
    if fallbacks and warn:
        warnings.warn(
            f"auto_partition_specs: {len(fallbacks)} leaf(s) have no "
            f"dimension divisible by {axis_name!r} axis size {axis_size}; "
            f"replicated fallback for: {', '.join(fallbacks)}",
            UserWarning, stacklevel=2)
    return jax.tree_util.tree_unflatten(treedef, specs)


def tree_shardings(mesh: Mesh, spec_tree: Any) -> Any:
    """Map a tree of ``PartitionSpec``s to ``NamedSharding``s on ``mesh``."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), spec_tree,
        is_leaf=lambda x: isinstance(x, P))


def prepend_axis(spec_tree: Any, axis_name: Optional[str]) -> Any:
    """Prefix every spec with a leading mesh axis (stacked per-client rows:
    dim 0 is the cohort axis, trailing dims keep the model layout)."""
    return jax.tree.map(
        lambda s: P(axis_name, *s), spec_tree,
        is_leaf=lambda x: isinstance(x, P))


def transformer_param_specs(params: Any) -> Any:
    """Megatron-style TP layout by parameter path.

    qkv / mlp-in kernels: column-sharded (output dim over ``model``);
    proj / mlp-out: row-sharded (input dim); head: vocab-sharded output;
    embeddings, norms, biases: replicated.
    """
    from .mesh import AXIS_MODEL

    def spec_for(path, leaf) -> P:
        names = [str(getattr(p, "key", p)) for p in path]
        joined = "/".join(names)
        if leaf.ndim < 2:
            return P()
        if "qkv" in joined and names[-1] == "kernel":
            return P(None, AXIS_MODEL)
        if "proj" in joined and names[-1] == "kernel":
            return P(AXIS_MODEL, None)
        if "MLPBlock" in joined and "Dense_0" in joined and names[-1] == "kernel":
            return P(None, AXIS_MODEL)
        if "MLPBlock" in joined and "Dense_1" in joined and names[-1] == "kernel":
            return P(AXIS_MODEL, None)
        if "head" in joined and names[-1] == "kernel":
            return P(None, AXIS_MODEL)
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, params)
