"""TPU parallelism layer: device mesh, shardings, typed collectives.

This replaces the reference's native communication stack
(``fedml/core/distributed/communication/{mpi,nccl}`` + torch.distributed, see
SURVEY.md §2.7/§5.8): inside a pod, point-to-point weight shipping dissolves
into XLA collectives over ICI, expressed with ``jax.sharding`` + ``shard_map``.
"""

from .mesh import (
    AXIS_CLIENT,
    AXIS_DATA,
    AXIS_MODEL,
    AXIS_PIPE,
    AXIS_SEQ,
    AXIS_EXPERT,
    MeshConfig,
    create_mesh,
    get_default_mesh,
    set_default_mesh,
)
from .sharding import (
    replicated,
    shard_along,
    shard_leading_axis,
    replicate_tree,
    auto_partition_specs,
    tree_shardings,
    prepend_axis,
    transformer_param_specs,
)
from .collectives import (
    psum_tree,
    pmean_tree,
    weighted_psum_tree,
    all_gather_tree,
    ppermute_tree,
    ring_neighbors,
)
from .pipeline import PipelineConfig, PipelinedLMTrainer, make_pipe_mesh

__all__ = [
    "AXIS_CLIENT", "AXIS_DATA", "AXIS_MODEL", "AXIS_PIPE", "AXIS_SEQ", "AXIS_EXPERT",
    "MeshConfig", "create_mesh", "get_default_mesh", "set_default_mesh",
    "replicated", "shard_along", "shard_leading_axis", "replicate_tree",
    "auto_partition_specs", "tree_shardings", "prepend_axis",
    "transformer_param_specs",
    "psum_tree", "pmean_tree", "weighted_psum_tree", "all_gather_tree",
    "ppermute_tree", "ring_neighbors",
    "PipelineConfig", "PipelinedLMTrainer", "make_pipe_mesh",
]
