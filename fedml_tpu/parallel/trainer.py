"""Cheetah: distributed training acceleration (dp × tp × sp over one mesh).

The reference reserves this product line as an empty placeholder
(``python/fedml/distributed/`` — SURVEY.md product table: "Cheetah ...
placeholder only"); here it is functional. A causal-LM training step is jit
over a ``(data, seq, model)`` mesh:

- **data**: batch sharding; XLA inserts the gradient psum (the DDP
  equivalent, reference ``trainer_dist_adapter.py:66-68``).
- **model**: tensor parallelism via parameter PartitionSpecs — column-sharded
  qkv/mlp-in kernels, row-sharded proj/mlp-out, vocab-sharded head; GSPMD
  places the activation collectives (Megatron layout, expressed as shardings
  not hand-written collectives, per the scaling-book recipe).
- **seq**: sequence/context parallelism — tokens sharded along T; attention
  runs as explicit ring attention (``ops/attention.py``) with K/V blocks
  rotating on ``ppermute`` over ICI. This is the long-context axis
  (SURVEY.md §5.7: absent in reference, first-class here).

Pipeline (``pipe``) is intentionally not in this trainer: at FL/LM scales the
same devices are better spent on dp×tp×sp; SplitNN (algorithms/split_nn.py)
covers the layer-split execution pattern.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.transformer import TransformerLM
from .mesh import AXIS_DATA, AXIS_MODEL, AXIS_SEQ, MeshConfig, create_mesh
from .sharding import transformer_param_specs, tree_shardings

PyTree = Any


@dataclasses.dataclass(frozen=True)
class DistTrainConfig:
    dp: int = 1
    tp: int = 1
    sp: int = 1
    lr: float = 3e-4
    weight_decay: float = 0.01
    use_remat: bool = True   # jax.checkpoint the blocks: FLOPs for HBM
    # "full" recomputes the whole block in bwd; "dots" saves matmul
    # outputs and recomputes only elementwise/norm ops — most of full
    # remat's memory win at a fraction of its recompute FLOPs
    # (models/transformer.py remat; A/B'd in bench_lm_attribution_r5)
    remat_policy: str = "full"
    # chunked LM cross-entropy (ops/losses.chunked_lm_cross_entropy):
    # never materializes the (B, T, V) f32 logits — the large-vocab HBM
    # hog. 0 disables; otherwise the sequence-chunk size.
    ce_chunk: int = 0
    # sequence-parallel collective pattern: "ring" (ppermute blockwise,
    # O(T/sp) memory) or "ulysses" (all-to-all seq<->heads re-shard,
    # full-sequence flash-eligible attention; heads % sp == 0)
    sp_impl: str = "ring"
    # AdamW first-moment dtype: "bfloat16" halves mu's HBM footprint and
    # the optimizer stage's read/write traffic (mu tolerates bf16; nu
    # stays f32 — bf16's 7-bit mantissa loses the small per-step squared
    # gradients against the accumulated sum, stalling the second moment).
    # Optimizer-stage bandwidth is a measured lever on the tunneled v5e
    # (scripts/bench_lm_attribution_r5.py).
    mu_dtype: Optional[str] = None


def make_lm_mesh(cfg: DistTrainConfig, devices=None) -> Mesh:
    return create_mesh(
        MeshConfig(axes=((AXIS_DATA, cfg.dp), (AXIS_SEQ, cfg.sp), (AXIS_MODEL, cfg.tp))),
        devices=devices,
    )


# spec logic lives in the shared sharding layer (the federated simulator's
# 2-D mesh uses the same module); re-exported here for back-compat
__all__ = ["transformer_param_specs", "DistTrainConfig", "DistributedLMTrainer",
           "make_lm_mesh"]


class DistributedLMTrainer:
    """Compiled distributed causal-LM trainer (the Cheetah engine)."""

    def __init__(
        self,
        cfg: DistTrainConfig,
        vocab_size: int = 1024,
        dim: int = 256,
        num_heads: int = 8,
        num_layers: int = 4,
        max_len: int = 2048,
        dtype=jnp.bfloat16,
        mesh: Optional[Mesh] = None,
        seed: int = 0,
    ):
        self.cfg = cfg
        self.mesh = mesh or make_lm_mesh(cfg)
        self.model = TransformerLM(
            vocab_size=vocab_size, dim=dim, num_heads=num_heads,
            num_layers=num_layers, max_len=max_len, dtype=dtype,
            seq_axis=AXIS_SEQ if cfg.sp > 1 else None,
            mesh=self.mesh if cfg.sp > 1 else None,
            sp_impl=cfg.sp_impl,
            # per-block remat: O(1) layers of activations alive in bwd —
            # strictly better than checkpointing the whole apply (which
            # still holds every layer alive during the recompute)
            remat=(cfg.remat_policy if cfg.remat_policy != "full" else True)
            if cfg.use_remat else False,
        )
        # init on host with a tiny batch, then place with TP shardings; the
        # init token length must divide by sp (ring attention shards T)
        variables = self.model.init(
            jax.random.PRNGKey(seed), jnp.zeros((1, 8 * max(1, cfg.sp)), jnp.int32)
        )
        self.param_specs = transformer_param_specs(variables)
        self.param_shardings = tree_shardings(self.mesh, self.param_specs)
        self.params = jax.device_put(variables, self.param_shardings)
        self.opt = optax.adamw(
            cfg.lr, weight_decay=cfg.weight_decay,
            mu_dtype=jnp.dtype(cfg.mu_dtype) if cfg.mu_dtype else None)
        # moments inherit the params' shardings (init maps over sharded params)
        self.opt_state = self.opt.init(self.params)
        self.batch_sharding = NamedSharding(self.mesh, P(AXIS_DATA, AXIS_SEQ))
        self._train_step = self._build_train_step()

    def _build_train_step(self) -> Callable:
        model = self.model
        opt = self.opt
        ce_chunk = self.cfg.ce_chunk

        def loss_fn(params, tokens, targets):
            # block-level remat is baked into the model (cfg.use_remat)
            if ce_chunk:
                from ..ops.losses import chunked_lm_cross_entropy

                hid = model.apply(params, tokens, return_hidden=True)
                head = params["params"]["head"]["kernel"].astype(hid.dtype)
                return chunked_lm_cross_entropy(hid, head, targets,
                                                chunk=ce_chunk)
            logits = model.apply(params, tokens)
            logz = jax.nn.log_softmax(logits.astype(jnp.float32))
            ll = jnp.take_along_axis(logz, targets[..., None], -1)[..., 0]
            return -ll.mean()

        def train_step(params, opt_state, tokens, targets):
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        rep = NamedSharding(self.mesh, P())
        return jax.jit(
            train_step,
            in_shardings=(self.param_shardings, None, self.batch_sharding, self.batch_sharding),
            out_shardings=(self.param_shardings, None, rep),
            donate_argnums=(0, 1),
        )

    def step(self, tokens: np.ndarray, targets: np.ndarray) -> float:
        tokens = jax.device_put(jnp.asarray(tokens, jnp.int32), self.batch_sharding)
        targets = jax.device_put(jnp.asarray(targets, jnp.int32), self.batch_sharding)
        self.params, self.opt_state, loss = self._train_step(
            self.params, self.opt_state, tokens, targets
        )
        return float(loss)

    def train(self, data_iter, steps: int, log_every: int = 10, log_fn=print) -> list:
        losses = []
        for i in range(steps):
            tokens, targets = next(data_iter)
            loss = self.step(tokens, targets)
            losses.append(loss)
            if log_fn and i % log_every == 0:
                log_fn(f"[cheetah step {i}] loss={loss:.4f}")
        return losses
