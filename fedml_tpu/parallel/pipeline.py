"""Pipeline parallelism (GPipe-style) over the ``pipe`` mesh axis.

The reference has no pipeline engine (its ``distributed/`` Cheetah line is an
empty placeholder; the closest pattern is SplitNN's layer-split activation
exchange, ``simulation/mpi/split_nn/client.py:23``). This is the TPU-native
version: every device owns one STAGE of the homogeneous decoder stack
(stage-stacked params sharded over ``pipe``), and microbatches stream through
the stages inside ``shard_map`` — the stage-to-stage activation transfer is a
``lax.ppermute`` on ICI, the schedule is a ``lax.scan`` over
``microbatches + stages - 1`` ticks (the classic GPipe fill/drain diagram),
and the backward pass is just JAX differentiating through scan + ppermute
(reverse-mode turns the +1 rotation into a -1 rotation automatically).

Embedding and the LM head sit OUTSIDE the pipeline (replicated / dp-sharded)
so every stage body is identical — which is what lets stage params stack
into one leading-axis pytree and the whole schedule compile to a single
program.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.transformer import Block
from .mesh import AXIS_DATA, AXIS_PIPE, MeshConfig, create_mesh

PyTree = Any


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    pp: int = 2            # pipeline stages (devices along ``pipe``)
    dp: int = 1            # data parallelism across replicas of the pipeline
    microbatches: int = 4  # per-step microbatches streamed through the pipe
    lr: float = 3e-4


def make_pipe_mesh(cfg: PipelineConfig, devices=None) -> Mesh:
    return create_mesh(
        MeshConfig(axes=((AXIS_DATA, cfg.dp), (AXIS_PIPE, cfg.pp))),
        devices=devices,
    )


class _StageBody(nn.Module):
    """The homogeneous per-stage body: ``layers_per_stage`` decoder blocks."""

    dim: int
    num_heads: int
    layers_per_stage: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        for _ in range(self.layers_per_stage):
            x = Block(self.dim, self.num_heads, causal=True, dtype=self.dtype)(x)
        return x


def _pipeline_apply(stage_apply, stage_params, x_mb, *, pp: int, axis: str):
    """Run microbatches through the stages. Called INSIDE shard_map over
    ``axis``: ``stage_params`` is this device's stage (leading axis already
    consumed), ``x_mb`` is (M, mb, T, D) — the full microbatch queue,
    replicated along ``axis`` (only stage 0 reads it; cheap at these sizes
    and keeps the schedule a pure scan).

    Returns (M, mb, T, D): the last stage's outputs in microbatch order
    (valid on the last stage; other stages return zeros and the caller
    selects via psum of the one-hot masked result).
    """
    idx = jax.lax.axis_index(axis)
    M, mb, T, D = x_mb.shape
    n_ticks = M + pp - 1

    def tick(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (garbage buffer beyond the fill)
        feed = x_mb[jnp.minimum(t, M - 1)]
        inp = jnp.where(idx == 0, feed, state)
        out = stage_apply(stage_params, inp)
        # last stage emits microbatch t-(pp-1) at tick t
        emit_idx = t - (pp - 1)
        is_emit = jnp.logical_and(idx == pp - 1, emit_idx >= 0)
        outputs = jax.lax.cond(
            is_emit,
            lambda o: jax.lax.dynamic_update_slice(
                o, out[None], (jnp.maximum(emit_idx, 0), 0, 0, 0)),
            lambda o: o,
            outputs,
        )
        # rotate activations one stage forward (stage pp-1 -> 0 wraps, but
        # stage 0 overwrites its input with the next microbatch anyway)
        state = jax.lax.ppermute(
            out, axis, [(i, (i + 1) % pp) for i in range(pp)]
        )
        return (state, outputs), None

    state0 = jnp.zeros((mb, T, D), x_mb.dtype)
    outputs0 = jnp.zeros((M, mb, T, D), x_mb.dtype)
    (_, outputs), _ = jax.lax.scan(
        tick, (state0, outputs0), jnp.arange(n_ticks)
    )
    # every non-last stage holds zeros; psum over the pipe axis broadcasts
    # the last stage's result to all stages (so the head computes everywhere
    # and the loss is replicated along ``pipe``)
    mask = (idx == pp - 1).astype(outputs.dtype)
    return jax.lax.psum(outputs * mask, axis)


class PipelinedLMTrainer:
    """Causal-LM trainer with the decoder stack pipelined over ``pipe``.

    Stage params live stacked on a leading stage axis sharded over the
    ``pipe`` mesh axis; embedding/norm/head params are replicated. Batch is
    sharded over ``data`` as usual (each dp replica runs its own pipeline;
    XLA psums the gradients).
    """

    def __init__(self, cfg: PipelineConfig, vocab_size: int, dim: int,
                 num_heads: int, num_layers: int, max_len: int,
                 dtype=jnp.float32, mesh: Optional[Mesh] = None, seed: int = 0):
        assert num_layers % cfg.pp == 0, "layers must split evenly into stages"
        self.cfg = cfg
        self.mesh = mesh or make_pipe_mesh(cfg)
        self.dim, self.max_len = dim, max_len
        layers_per_stage = num_layers // cfg.pp
        self.stage = _StageBody(dim, num_heads, layers_per_stage, dtype)

        rng = jax.random.PRNGKey(seed)
        keys = jax.random.split(rng, cfg.pp + 3)
        x0 = jnp.zeros((1, max_len, dim), dtype)
        # one init per stage, stacked on the leading axis
        stage_params = jax.vmap(
            lambda k: self.stage.init(k, x0)
        )(jnp.stack(keys[: cfg.pp]))
        self.embed = nn.Embed(vocab_size, dim, dtype=dtype)
        embed_params = self.embed.init(keys[-3], jnp.zeros((1, 1), jnp.int32))
        self.head = nn.Dense(vocab_size, use_bias=False, dtype=dtype)
        head_params = self.head.init(keys[-2], x0)
        pos = 0.02 * jax.random.normal(keys[-1], (max_len, dim), dtype)
        self.params = {
            "stages": stage_params, "embed": embed_params,
            "head": head_params, "pos": pos,
        }
        pipe_first = NamedSharding(self.mesh, P(AXIS_PIPE))
        rep = NamedSharding(self.mesh, P())
        self._param_sh = {
            "stages": jax.tree.map(lambda _: pipe_first, stage_params),
            "embed": jax.tree.map(lambda _: rep, embed_params),
            "head": jax.tree.map(lambda _: rep, head_params),
            "pos": rep,
        }
        self.params = jax.device_put(self.params, self._param_sh)
        self.opt = optax.adam(cfg.lr)
        # init AFTER placement: zeros_like on sharded params gives the adam
        # moments the same pipe/replicated layout
        self.opt_state = self.opt.init(self.params)
        self._step = self._build_step()

    def _build_step(self):
        cfg, mesh, stage = self.cfg, self.mesh, self.stage
        pp, M = cfg.pp, cfg.microbatches

        stage_apply = lambda p, x: stage.apply(p, x)  # noqa: E731

        pipe_spec = P(AXIS_PIPE)

        def run_pipeline(stages_stacked, h_mb):
            # shard_map over pipe: each device gets its (1, ...) stage slice
            def inner(stage_slice, x_mb):
                local = jax.tree.map(lambda a: a[0], stage_slice)
                return _pipeline_apply(
                    stage_apply, local, x_mb, pp=pp, axis=AXIS_PIPE
                )

            return jax.shard_map(
                inner, mesh=mesh,
                in_specs=(jax.tree.map(lambda _: pipe_spec, stages_stacked),
                          P(None, AXIS_DATA)),
                out_specs=P(None, AXIS_DATA),
                check_vma=False,
            )(stages_stacked, h_mb)

        def loss_fn(params, tokens, targets):
            B, T = tokens.shape
            h = self.embed.apply(params["embed"], tokens)
            h = h + params["pos"][None, :T]
            mb = B // M
            h_mb = h.reshape(M, mb, T, self.dim)
            out = run_pipeline(params["stages"], h_mb)
            out = out.reshape(B, T, self.dim)
            logits = self.head.apply(params["head"], out)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), targets
            ).mean()

        @jax.jit
        def step(params, opt_state, tokens, targets):
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        return step

    def step(self, tokens: np.ndarray, targets: np.ndarray) -> float:
        self.params, self.opt_state, loss = self._step(
            self.params, self.opt_state,
            jnp.asarray(tokens, jnp.int32), jnp.asarray(targets, jnp.int32),
        )
        return float(loss)
