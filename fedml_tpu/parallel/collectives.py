"""Typed pytree collectives over named mesh axes.

TPU-native replacement for the reference's collective wrappers
(``simulation/nccl/base_framework/common.py:184-233``: ``fedml_nccl_broadcast``,
``fedml_nccl_reduce``, ``broadcast_model_state``) and its declarative
collective-params layer (``nccl/base_framework/params.py``). Where the
reference loops per-tensor ``dist.broadcast``/``dist.reduce`` calls, these
operate on whole pytrees inside a single traced program, so XLA fuses and
schedules them onto ICI.

All functions here must be called inside ``shard_map``/``pjit`` tracing with
the named axis bound.
"""

from __future__ import annotations

from typing import Any, List, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def psum_tree(tree: Any, axis_name: str) -> Any:
    """SUM-reduce every leaf across the axis. FedAvg aggregation core:
    the reference's ``fedml_nccl_reduce`` (common.py:193) becomes one psum."""
    return jax.tree.map(lambda x: lax.psum(x, axis_name), tree)


def pmean_tree(tree: Any, axis_name: str) -> Any:
    return jax.tree.map(lambda x: lax.pmean(x, axis_name), tree)


def weighted_psum_tree(tree: Any, weight: jax.Array, axis_name: str) -> Any:
    """Pre-scale by ``weight`` then SUM — the exact weighted-FedAvg trick the
    reference uses (``nccl/base_framework/LocalAggregator.py:84`` scales params
    by average_weight before the reduce). Weights are applied in f32 for
    accuracy parity (SURVEY.md §7 hard parts)."""
    def scale_sum(x):
        w = weight.astype(jnp.float32)
        return lax.psum((x.astype(jnp.float32) * w), axis_name).astype(x.dtype)

    return jax.tree.map(scale_sum, tree)


def all_gather_tree(tree: Any, axis_name: str, axis: int = 0, tiled: bool = False) -> Any:
    return jax.tree.map(lambda x: lax.all_gather(x, axis_name, axis=axis, tiled=tiled), tree)


def ppermute_tree(tree: Any, axis_name: str, perm: List[Tuple[int, int]]) -> Any:
    """Point-to-point neighbor exchange — replaces decentralized-FL gossip
    sends (``simulation/sp/decentralized``) and ring-attention block rotation."""
    return jax.tree.map(lambda x: lax.ppermute(x, axis_name, perm), tree)


def ring_neighbors(n: int, offset: int = 1) -> List[Tuple[int, int]]:
    """Ring permutation [(src, dst)] used for gossip and ring attention."""
    return [(i, (i + offset) % n) for i in range(n)]


def reduce_scatter_tree(tree: Any, axis_name: str, scatter_dim: int = 0) -> Any:
    return jax.tree.map(
        lambda x: lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dim, tiled=True),
        tree,
    )
