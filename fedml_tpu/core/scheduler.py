"""Client-workload → device scheduler.

Parity: reference ``core/schedule/scheduler.py:4`` — a branch-and-bound search
assigning heterogeneous client workloads to devices under per-device memory
constraints, minimizing the makespan (max per-device cost). Redesign: the
reference explores every feasible partial map recursively (exponential fan-out,
kept "DP" only by pruning); here the same objective is solved with the classic
LPT greedy + local-refinement, which is O(n log n), deterministic, and within
4/3 of optimal — and the assignment feeds a *static* schedule so the compiled
per-shard client loop (Parrot-TPU) keeps rectangular shapes.
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import numpy as np


def dp_schedule(
    workloads: Sequence[float],
    constraints: Sequence[float],
    memory: Sequence[float],
) -> Tuple[List[List[int]], np.ndarray]:
    """Assign workload i (cost workloads[i] * constraints[device]) to devices.

    Args:
      workloads: per-client relative cost (e.g. sample counts).
      constraints: per-device slowdown factor (1.0 = fastest device).
      memory: per-device cost capacity; assignment never exceeds it.

    Returns:
      (assignment, device_costs): ``assignment[d]`` = client indices on device
      d; ``device_costs[d]`` = accumulated cost. Raises if infeasible.
    """
    workloads = np.asarray(workloads, dtype=np.float64)
    constraints = np.asarray(constraints, dtype=np.float64)
    memory = np.asarray(memory, dtype=np.float64)
    n_dev = len(constraints)
    order = np.argsort(workloads)[::-1]  # longest processing time first
    assignment: List[List[int]] = [[] for _ in range(n_dev)]
    costs = np.zeros(n_dev)
    for i in order:
        # device that ends up with the smallest resulting makespan and fits
        cand_costs = costs + constraints * workloads[i]
        feasible = cand_costs <= memory
        if not feasible.any():
            raise ValueError(
                f"workload {int(i)} (cost {workloads[i]}) fits no device memory"
            )
        cand = np.where(feasible, cand_costs, np.inf)
        d = int(np.argmin(cand))
        assignment[d].append(int(i))
        costs[d] = cand_costs[d]
    # local refinement: move a job from the busiest device if it lowers makespan
    improved = True
    while improved:
        improved = False
        busiest = int(np.argmax(costs))
        for job in sorted(assignment[busiest], key=lambda j: workloads[j]):
            for d in np.argsort(costs):
                d = int(d)
                if d == busiest:
                    continue
                new_cost = costs[d] + constraints[d] * workloads[job]
                if new_cost < costs[busiest] and new_cost <= memory[d]:
                    assignment[busiest].remove(job)
                    assignment[d].append(job)
                    costs[busiest] -= constraints[busiest] * workloads[job]
                    costs[d] = new_cost
                    improved = True
                    break
            if improved:
                break
    return assignment, costs


def bucket_schedule(
    batch_counts: Sequence[int],
    axis: int,
    max_buckets: int = 4,
    max_width: int | None = None,
) -> List[Tuple[np.ndarray, int]]:
    """Group cohort positions into width-buckets minimizing padded compute.

    The compiled round step is rectangular: every client slot costs
    ``width`` batches regardless of its true batch count, and slot counts
    pad up to a multiple of the mesh client axis. Splitting a skewed cohort
    into a few width-classes (each compiled once — widths are cohort maxima,
    so at most ``max_buckets`` distinct shapes) trades a handful of extra
    XLA programs for dropping the padding waste.

    Exact dynamic program over the sorted counts (the honest successor of
    the reference's branch-and-bound ``DP_schedule``,
    ``core/schedule/scheduler.py:110``): cost of a contiguous sorted group
    = padded_slots(group) * width(group); minimize the total over at most
    ``max_buckets`` groups. Widths are rounded UP to powers of two so the
    per-(slots, width) compiled programs converge to a handful of shapes
    across rounds with varying cohorts instead of recompiling every round.

    Returns: list of (positions, width) — positions index into
    ``batch_counts``; widths ascending powers of two.

    Pure in its arguments, and on the per-round host hot path (the async
    cohort pipeline rebuilds the schedule every round): results are
    memoized on the (counts, axis, max_buckets, max_width) key, with
    defensive copies returned so callers can never corrupt the cache.
    """
    cached = _bucket_schedule_cached(
        tuple(int(c) for c in batch_counts), int(axis), int(max_buckets),
        None if max_width is None else int(max_width))
    return [(pos.copy(), w) for pos, w in cached]


@functools.lru_cache(maxsize=64)
def _bucket_schedule_cached(
    batch_counts: Tuple[int, ...],
    axis: int,
    max_buckets: int,
    max_width: int | None,
) -> List[Tuple[np.ndarray, int]]:
    counts = np.asarray(batch_counts, dtype=np.int64)
    n = len(counts)
    axis = max(1, int(axis))
    if n == 0:
        return []
    order = np.argsort(counts, kind="stable")
    # quantize each client's width requirement up to a power of two; the DP
    # then groups on the quantized ladder (a group's width = its max).
    # max_width caps the ladder (callers pass their per-client batch cap so
    # quantization never raises a client's effective training budget).
    sc = 1 << np.ceil(np.log2(np.maximum(counts[order], 1))).astype(np.int64)
    if max_width is not None:
        sc = np.minimum(sc, int(max_width))

    B = max(1, min(int(max_buckets), n))
    INF = np.inf
    # f[b][j] = min cost of first j sorted clients using <= b groups;
    # inner minimization vectorized over the split point i (this runs on the
    # per-round hot path, so no O(n^2) pure-Python loops)
    i_idx = np.arange(n)  # candidate split starts
    f_prev = np.full(n + 1, INF)
    f_prev[0] = 0.0
    back = np.zeros((B + 1, n + 1), dtype=np.int64)
    for b in range(1, B + 1):
        f_cur = np.full(n + 1, INF)
        f_cur[0] = 0.0
        for j in range(1, n + 1):
            # group [i, j) at width sc[j-1]; slot count mirrors execution:
            # ceil(k/axis) rounded UP to a power of two, times axis
            k = j - i_idx[:j]
            per_axis = -(-k // axis)
            per_axis = (2 ** np.ceil(np.log2(np.maximum(per_axis, 1)))).astype(np.int64)
            cand = f_prev[:j] + per_axis * axis * int(sc[j - 1])
            arg = int(np.argmin(cand))
            f_cur[j] = cand[arg]
            back[b][j] = arg
        f_prev = f_cur
    # reconstruct
    cuts = []
    j, b = n, B
    while j > 0:
        i = int(back[b][j])
        cuts.append((i, j))
        j, b = i, b - 1
    cuts.reverse()
    return [
        (order[i:j].astype(np.int64), int(sc[j - 1])) for i, j in cuts if j > i
    ]


def lane_schedule(
    batch_counts: Sequence[int],
    axis: int,
    max_lanes: int | None = None,
    force_lanes: int | None = None,
) -> Tuple[List[List[int]], int]:
    """Pack cohort positions into G balanced lanes for the packed executor.

    The packed cohort schedule trains clients BACK-TO-BACK inside one
    compiled scan (param reset at client boundaries), so the only padding is
    the lane-length imbalance: cost = G * L where L = max lane load. This
    searches G over multiples of ``axis`` (lanes shard over the mesh client
    axis), assigns clients to lanes with LPT (longest-processing-time
    greedy), and keeps the (G, L) minimizing total padded batch-work —
    ties broken toward MORE lanes (fatter per-step batches, fewer
    sequential steps).

    Returns: (lanes, L) — lanes[g] is the ordered list of cohort positions
    lane g trains; L = max lane length in batches.

    Memoized like ``bucket_schedule`` (pure, per-round hot path); lane
    lists are copied on the way out so callers can't corrupt the cache.
    """
    lanes, L = _lane_schedule_cached(
        tuple(int(c) for c in batch_counts), int(axis),
        None if max_lanes is None else int(max_lanes),
        None if force_lanes is None else int(force_lanes))
    return [list(lane) for lane in lanes], L


@functools.lru_cache(maxsize=64)
def _lane_schedule_cached(
    batch_counts: Tuple[int, ...],
    axis: int,
    max_lanes: int | None,
    force_lanes: int | None,
) -> Tuple[List[List[int]], int]:
    counts = np.asarray(batch_counts, dtype=np.int64)
    n = len(counts)
    axis = max(1, int(axis))
    cap = n if max_lanes is None else min(n, int(max_lanes))
    order = np.argsort(-counts, kind="stable")  # LPT: biggest first
    best = None
    # candidate lane counts: axis * powers of two only — every distinct G
    # is a fresh vmap width and therefore a full XLA recompile of the
    # training scan, so the candidate set must stay tiny as cohorts
    # resample round to round (the bucketed schedule bounds its shapes the
    # same way with pow2 slot counts)
    candidates = []
    if force_lanes is not None:
        # caller pins G (bench-swept: per-step cost is superlinear in lane
        # count because per-lane weights lower to grouped convs); still a
        # multiple of the mesh axis — both the round-up and the cohort
        # clamp floor to axis multiples so mesh shards stay even
        g = max(axis, -(-int(force_lanes) // axis) * axis)
        g = min(g, max(axis, (cap // axis) * axis))
        if g <= cap:
            candidates.append(g)
        # g > cap (cohort smaller than one axis-multiple) falls through to
        # the n < axis pad fallback below
    else:
        g = axis
        while g <= cap:
            candidates.append(g)
            g *= 2
    for g in candidates:
        loads = np.zeros(g, dtype=np.int64)
        lanes: List[List[int]] = [[] for _ in range(g)]
        for pos in order:
            lane = int(np.argmin(loads))
            lanes[lane].append(int(pos))
            loads[lane] += counts[pos]
        L = int(loads.max())
        cost = g * L
        # ties -> larger g (checked last wins on <=)
        if best is None or cost <= best[0]:
            best = (cost, lanes, L)
    if best is None:  # n < axis: one client per lane, pad lanes to axis
        lanes = [[int(p)] for p in order] + [[] for _ in range(axis - n)]
        return lanes, int(counts.max(initial=1))
    return best[1], best[2]


def even_client_schedule(client_indexes: Sequence[int], n_shards: int) -> List[np.ndarray]:
    """np.array_split semantics of the reference NCCL simulator's
    ``client_schedule`` (``nccl/base_framework/Server.py:109``): contiguous
    even split of the sampled cohort across mesh shards."""
    return list(np.array_split(np.asarray(client_indexes, dtype=np.int32), n_shards))


def balanced_client_schedule(
    client_indexes: Sequence[int],
    sample_counts: Sequence[int],
    n_shards: int,
) -> List[np.ndarray]:
    """Workload-aware split: LPT-balance sampled clients across shards by
    sample count (what the reference's commented-out scheduler integration,
    ``Server.py:113-120``, intended), then pad shards to equal length by
    repeating the last client so shapes stay rectangular for the compiled
    per-shard scan — repeated entries get zero aggregation weight upstream."""
    counts = np.asarray([sample_counts[i] for i in client_indexes], dtype=np.float64)
    assignment, _ = dp_schedule(counts, np.ones(n_shards), np.full(n_shards, np.inf))
    shards = [np.asarray([client_indexes[j] for j in a], dtype=np.int32) for a in assignment]
    width = max(1, max(len(s) for s in shards))
    return [
        np.pad(s, (0, width - len(s)), mode="edge") if len(s) else
        np.full(width, client_indexes[0], np.int32)
        for s in shards
    ]
