"""Unified telemetry: metrics registry, span tracer, exporters, collectors.

One process-wide, thread-safe home for every number the framework emits —
the generalization of PR 1's ad-hoc ``pack_time``/``pack_wait`` history
fields into a subsystem all layers report through:

- **MetricsRegistry** — counters, gauges, and histograms (fixed exponential
  buckets) keyed by (name, labels). Snapshots are plain dicts; snapshots
  from different processes merge (counters/histogram buckets add, gauges
  last-write-wins) so multi-process cross-silo runs aggregate offline.
- **Tracer** — spans carrying ``trace_id``/``span_id``/``round_idx``
  context (a contextvar, restored explicitly on receive threads). The
  context rides ``comm.Message`` params on all four backends, so the
  server and client sides of one FL round share a ``trace_id`` and round
  latency decomposes into server compute, wire time, and straggler tail.
- **Exporters** — JSONL (``MetricsSink``), a Prometheus textfile writer
  (node-exporter textfile-collector format), and the
  ``python -m fedml_tpu.cli telemetry summary`` pretty-printer.
- **Collectors** — JAX compilation-event listeners (``jax.monitoring``)
  and a daemon-thread sampler for ``SysStats`` + ``device.memory_stats()``.

The defining constraint is overhead (<1% of round wall-clock, guarded by
``bench.py --telemetry-overhead``): when disabled, every accessor returns a
shared null metric whose methods are empty, ``inject``/``extract`` are
no-ops, and spans neither allocate ids nor record. Enabled-path costs are a
few dict lookups and ``perf_counter`` calls per round — microseconds
against rounds that take milliseconds to seconds.
"""

from __future__ import annotations

import bisect
import contextlib
import contextvars
import dataclasses
import logging
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

# --- bucket schemes ---------------------------------------------------------

# (start, factor, count): bounds[i] = start * factor**i, plus a +Inf overflow
# bucket. Mergeability across processes requires IDENTICAL schemes, so these
# are named constants, not per-call tuning knobs.
SECONDS_SCHEME = (1e-4, 2.0, 24)   # 0.1 ms .. ~14 min
BYTES_SCHEME = (64.0, 4.0, 16)     # 64 B .. ~69 GB


def _bounds(scheme: Tuple[float, float, int]) -> List[float]:
    start, factor, count = scheme
    return [start * factor ** i for i in range(int(count))]


# --- metric types -----------------------------------------------------------


class Counter:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    __slots__ = ("_value",)

    def __init__(self):
        self._value = 0.0

    def set(self, v: float) -> None:
        # last-writer-wins by design: one GIL-atomic float store keeps the
        # sampler path lock-free — graftcheck: disable=thread-hazard
        self._value = float(v)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed-exponential-bucket histogram. ``counts`` has one extra slot for
    the +Inf overflow bucket; ``bounds`` are upper edges (le semantics)."""

    __slots__ = ("scheme", "bounds", "counts", "sum", "count", "_lock")

    def __init__(self, scheme: Tuple[float, float, int] = SECONDS_SCHEME):
        self.scheme = tuple(scheme)
        self.bounds = _bounds(self.scheme)
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def quantile(self, q: float) -> float:
        """Bucket-resolution quantile estimate (upper edge of the bucket
        holding the q-th observation; +Inf bucket reports the last edge)."""
        if not self.count:
            return 0.0
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                return self.bounds[min(i, len(self.bounds) - 1)]
        return self.bounds[-1]


class _NullMetric:
    """Shared do-nothing stand-in returned when telemetry is disabled."""

    __slots__ = ()
    value = 0.0
    sum = 0.0
    count = 0
    mean = 0.0

    def inc(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    def quantile(self, q: float) -> float:
        return 0.0


_NULL = _NullMetric()


# --- tenant scoping ----------------------------------------------------------

# The multi-tenant control plane (core/tenancy.py) isolates telemetry by
# stamping a ``tenant`` label on every series created while a tenant scope is
# active. The scope is a contextvar — it does NOT inherit into new threads,
# so per-tenant worker threads must enter :func:`tenant_scope` inside their
# own thread body (the multi-run driver and chaos drill both do).
_tenant_var: "contextvars.ContextVar[Optional[str]]" = (
    contextvars.ContextVar("fedml_tpu_tenant", default=None))


def current_tenant() -> Optional[str]:
    return _tenant_var.get()


@contextlib.contextmanager
def tenant_scope(tenant: Optional[str]):
    """Attribute every metric created in the block to ``tenant``. ``None``
    is a no-op scope (series stay unlabeled — byte-identical to today)."""
    token = _tenant_var.set(None if tenant is None else str(tenant))
    try:
        yield tenant
    finally:
        _tenant_var.reset(token)


# --- registry ---------------------------------------------------------------


def _key(name: str, labels: Dict[str, Any]) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


class MetricsRegistry:
    """Process-wide metric store: ``(name, labels) -> metric``.

    First creation wins the type/scheme; later accessors with the same key
    return the existing instance (a kind mismatch raises — silent type
    punning would corrupt exports).
    """

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        # key -> (kind, labels-dict, metric)
        self._metrics: Dict[str, Tuple[str, Dict[str, Any], Any]] = {}

    def _get(self, kind: str, name: str, labels: Dict[str, Any],
             factory: Callable[[], Any]):
        if not self.enabled:
            return _NULL
        # active tenant scope: the series splits per tenant (an explicit
        # tenant= label from the caller wins over the ambient scope)
        tenant = _tenant_var.get()
        if tenant is not None and "tenant" not in labels:
            labels = dict(labels, tenant=tenant)
        key = _key(name, labels)
        with self._lock:
            ent = self._metrics.get(key)
            if ent is None:
                ent = (kind, dict(labels), factory())
                self._metrics[key] = ent
            elif ent[0] != kind:
                raise TypeError(
                    f"metric {key!r} already registered as {ent[0]}, "
                    f"requested as {kind}")
            return ent[2]

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", name, labels, Counter)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", name, labels, Gauge)

    def histogram(self, name: str,
                  scheme: Tuple[float, float, int] = SECONDS_SCHEME,
                  **labels) -> Histogram:
        return self._get("histogram", name, labels,
                         lambda: Histogram(scheme))

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()

    def counter_total(self, name: str) -> float:
        """Sum of one counter family across every label set. Cheap — no
        histogram bucket copies — so per-round pollers (the trace plane's
        recompile detector) can afford it."""
        with self._lock:
            items = list(self._metrics.items())
        return sum(m.value for key, (kind, _labels, m) in items
                   if kind == "counter"
                   and (key == name or key.startswith(name + "{")))

    def snapshot(self) -> Dict[str, Any]:
        """Plain-dict dump, stable across processes and mergeable."""
        out: Dict[str, Any] = {"counters": {}, "gauges": {}, "histograms": {}}
        with self._lock:
            items = list(self._metrics.items())
        for key, (kind, _labels, m) in items:
            if kind == "counter":
                out["counters"][key] = m.value
            elif kind == "gauge":
                out["gauges"][key] = m.value
            else:
                out["histograms"][key] = {
                    "scheme": list(m.scheme),
                    "counts": list(m.counts),
                    "sum": m.sum,
                    "count": m.count,
                }
        return out

    def merge_snapshot(self, snap: Dict[str, Any]) -> None:
        """Fold another process's snapshot into this registry: counters and
        histogram buckets add; gauges last-write-wins. Histogram scheme
        mismatches raise — adding buckets with different edges is silent
        data corruption."""
        for key, v in (snap.get("counters") or {}).items():
            _name, labels = _parse_key(key)
            with self._lock:
                ent = self._metrics.setdefault(
                    key, ("counter", labels, Counter()))
            ent[2].inc(v)
        for key, v in (snap.get("gauges") or {}).items():
            _name, labels = _parse_key(key)
            with self._lock:
                ent = self._metrics.setdefault(key, ("gauge", labels, Gauge()))
            ent[2].set(v)
        for key, h in (snap.get("histograms") or {}).items():
            _name, labels = _parse_key(key)
            scheme = tuple(h["scheme"])
            with self._lock:
                ent = self._metrics.setdefault(
                    key, ("histogram", labels, Histogram(scheme)))
            hist = ent[2]
            if tuple(hist.scheme) != scheme:
                raise ValueError(
                    f"histogram {key!r} scheme mismatch: "
                    f"{hist.scheme} vs {scheme}")
            with hist._lock:
                for i, c in enumerate(h["counts"]):
                    hist.counts[i] += int(c)
                hist.sum += float(h["sum"])
                hist.count += int(h["count"])


class TenantRegistry:
    """Tenant-scoped facade over a :class:`MetricsRegistry`: every series
    accessed through it carries ``tenant=<name>``, and :meth:`snapshot`
    keeps only that tenant's series — the isolated registry view the chaos
    drill and the multi-run driver hand each job."""

    def __init__(self, tenant: str, registry: Optional[MetricsRegistry] = None):
        self.tenant = str(tenant)
        self._reg = registry if registry is not None else _state.registry

    @property
    def enabled(self) -> bool:
        return self._reg.enabled

    def counter(self, name: str, **labels) -> Counter:
        labels.setdefault("tenant", self.tenant)
        return self._reg.counter(name, **labels)

    def gauge(self, name: str, **labels) -> Gauge:
        labels.setdefault("tenant", self.tenant)
        return self._reg.gauge(name, **labels)

    def histogram(self, name: str,
                  scheme: Tuple[float, float, int] = SECONDS_SCHEME,
                  **labels) -> Histogram:
        labels.setdefault("tenant", self.tenant)
        return self._reg.histogram(name, scheme, **labels)

    def snapshot(self) -> Dict[str, Any]:
        """The underlying snapshot restricted to this tenant's series."""
        return filter_snapshot(self._reg.snapshot(), self.tenant)


def filter_snapshot(snap: Dict[str, Any], tenant: str) -> Dict[str, Any]:
    """Restrict a registry snapshot to one tenant's series — the filtering
    :class:`TenantRegistry` applies, shared so offline consumers (the CLI
    ``telemetry summary --tenant``) match it exactly."""
    tenant = str(tenant)
    out: Dict[str, Any] = {}
    for kind, series in snap.items():
        out[kind] = {
            k: v for k, v in series.items()
            if _parse_key(k)[1].get("tenant") == tenant
        }
    return out


def scoped_registry(tenant: str,
                    registry: Optional[MetricsRegistry] = None) -> TenantRegistry:
    return TenantRegistry(tenant, registry)


def _parse_key(key: str) -> Tuple[str, Dict[str, str]]:
    if "{" not in key:
        return key, {}
    name, rest = key.split("{", 1)
    labels = {}
    for pair in rest.rstrip("}").split(","):
        if "=" in pair:
            k, v = pair.split("=", 1)
            labels[k] = v
    return name, labels


# --- trace context ----------------------------------------------------------


@dataclasses.dataclass
class TraceContext:
    trace_id: str
    span_id: str
    round_idx: Optional[int] = None


_current: "contextvars.ContextVar[Optional[TraceContext]]" = (
    contextvars.ContextVar("fedml_tpu_trace", default=None))

# Message param keys the trace context rides on (plain msgpack-able scalars;
# every backend's send stamps them, every receive path restores them).
TRACE_ID_KEY = "telemetry_trace_id"
SPAN_ID_KEY = "telemetry_span_id"
ROUND_IDX_KEY = "telemetry_round_idx"


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


def current_context() -> Optional[TraceContext]:
    return _current.get()


@contextlib.contextmanager
def use_context(ctx: Optional[TraceContext]):
    """Install ``ctx`` as the current trace context for the block (receive
    paths restore the sender's context around observer dispatch)."""
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


def new_round_context(round_idx: int) -> Optional[TraceContext]:
    """Fresh root context for one FL round (server-side round start). All
    messages sent under it — and every reply sent from within their
    handlers — share its ``trace_id``."""
    if not _state.enabled:
        return None
    return TraceContext(trace_id=_new_id(), span_id=_new_id(),
                        round_idx=int(round_idx))


def inject_trace(msg) -> None:
    """Stamp the current trace context onto an outbound ``comm.Message``.
    No context (or disabled telemetry) means no stamp — messages outside
    any round/span stay byte-identical to the pre-telemetry wire format."""
    if not _state.enabled:
        return
    ctx = _current.get()
    if ctx is None or TRACE_ID_KEY in msg.msg_params:
        return
    msg.add_params(TRACE_ID_KEY, ctx.trace_id)
    msg.add_params(SPAN_ID_KEY, ctx.span_id)
    if ctx.round_idx is not None:
        msg.add_params(ROUND_IDX_KEY, int(ctx.round_idx))


def extract_trace(msg) -> Optional[TraceContext]:
    """Read a trace context off an inbound ``comm.Message`` (None if the
    sender stamped nothing)."""
    if not _state.enabled:
        return None
    trace_id = msg.get(TRACE_ID_KEY)
    if trace_id is None:
        return None
    rnd = msg.get(ROUND_IDX_KEY)
    return TraceContext(trace_id=str(trace_id),
                        span_id=str(msg.get(SPAN_ID_KEY) or _new_id()),
                        round_idx=int(rnd) if rnd is not None else None)


class Tracer:
    """Span recorder. Finished spans land in a bounded ring (inspection /
    tests), the JSONL sink when configured, and the
    ``fedml_span_seconds{name=...}`` histogram."""

    def __init__(self, registry: MetricsRegistry, buffer: int = 4096):
        self.registry = registry
        self._finished: "deque[Dict[str, Any]]" = deque(maxlen=buffer)
        self.sink = None  # optional MetricsSink
        # oldest-span evictions from the ring (mirrors
        # MetricsSink.dropped_records — a silent discard is a lie in the data)
        self.dropped = 0

    @contextlib.contextmanager
    def span(self, name: str, round_idx: Optional[int] = None, **attrs):
        if not _state.enabled:
            yield None
            return
        parent = _current.get()
        ctx = TraceContext(
            trace_id=parent.trace_id if parent else _new_id(),
            span_id=_new_id(),
            round_idx=(int(round_idx) if round_idx is not None
                       else (parent.round_idx if parent else None)),
        )
        token = _current.set(ctx)
        wall0 = time.time()
        t0 = time.perf_counter()
        status = "ok"
        try:
            yield ctx
        except BaseException:
            status = "error"
            raise
        finally:
            _current.reset(token)
            rec = {
                "kind": "span",
                "name": name,
                "trace_id": ctx.trace_id,
                "span_id": ctx.span_id,
                "parent_span_id": parent.span_id if parent else None,
                "round_idx": ctx.round_idx,
                "start": wall0,
                "duration": time.perf_counter() - t0,
                "status": status,
            }
            if attrs:
                rec.update(attrs)
            tenant = _tenant_var.get()
            if tenant is not None:
                rec["tenant"] = tenant
            if len(self._finished) == self._finished.maxlen:
                self.dropped += 1
                self.registry.counter("fedml_spans_dropped_total").inc()
            self._finished.append(rec)
            if self.sink is not None:
                try:
                    self.sink.emit(rec)
                except Exception:  # a full disk must not fail the traced op
                    logging.exception("telemetry: span sink emit failed")
            self.registry.histogram(
                "fedml_span_seconds", span=name).observe(rec["duration"])

    def finished_spans(self) -> List[Dict[str, Any]]:
        return list(self._finished)

    def clear(self) -> None:
        self._finished.clear()
        self.dropped = 0


# --- global state / configuration -------------------------------------------


class _State:
    def __init__(self):
        self.enabled = True
        self.registry = MetricsRegistry(enabled=True)
        self.tracer = Tracer(self.registry)
        self.prometheus_path: Optional[str] = None
        self.jsonl_sink = None
        self.sampler: Optional["SysStatsSampler"] = None
        self.atexit_registered = False


_state = _State()


def get_registry() -> MetricsRegistry:
    return _state.registry


def get_tracer() -> Tracer:
    return _state.tracer


def enabled() -> bool:
    return _state.enabled


def configure(enabled: bool = True,
              jsonl_path: Optional[str] = None,
              prometheus_path: Optional[str] = None,
              sysstats_interval_s: float = 0.0,
              span_buffer: int = 4096,
              reset: bool = False) -> None:
    """(Re)configure the process-wide telemetry state. Idempotent; called by
    ``fedml_tpu.init()`` from the ``telemetry.*`` config family."""
    _state.enabled = bool(enabled)
    _state.registry.enabled = bool(enabled)
    if reset:
        _state.registry.reset()
        _state.tracer.clear()
        from . import trace_plane

        trace_plane.reset()
    if _state.tracer._finished.maxlen != span_buffer:
        old = list(_state.tracer._finished)
        _state.tracer._finished = deque(old, maxlen=int(span_buffer))
    if _state.jsonl_sink is not None and (
            not jsonl_path or _state.jsonl_sink.path != jsonl_path):
        _state.jsonl_sink.close()
        _state.jsonl_sink = None
    if jsonl_path and _state.jsonl_sink is None:
        from .mlops import MetricsSink

        _state.jsonl_sink = MetricsSink(path=jsonl_path)
    _state.tracer.sink = _state.jsonl_sink
    _state.prometheus_path = prometheus_path
    if _state.sampler is not None:
        _state.sampler.stop()
        _state.sampler = None
    if enabled and sysstats_interval_s and sysstats_interval_s > 0:
        _state.sampler = SysStatsSampler(float(sysstats_interval_s))
        _state.sampler.start()
    if enabled:
        install_jax_collectors()
    if (jsonl_path or prometheus_path) and not _state.atexit_registered:
        import atexit

        atexit.register(flush)
        _state.atexit_registered = True


def configure_from_args(args) -> None:
    """Map the flat ``telemetry_*`` config keys onto :func:`configure`."""
    configure(
        enabled=bool(getattr(args, "telemetry_enabled", True)),
        jsonl_path=getattr(args, "telemetry_jsonl_path", None),
        prometheus_path=getattr(args, "telemetry_prometheus_path", None),
        sysstats_interval_s=float(
            getattr(args, "telemetry_sysstats_interval_s", 0.0) or 0.0),
        span_buffer=int(getattr(args, "telemetry_span_buffer", 4096)),
    )
    from . import trace_plane

    trace_plane.configure_from_args(args)


def flush() -> None:
    """Export current state: Prometheus textfile (if configured) + one
    registry-snapshot record on the JSONL sink (if configured)."""
    if not _state.enabled:
        return
    if _state.prometheus_path:
        try:
            write_prometheus(_state.prometheus_path)
        except OSError:
            logging.exception("telemetry: prometheus write failed")
    if _state.jsonl_sink is not None:
        _state.jsonl_sink.emit({
            "kind": "registry_snapshot",
            "timestamp": time.time(),
            "registry": _state.registry.snapshot(),
        })


def emit_record(rec: Dict[str, Any]) -> None:
    """Write one record to the JSONL sink, if configured. The trace plane
    uses this for its ``phase_record`` / ``instant`` / ``clock_offset`` /
    shipped-span kinds; a full disk never fails the emitting operation."""
    if not _state.enabled or _state.jsonl_sink is None:
        return
    try:
        _state.jsonl_sink.emit(rec)
    except Exception:
        logging.exception("telemetry: record emit failed")


# --- comm-plane helpers (hot path: one guard + dict lookup per message) -----


def record_send(backend: str, nbytes: Optional[int],
                serialize_s: Optional[float] = None) -> None:
    if not _state.enabled:
        return
    reg = _state.registry
    reg.counter("fedml_comm_messages_total",
                backend=backend, direction="send").inc()
    if nbytes is not None:
        reg.histogram("fedml_comm_message_bytes", scheme=BYTES_SCHEME,
                      backend=backend, direction="send").observe(nbytes)
    if serialize_s is not None:
        reg.histogram("fedml_comm_serialize_seconds",
                      backend=backend).observe(serialize_s)


def record_receive(backend: str, nbytes: Optional[int] = None) -> None:
    if not _state.enabled:
        return
    reg = _state.registry
    reg.counter("fedml_comm_messages_total",
                backend=backend, direction="recv").inc()
    if nbytes is not None:
        reg.histogram("fedml_comm_message_bytes", scheme=BYTES_SCHEME,
                      backend=backend, direction="recv").observe(nbytes)


# --- resilience hooks (comm retry loop + fault injector + dispatch guard) ----


def record_send_retry(backend: str) -> None:
    if _state.enabled:
        _state.registry.counter("fedml_send_retries_total",
                                backend=backend).inc()


def record_send_failure(backend: str) -> None:
    if _state.enabled:
        _state.registry.counter("fedml_send_failures_total",
                                backend=backend).inc()


def record_fault(action: str) -> None:
    if _state.enabled:
        _state.registry.counter("fedml_faults_injected_total",
                                action=action).inc()


def record_observer_error(msg_type) -> None:
    if _state.enabled:
        _state.registry.counter("fedml_observer_errors_total",
                                msg_type=str(msg_type)).inc()


# --- exporters --------------------------------------------------------------


def _prom_name(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _prom_labels(labels: Dict[str, Any], extra: str = "") -> str:
    pairs = [f'{_prom_name(str(k))}="{v}"' for k, v in sorted(labels.items())]
    if extra:
        pairs.append(extra)
    return "{" + ",".join(pairs) + "}" if pairs else ""


def write_prometheus(path: str, registry: Optional[MetricsRegistry] = None) -> None:
    """Prometheus text exposition (textfile-collector format), written
    atomically (tmp + rename) so a scraper never reads a torn file."""
    reg = registry or _state.registry
    with reg._lock:
        items = sorted(reg._metrics.items())
    lines: List[str] = []
    typed: set = set()
    for key, (kind, labels, m) in items:
        name = _prom_name(_parse_key(key)[0])
        if name not in typed:
            lines.append(f"# TYPE {name} {kind}")
            typed.add(name)
        if kind in ("counter", "gauge"):
            lines.append(f"{name}{_prom_labels(labels)} {m.value}")
        else:
            cum = 0
            for i, edge in enumerate(m.bounds):
                cum += m.counts[i]
                le = 'le="%s"' % edge
                lines.append(f"{name}_bucket{_prom_labels(labels, le)} {cum}")
            cum += m.counts[-1]
            le = 'le="+Inf"'
            lines.append(f"{name}_bucket{_prom_labels(labels, le)} {cum}")
            lines.append(f"{name}_sum{_prom_labels(labels)} {m.sum}")
            lines.append(f"{name}_count{_prom_labels(labels)} {m.count}")
    body = "\n".join(lines) + "\n"
    tmp = f"{path}.tmp.{os.getpid()}"
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(tmp, "w") as f:
        f.write(body)
    os.replace(tmp, path)


# --- JAX collectors ---------------------------------------------------------


_jax_collectors_installed = False


def install_jax_collectors() -> bool:
    """Count XLA compilation events via ``jax.monitoring`` listeners.
    Registration is global and permanent in jax, so this installs once per
    process; the listeners consult the enabled flag at fire time."""
    global _jax_collectors_installed
    if _jax_collectors_installed:
        return True
    try:
        from jax import monitoring
    except Exception:  # jax absent/old — telemetry must not require it
        return False

    def _on_event(event: str, **kw) -> None:
        if _state.enabled and "compil" in event:
            _state.registry.counter(
                "fedml_jax_compilation_events_total", event=event).inc()

    def _on_duration(event: str, duration: float, **kw) -> None:
        if _state.enabled and "compil" in event:
            _state.registry.histogram(
                "fedml_jax_compilation_seconds", event=event).observe(duration)

    try:
        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:
        return False
    _jax_collectors_installed = True
    return True


class SysStatsSampler:
    """Daemon thread sampling ``SysStats`` (psutil + device.memory_stats())
    into registry gauges at a fixed cadence, flushing the Prometheus file
    each tick when one is configured (textfile-collector scrape pattern)."""

    def __init__(self, interval_s: float,
                 registry: Optional[MetricsRegistry] = None):
        self.interval_s = float(interval_s)
        self.registry = registry or _state.registry
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def sample_once(self) -> None:
        from .mlops import SysStats

        s = SysStats()
        reg = self.registry
        reg.gauge("fedml_cpu_utilization").set(s.cpu_utilization)
        reg.gauge("fedml_process_memory_gb").set(s.process_memory_gb)
        reg.gauge("fedml_host_memory_used_gb").set(s.host_memory_used_gb)
        reg.gauge("fedml_net_sent_mb_interval").set(s.net_sent_mb)
        reg.gauge("fedml_net_recv_mb_interval").set(s.net_recv_mb)
        for dm in s.device_memory:
            reg.gauge("fedml_device_bytes_in_use_gb",
                      device=dm["device"]).set(dm["bytes_in_use_gb"])
            reg.gauge("fedml_device_bytes_limit_gb",
                      device=dm["device"]).set(dm["bytes_limit_gb"])

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.sample_once()
                except Exception:
                    logging.exception("telemetry: sysstats sample failed")
                if _state.prometheus_path:
                    try:
                        write_prometheus(_state.prometheus_path, self.registry)
                    except OSError:
                        logging.exception("telemetry: prometheus write failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="telemetry-sysstats")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
