"""Algorithm frame: the FL operator interfaces.

Two layers:

1. **Functional core** (`FedAlgorithm`): an FL optimizer is a bundle of pure,
   jittable pytree functions. This is what the TPU simulators compile — the
   reference's mutable ``get/set_model_params`` dict-of-tensors contract
   (``core/alg_frame/client_trainer.py:4``) becomes immutable pytrees flowing
   through ``local_update`` / ``aggregate`` / ``server_update``.

2. **Object shell** (`ClientTrainer` / `ServerAggregator`): abstract classes
   with the reference's exact method names, for cross-silo user code and API
   parity (reference ``core/alg_frame/client_trainer.py:4``,
   ``core/alg_frame/server_aggregator.py:4``). The shells are thin: the default
   implementations delegate to a FedAlgorithm.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Any, Callable, Dict, NamedTuple, Optional

import jax

PyTree = Any


class Params(dict):
    """Typed key-value parameter bag.

    Parity: reference ``core/alg_frame/params.py:1`` — attribute + item access.
    """

    def __getattr__(self, key):
        try:
            return self[key]
        except KeyError as e:
            raise AttributeError(key) from e

    def __setattr__(self, key, value):
        self[key] = value

    def add(self, key: str, value: Any) -> None:
        self[key] = value


class Context(Params):
    """Global context singleton (reference ``core/alg_frame/context.py``)."""

    _instance: Optional["Context"] = None

    @classmethod
    def get_instance(cls) -> "Context":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance


class ClientOutput(NamedTuple):
    """Result of one client's local work in a round.

    ``update`` is a pytree matching the model params (a delta or full params,
    algorithm-dependent); ``weight`` is the aggregation weight (usually the
    local sample count); ``metrics`` is a small dict of scalars; ``state`` is
    persistent per-client state (e.g. SCAFFOLD control variates) or None.
    """

    update: PyTree
    weight: jax.Array
    metrics: Dict[str, jax.Array]
    state: PyTree = None


# --- functional algorithm bundle -------------------------------------------

LocalUpdateFn = Callable[..., ClientOutput]
# (global_params, client_state, data, rng, *static) -> ClientOutput

AggregateFn = Callable[[PyTree, jax.Array], PyTree]
# (stacked_or_summed_updates, weights) -> aggregated update

ServerUpdateFn = Callable[[PyTree, PyTree, PyTree], tuple]
# (global_params, aggregated_update, server_state) -> (new_params, server_state)


def weighted_mean(stacked_updates: PyTree, weights) -> PyTree:
    """Sample-weighted mean over the leading client axis, accumulated in f32
    (the reference pre-scale trick, ``nccl/base_framework/LocalAggregator.py:84``)
    and cast back to each leaf's dtype. The default FL aggregation."""
    import jax.numpy as jnp

    w = weights.astype(jnp.float32)
    total = jnp.maximum(w.sum(), 1.0)
    return jax.tree.map(
        lambda u: jnp.tensordot(w / total, u.astype(jnp.float32),
                                axes=(0, 0)).astype(u.dtype),
        stacked_updates,
    )


@dataclasses.dataclass(frozen=True)
class FedAlgorithm:
    """A federated optimizer as pure functions (all jittable).

    The reference implements each optimizer as a (API, Aggregator,
    ServerManager, ClientManager) quartet (SURVEY.md §2.3); here the quartet
    collapses to this bundle — managers only reappear at real network
    boundaries (cross-silo).
    """

    name: str
    init_server_state: Callable[[PyTree], PyTree]
    init_client_state: Callable[[PyTree], PyTree]
    local_update: LocalUpdateFn
    server_update: ServerUpdateFn
    # Most algorithms aggregate by weighted mean of updates; override for
    # robust/median aggregation.
    aggregate: Optional[AggregateFn] = None
    # Optional per-round injection of server state into client state before
    # local_update (e.g. SCAFFOLD broadcasting the server control variate).
    prepare_client_state: Optional[Callable[[PyTree, PyTree], PyTree]] = None
    # True when ClientOutput.update mirrors the params pytree (the common
    # case). Algorithms whose update carries a different structure (FedNova's
    # {norm_delta, tau}) set False — the simulator's bucketed partial
    # aggregation requires params-shaped updates and falls back to the even
    # schedule otherwise.
    update_is_params: bool = True
    # The RobustAggregator behind ``aggregate`` when there is one: lets the
    # simulator see the defense config (e.g. fuse sanitize+Krum into one
    # kernel pass under agg_kernels) without unwrapping the closure.
    robust: Optional[Any] = None


# --- object shells (reference API parity) -----------------------------------


class ClientTrainer(abc.ABC):
    """Abstract local trainer — reference ``core/alg_frame/client_trainer.py:4``.

    Subclass for custom cross-silo training logic. ``model`` here is a pytree
    of params (not a torch module); ``set_model_params`` replaces the tree.
    """

    def __init__(self, model: PyTree, args=None):
        self.model = model
        self.id = 0
        self.args = args
        self.local_sample_number = 0

    def set_id(self, trainer_id: int) -> None:
        self.id = trainer_id

    def get_model_params(self) -> PyTree:
        return self.model

    def set_model_params(self, model_parameters: PyTree) -> None:
        self.model = model_parameters

    @abc.abstractmethod
    def train(self, train_data, device, args) -> None:
        ...

    def test(self, test_data, device, args):
        return None


class ServerAggregator(abc.ABC):
    """Abstract aggregator — reference ``core/alg_frame/server_aggregator.py:4``."""

    def __init__(self, model: PyTree, args=None):
        self.model = model
        self.id = 0
        self.args = args

    def set_id(self, aggregator_id: int) -> None:
        self.id = aggregator_id

    def get_model_params(self) -> PyTree:
        return self.model

    def set_model_params(self, model_parameters: PyTree) -> None:
        self.model = model_parameters

    @abc.abstractmethod
    def aggregate(self, raw_client_model_list) -> PyTree:
        ...

    def test(self, test_data, device, args):
        return None

    def test_on_the_server(self, train_data_local_dict, test_data_local_dict, device, args=None):
        return None
