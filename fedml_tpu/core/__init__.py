"""Core FL runtime: algorithm frame, partitioning, scheduling, robustness, MPC.

Parity surface: reference ``python/fedml/core/__init__.py:1-9`` exports
``ClientTrainer``, ``ServerAggregator``,
``partition_class_samples_with_dirichlet_distribution`` — same here, plus the
pure-functional equivalents that the TPU simulators compile.
"""

from .algframe import (
    ClientTrainer,
    ServerAggregator,
    Params,
    Context,
    FedAlgorithm,
    ClientOutput,
)
from .partition import (
    non_iid_partition_with_dirichlet_distribution,
    partition_class_samples_with_dirichlet_distribution,
    homo_partition,
)
from .dp import epsilon_for_training, rdp_epsilon
from .security import (
    FedMLAttacker,
    gaussian_attack,
    label_flip_data,
    scale_attack,
    sign_flip_attack,
)
from .robust import RobustAggregator, coordinate_median, norm_clip_update, trimmed_mean
from .scheduler import balanced_client_schedule, dp_schedule, even_client_schedule

__all__ = [
    "ClientTrainer",
    "ServerAggregator",
    "Params",
    "Context",
    "FedAlgorithm",
    "ClientOutput",
    "non_iid_partition_with_dirichlet_distribution",
    "partition_class_samples_with_dirichlet_distribution",
    "homo_partition",
    "rdp_epsilon", "epsilon_for_training", "RobustAggregator",
    "FedMLAttacker", "scale_attack", "sign_flip_attack", "gaussian_attack",
    "label_flip_data",
    "coordinate_median",
    "norm_clip_update",
    "trimmed_mean",
    "dp_schedule",
    "even_client_schedule",
    "balanced_client_schedule",
]
