"""Robust aggregation defenses: norm clipping, weak DP, coordinate median.

Parity: reference ``core/robustness/robust_aggregation.py:41``
(``norm_diff_clipping:46``, ``add_noise:61``, ``coordinate_median_agg:66``).
Redesign: defenses are pure pytree functions over *stacked* client updates
(leading client axis), so they jit and vmap — a whole cohort is clipped in one
fused XLA program instead of a per-client Python loop, and they slot directly
into ``FedAlgorithm.aggregate``. BatchNorm running stats are excluded from
clipping by name, matching the reference's ``is_weight_param`` filter
(robust_aggregation.py:34-39).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

PyTree = Any

_NON_WEIGHT_KEYS = ("running_mean", "running_var", "num_batches_tracked", "batch_stats")


def _is_weight_path(path) -> bool:
    names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
    return not any(any(nk in str(n) for nk in _NON_WEIGHT_KEYS) for n in names)


def global_norm(tree: PyTree, weights_only: bool = False) -> jax.Array:
    """L2 norm over all (weight) leaves of a pytree."""
    if weights_only:
        leaves = [
            v for p, v in jax.tree_util.tree_leaves_with_path(tree) if _is_weight_path(p)
        ]
    else:
        leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves))


def norm_clip_update(update: PyTree, norm_bound: float) -> PyTree:
    """Scale one client's update so ‖update‖₂ ≤ norm_bound (reference
    ``norm_diff_clipping:46`` computes the same on (local - global)); batch
    stats pass through unscaled, as the reference excludes them."""
    norm = global_norm(update, weights_only=True)
    scale = 1.0 / jnp.maximum(1.0, norm / norm_bound)

    def _clip(path, leaf):
        return leaf * scale if _is_weight_path(path) else leaf

    return jax.tree_util.tree_map_with_path(_clip, update)


def norm_clip_stacked(stacked_updates: PyTree, norm_bound: float) -> PyTree:
    """vmap of norm_clip_update over the leading client axis."""
    return jax.vmap(lambda u: norm_clip_update(u, norm_bound))(stacked_updates)


def add_gaussian_noise(tree: PyTree, stddev: float, rng: jax.Array) -> PyTree:
    """Weak-DP Gaussian noise on the aggregate (reference ``add_noise:61``)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(rng, len(leaves))
    noised = [
        leaf + stddev * jax.random.normal(k, leaf.shape, leaf.dtype)
        for leaf, k in zip(leaves, keys)
    ]
    return jax.tree_util.tree_unflatten(treedef, noised)


def coordinate_median(stacked_updates: PyTree) -> PyTree:
    """Coordinate-wise median over the leading client axis (Yin et al. 2018;
    reference ``coordinate_median_agg:66`` — there a vectorize/concat/median/
    unflatten dance over state_dicts; here one tree_map of jnp.median)."""
    return jax.tree_util.tree_map(lambda x: jnp.median(x, axis=0), stacked_updates)


def trimmed_mean(stacked_updates: PyTree, trim_ratio: float = 0.1) -> PyTree:
    """Coordinate-wise β-trimmed mean (same paper as coordinate median; the
    reference doesn't ship it but lists it in its robustness docs)."""

    def _tm(x):
        n = x.shape[0]
        k = int(n * trim_ratio)
        s = jnp.sort(x, axis=0)
        return jnp.mean(s[k: n - k if n - k > k else k + 1], axis=0)

    return jax.tree_util.tree_map(_tm, stacked_updates)


@dataclasses.dataclass(frozen=True)
class RobustAggregator:
    """Config-driven defense bundle (reference ``RobustAggregator:41``).

    defense_type: 'norm_diff_clipping' | 'weak_dp' | 'coordinate_median' |
    'trimmed_mean' | None. Call :meth:`aggregate` with stacked updates and
    normalized weights; returns the defended aggregate.
    """

    defense_type: Optional[str] = None
    norm_bound: float = 1.0
    stddev: float = 0.0
    trim_ratio: float = 0.1

    def aggregate(self, stacked_updates: PyTree, weights: jax.Array, rng=None) -> PyTree:
        w = weights / jnp.sum(weights)

        def weighted_mean(tree):
            return jax.tree_util.tree_map(
                lambda x: jnp.tensordot(w.astype(x.dtype), x, axes=1), tree
            )

        if self.defense_type in (None, "none"):
            return weighted_mean(stacked_updates)
        if self.defense_type == "norm_diff_clipping":
            return weighted_mean(norm_clip_stacked(stacked_updates, self.norm_bound))
        if self.defense_type == "weak_dp":
            if rng is None:
                raise ValueError(
                    "weak_dp requires a fresh per-round rng; a fixed default "
                    "key would add the same noise every round (no privacy)"
                )
            clipped = weighted_mean(norm_clip_stacked(stacked_updates, self.norm_bound))
            return add_gaussian_noise(clipped, self.stddev, rng)
        if self.defense_type == "coordinate_median":
            return coordinate_median(stacked_updates)
        if self.defense_type == "trimmed_mean":
            return trimmed_mean(stacked_updates, self.trim_ratio)
        raise ValueError(f"unknown defense_type '{self.defense_type}'")
