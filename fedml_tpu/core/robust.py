"""Robust aggregation defenses: clipping, weak DP, median, Krum, sanitizer.

Parity: reference ``core/robustness/robust_aggregation.py:41``
(``norm_diff_clipping:46``, ``add_noise:61``, ``coordinate_median_agg:66``).
Redesign: defenses are pure pytree functions over *stacked* client updates
(leading client axis), so they jit and vmap — a whole cohort is clipped in one
fused XLA program instead of a per-client Python loop, and they slot directly
into ``FedAlgorithm.aggregate``. BatchNorm running stats are excluded from
clipping by name, matching the reference's ``is_weight_param`` filter
(robust_aggregation.py:34-39).

Beyond the reference: the **update sanitizer** (:func:`sanitize_stacked` —
non-finite leaves and robust-z norm outliers get their aggregation weight
zeroed and land in a per-round quarantine set) and the **Krum family**
(:func:`krum_aggregate` — Blanchard et al. 2017 selection over pairwise
squared distances, all inside XLA), which the reference only documents.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

PyTree = Any

_NON_WEIGHT_KEYS = ("running_mean", "running_var", "num_batches_tracked", "batch_stats")


def _is_weight_path(path) -> bool:
    names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
    return not any(any(nk in str(n) for nk in _NON_WEIGHT_KEYS) for n in names)


def global_norm(tree: PyTree, weights_only: bool = False) -> jax.Array:
    """L2 norm over all (weight) leaves of a pytree."""
    if weights_only:
        leaves = [
            v for p, v in jax.tree_util.tree_leaves_with_path(tree) if _is_weight_path(p)
        ]
    else:
        leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves))


def tree_finite(tree: PyTree) -> jax.Array:
    """Scalar bool: every entry of every leaf is finite. The shared
    last-good gate of the self-healing plane — the divergence watchdog
    rejects a round whose output params fail it, and the serving canary
    refuses to promote a committed version that fails it (a non-finite
    model would serve NaN scores to every request). jit-able; callers on
    a hot path wrap it in ``jax.jit`` once and reuse the executable."""
    return jax.tree_util.tree_reduce(
        lambda a, x: jnp.logical_and(a, jnp.all(jnp.isfinite(x))),
        tree, jnp.bool_(True))


def tree_finite_host(tree: PyTree) -> bool:
    """Host-side companion to :func:`tree_finite` — identical verdict,
    pure numpy over the leaves. The serving plane's publish pre-gate uses
    this one: checking a candidate must never dispatch a device op (the
    first jax op of a process boots the XLA backend — seconds on a loaded
    host — which would stall the publish path and starve the canary)."""
    import numpy as _np

    return all(bool(_np.all(_np.isfinite(_np.asarray(l))))
               for l in jax.tree_util.tree_leaves(tree))


def norm_clip_update(update: PyTree, norm_bound: float) -> PyTree:
    """Scale one client's update so ‖update‖₂ ≤ norm_bound (reference
    ``norm_diff_clipping:46`` computes the same on (local - global)); batch
    stats pass through unscaled, as the reference excludes them."""
    norm = global_norm(update, weights_only=True)
    scale = 1.0 / jnp.maximum(1.0, norm / norm_bound)

    def _clip(path, leaf):
        return leaf * scale if _is_weight_path(path) else leaf

    return jax.tree_util.tree_map_with_path(_clip, update)


def norm_clip_stacked(stacked_updates: PyTree, norm_bound: float) -> PyTree:
    """vmap of norm_clip_update over the leading client axis."""
    return jax.vmap(lambda u: norm_clip_update(u, norm_bound))(stacked_updates)


def add_gaussian_noise(tree: PyTree, stddev: float, rng: jax.Array) -> PyTree:
    """Weak-DP Gaussian noise on the aggregate (reference ``add_noise:61``)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(rng, len(leaves))
    noised = [
        leaf + stddev * jax.random.normal(k, leaf.shape, leaf.dtype)
        for leaf, k in zip(leaves, keys)
    ]
    return jax.tree_util.tree_unflatten(treedef, noised)


def coordinate_median(stacked_updates: PyTree) -> PyTree:
    """Coordinate-wise median over the leading client axis (Yin et al. 2018;
    reference ``coordinate_median_agg:66`` — there a vectorize/concat/median/
    unflatten dance over state_dicts; here one tree_map of jnp.median)."""
    return jax.tree_util.tree_map(lambda x: jnp.median(x, axis=0), stacked_updates)


def trimmed_mean(stacked_updates: PyTree, trim_ratio: float = 0.1,
                 weights: Optional[jax.Array] = None) -> PyTree:
    """Coordinate-wise β-trimmed mean (same paper as coordinate median; the
    reference doesn't ship it but lists it in its robustness docs).

    Weight-aware: with ``weights`` the surviving (untrimmed) coordinates are
    combined by their owners' weights instead of a plain mean, so e.g. a
    zero-weight (quarantined) client's coordinates can survive the trim
    without contributing. ``k = min(int(n*trim_ratio), (n-1)//2)`` guarantees
    a non-empty slice for any cohort size (k <= (n-1)//2 implies n-k > k)."""

    def _tm(x):
        n = x.shape[0]
        k = min(int(n * trim_ratio), (n - 1) // 2)
        if weights is None:
            s = jnp.sort(x, axis=0)
            return jnp.mean(s[k: n - k], axis=0)
        order = jnp.argsort(x.astype(jnp.float32), axis=0)
        xs = jnp.take_along_axis(x.astype(jnp.float32), order, axis=0)
        # fancy-index the (n,) weight vector by the per-coordinate order so
        # each sorted coordinate carries its owner's weight
        ws = weights.astype(jnp.float32)[order]
        num = jnp.sum(xs[k: n - k] * ws[k: n - k], axis=0)
        den = jnp.maximum(jnp.sum(ws[k: n - k], axis=0), 1e-12)
        return (num / den).astype(x.dtype)

    return jax.tree_util.tree_map(_tm, stacked_updates)


def _masked_median(x: jax.Array, valid, n_valid: int) -> jax.Array:
    """Median over the ``valid`` entries of a 1-D array. ``valid`` is a
    HOST (static) bool mask — invalid entries sort to +inf and the middle
    indices are Python ints, so this stays one fused sort, no dynamic
    shapes. Matches ``jnp.median`` exactly on the valid subset (mean of the
    two middle order statistics for even counts)."""
    s = jnp.sort(jnp.where(jnp.asarray(valid), x, jnp.inf))
    return 0.5 * (s[(n_valid - 1) // 2] + s[n_valid // 2])


def sanitize_stacked(stacked_updates: PyTree, weights: jax.Array,
                     z_thresh: float = 6.0, valid=None, out_shardings=None,
                     staleness_scale=None):
    """Quarantine poisoned rows of a stacked cohort before any aggregation.

    Two detectors, both jit-able over the whole cohort at once:

    - **non-finite**: any NaN/Inf leaf entry quarantines the client — one
      non-finite upload would otherwise poison the global params forever
      (``0 * nan == nan``, so even zero-weighting is not enough);
    - **norm outlier**: robust z-score of each client's update L2 norm,
      ``z = (norm - median) / max(1.4826 * MAD, floor)``, upper side only —
      scaled-boost (model replacement) uploads sit far above the honest
      norm band. The MAD floor is relative (5% of the median) so a cohort
      of near-identical norms doesn't turn fp jitter into outliers.

    Returns ``(clean_updates, clean_weights, quarantine, z)``: quarantined
    rows are **zeroed** (not just zero-weighted) and their weight is 0;
    ``quarantine`` is a (C,) bool mask and ``z`` the (C,) robust z-scores
    (``+inf`` for non-finite rows).

    ``valid`` (optional, HOST bool array of shape (C,)) marks real cohort
    rows when the cohort was padded to a mesh-axis multiple: padded rows
    are excluded from the median/MAD statistics (an all-zero pad row is a
    perfectly plausible "inlier" that would drag both) and are never
    quarantined (their z is 0). ``valid=None`` is byte-identical to the
    pre-padding behavior.

    ``out_shardings`` (optional, a pytree of shardings matching
    ``stacked_updates``) re-pins the cleaned stack's layout inside a sharded
    jit — the zeroing ``where`` is elementwise, but on a 2-D (client×model)
    mesh the constraint keeps GSPMD from gathering the stack before the
    aggregation that follows. Numerically a no-op.

    ``staleness_scale`` (optional, (C,) f32) makes the z-score
    staleness-aware for buffered-async cohorts: each row's norm is
    multiplied by its staleness down-weight ``(1+s)^-α`` BEFORE the
    median/MAD statistics, so the detector judges updates by what they
    will actually contribute post-weighting — a stale honest client whose
    raw norm drifted high is not flagged, while a fresh boosted upload
    still is. ``None`` is byte-identical to the synchronous behavior.
    """
    leaves = jax.tree_util.tree_leaves(stacked_updates)
    C = leaves[0].shape[0]
    bad = jnp.zeros((C,), bool)
    sq = jnp.zeros((C,), jnp.float32)
    for x in leaves:
        xf = x.astype(jnp.float32).reshape(C, -1)
        bad = bad | ~jnp.isfinite(xf).all(axis=1)
        sq = sq + jnp.sum(jnp.square(jnp.nan_to_num(xf)), axis=1)
    norm = jnp.sqrt(sq)
    if staleness_scale is not None:
        norm = norm * staleness_scale.astype(jnp.float32)
    if valid is None:
        med = jnp.median(norm)
        mad = jnp.median(jnp.abs(norm - med))
    else:
        import numpy as _np

        valid = _np.asarray(valid, bool)
        n_valid = int(valid.sum())
        med = _masked_median(norm, valid, n_valid)
        mad = _masked_median(jnp.abs(norm - med), valid, n_valid)
    scale = jnp.maximum(1.4826 * mad, 1e-6 + 0.05 * med)
    z = jnp.where(bad, jnp.inf, (norm - med) / scale)
    quarantine = bad | (z > z_thresh)
    if valid is not None:
        v = jnp.asarray(valid)
        quarantine = quarantine & v
        z = jnp.where(v, z, 0.0)
    keep = 1.0 - quarantine.astype(jnp.float32)
    clean = jax.tree_util.tree_map(
        lambda x: jnp.where(
            quarantine.reshape((C,) + (1,) * (x.ndim - 1)),
            jnp.zeros_like(x), x),
        stacked_updates,
    )
    if out_shardings is not None:
        clean = jax.tree_util.tree_map(
            lambda x, s: jax.lax.with_sharding_constraint(x, s),
            clean, out_shardings)
    return clean, weights * keep, quarantine, z


def pairwise_sq_dists(stacked_updates: PyTree, valid=None,
                      tile_size: Optional[int] = None) -> jax.Array:
    """(C, C) squared L2 distances between clients' updates, computed as one
    vmap-ed reduction over the flattened cohort matrix — XLA lowers the
    ``vmap(row . matrix)`` to a single (C, D) x (D, C) matmul (MXU-friendly)
    instead of C² per-pair subtractions. Non-finite entries are zeroed first
    so a NaN upload cannot poison every distance (its row is caught by
    :func:`sanitize_stacked` / the Krum score penalty instead).

    ``tile_size`` computes the Gram matrix in client-axis row tiles of that
    size (``lax.map`` over ``(C/t, t, D) @ (D, C)`` blocks): peak live
    intermediate drops from the full (C, D) x (C, D) product's workspace to
    one tile's, and under a sharded jit each device only materializes its
    own row tiles. Any positive size works — a final partial tile is padded
    with zero rows whose Gram outputs are sliced away (zero pad rows cannot
    perturb the real elements' bits, and they never reach the distance
    matrix). ``None`` is the original single matmul.

    ``valid`` (HOST bool (C,)) marks real rows of a padded cohort: any
    distance involving a padded row is +inf (so Krum never counts a pad row
    among a client's nearest peers), except the diagonal which stays 0.
    """
    leaves = jax.tree_util.tree_leaves(stacked_updates)
    C = leaves[0].shape[0]
    flat = jnp.concatenate(
        [jnp.nan_to_num(x.astype(jnp.float32)).reshape(C, -1) for x in leaves],
        axis=1,
    )
    sqn = jnp.sum(flat * flat, axis=1)
    if tile_size is None:
        gram = jax.vmap(lambda r: flat @ r)(flat)
    else:
        t = int(tile_size)
        if t <= 0:
            raise ValueError(f"tile_size={t} must be positive")
        cpad = -(-C // t) * t
        fp = flat if cpad == C else jnp.concatenate(
            [flat, jnp.zeros((cpad - C, flat.shape[1]), jnp.float32)], axis=0)
        tiles = fp.reshape(cpad // t, t, flat.shape[1])
        gram = jax.lax.map(
            lambda blk: blk @ flat.T, tiles).reshape(cpad, C)[:C]
    d = jnp.maximum(sqn[:, None] + sqn[None, :] - 2.0 * gram, 0.0)
    if valid is not None:
        v = jnp.asarray(valid)
        pair_ok = v[:, None] & v[None, :]
        d = jnp.where(pair_ok, d, jnp.inf)
        d = jnp.where(jnp.eye(C, dtype=bool), 0.0, d)
    return d


def krum_scores(dists: jax.Array, n_byz: int,
                n_valid: Optional[int] = None) -> jax.Array:
    """Krum score per client (Blanchard et al. 2017): the sum of its
    ``C - f - 2`` smallest squared distances to OTHER clients (the self
    distance — the zero first column of the row-sorted matrix — is dropped).
    Lower = better surrounded by honest peers. ``n_valid`` caps the
    neighbor count for padded cohorts (pad rows' distances are +inf, so the
    cap keeps every real client's score finite)."""
    C = dists.shape[0]
    n = C if n_valid is None else int(n_valid)
    k = max(1, min(n - n_byz - 2, n - 1))
    s = jnp.sort(dists, axis=1)
    return s[:, 1:k + 1].sum(axis=1)


def krum_aggregate(stacked_updates: PyTree, weights: jax.Array,
                   n_byz: int = 0, m: int = 1,
                   sample_weighted: bool = False, valid=None,
                   tile_size: Optional[int] = None):
    """Krum-family aggregation, selection fully inside XLA.

    ``m=1`` is classic Krum (the single best-surrounded update), ``m>1`` is
    multi-Krum over the ``m`` lowest-scoring clients — averaged uniformly
    (the paper's form) or by sample weight (``sample_weighted=True``,
    FedAvg-over-Krum-survivors). Zero-weight clients (dropped or already
    quarantined) get an infinite score so they can never be selected.
    ``valid``/``tile_size`` thread through to :func:`pairwise_sq_dists` /
    :func:`krum_scores` for padded or memory-tiled cohorts.
    Returns ``(aggregate, selected)`` with ``selected`` a (C,) float mask.
    """
    import numpy as _np

    n_valid = None if valid is None else int(_np.asarray(valid, bool).sum())
    scores = krum_scores(
        pairwise_sq_dists(stacked_updates, valid=valid, tile_size=tile_size),
        n_byz, n_valid=n_valid)
    scores = jnp.where(weights > 0, scores, jnp.inf)
    if valid is not None:
        scores = jnp.where(jnp.asarray(valid), scores, jnp.inf)
    C = scores.shape[0]
    m = max(1, min(int(m), C))
    _, idx = jax.lax.top_k(-scores, m)
    selected = jnp.zeros((C,), jnp.float32).at[idx].set(1.0)
    # a selected-but-zero-weight client (cohort smaller than m) still must
    # not contribute
    selected = selected * (weights > 0)
    w = selected * weights.astype(jnp.float32) if sample_weighted else selected
    w = w / jnp.maximum(jnp.sum(w), 1e-12)
    agg = jax.tree_util.tree_map(
        lambda x: jnp.tensordot(w.astype(x.dtype), x, axes=1), stacked_updates
    )
    return agg, selected


def fused_sanitize_krum(stacked_updates: PyTree, weights: jax.Array,
                        z_thresh: float = 6.0, n_byz: int = 0, m: int = 1,
                        sample_weighted: bool = False, valid=None,
                        out_shardings=None, use_kernel: bool = True,
                        interpret=None, staleness_scale=None):
    """Fused ``sanitize_stacked`` + ``krum_aggregate`` over one read of the
    cohort stack — the agg_kernels fast path for the Krum defense family.

    Bit-identical to the sequential pair the simulator runs unfused
    (``sanitize_stacked(valid=..., out_shardings=...)`` followed by
    ``krum_aggregate`` WITHOUT ``valid`` — mirroring
    :meth:`RobustAggregator.aggregate_with_info`'s exact call). The zeroed
    "clean" copy of the stack is never materialized: the pairwise Gram
    matrix is computed from the raw (nan-sanitized) stack in one Pallas
    pass (``ops.pallas.agg_robust.fused_gram``) and the quarantine zeroing
    is applied algebraically afterwards — zeroing a matmul operand row
    cannot perturb any other output element's bits, so exact ``where``
    masks on the Gram/sq-norm planes reproduce the zero-copy-then-matmul
    distances. The cheap O(C*D) sanitize statistics stay in plain jnp with
    ``sanitize_stacked``'s verbatim per-leaf expressions (same shapes =>
    same reduction order => same bits; a strided-slice sum inside the
    kernel's fused row tiles is NOT reduction-order-stable — see
    agg_robust's module docstring). The only remaining reads of the update
    are fused into the final weighted ``tensordot``.

    ``staleness_scale`` mirrors :func:`sanitize_stacked`'s parameter (the
    identical expression on the identical shape, so the fused/unfused
    parity contract holds with or without it).

    Returns ``(agg, clean_weights, quarantine, z, selected)``.
    """
    leaves = jax.tree_util.tree_leaves(stacked_updates)
    C = leaves[0].shape[0]
    # --- sanitize_stacked's statistics, expression for expression on the
    # oracle's own per-leaf (C, -1) shapes
    bad = jnp.zeros((C,), bool)
    sq = jnp.zeros((C,), jnp.float32)
    for x in leaves:
        xf = x.astype(jnp.float32).reshape(C, -1)
        bad = bad | ~jnp.isfinite(xf).all(axis=1)
        sq = sq + jnp.sum(jnp.square(jnp.nan_to_num(xf)), axis=1)
    norm = jnp.sqrt(sq)
    if staleness_scale is not None:
        norm = norm * staleness_scale.astype(jnp.float32)
    if valid is None:
        med = jnp.median(norm)
        mad = jnp.median(jnp.abs(norm - med))
    else:
        import numpy as _np

        v_np = _np.asarray(valid, bool)
        n_valid = int(v_np.sum())
        med = _masked_median(norm, v_np, n_valid)
        mad = _masked_median(jnp.abs(norm - med), v_np, n_valid)
    scale = jnp.maximum(1.4826 * mad, 1e-6 + 0.05 * med)
    z = jnp.where(bad, jnp.inf, (norm - med) / scale)
    quarantine = bad | (z > z_thresh)
    if valid is not None:
        v = jnp.asarray(valid)
        quarantine = quarantine & v
        z = jnp.where(v, z, 0.0)
    keep = 1.0 - quarantine.astype(jnp.float32)
    clean_weights = weights * keep
    # --- pairwise_sq_dists on the zeroed stack, algebraically: flat/sqn are
    # its verbatim expressions on the RAW stack (bit-identical rows for
    # non-quarantined clients); a zeroed row has sq-norm exactly +0.0 and
    # Gram entries exactly +0.0, so masking with where (NOT multiplying —
    # 0 * inf from an overflowed norm would differ) reproduces the unfused
    # distance bits. Only the O(C^2*D) Gram plane runs in the kernel.
    flat = jnp.concatenate(
        [jnp.nan_to_num(x.astype(jnp.float32)).reshape(C, -1) for x in leaves],
        axis=1,
    )
    sqn = jnp.sum(flat * flat, axis=1)
    from ..ops.pallas import agg_robust as _ar

    gram = _ar.fused_gram(flat, use_kernel=use_kernel, interpret=interpret)
    sqn_m = jnp.where(quarantine, jnp.float32(0.0), sqn)
    pair_q = quarantine[:, None] | quarantine[None, :]
    gram_m = jnp.where(pair_q, jnp.float32(0.0), gram)
    d = jnp.maximum(sqn_m[:, None] + sqn_m[None, :] - 2.0 * gram_m, 0.0)
    # --- krum_aggregate, expression for expression (no valid= here: the
    # simulator's unfused path never threads it into the Krum stage either)
    scores = krum_scores(d, n_byz, n_valid=None)
    scores = jnp.where(clean_weights > 0, scores, jnp.inf)
    m = max(1, min(int(m), C))
    _, idx = jax.lax.top_k(-scores, m)
    selected = jnp.zeros((C,), jnp.float32).at[idx].set(1.0)
    selected = selected * (clean_weights > 0)
    w = (selected * clean_weights.astype(jnp.float32) if sample_weighted
         else selected)
    w = w / jnp.maximum(jnp.sum(w), 1e-12)

    def _agg_leaf(x, s=None):
        xm = jnp.where(
            quarantine.reshape((C,) + (1,) * (x.ndim - 1)),
            jnp.zeros_like(x), x)
        if s is not None:
            xm = jax.lax.with_sharding_constraint(xm, s)
        return jnp.tensordot(w.astype(x.dtype), xm, axes=1)

    if out_shardings is None:
        agg = jax.tree_util.tree_map(_agg_leaf, stacked_updates)
    else:
        agg = jax.tree_util.tree_map(
            _agg_leaf, stacked_updates, out_shardings)
    return agg, clean_weights, quarantine, z, selected


@dataclasses.dataclass(frozen=True)
class RobustAggregator:
    """Config-driven defense bundle (reference ``RobustAggregator:41``).

    defense_type: 'norm_diff_clipping' | 'weak_dp' | 'coordinate_median' |
    'trimmed_mean' | 'krum' | 'multi_krum' | 'krum_fedavg' | None. Call
    :meth:`aggregate` with stacked updates and weights; returns the defended
    aggregate. :meth:`aggregate_with_info` additionally reports the per-round
    quarantine/selection masks for telemetry and rollback decisions.

    ``sanitize=True`` runs :func:`sanitize_stacked` before the defense:
    non-finite and norm-outlier rows are zeroed and zero-weighted (for the
    weight-blind median/trimmed defenses a zeroed row is a conservative
    "no-op update" vote — still within those estimators' breakdown point).

    ``byzantine_n`` is Krum's f (0 = auto ``(C-3)//2``, the paper's maximum
    admissible); ``multi_krum_m`` the survivor count (None = ``C - f``).
    """

    defense_type: Optional[str] = None
    norm_bound: float = 1.0
    stddev: float = 0.0
    trim_ratio: float = 0.1
    byzantine_n: int = 0
    multi_krum_m: Optional[int] = None
    sanitize: bool = False
    z_thresh: float = 6.0
    # Krum Gram-matrix row-tile size (must divide the cohort size); None =
    # one full (C, D) x (D, C) matmul. See pairwise_sq_dists.
    krum_tile: Optional[int] = None

    KRUM_FAMILY = ("krum", "multi_krum", "krum_fedavg")

    def _krum_fm(self, cohort_size: int) -> tuple:
        f = self.byzantine_n if self.byzantine_n > 0 else max(
            0, (cohort_size - 3) // 2)
        if self.defense_type == "krum":
            return f, 1
        m = (int(self.multi_krum_m) if self.multi_krum_m
             else max(1, cohort_size - f))
        return f, m

    def aggregate(self, stacked_updates: PyTree, weights: jax.Array, rng=None) -> PyTree:
        agg, _ = self.aggregate_with_info(stacked_updates, weights, rng)
        return agg

    def aggregate_with_info(self, stacked_updates: PyTree, weights: jax.Array,
                            rng=None, staleness_scale=None) -> tuple:
        """Defended aggregate plus a jit-compatible info dict:
        ``quarantine`` (C,) bool, ``z`` (C,) robust z-scores, ``selected``
        (C,) float — the clients that actually contributed.

        ``staleness_scale`` forwards to :func:`sanitize_stacked` (buffered-
        async cohorts judge norms post-down-weighting); ``None`` keeps the
        synchronous behavior bit-for-bit."""
        C = jax.tree_util.tree_leaves(stacked_updates)[0].shape[0]
        if self.sanitize:
            stacked_updates, weights, quarantine, z = sanitize_stacked(
                stacked_updates, weights, self.z_thresh,
                staleness_scale=staleness_scale)
        else:
            quarantine = jnp.zeros((C,), bool)
            z = jnp.zeros((C,), jnp.float32)
        # all-quarantined cohort: the eps floor turns the round into a no-op
        # (zero aggregate) instead of a NaN division
        w = weights / jnp.maximum(jnp.sum(weights), 1e-12)
        selected = (weights > 0).astype(jnp.float32)
        info = lambda: {"quarantine": quarantine, "z": z,  # noqa: E731
                        "selected": selected}

        def weighted_mean(tree):
            return jax.tree_util.tree_map(
                lambda x: jnp.tensordot(w.astype(x.dtype), x, axes=1), tree
            )

        if self.defense_type in (None, "none"):
            return weighted_mean(stacked_updates), info()
        if self.defense_type == "norm_diff_clipping":
            return weighted_mean(
                norm_clip_stacked(stacked_updates, self.norm_bound)), info()
        if self.defense_type == "weak_dp":
            if rng is None:
                raise ValueError(
                    "weak_dp requires a fresh per-round rng; a fixed default "
                    "key would add the same noise every round (no privacy)"
                )
            clipped = weighted_mean(norm_clip_stacked(stacked_updates, self.norm_bound))
            return add_gaussian_noise(clipped, self.stddev, rng), info()
        if self.defense_type == "coordinate_median":
            return coordinate_median(stacked_updates), info()
        if self.defense_type == "trimmed_mean":
            return trimmed_mean(
                stacked_updates, self.trim_ratio, weights=weights), info()
        if self.defense_type in self.KRUM_FAMILY:
            f, m = self._krum_fm(C)
            agg, selected = krum_aggregate(
                stacked_updates, weights, n_byz=f, m=m,
                sample_weighted=self.defense_type == "krum_fedavg",
                tile_size=self.krum_tile)
            return agg, info()
        raise ValueError(f"unknown defense_type '{self.defense_type}'")
