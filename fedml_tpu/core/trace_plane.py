"""Trace plane: cross-process round timelines, flight recorder, anomalies.

PR 2's telemetry layer left finished spans to die in each process's ring
buffer: the server could never see a client's ``client.train`` span, and a
watchdog rollback or chaos crash destroyed the evidence with the process.
This module is the forensic layer on top:

- **span shipping & assembly** — clients attach their finished spans for the
  round (bounded count, size-capped msgpack) to the model-upload message;
  the server folds them into a :class:`TraceAssembler` keyed by the
  already-propagated ``trace_id``, de-duplicated by ``span_id`` and
  clock-skew-corrected from the handshake exchange (the client stamps its
  wall clock on the CLIENT_STATUS reply; the server records
  ``offset = server_wall - client_wall``).
- **Perfetto/Chrome trace-event export** — :func:`export_chrome_trace`
  renders spans, per-round phase slices, and instant events (quarantine,
  rollback, admission, shed, crash, anomaly) as Chrome ``traceEvents``
  JSON: one process (pid) per tenant, one track (tid) per rank.
- **flight recorder** — a bounded ring of the last K rounds' phase records
  and instants, dumped with the span ring, a registry snapshot, and a log
  tail as one timestamped JSON bundle on watchdog rollback, terminal
  ``SendFailure``, chaos crash, or SIGTERM (plus manual triggers).
- **phase-anomaly detection** — robust-z regression of per-phase times
  against a rolling in-run baseline (median/MAD, warmup-gated), annotated
  into ``history[i]["phase_anomalies"]`` and counted in
  ``fedml_phase_anomalies_total{phase=}``, plus a recompile detector that
  flags post-warmup ``jax.monitoring`` compilation events with the round
  that triggered them.

Everything is OFF by default: with the plane disabled every hook is a
single attribute check, no message grows a byte (the disabled wire format
stays byte-identical), and ``bench.py --telemetry-overhead`` holds the <1%
budget with the plane on.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import logging
import os
import signal
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

from . import telemetry

# Message param keys (same family as telemetry.TRACE_ID_KEY): only stamped
# when span shipping is on, so the disabled wire format never changes.
SPANS_KEY = "telemetry_spans"
CLOCK_KEY = "telemetry_wall_clock"


# --- configuration -----------------------------------------------------------


@dataclasses.dataclass
class TracePlaneConfig:
    """The ``trace_*`` / ``flight_*`` config family (see
    docs/observability.md). All features default off."""

    ship_spans: bool = False
    ship_max_spans: int = 256
    ship_max_bytes: int = 262144
    anomaly_detection: bool = False
    anomaly_window: int = 32
    anomaly_warmup: int = 5
    anomaly_z: float = 8.0
    anomaly_min_seconds: float = 0.05
    flight_recorder: bool = False
    flight_dir: str = "flight_records"
    flight_rounds: int = 8
    flight_log_lines: int = 200
    flight_min_interval_s: float = 1.0


class _RingLogHandler(logging.Handler):
    """Bounded tail of formatted log lines for flight bundles. The deque's
    maxlen does the truncation; ``emit`` never raises into the logger."""

    def __init__(self, maxlen: int):
        super().__init__()
        self.lines: "deque[str]" = deque(maxlen=maxlen)

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self.lines.append(self.format(record))
        except Exception:
            pass


class _Plane:
    def __init__(self):
        self.cfg = TracePlaneConfig()
        self.active = False  # any feature on (single-attr fast path)
        self.lock = threading.Lock()
        self.assembler = TraceAssembler()  # defined below; _plane is
        # instantiated at the bottom of the module, after every class
        # (tenant or "", rank) -> PhaseAnomalyDetector
        self.detectors: Dict[Tuple[str, int], "PhaseAnomalyDetector"] = {}
        # recompile detector state, keyed like detectors
        self.compile_baseline: Dict[Tuple[str, int], float] = {}
        self.rounds_seen: Dict[Tuple[str, int], int] = {}
        # flight recorder ring: phase records + instants, newest last
        self.flight_ring: "deque[Dict[str, Any]]" = deque(maxlen=64)
        self.clock_offsets: Dict[Tuple[Optional[str], int], float] = {}
        self.log_handler: Optional[_RingLogHandler] = None
        self.sigterm_installed = False
        self.last_dump_wall = 0.0


def config() -> TracePlaneConfig:
    return _plane.cfg


def active() -> bool:
    return _plane.active


def configure(**kw) -> None:
    """(Re)configure the process-wide trace plane. Unknown keys raise —
    a typo silently disabling the flight recorder is the exact failure
    mode this plane exists to prevent."""
    cfg = _plane.cfg
    for key, value in kw.items():
        if not hasattr(cfg, key):
            raise TypeError(f"unknown trace-plane option {key!r}")
        setattr(cfg, key, type(getattr(TracePlaneConfig(), key))(value))
    _plane.active = bool(
        cfg.ship_spans or cfg.anomaly_detection or cfg.flight_recorder)
    with _plane.lock:
        if _plane.flight_ring.maxlen != max(cfg.flight_rounds * 8, 8):
            _plane.flight_ring = deque(
                _plane.flight_ring, maxlen=max(cfg.flight_rounds * 8, 8))
    if cfg.flight_recorder:
        _install_log_handler()
        _install_sigterm()
    elif _plane.log_handler is not None:
        logging.getLogger().removeHandler(_plane.log_handler)
        _plane.log_handler = None


def configure_from_args(args) -> None:
    """Map the flat ``trace_*`` / ``flight_*`` config keys onto
    :func:`configure` — the single read site for this config family."""
    configure(
        ship_spans=bool(getattr(args, "trace_ship_spans", False)),
        ship_max_spans=int(getattr(args, "trace_ship_max_spans", 256)),
        ship_max_bytes=int(getattr(args, "trace_ship_max_bytes", 262144)),
        anomaly_detection=bool(
            getattr(args, "trace_anomaly_detection", False)),
        anomaly_window=int(getattr(args, "trace_anomaly_window", 32)),
        anomaly_warmup=int(getattr(args, "trace_anomaly_warmup", 5)),
        anomaly_z=float(getattr(args, "trace_anomaly_z", 8.0)),
        anomaly_min_seconds=float(
            getattr(args, "trace_anomaly_min_seconds", 0.05)),
        flight_recorder=bool(getattr(args, "flight_recorder", False)),
        flight_dir=str(getattr(args, "flight_dir", "flight_records")),
        flight_rounds=int(getattr(args, "flight_rounds", 8)),
        flight_log_lines=int(getattr(args, "flight_log_lines", 200)),
    )


def reset() -> None:
    """Restore the default (all-off) state — test isolation hook, called by
    ``telemetry.configure(reset=True)``."""
    if _plane.log_handler is not None:
        logging.getLogger().removeHandler(_plane.log_handler)
    old_sigterm = _plane.sigterm_installed
    _plane.__init__()
    # signal handlers are process-global and cannot be meaningfully
    # re-installed per test; remember so configure() doesn't re-stack them
    _plane.sigterm_installed = old_sigterm


def _install_log_handler() -> None:
    if _plane.log_handler is not None:
        _plane.log_handler.lines = deque(
            _plane.log_handler.lines, maxlen=_plane.cfg.flight_log_lines)
        return
    handler = _RingLogHandler(_plane.cfg.flight_log_lines)
    handler.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s %(name)s: %(message)s"))
    logging.getLogger().addHandler(handler)
    _plane.log_handler = handler


def _install_sigterm() -> None:
    if _plane.sigterm_installed:
        return
    try:
        prev = signal.getsignal(signal.SIGTERM)

        def _on_sigterm(signum, frame):
            flight_dump("sigterm")
            if callable(prev):
                prev(signum, frame)
            else:
                raise SystemExit(143)

        signal.signal(signal.SIGTERM, _on_sigterm)
    except (ValueError, OSError):
        return  # not the main thread / no signal support — dump-less exit
    _plane.sigterm_installed = True


# --- span shipping -----------------------------------------------------------


def _msgpack():
    import msgpack

    return msgpack


def pack_spans(spans: List[Dict[str, Any]], max_spans: int,
               max_bytes: int) -> Tuple[Optional[bytes], int, int]:
    """Serialize a span list under both caps. Oldest spans are dropped
    first (the newest spans are the round being shipped). Returns
    ``(payload, shipped, dropped)``; payload None when nothing fits."""
    dropped = max(0, len(spans) - max_spans)
    spans = spans[dropped:]
    msgpack = _msgpack()
    while spans:
        payload = msgpack.packb(spans, use_bin_type=True)
        if len(payload) <= max_bytes:
            return payload, len(spans), dropped
        shed = max(1, len(spans) // 2)
        dropped += shed
        spans = spans[shed:]
    return None, 0, dropped


def unpack_spans(payload: bytes, origin_rank: int) -> List[Dict[str, Any]]:
    """Decode a shipped span payload, stamping each span with its origin
    rank (the wire sender is authoritative — a span can't lie about which
    process recorded it)."""
    spans = _msgpack().unpackb(payload, raw=False)
    out = []
    for rec in spans:
        if isinstance(rec, dict):
            rec = dict(rec, rank=int(origin_rank), shipped=True)
            out.append(rec)
    return out


def spans_for_round(round_idx: int, rank: int) -> List[Dict[str, Any]]:
    """This process's finished spans for ``round_idx`` attributable to
    ``rank``. In a multi-process deployment the ring only holds local
    spans; over loopback (all actors in one process sharing the tracer)
    the client/rank attribute keeps each actor shipping only its own."""
    out = []
    for rec in telemetry.get_tracer().finished_spans():
        if rec.get("round_idx") != round_idx:
            continue
        owner = rec.get("rank", rec.get("client"))
        if owner is None or int(owner) != int(rank):
            continue
        out.append(rec)
    return out


def attach_spans(msg, round_idx: int, rank: int) -> int:
    """Client-side: attach this round's finished spans to the upload
    message. No-op (zero wire change) unless span shipping is on."""
    if not _plane.active or not _plane.cfg.ship_spans \
            or not telemetry.enabled():
        return 0
    cfg = _plane.cfg
    payload, shipped, dropped = pack_spans(
        spans_for_round(round_idx, rank),
        cfg.ship_max_spans, cfg.ship_max_bytes)
    if dropped:
        telemetry.get_registry().counter(
            "fedml_trace_spans_ship_dropped_total").inc(dropped)
    if payload is None:
        return 0
    msg.add_params(SPANS_KEY, payload)
    telemetry.get_registry().counter(
        "fedml_trace_spans_shipped_total").inc(shipped)
    return shipped


def ingest_shipped(payload: bytes, origin_rank: int) -> int:
    """Server-side: fold a shipped span payload into the assembler and
    re-emit each span (rank-stamped) to the JSONL sink so the CLI trace
    export sees every rank's spans in one file."""
    if not telemetry.enabled():
        return 0
    try:
        spans = unpack_spans(payload, origin_rank)
    except Exception:
        logging.exception("trace_plane: undecodable span payload from rank %s",
                          origin_rank)
        return 0
    tenant = telemetry.current_tenant()
    fresh = 0
    for rec in spans:
        if tenant is not None and "tenant" not in rec:
            rec["tenant"] = tenant
        if _plane.assembler.add(rec):
            fresh += 1
            telemetry.emit_record(rec)
    if fresh:
        telemetry.get_registry().counter(
            "fedml_trace_spans_ingested_total").inc(fresh)
    return fresh


def get_assembler() -> "TraceAssembler":
    return _plane.assembler


# --- clock skew --------------------------------------------------------------


def attach_clock(msg) -> None:
    """Client-side handshake reply: stamp this process's wall clock so the
    server can estimate per-rank skew. Gated on span shipping (the stamp is
    useless without spans to correct, and the wire must not change)."""
    if _plane.active and _plane.cfg.ship_spans and telemetry.enabled():
        msg.add_params(CLOCK_KEY, time.time())


def note_client_clock(rank: int, client_wall) -> None:
    """Server-side: record ``offset = server_wall - client_wall`` for a
    rank (one-way message latency biases the estimate by at most the wire
    delay — good enough to line tracks up on one timeline). The offset is
    also emitted as a sink record so offline export can apply it."""
    if client_wall is None or not telemetry.enabled():
        return
    tenant = telemetry.current_tenant()
    offset = time.time() - float(client_wall)
    with _plane.lock:
        _plane.clock_offsets[(tenant, int(rank))] = offset
    rec = {"kind": "clock_offset", "rank": int(rank), "offset": offset}
    if tenant is not None:
        rec["tenant"] = tenant
    telemetry.emit_record(rec)


def clock_offsets() -> Dict[Tuple[Optional[str], int], float]:
    with _plane.lock:
        return dict(_plane.clock_offsets)


# --- assembler ---------------------------------------------------------------


class TraceAssembler:
    """Per-round span trees across ranks, keyed by ``trace_id``.

    Spans are de-duplicated by ``span_id`` (over loopback the server's own
    ring already holds the client spans a ship re-delivers) and evicted
    oldest-first past ``max_spans``.
    """

    def __init__(self, max_spans: int = 16384):
        self._lock = threading.Lock()
        self._spans: Dict[str, Dict[str, Any]] = {}
        self._order: "deque[str]" = deque()
        self.max_spans = int(max_spans)

    def add(self, rec: Dict[str, Any]) -> bool:
        span_id = rec.get("span_id")
        if not span_id:
            return False
        with self._lock:
            if span_id in self._spans:
                return False
            self._spans[span_id] = dict(rec)
            self._order.append(span_id)
            while len(self._order) > self.max_spans:
                self._spans.pop(self._order.popleft(), None)
        return True

    def spans(self, trace_id: Optional[str] = None,
              round_idx: Optional[int] = None,
              tenant: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            recs = [dict(r) for r in self._spans.values()]
        if trace_id is not None:
            recs = [r for r in recs if r.get("trace_id") == trace_id]
        if round_idx is not None:
            recs = [r for r in recs if r.get("round_idx") == round_idx]
        if tenant is not None:
            recs = [r for r in recs if r.get("tenant") == tenant]
        recs.sort(key=lambda r: (float(r.get("start", 0.0)),
                                 str(r.get("span_id"))))
        return recs

    def trace_ids(self) -> Dict[Optional[int], List[str]]:
        """``{round_idx: [trace_id...]}`` for every assembled round."""
        out: Dict[Optional[int], List[str]] = {}
        for rec in self.spans():
            tid = rec.get("trace_id")
            if tid and tid not in out.setdefault(rec.get("round_idx"), []):
                out[rec.get("round_idx")].append(tid)
        return out

    def signature(self, trace_id: str):
        """Canonical structure of one round tree: nested
        ``(name, rank, (children...))`` tuples sorted by (name, rank) —
        identical for the same logical round regardless of backend, span
        ids, or wall-clock."""
        recs = self.spans(trace_id=trace_id)
        by_id = {r["span_id"]: r for r in recs}
        children: Dict[Optional[str], List[Dict[str, Any]]] = {}
        for r in recs:
            parent = r.get("parent_span_id")
            if parent not in by_id:
                parent = None  # orphan (parent not shipped) -> root
            children.setdefault(parent, []).append(r)

        def build(rec):
            kids = tuple(sorted(
                (build(c) for c in children.get(rec["span_id"], [])),
            ))
            rank = rec.get("rank", rec.get("client"))
            return (str(rec.get("name")),
                    int(rank) if rank is not None else None, kids)

        return tuple(sorted(build(r) for r in children.get(None, [])))


# --- round records, instants, anomaly detection ------------------------------


class PhaseAnomalyDetector:
    """Robust-z regression detector over per-phase round times.

    Each phase keeps a rolling window; an observation is anomalous when its
    z-score against the window's median/MAD exceeds ``z_thresh`` AND it
    clears the absolute ``min_seconds`` floor (micro-phases jitter by large
    ratios that mean nothing in wall-clock). Anomalous values are NOT fed
    back into the baseline — a regression must keep firing, not become the
    new normal. The first ``warmup`` samples per phase only feed the
    baseline (compile rounds are always "anomalous" against nothing).
    """

    def __init__(self, window: int = 32, z_thresh: float = 8.0,
                 warmup: int = 5, min_seconds: float = 0.05):
        self.window = int(window)
        self.z_thresh = float(z_thresh)
        self.warmup = max(int(warmup), 2)
        self.min_seconds = float(min_seconds)
        self._baseline: Dict[str, "deque[float]"] = {}

    def observe(self, phases: Dict[str, float]) -> Dict[str, float]:
        anomalies: Dict[str, float] = {}
        for name in sorted(phases):
            dt = float(phases[name])
            base = self._baseline.setdefault(
                name, deque(maxlen=self.window))
            if len(base) >= self.warmup and dt > self.min_seconds:
                ordered = sorted(base)
                med = ordered[len(ordered) // 2]
                mad = sorted(abs(x - med) for x in ordered)[len(ordered) // 2]
                # MAD floor: a near-constant baseline must not turn every
                # microsecond of jitter into an infinite z
                scale = 1.4826 * mad + 0.05 * med + 1e-6
                z = (dt - med) / scale
                if z >= self.z_thresh:
                    anomalies[name] = round(z, 2)
                    continue
            base.append(dt)
        return anomalies


def _detector_key() -> Tuple[str, int]:
    return (telemetry.current_tenant() or "", 0)


def _recompile_delta(key: Tuple[str, int]) -> float:
    """Post-warmup delta of ``fedml_jax_compilation_events_total`` since the
    last round — a nonzero value names the round that re-triggered XLA."""
    total = telemetry.get_registry().counter_total(
        "fedml_jax_compilation_events_total")
    prev = _plane.compile_baseline.get(key)
    _plane.compile_baseline[key] = total
    return 0.0 if prev is None else max(0.0, total - prev)


def absorb_planned_compiles(rank: int = 0) -> None:
    """Fold a PLANNED compilation into the recompile detector's baseline.

    The multi-round scan engine compiles one program per block length, so
    the first dispatch of a new length (a plan's short tail block, a
    resume that re-anchors mid-block) legitimately triggers XLA after
    warmup. The engine calls this right after such a dispatch, so
    ``fedml_recompiles_post_warmup_total`` keeps meaning "unexpected
    shape/donation instability" whether rounds are fused or not."""
    if not _plane.active or not telemetry.enabled():
        return
    total = telemetry.get_registry().counter_total(
        "fedml_jax_compilation_events_total")
    _plane.compile_baseline[
        (telemetry.current_tenant() or "", int(rank))] = total


def on_round_record(rec: Dict[str, Any], rank: int = 0) -> None:
    """Fold one finished round into the trace plane: emit a phase record
    (the Chrome export's phase slices), run anomaly + recompile detection
    (annotating ``rec`` in place — it IS ``history[i]``), and feed the
    flight ring. Cheap no-op when the plane is off."""
    if not _plane.active or not telemetry.enabled():
        return
    cfg = _plane.cfg
    tenant = telemetry.current_tenant()
    phases = rec.get("phases") or {}
    record: Dict[str, Any] = {
        "kind": "phase_record",
        "rank": int(rank),
        "round": int(rec.get("round", -1)),
        "end": time.time(),
        "round_time": float(rec.get("round_time",
                                    sum(phases.values()) or 0.0)),
        "phases": [[name, float(dt)] for name, dt in phases.items()],
    }
    if tenant is not None:
        record["tenant"] = tenant
    if cfg.anomaly_detection and phases:
        key = (tenant or "", int(rank))
        det = _plane.detectors.get(key)
        if det is None:
            det = _plane.detectors[key] = PhaseAnomalyDetector(
                cfg.anomaly_window, cfg.anomaly_z, cfg.anomaly_warmup,
                cfg.anomaly_min_seconds)
        anomalies = det.observe(phases)
        if anomalies:
            rec["phase_anomalies"] = anomalies
            record["anomalies"] = anomalies
            reg = telemetry.get_registry()
            for name in anomalies:
                reg.counter("fedml_phase_anomalies_total", phase=name).inc()
            record_instant("phase_anomaly", round_idx=record["round"],
                           rank=rank, attrs={"phases": anomalies})
        n_seen = _plane.rounds_seen.get(key, 0) + 1
        _plane.rounds_seen[key] = n_seen
        delta = _recompile_delta(key)
        if n_seen > cfg.anomaly_warmup and delta > 0:
            rec["recompile_events"] = delta
            record["recompile_events"] = delta
            telemetry.get_registry().counter(
                "fedml_recompiles_post_warmup_total").inc(delta)
            record_instant("recompile", round_idx=record["round"], rank=rank,
                           attrs={"events": delta})
    if cfg.flight_recorder:
        with _plane.lock:
            _plane.flight_ring.append(record)
    telemetry.emit_record(record)


def record_instant(name: str, round_idx: Optional[int] = None, rank: int = 0,
                   attrs: Optional[Dict[str, Any]] = None) -> None:
    """One point-in-time event (quarantine / rollback / admission / shed /
    crash / anomaly, plus the serving plane's ``promote`` /
    ``rollback_served`` swaps) on a rank's track. No-op when the plane is
    off."""
    if not _plane.active or not telemetry.enabled():
        return
    rec: Dict[str, Any] = {
        "kind": "instant", "name": str(name), "ts": time.time(),
        "rank": int(rank),
    }
    tenant = telemetry.current_tenant()
    if tenant is not None:
        rec["tenant"] = tenant
    if round_idx is not None:
        rec["round"] = int(round_idx)
    if attrs:
        rec.update(attrs)
    if _plane.cfg.flight_recorder:
        with _plane.lock:
            _plane.flight_ring.append(rec)
    telemetry.emit_record(rec)


# --- comm instrumentation ----------------------------------------------------


def comm_send_span(backend: str, msg, rank: int):
    """Span around one backend send, only for in-round traffic with span
    shipping on — out-of-round messages (probes, handshakes) and the
    disabled path never allocate a span."""
    if not _plane.active or not _plane.cfg.ship_spans \
            or telemetry.current_context() is None:
        return contextlib.nullcontext()
    return telemetry.get_tracer().span(
        "comm.send", backend=backend, rank=int(rank),
        receiver=int(msg.get_receiver_id()))


# --- flight recorder ---------------------------------------------------------


def flight_dump(reason: str, force: bool = False) -> Optional[str]:
    """Write one flight-recorder bundle: the round/instant ring, the span
    ring, clock offsets, a registry snapshot, and the log tail. Returns the
    bundle path (None when the recorder is off or rate-limited). ``force``
    bypasses the enable check for manual ``--flight-record`` triggers."""
    cfg = _plane.cfg
    if not (cfg.flight_recorder or force) or not telemetry.enabled():
        return None
    now = time.time()
    with _plane.lock:
        if not force and now - _plane.last_dump_wall < cfg.flight_min_interval_s:
            return None  # a failure storm must not write a bundle per event
        _plane.last_dump_wall = now
        ring = list(_plane.flight_ring)
        offsets = dict(_plane.clock_offsets)
    records: List[Dict[str, Any]] = []
    records.extend(telemetry.get_tracer().finished_spans()[-2048:])
    records.extend(ring)
    for (tenant, rank), offset in sorted(
            offsets.items(), key=lambda kv: (kv[0][0] or "", kv[0][1])):
        rec = {"kind": "clock_offset", "rank": rank, "offset": offset}
        if tenant is not None:
            rec["tenant"] = tenant
        records.append(rec)
    bundle = {
        "kind": "flight_bundle",
        "reason": str(reason),
        "wall": now,
        "records": records,
        "registry": telemetry.get_registry().snapshot(),
        "log_tail": (list(_plane.log_handler.lines)
                     if _plane.log_handler is not None else []),
    }
    path = os.path.join(
        cfg.flight_dir, f"flight_{int(now * 1000)}_{reason}.json")
    try:
        os.makedirs(cfg.flight_dir, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(bundle, f, default=str)
        os.replace(tmp, path)
    except OSError:
        logging.exception("trace_plane: flight dump failed")
        return None
    logging.warning("trace_plane: flight bundle (%s) -> %s", reason, path)
    return path


# --- Chrome trace-event export -----------------------------------------------


def load_records(source: str) -> List[Dict[str, Any]]:
    """Read trace-plane records from a telemetry JSONL file or a flight
    bundle (dispatch on content, not extension)."""
    with open(source) as f:
        first = f.readline()
        f.seek(0)
        try:
            head = json.loads(first) if first.strip() else None
        except json.JSONDecodeError:
            head = None
        if isinstance(head, dict) and head.get("kind") == "flight_bundle":
            bundle = json.load(f)
            return list(bundle.get("records") or [])
        records = []
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue
        return records


def export_chrome_trace(records: Iterable[Dict[str, Any]],
                        out_path: Optional[str] = None,
                        tenant: Optional[str] = None,
                        round_idx: Optional[int] = None) -> Dict[str, Any]:
    """Render trace-plane records as Chrome trace-event JSON (loadable in
    Perfetto / ``chrome://tracing``): pid per tenant, tid per rank,
    ``ph:"X"`` slices for spans and phases, ``ph:"i"`` instants, skew
    correction from ``clock_offset`` records. Phase slices are laid
    sequentially inside ``[end - round_time, end]`` so their durations sum
    exactly to the recorded ``round_time``."""
    spans: Dict[str, Dict[str, Any]] = {}
    phase_recs: List[Dict[str, Any]] = []
    instants: List[Dict[str, Any]] = []
    offsets: Dict[Tuple[Optional[str], int], float] = {}
    for rec in records:
        kind = rec.get("kind")
        if tenant is not None and kind != "clock_offset" \
                and rec.get("tenant") != tenant:
            continue
        if kind == "span":
            rnd = rec.get("round_idx")
            if round_idx is not None and rnd != round_idx:
                continue
            sid = rec.get("span_id") or f"anon{len(spans)}"
            spans.setdefault(sid, rec)  # span_id dedupe: shipped copies
        elif kind == "phase_record":
            if round_idx is None or rec.get("round") == round_idx:
                phase_recs.append(rec)
        elif kind == "instant":
            if round_idx is None or rec.get("round", round_idx) == round_idx:
                instants.append(rec)
        elif kind == "clock_offset":
            offsets[(rec.get("tenant"), int(rec.get("rank", 0)))] = float(
                rec.get("offset", 0.0))

    def rank_of(rec) -> int:
        owner = rec.get("rank", rec.get("client", 0))
        try:
            return int(owner)
        except (TypeError, ValueError):
            return 0

    def corrected(rec, ts: float) -> float:
        return ts + offsets.get((rec.get("tenant"), rank_of(rec)), 0.0)

    tenants = sorted({r.get("tenant") for r in
                      list(spans.values()) + phase_recs + instants},
                     key=lambda t: (t is not None, t))
    pid_of = {t: i for i, t in enumerate(tenants)}
    events: List[Dict[str, Any]] = []
    tracks = set()
    for t, pid in pid_of.items():
        events.append({"ph": "M", "pid": pid, "tid": 0,
                       "name": "process_name",
                       "args": {"name": f"tenant:{t}" if t else "default"}})
    for rec in sorted(spans.values(),
                      key=lambda r: (float(r.get("start", 0.0)),
                                     str(r.get("span_id")))):
        pid = pid_of.get(rec.get("tenant"), 0)
        tid = rank_of(rec)
        tracks.add((pid, tid))
        events.append({
            "ph": "X", "pid": pid, "tid": tid, "cat": "span",
            "name": str(rec.get("name", "?")),
            "ts": corrected(rec, float(rec.get("start", 0.0))) * 1e6,
            "dur": float(rec.get("duration", 0.0)) * 1e6,
            "args": {k: rec.get(k) for k in
                     ("trace_id", "span_id", "round_idx", "status", "backend",
                      "receiver") if rec.get(k) is not None},
        })
    for rec in phase_recs:
        pid = pid_of.get(rec.get("tenant"), 0)
        tid = rank_of(rec)
        tracks.add((pid, tid))
        cursor = corrected(
            rec, float(rec.get("end", 0.0)) - float(rec.get("round_time", 0.0)))
        for name, dt in rec.get("phases") or []:
            events.append({
                "ph": "X", "pid": pid, "tid": tid, "cat": "phase",
                "name": str(name), "ts": cursor * 1e6,
                "dur": float(dt) * 1e6,
                "args": {"round": rec.get("round")},
            })
            cursor += float(dt)
    for rec in instants:
        pid = pid_of.get(rec.get("tenant"), 0)
        tid = rank_of(rec)
        tracks.add((pid, tid))
        args = {k: v for k, v in rec.items()
                if k not in ("kind", "name", "ts", "rank", "tenant")}
        events.append({
            "ph": "i", "pid": pid, "tid": tid, "cat": "instant", "s": "p",
            "name": str(rec.get("name", "?")),
            "ts": corrected(rec, float(rec.get("ts", 0.0))) * 1e6,
            "args": args,
        })
    for pid, tid in sorted(tracks):
        events.append({"ph": "M", "pid": pid, "tid": tid,
                       "name": "thread_name",
                       "args": {"name": f"rank {tid}"}})
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    if out_path:
        d = os.path.dirname(os.path.abspath(out_path))
        os.makedirs(d, exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(doc, f)
    return doc


_plane = _Plane()
