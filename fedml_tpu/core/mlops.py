"""Observability: runtime log, metrics, profiler events, system stats.

Parity: reference ``core/mlops/`` (SURVEY.md §5.1/§5.5) —
``MLOpsRuntimeLog:15`` (prefixed logging + excepthook), ``MLOpsMetrics:16``
(training status/round/model reports), ``MLOpsProfilerEvent:11``
(started/ended event spans), ``SysStats:8`` (psutil system metrics).
Redesign: reports go to pluggable *sinks* (in-memory ring, JSONL file, or a
comm-backend messenger) instead of a hard-wired MQTT broker + hosted
platform; the reporting API is kept so cross-silo managers can emit the same
spans the reference wraps around its round FSM
(``fedml_server_manager.py:66-69``: ``server.wait``, ``server.agg_and_eval``).
"""

from __future__ import annotations

import collections
import contextlib
import json
import logging
import os
import sys
import threading
import time
import uuid
from typing import Any, Dict, List, Optional


class MetricsSink:
    """Default sink: bounded in-memory record ring + optional JSONL file.

    The in-memory buffer is a RING: at ``max_records`` the oldest record is
    evicted (a long run keeps its most recent telemetry, and the JSONL file
    — when configured — still holds everything). Eviction is counted in
    ``dropped_records`` so truncation is visible, never silent."""

    def __init__(self, path: Optional[str] = None, max_records: int = 100_000):
        self.path = path
        self.records: "collections.deque[Dict[str, Any]]" = collections.deque(
            maxlen=max_records)
        self.max_records = max_records
        self.dropped_records = 0
        # held for the sink's lifetime; released in close()
        self._fh = open(path, "a") if path else None

    def emit(self, record: Dict[str, Any]) -> None:
        if len(self.records) == self.max_records:
            self.dropped_records += 1  # deque evicts the oldest on append
        self.records.append(record)
        if self._fh:
            self._fh.write(json.dumps(record, default=str) + "\n")
            self._fh.flush()

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None


class MLOpsRuntimeLog:
    """Prefixed logging + excepthook capture (reference
    ``mlops_runtime_log.py:15``; prefix format at :37-85)."""

    _instance: Optional["MLOpsRuntimeLog"] = None

    def __init__(self, args):
        self.args = args
        self.origin_excepthook = None
        self._hook_installed = False

    @classmethod
    def get_instance(cls, args) -> "MLOpsRuntimeLog":
        if cls._instance is None:
            cls._instance = cls(args)
        else:
            # re-bind on every call: a second run in one process must log
            # the NEW rank/run_id, not the args of whoever called first
            cls._instance.args = args
        return cls._instance

    def init_logs(self, show_stdout: bool = True) -> None:
        rank = int(getattr(self.args, "rank", 0))
        role = "Server" if rank == 0 else "Client"
        edge_id = getattr(self.args, "edge_id", rank)
        fmt = (
            f"[FedML-{role}({rank}) @device-id-{edge_id}] "
            "[%(asctime)s] [%(levelname)s] [%(filename)s:%(lineno)d] %(message)s"
        )
        handlers: List[logging.Handler] = []
        if show_stdout:
            handlers.append(logging.StreamHandler(sys.stdout))
        log_dir = getattr(self.args, "log_file_dir", None)
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            run_id = getattr(self.args, "run_id", "0")
            handlers.append(logging.FileHandler(
                os.path.join(log_dir, f"fedml-run-{run_id}-edge-{edge_id}.log")
            ))
        logging.basicConfig(level=logging.INFO, format=fmt, handlers=handlers, force=True)
        # capture uncaught exceptions into the log (reference :30); install
        # once — re-init must not capture our own hook as the "original"
        # (that would recurse on the next uncaught exception)
        if self._hook_installed:
            return
        self.origin_excepthook = sys.excepthook

        def hook(exc_type, exc_value, exc_tb):
            logging.exception("uncaught", exc_info=(exc_type, exc_value, exc_tb))
            if self.origin_excepthook:
                self.origin_excepthook(exc_type, exc_value, exc_tb)

        sys.excepthook = hook
        self._hook_installed = True


class MLOpsMetrics:
    """Training/round/model/system metric reports (reference
    ``mlops_metrics.py:16``). ``messenger`` may be a MetricsSink or a comm
    manager (anything with ``emit``/``send_message``)."""

    STATUS_IDLE = "IDLE"
    STATUS_RUNNING = "RUNNING"
    STATUS_KILLED = "KILLED"
    STATUS_FAILED = "FAILED"
    STATUS_FINISHED = "FINISHED"

    def __init__(self, sink: Optional[MetricsSink] = None):
        self.sink = sink or MetricsSink()
        self.run_id = "0"
        self.edge_id = 0

    def set_messenger(self, sink, args=None) -> None:
        self.sink = sink
        if args is not None:
            self.run_id = getattr(args, "run_id", "0")
            self.edge_id = getattr(args, "rank", 0)

    def _emit(self, kind: str, payload: Dict[str, Any]) -> None:
        self.sink.emit({
            "kind": kind, "run_id": self.run_id, "edge_id": self.edge_id,
            "timestamp": time.time(), **payload,
        })

    def report_client_training_status(self, edge_id: int, status: str) -> None:
        self._emit("client_status", {"edge_id": edge_id, "status": status})

    def report_server_training_status(self, run_id, status: str) -> None:
        self._emit("server_status", {"run_id": run_id, "status": status})

    def report_server_training_round_info(self, round_info: Dict[str, Any]) -> None:
        """Reference ``report_server_training_round_info:98``."""
        self._emit("round_info", round_info)

    def report_aggregated_model_info(self, model_info: Dict[str, Any]) -> None:
        """Reference ``report_aggregated_model_info:112``."""
        self._emit("model_info", model_info)

    def report_system_metric(self, metric: Optional[Dict[str, Any]] = None) -> None:
        self._emit("system", metric or SysStats().to_dict())


class MLOpsProfilerEvent:
    """Started/ended event spans (reference ``mlops_profiler_event.py:11``)."""

    def __init__(self, args=None, sink: Optional[MetricsSink] = None):
        self.args = args
        self.sink = sink or MetricsSink()
        self.run_id = getattr(args, "run_id", "0") if args else "0"
        self._open_events: Dict[str, float] = {}

    def log_event_started(self, event_name: str, event_value: Optional[str] = None,
                          event_edge_id: Optional[int] = None) -> None:
        self._open_events[event_name] = time.time()
        self.sink.emit({
            "kind": "event_started", "run_id": self.run_id, "event": event_name,
            "value": event_value, "edge_id": event_edge_id, "timestamp": time.time(),
        })

    def log_event_ended(self, event_name: str, event_value: Optional[str] = None,
                        event_edge_id: Optional[int] = None) -> None:
        now = time.time()
        started = self._open_events.pop(event_name, None)
        self.sink.emit({
            "kind": "event_ended", "run_id": self.run_id, "event": event_name,
            "value": event_value, "edge_id": event_edge_id, "timestamp": now,
            "duration": (now - started) if started is not None else None,
        })

    @contextlib.contextmanager
    def span(self, event_name: str, event_value: Optional[str] = None,
             event_edge_id: Optional[int] = None):
        """Paired started/ended emission around a block. The simulator brackets
        its per-round phases with these (``host_pack`` on the prefetch worker,
        ``round_dispatch`` on the round loop) so the sink shows how much of
        each round's host packing ran under the previous round's device
        compute. The ended event fires on exceptions too — no dangling spans."""
        self.log_event_started(event_name, event_value, event_edge_id)
        try:
            yield
        finally:
            self.log_event_ended(event_name, event_value, event_edge_id)

    @contextlib.contextmanager
    def device_trace(self, trace_dir: str):
        """Context manager capturing an XLA device trace (TensorBoard
        'trace_viewer' format) around the wrapped block — the TPU-native
        answer to the reference's host-side-only profiler spans: device
        op timelines, fusion boundaries, and transfer lanes come from the
        runtime itself via ``jax.profiler``. A span event brackets the
        capture in the sink so trace files correlate with round metrics.
        start_trace runs BEFORE the started span so a failed start (dir
        unwritable, trace already active) leaves no dangling open span."""
        import jax

        jax.profiler.start_trace(trace_dir)
        self.log_event_started("device_trace", event_value=trace_dir)
        try:
            yield trace_dir
        finally:
            jax.profiler.stop_trace()
            self.log_event_ended("device_trace", event_value=trace_dir)


class SysStats:
    """psutil CPU/mem/disk/net + JAX device memory (reference
    ``system_stats.py:8`` uses psutil+pynvml; TPU memory comes from
    ``device.memory_stats()`` instead of NVML).

    ``net_*_mb``/``disk_*_mb`` are PER-INTERVAL deltas since the previous
    ``SysStats()`` sample in this process (the first sample anchors the
    baseline and reports 0.0) — psutil's raw counters are monotonic
    host-lifetime cumulatives, useless for "what did this round ship". The
    psutil process handle is created once and cached (each ``Process()``
    construction re-reads /proc)."""

    _process = None           # cached psutil.Process handle
    _last_counters = None     # (monotonic_ts, net_sent, net_recv, disk_r, disk_w)
    _lock = threading.Lock()

    def __init__(self):
        import psutil

        cls = SysStats
        if cls._process is None:
            cls._process = psutil.Process()
        self.cpu_utilization = psutil.cpu_percent(interval=None)
        vm = psutil.virtual_memory()
        self.process_memory_gb = cls._process.memory_info().rss / 1e9
        self.host_memory_used_gb = vm.used / 1e9
        self.host_memory_total_gb = vm.total / 1e9
        du = psutil.disk_usage("/")
        self.disk_utilization = du.percent
        net = psutil.net_io_counters()
        dio = None
        try:
            dio = psutil.disk_io_counters()
        except Exception:  # unavailable in some containers
            pass
        now = time.monotonic()
        cur = (now, net.bytes_sent, net.bytes_recv,
               dio.read_bytes if dio else 0, dio.write_bytes if dio else 0)
        with cls._lock:
            prev = cls._last_counters
            cls._last_counters = cur
        if prev is None:
            self.interval_s = 0.0
            self.net_sent_mb = self.net_recv_mb = 0.0
            self.disk_read_mb = self.disk_write_mb = 0.0
        else:
            self.interval_s = now - prev[0]
            # max(0): counters can reset (interface bounce, container restart)
            self.net_sent_mb = max(0, cur[1] - prev[1]) / 1e6
            self.net_recv_mb = max(0, cur[2] - prev[2]) / 1e6
            self.disk_read_mb = max(0, cur[3] - prev[3]) / 1e6
            self.disk_write_mb = max(0, cur[4] - prev[4]) / 1e6
        self.device_memory: List[Dict[str, float]] = []
        try:
            import jax

            for d in jax.devices():
                ms = d.memory_stats() or {}
                if ms:
                    self.device_memory.append({
                        "device": str(d),
                        "bytes_in_use_gb": ms.get("bytes_in_use", 0) / 1e9,
                        "bytes_limit_gb": ms.get("bytes_limit", 0) / 1e9,
                    })
        except Exception:  # devices unavailable in some contexts — not fatal
            pass

    def to_dict(self) -> Dict[str, Any]:
        return dict(self.__dict__)


def generate_run_id() -> str:
    return uuid.uuid4().hex[:12]


class MLOpsConfigs:
    """Comm-plane credential/endpoint resolution (reference
    ``core/mlops/mlops_configs.py:15`` — fetches MQTT/S3 configs from the
    hosted platform over cert-pinned HTTPS). Resolution order here:

    1. ``args.mlops_config_path`` — a local JSON/YAML file with
       ``mqtt_config`` / ``s3_config`` sections (the platform response
       format, cached on disk);
    2. ``FEDML_TPU_MQTT_DIR`` / ``FEDML_TPU_STORE_DIR`` environment
       variables (filesystem broker/store roots);
    3. defaults under ``~/.fedml_tpu``.

    Per-key precedence: explicit args attribute (most user-proximate) >
    cached config file > environment > home-dir default — so a stale
    exported env var can never hijack a run that passed its dirs
    explicitly.

    ``fetch_remote`` keeps the reference's pinned-HTTPS path for deployments
    with a config service: ``verify`` takes the CA bundle path (the
    pinning role of the reference's ``core/mlops/ssl/*.crt``).
    """

    def __init__(self, args=None):
        self.args = args

    def fetch_configs(self):
        """-> (mqtt_config, s3_config) dicts; ``broker_dir``/``store_dir``
        are always resolved."""
        doc = {}
        path = getattr(self.args, "mlops_config_path", None)
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    if path.endswith((".yaml", ".yml")):
                        import yaml

                        doc = yaml.safe_load(f) or {}
                    else:
                        doc = json.load(f)
            except Exception as e:  # corrupt cache must name itself
                raise ValueError(
                    f"unparseable mlops config {path}: {e}") from e
        home = os.path.expanduser(os.environ.get("FEDML_TPU_HOME",
                                                 "~/.fedml_tpu"))

        def resolve(args_attr, section, key, env_var, default):
            v = getattr(self.args, args_attr, None)
            if v:
                return v
            v = (doc.get(section) or {}).get(key)
            if v:
                return v
            return os.environ.get(env_var) or default

        mqtt = dict(doc.get("mqtt_config") or {})
        s3 = dict(doc.get("s3_config") or {})
        mqtt["broker_dir"] = resolve(
            "mqtt_broker_dir", "mqtt_config", "broker_dir",
            "FEDML_TPU_MQTT_DIR", os.path.join(home, "broker"))
        s3["store_dir"] = resolve(
            "blob_store_dir", "s3_config", "store_dir",
            "FEDML_TPU_STORE_DIR", os.path.join(home, "store"))
        return mqtt, s3

    @staticmethod
    def fetch_remote(url: str, ca_path: Optional[str] = None,
                     timeout: float = 10.0):
        """Pinned-HTTPS config fetch (reference ``fetch_configs`` over
        ``https://open.fedml.ai`` with bundled certs). Returns the parsed
        JSON body; ``ca_path`` pins the trust root."""
        import requests

        resp = requests.get(url, verify=ca_path or True, timeout=timeout)
        resp.raise_for_status()
        return resp.json()


# --- hosted-agent surface (reference cli/edge_deployment + mlops_runtime_log)


def get_device_id() -> str:
    """Stable device identifier (reference ``client_runner.get_device_id``:
    the posix branch — ``hex(uuid.getnode())``; the wmic/hal branches are
    Windows/HAL-specific and out of scope for TPU hosts)."""
    return hex(uuid.getnode())


def _default_http_post(url: str, json_params: Dict[str, Any],
                       headers: Dict[str, str],
                       ca_path: Optional[str] = None,
                       timeout: float = 10.0) -> Dict[str, Any]:
    import requests

    resp = requests.post(url, json=json_params, headers=headers,
                         verify=ca_path or True, timeout=timeout)
    resp.raise_for_status()
    return resp.json()


def bind_account_and_device_id(
    url: str,
    account_id: str,
    device_id: Optional[str] = None,
    os_name: str = "posix",
    http_post=None,
    ca_path: Optional[str] = None,
) -> int:
    """Register this host under an account with the hosted platform and get
    back its edge id (reference ``client_runner.bind_account_and_device_id``
    :666 — same request/response schema). The transport is injectable so the
    protocol is testable in zero-egress environments; 0 = refused, matching
    the reference."""
    post = http_post or _default_http_post
    json_params = {
        "accountid": str(account_id),
        "deviceid": device_id or get_device_id(),
        "type": os_name,
        "gpu": "None", "processor": "", "network": "",
    }
    body = post(url, json_params, {"Connection": "close"}, ca_path)
    if body.get("code") == "SUCCESS":
        return int((body.get("data") or {}).get("id", 0))
    return 0


class MLOpsRuntimeLogUploader:
    """Incremental log shipping to the hosted platform (reference
    ``mlops_runtime_log.py:136 log_upload``: read new lines from the run's
    log file, post them with the run/edge attribution schema). The cursor
    only advances on a successful post, so an outage replays, never drops.
    Transport injectable (zero-egress testable); ``start()`` runs the loop
    on a daemon thread like the reference's log processor."""

    def __init__(self, run_id, edge_id, log_file_path: str, upload_url: str,
                 http_post=None, interval: float = 10.0,
                 ca_path: Optional[str] = None, max_lines_per_post: int = 1000):
        self.run_id = run_id
        self.edge_id = edge_id
        self.log_file_path = log_file_path
        self.upload_url = upload_url
        self._post = http_post or _default_http_post
        self.interval = interval
        self.ca_path = ca_path
        self.max_lines = int(max_lines_per_post)
        self.log_line_index = 0   # total lines shipped (info/parity)
        self._offset = 0          # byte cursor: O(new bytes) per tick
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._upload_lock = threading.Lock()  # stop()-flush vs loop thread

    def log_read(self):
        """New complete lines since the byte cursor, as ``(lines, nbytes)``
        where ``nbytes`` is the raw on-disk byte count consumed. The file is
        read in binary so the cursor tracks real bytes — decoding with
        ``errors='replace'`` happens per line for the payload only (a U+FFFD
        re-encodes wider than the bad byte it stands for, so counting decoded
        text would drift the cursor). Rotation/truncation (file smaller than
        the cursor) resets to the file head rather than stalling forever."""
        try:
            size = os.path.getsize(self.log_file_path)
        except OSError:
            return [], 0
        if size < self._offset:
            self._offset = 0  # rotated or truncated: start over on the new file
        with open(self.log_file_path, "rb") as f:
            f.seek(self._offset)
            raw_lines = f.readlines()
        # a partial trailing line (no newline yet) waits for the next tick
        if raw_lines and not raw_lines[-1].endswith(b"\n"):
            raw_lines.pop()
        raw_lines = raw_lines[: self.max_lines]
        consumed = sum(len(raw) for raw in raw_lines)
        return [raw.decode("utf-8", errors="replace") for raw in raw_lines], consumed

    def log_upload(self) -> int:
        """Ship pending lines; returns how many were uploaded."""
        with self._upload_lock:
            lines, consumed = self.log_read()
            if not lines:
                return 0
            now = time.time()
            request = {  # schema parity: mlops_runtime_log.py:143-152
                "run_id": self.run_id,
                "edge_id": self.edge_id,
                "logs": lines,
                "create_time": now,
                "update_time": now,
                "created_by": str(self.edge_id),
                "updated_by": str(self.edge_id),
            }
            self._post(
                self.upload_url, request,
                {"Content-Type": "application/json", "Connection": "close"},
                self.ca_path)
            # only after a successful post, so an outage replays
            self._offset += consumed
            self.log_line_index += len(lines)
            return len(lines)

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.log_upload()
                except Exception:
                    logging.exception("log upload failed; will retry")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="mlops-log-upload")
        self._thread.start()

    def stop(self, flush: bool = True) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        if flush:
            try:
                self.log_upload()
            except Exception:
                logging.exception("final log flush failed")
