"""Differential-privacy accounting for the DP-SGD mechanism.

Parity note: the reference's ``core/dp/__init__.py`` is an EMPTY stub
(SURVEY.md §2.1 "Attack/DP: stubs") — this module implements the real thing.
The mechanism lives in ``algorithms/local_sgd.py`` (``dp_l2_clip`` +
``dp_noise_multiplier``: per-example gradient clipping, Gaussian noise on the
batch sum); this module converts (noise multiplier, steps) into an (eps,
delta) guarantee via Renyi-DP composition of the Gaussian mechanism.

The bound used is the standard RDP of the Gaussian mechanism composed T
times — RDP_alpha = T * alpha / (2 sigma^2) — converted with
eps = min_alpha RDP_alpha + log(1/delta)/(alpha - 1). It does NOT apply
subsampling amplification, so it is CONSERVATIVE (reported eps is an upper
bound on the true privacy loss whenever batches are subsampled).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def rdp_epsilon(
    noise_multiplier: float,
    steps: int,
    delta: float = 1e-5,
    orders: Optional[np.ndarray] = None,
) -> float:
    """(eps, delta)-DP upper bound after ``steps`` compositions of the
    Gaussian mechanism with the given noise multiplier (sigma = multiplier
    * sensitivity; sensitivity = the clip norm).

    Conservative: no subsampling amplification (see module docstring).
    Returns inf when noise_multiplier == 0.
    """
    if noise_multiplier <= 0:
        return float("inf")
    if orders is None:
        orders = np.concatenate([
            np.linspace(1.1, 10.9, 99), np.arange(11, 256, dtype=np.float64),
        ])
    rdp = steps * orders / (2.0 * noise_multiplier ** 2)
    eps = rdp + np.log(1.0 / delta) / (orders - 1.0)
    return float(np.min(eps))


def epsilon_for_training(
    noise_multiplier: float,
    comm_rounds: int,
    steps_per_round: int,
    delta: float = 1e-5,
) -> float:
    """eps for a full FL run: every local DP-SGD step composes."""
    return rdp_epsilon(noise_multiplier, comm_rounds * steps_per_round, delta)
