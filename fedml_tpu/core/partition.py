"""Non-IID data partitioning (Dirichlet / LDA) + homogeneous split.

Semantics parity with reference ``core/data/noniid_partition.py``
(``non_iid_partition_with_dirichlet_distribution:6``,
``partition_class_samples_with_dirichlet_distribution:87``): same seeded
numpy draws, same min-10-samples retry loop, same proportion-balancing rule,
so that with equal seeds the client->indices map matches the reference and
accuracy curves are comparable round-for-round (SURVEY.md §7).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np


def non_iid_partition_with_dirichlet_distribution(
    label_list: np.ndarray,
    client_num: int,
    classes: int,
    alpha: float,
    task: str = "classification",
) -> Dict[int, List[int]]:
    """Partition sample indices across clients by per-class Dirichlet draws.

    Reference: noniid_partition.py:6-84. Retries until every client has >= 10
    samples (min_size loop), then shuffles each client's indices.
    """
    net_dataidx_map: Dict[int, List[int]] = {}
    K = classes
    N = len(label_list)
    # reference parity: retry until every client holds >= 10 samples
    # (noniid_partition.py:14). When the dataset itself cannot give every
    # client 10 (N // client_num < 10, e.g. tiny test fixtures), that loop
    # would spin forever — degrade the target to what is feasible. A retry
    # CAP guards the statistically-unreachable case (many clients, few
    # samples, low alpha: each draw leaves someone near-empty), falling
    # back to deterministic rebalancing — the reference would spin.
    target = min(10, max(1, N // client_num))
    max_retries = 500
    attempts = 0
    min_size = 0
    while min_size < target:
        attempts += 1
        if attempts > max_retries:
            _rebalance_to_min(idx_batch, target)
            break
        idx_batch: List[List[int]] = [[] for _ in range(client_num)]
        if task == "segmentation":
            # label_list here is (classes, samples) of per-class presence
            for k in range(K):
                idx_k = np.asarray(
                    [np.any(label_list[i] == k) for i in range(len(label_list))]
                ).nonzero()[0]
                idx_batch, min_size = partition_class_samples_with_dirichlet_distribution(
                    N, alpha, client_num, idx_batch, idx_k
                )
        else:
            for k in range(K):
                idx_k = np.where(label_list == k)[0]
                idx_batch, min_size = partition_class_samples_with_dirichlet_distribution(
                    N, alpha, client_num, idx_batch, idx_k
                )
    for i in range(client_num):
        np.random.shuffle(idx_batch[i])
        net_dataidx_map[i] = idx_batch[i]
    return net_dataidx_map


def _rebalance_to_min(idx_batch: List[List[int]], target: int) -> None:
    """Deterministically move samples from the largest clients to those
    below ``target`` until everyone meets it (retry-cap fallback)."""
    while True:
        sizes = [len(b) for b in idx_batch]
        lo = int(np.argmin(sizes))
        hi = int(np.argmax(sizes))
        if sizes[lo] >= target or sizes[hi] <= max(target, 1):
            return
        idx_batch[lo].append(idx_batch[hi].pop())


def partition_class_samples_with_dirichlet_distribution(
    N: int,
    alpha: float,
    client_num: int,
    idx_batch: List[List[int]],
    idx_k: np.ndarray,
):
    """One class's samples split by a Dirichlet(alpha) draw.

    Reference: noniid_partition.py:87-124 — including the balancing rule that
    zeroes proportions for clients already holding >= N/client_num samples.
    """
    np.random.shuffle(idx_k)
    proportions = np.random.dirichlet(np.repeat(alpha, client_num))
    proportions = np.array(
        [p * (len(idx_j) < N / client_num) for p, idx_j in zip(proportions, idx_batch)]
    )
    proportions = proportions / proportions.sum()
    proportions = (np.cumsum(proportions) * len(idx_k)).astype(int)[:-1]
    idx_batch = [
        idx_j + idx.tolist() for idx_j, idx in zip(idx_batch, np.split(idx_k, proportions))
    ]
    min_size = min([len(idx_j) for idx_j in idx_batch])
    return idx_batch, min_size


def homo_partition(n_samples: int, client_num: int) -> Dict[int, List[int]]:
    """IID partition: shuffled equal split (reference data loaders' ``homo``)."""
    idxs = np.random.permutation(n_samples)
    batch_idxs = np.array_split(idxs, client_num)
    return {i: batch_idxs[i].tolist() for i in range(client_num)}


def record_net_data_stats(label_list: np.ndarray, net_dataidx_map: Dict[int, List[int]]):
    """Per-client class histogram (reference noniid_partition.py tail helper)."""
    net_cls_counts = {}
    for net_i, dataidx in net_dataidx_map.items():
        unq, unq_cnt = np.unique(label_list[dataidx], return_counts=True)
        net_cls_counts[net_i] = {int(u): int(c) for u, c in zip(unq, unq_cnt)}
    return net_cls_counts
