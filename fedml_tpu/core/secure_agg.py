"""Secure aggregation: Lagrange Coded Computing (LCC) + LightSecAgg protocol.

Parity: reference ``core/mpc/secure_aggregation.py`` (``LCC_encoding_with_points:41``,
``LCC_decoding_with_points:50``, ``model_masking:83``, ``mask_encoding:97``,
``compute_aggregate_encoded_mask:126``) and the LightSecAgg server flow
(``cross_device/server_mnn_lsa/fedml_aggregator.py:33-89``).

Redesign: prime-field arithmetic stays on the host (int64 modular math maps
poorly onto the MXU — SURVEY.md §7 hard parts); the TPU only ever sees the
masked fixed-point tensors. Lagrange coefficient generation is vectorized
numpy (the reference loops Python-level over O(U·N) pairs), and modular
inverses use Fermat via ``pow(a, p-2, p)``. The prime is 2³¹−1 so products of
two residues fit int64 without overflow.
"""

from __future__ import annotations

import dataclasses
import secrets
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

PyTree = Any

DEFAULT_PRIME = (1 << 31) - 1  # Mersenne prime M31


# --- field primitives -------------------------------------------------------

def modular_inv(a: int, p: int = DEFAULT_PRIME) -> int:
    """Reference ``modular_inv`` (extended Euclid); here Fermat's little theorem."""
    return pow(int(a) % p, p - 2, p)


def _mod_matmul(a: np.ndarray, b: np.ndarray, p: int) -> np.ndarray:
    """(a @ b) mod p without int64 overflow: operands are reduced first, and
    the contraction is chunked so each partial sum stays below 2**62."""
    a = np.mod(a, p).astype(np.int64)
    b = np.mod(b, p).astype(np.int64)
    # max term = (p-1)^2 < 2^62; chunk so that chunk_size terms can't overflow
    chunk = max(1, (1 << 62) // int(p - 1) ** 2)
    out = np.zeros((a.shape[0], b.shape[1]), dtype=np.int64)
    for start in range(0, a.shape[1], chunk):
        out = np.mod(out + a[:, start:start + chunk] @ b[start:start + chunk], p)
    return out


def lagrange_coeffs(
    alphas: Sequence[int], betas: Sequence[int], p: int = DEFAULT_PRIME
) -> np.ndarray:
    """U[i, j] = ℓ_j(alpha_i) — the Lagrange basis poly through points betas
    evaluated at alphas (reference ``gen_Lagrange_coeffs:58``, vectorized)."""
    alphas = np.asarray(alphas, dtype=np.int64) % p
    betas = np.asarray(betas, dtype=np.int64) % p
    nb = len(betas)
    # w[j] = prod_{o != j} (beta_j - beta_o) mod p
    w = np.ones(nb, dtype=np.int64)
    for j in range(nb):
        for o in range(nb):
            if o != j:
                w[j] = (w[j] * ((betas[j] - betas[o]) % p)) % p
    # l[i] = prod_o (alpha_i - beta_o) mod p
    l = np.ones(len(alphas), dtype=np.int64)
    for o in range(nb):
        l = (l * ((alphas - betas[o]) % p)) % p
    U = np.zeros((len(alphas), nb), dtype=np.int64)
    for j in range(nb):
        denom = np.mod((alphas - betas[j]) * w[j], p)
        inv = np.array([modular_inv(d, p) for d in denom], dtype=np.int64)
        U[:, j] = np.mod(l * inv, p)
    # coincident points: ℓ_j(beta_j) = 1 exactly (the formula above hits 0·0⁻¹)
    for i, a in enumerate(alphas):
        hits = np.where(betas == a)[0]
        if hits.size:
            U[i, :] = 0
            U[i, hits[0]] = 1
    return U


def lcc_encode(
    X: np.ndarray, alphas: Sequence[int], betas: Sequence[int], p: int = DEFAULT_PRIME
) -> np.ndarray:
    """Encode the (m, d) secret matrix X (rows = poly values at betas) into
    evaluations at alphas (reference ``LCC_encoding_with_points:41``)."""
    return _mod_matmul(lagrange_coeffs(alphas, betas, p), X, p)


def lcc_decode(
    shares: np.ndarray,
    eval_points: Sequence[int],
    target_points: Sequence[int],
    p: int = DEFAULT_PRIME,
) -> np.ndarray:
    """Interpolate from evaluations back to target points (reference
    ``LCC_decoding_with_points:50``)."""
    return _mod_matmul(lagrange_coeffs(target_points, eval_points, p), shares, p)


# --- fixed-point pytree <-> finite field ------------------------------------

def tree_dimensions(tree: PyTree) -> List[int]:
    """Per-leaf flat sizes (reference ``model_dimension:178``)."""
    import jax

    return [int(np.prod(np.shape(x))) for x in jax.tree_util.tree_leaves(tree)]


def quantize_tree(tree: PyTree, q_bits: int = 16, p: int = DEFAULT_PRIME) -> np.ndarray:
    """Pytree → flat int64 field vector: round(x * 2^q), negatives wrapped to
    the upper half of the field (reference ``transform_tensor_to_finite``)."""
    import jax

    flat = np.concatenate([
        np.asarray(x, dtype=np.float64).ravel() for x in jax.tree_util.tree_leaves(tree)
    ])
    q = np.round(flat * (1 << q_bits)).astype(np.int64)
    return np.mod(q, p)


def dequantize_tree(
    vec: np.ndarray, template: PyTree, q_bits: int = 16, p: int = DEFAULT_PRIME,
    n_summands: int = 1,
) -> PyTree:
    """Inverse of quantize_tree; values in the upper half of the field are
    negative (reference ``my_q_inv:150``). Correct as long as the true sum of
    ``n_summands`` client vectors stays within ±(p-1)/2 after quantization."""
    import jax

    del n_summands  # magnitude headroom is the caller's contract, not a knob
    vec = np.mod(np.asarray(vec, dtype=np.int64), p)
    negative = vec > (p - 1) // 2
    real = (vec - p * negative).astype(np.float64) / (1 << q_bits)
    leaves = jax.tree_util.tree_leaves(template)
    treedef = jax.tree_util.tree_structure(template)
    out, pos = [], 0
    for leaf in leaves:
        d = int(np.prod(np.shape(leaf)))
        out.append(real[pos: pos + d].reshape(np.shape(leaf)).astype(np.asarray(leaf).dtype))
        pos += d
    return jax.tree_util.tree_unflatten(treedef, out)


# --- LightSecAgg protocol ---------------------------------------------------

@dataclasses.dataclass
class LightSecAggConfig:
    num_clients: int                 # N
    target_active: int               # U — #shares needed to reconstruct
    privacy_guarantee: int           # T — colluding clients tolerated
    model_dimension: int             # d (padded to multiple of U - T)
    prime: int = DEFAULT_PRIME
    q_bits: int = 16

    @property
    def chunk(self) -> int:
        return self.target_active - self.privacy_guarantee

    @property
    def padded_dim(self) -> int:
        return -(-self.model_dimension // self.chunk) * self.chunk

    @property
    def betas(self) -> np.ndarray:
        return np.arange(1, self.num_clients + 1, dtype=np.int64)

    @property
    def alphas(self) -> np.ndarray:
        return np.arange(self.num_clients + 1, self.num_clients + 1 + self.target_active, dtype=np.int64)


class LightSecAggClient:
    """Client side: generate a random mask, LCC-encode it into N shares
    (reference ``mask_encoding:97``), mask the local update.

    ``seed`` is for deterministic *tests only* — in deployment leave it None
    so mask and noise come from OS entropy; a seed known to the server lets
    it regenerate the mask and unmask this client's individual update.
    (numpy's PCG is not a CSPRNG; a production deployment should swap in a
    crypto-grade generator, as should the reference, which zeroes its noise
    rows entirely — ``mask_encoding:112``.)
    """

    def __init__(self, cfg: LightSecAggConfig, client_id: int, seed: Optional[int] = None):
        self.cfg = cfg
        self.client_id = client_id
        if seed is None:
            self._rng = np.random.Generator(np.random.PCG64(secrets.randbits(128)))
        else:
            self._rng = np.random.Generator(np.random.PCG64([seed, client_id]))
        self.local_mask = self._rng.integers(
            0, cfg.prime, size=(cfg.padded_dim, 1), dtype=np.int64
        )

    def encode_mask_shares(self) -> np.ndarray:
        """(N, padded_dim/(U-T)) — row j goes to client j."""
        cfg = self.cfg
        pad_rows = cfg.privacy_guarantee * cfg.padded_dim // cfg.chunk
        noise = self._rng.integers(0, cfg.prime, size=(pad_rows, 1), dtype=np.int64)
        lcc_in = np.concatenate([self.local_mask, noise], axis=0).reshape(
            cfg.target_active, cfg.padded_dim // cfg.chunk
        )
        # secret rows sit at the alphas; shares are evaluations at the betas
        # (reference mask_encoding:97 places beta_s=1..N for clients,
        # alpha_s=N+1..N+U for the secret+noise rows)
        return lcc_encode(lcc_in, cfg.betas, cfg.alphas, cfg.prime)

    def mask_update(self, update: PyTree) -> np.ndarray:
        """Quantize + add mask in the field (reference ``model_masking:83``)."""
        cfg = self.cfg
        q = quantize_tree(update, cfg.q_bits, cfg.prime)
        q = np.pad(q, (0, cfg.padded_dim - len(q)))
        return np.mod(q + self.local_mask.ravel(), cfg.prime)


class LightSecAggServer:
    """Server side: collect per-client aggregate-mask shares from the active
    set, LCC-decode the aggregate mask, unmask the summed update (reference
    ``server_mnn_lsa/fedml_aggregator.py:33-89`` +
    ``compute_aggregate_encoded_mask:126``)."""

    def __init__(self, cfg: LightSecAggConfig):
        self.cfg = cfg

    @staticmethod
    def aggregate_encoded_masks(shares_for_me: Dict[int, np.ndarray], active: Sequence[int], p: int) -> np.ndarray:
        """Each surviving client sums the shares it holds from active clients."""
        total = np.zeros_like(next(iter(shares_for_me.values())))
        for cid in active:
            total = np.mod(total + shares_for_me[cid], p)
        return total

    def reconstruct_aggregate_mask(
        self, agg_shares: Dict[int, np.ndarray], active: Sequence[int]
    ) -> np.ndarray:
        cfg = self.cfg
        surviving = sorted(agg_shares)[: cfg.target_active]
        if len(surviving) < cfg.target_active:
            raise ValueError(
                f"need {cfg.target_active} surviving clients, got {len(surviving)}"
            )
        f_eval = np.stack([agg_shares[cid] for cid in surviving])  # (U, d/chunk)
        eval_points = cfg.betas[np.asarray(surviving)]
        # reconstruct all U secret rows at the alphas; the first U-T rows are
        # the true aggregate mask, the last T are summed noise — dropped
        recon = lcc_decode(f_eval, eval_points, cfg.alphas, cfg.prime)
        return recon[: cfg.chunk].reshape(-1)

    def unmask(
        self,
        summed_masked: np.ndarray,
        aggregate_mask: np.ndarray,
        template: PyTree,
        n_active: int,
    ) -> PyTree:
        cfg = self.cfg
        unmasked = np.mod(summed_masked - aggregate_mask, cfg.prime)
        return dequantize_tree(unmasked, template, cfg.q_bits, cfg.prime, n_summands=n_active)


def secure_aggregate(
    updates: List[PyTree], cfg: LightSecAggConfig, active: Sequence[int],
    seed: Optional[int] = None,
) -> PyTree:
    """End-to-end LightSecAgg round over in-process clients (used by the
    TurboAggregate/LSA simulators and tests): returns the *sum* of active
    clients' updates, reconstructed without seeing any individual update."""
    clients = [LightSecAggClient(cfg, cid, seed) for cid in range(cfg.num_clients)]
    # offline: all-to-all mask-share exchange; shares_held[j][i] = share of
    # client i's mask held by client j
    encoded = {c.client_id: c.encode_mask_shares() for c in clients}
    shares_held = {
        j: {i: encoded[i][j] for i in range(cfg.num_clients)} for j in range(cfg.num_clients)
    }
    # online: active clients upload masked updates; server sums in the field
    summed = np.zeros(cfg.padded_dim, dtype=np.int64)
    for cid in active:
        summed = np.mod(summed + clients[cid].mask_update(updates[cid]), cfg.prime)
    # unmasking: surviving clients (here: all active) send aggregate-mask shares
    server = LightSecAggServer(cfg)
    agg_shares = {
        j: LightSecAggServer.aggregate_encoded_masks(shares_held[j], active, cfg.prime)
        for j in list(active)[: cfg.target_active]
    }
    agg_mask = server.reconstruct_aggregate_mask(agg_shares, active)
    return server.unmask(summed, agg_mask, updates[0], n_active=len(active))
