"""Adversarial-client attack harness.

Parity-plus: the reference's ``core/security/fedml_attacker.py:1-4`` is a
stub that returns ``(None, None)``; its actual robustness surface is the
defense side only (``core/robustness``). Here the ATTACK side is functional
too, so the defenses can be evaluated: attacks are pure functions on the
stacked per-client update pytree (leading client axis C) — exactly what the
simulators aggregate — selected by a boolean attacker mask. All jittable.

Attacks implemented (standard FL threat models):
- ``scale_attack`` — model replacement (Bagdasaryan et al.): the attacker
  boosts its update by ~C/eta to survive averaging.
- ``sign_flip_attack`` — gradient ascent by flipped updates.
- ``gaussian_attack`` — random-noise updates (untargeted disruption).
- ``label_flip_data`` — data-level label flipping (complements the backdoor
  ``poison_clients`` in ``data/__init__.py``).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

PyTree = Any


def _mask_bcast(mask: jax.Array, leaf: jax.Array) -> jax.Array:
    return mask.reshape((-1,) + (1,) * (leaf.ndim - 1)).astype(leaf.dtype)


def scale_attack(updates: PyTree, attacker_mask: jax.Array,
                 boost: float = 10.0) -> PyTree:
    """Model replacement: attackers' updates scaled by ``boost``."""
    return jax.tree.map(
        lambda u: u * (1.0 + (boost - 1.0) * _mask_bcast(attacker_mask, u)),
        updates,
    )


def sign_flip_attack(updates: PyTree, attacker_mask: jax.Array,
                     strength: float = 1.0) -> PyTree:
    """Attackers ship the negated (scaled) honest update."""
    return jax.tree.map(
        lambda u: u * (1.0 - (1.0 + strength) * _mask_bcast(attacker_mask, u)),
        updates,
    )


def nan_attack(updates: PyTree, attacker_mask: jax.Array) -> PyTree:
    """Attackers upload all-NaN deltas — the availability attack a single
    crashed/overflowed client mounts by accident: without sanitization one
    such row makes the aggregate (and every later round) NaN."""
    return jax.tree.map(
        lambda u: jnp.where(_mask_bcast(attacker_mask, u) > 0,
                            jnp.full_like(u, jnp.nan), u),
        updates,
    )


def gaussian_attack(updates: PyTree, attacker_mask: jax.Array, rng,
                    std: float = 1.0) -> PyTree:
    """Attackers replace their update with pure Gaussian noise."""
    leaves, treedef = jax.tree.flatten(updates)
    keys = jax.random.split(rng, len(leaves))
    out = []
    for leaf, key in zip(leaves, keys):
        m = _mask_bcast(attacker_mask, leaf)
        noise = std * jax.random.normal(key, leaf.shape, leaf.dtype)
        out.append(leaf * (1 - m) + noise * m)
    return jax.tree.unflatten(treedef, out)


def label_flip_data(y: np.ndarray, num_classes: int) -> np.ndarray:
    """Deterministic label flip y -> (num_classes - 1 - y)."""
    return (num_classes - 1 - np.asarray(y)).astype(np.asarray(y).dtype)


class FedMLAttacker:
    """Reference API shell (``fedml_attacker.py``) made functional: holds an
    attacker mask and applies the configured attack to stacked updates."""

    ATTACK_TYPES = ("scale", "sign_flip", "gaussian", "nan")

    def __init__(self, attack_type: str = "scale", attacker_ratio: float = 0.2,
                 boost: float = 10.0, std: float = 1.0, *,
                 strength: float = 1.0, seed: int = 0):
        if attack_type not in self.ATTACK_TYPES:
            hint = (" (label flipping is data-level: use label_flip_data "
                    "on the attacker clients' labels)"
                    if attack_type == "label_flip" else "")
            raise ValueError(
                f"unknown attack '{attack_type}'; one of {self.ATTACK_TYPES}"
                + hint)
        if not 0.0 <= float(attacker_ratio) <= 1.0:
            raise ValueError(
                f"attacker_ratio must be in [0, 1], got {attacker_ratio}")
        self.attack_type = attack_type
        self.attacker_ratio = float(attacker_ratio)
        self.boost = float(boost)
        self.std = float(std)
        self.strength = float(strength)
        self.seed = int(seed)
        self._calls = 0

    def attacker_mask(self, cohort_size: int) -> np.ndarray:
        mask = np.zeros(cohort_size, np.float32)
        if self.attacker_ratio <= 0.0:
            return mask  # ratio 0 = clean baseline, truly no attacker
        rng = np.random.default_rng(self.seed)
        n = max(1, int(round(self.attacker_ratio * cohort_size)))
        mask[rng.choice(cohort_size, n, replace=False)] = 1.0
        return mask

    def attack(self, updates: PyTree, cohort_size: int) -> PyTree:
        mask = jnp.asarray(self.attacker_mask(cohort_size))
        self._calls += 1
        if self.attack_type == "scale":
            return scale_attack(updates, mask, self.boost)
        if self.attack_type == "sign_flip":
            return sign_flip_attack(updates, mask, self.strength)
        if self.attack_type == "nan":
            return nan_attack(updates, mask)
        # gaussian: fresh noise per call — the key advances with a counter so
        # multi-round attacks are not a fixed-direction bias
        rng = jax.random.fold_in(jax.random.PRNGKey(self.seed), self._calls)
        return gaussian_attack(updates, mask, rng, self.std)
