"""Multi-tenant job control plane: admission, fair scheduling, overload.

Production FL platforms run many concurrent federated jobs against one
shared accelerator pool (FedML MLOps in PAPER.md; Flower / NVIDIA FLARE
interop). This module is the job-level layer above the round FSM:

- **ResourceEnvelope / AdmissionVerdict / JobRegistry** — jobs declare what
  they will consume (cohort size, model bytes, a device-memory estimate
  priced with ``core/scheduler.py``'s cost model); the registry admits jobs
  under a byte-capacity budget and bounded concurrency, queues the next few,
  and rejects the rest with a typed verdict instead of letting an oversized
  job OOM the mesh mid-round.
- **DeficitRoundRobinScheduler** — fair interleaving of round steps across
  admitted tenants: each scheduling cycle tops a tenant's deficit up by
  ``quantum * priority`` and a tenant runs one round step when its deficit
  covers its declared per-round cost, so cheap jobs are not starved behind
  expensive ones and long-run service converges to the priority weights.
  Tenants whose *measured* step cost chronically overruns their declared
  envelope are demoted (priority multiplied down), the polite version of
  killing a noisy neighbor.
- **CheckinQueue** — overload as a first-class state: a bounded device
  check-in queue with backpressure. A full queue sheds (rejects) the
  check-in and counts it (``fedml_checkins_shed_total{tenant=...}``) rather
  than growing without bound; the depth gauge makes saturation visible.

Telemetry flows through :mod:`fedml_tpu.core.telemetry`'s tenant scoping:
every series these classes write is tenant-labeled when created under a
:func:`telemetry.tenant_scope` (or when a tenant is passed explicitly), so
one tenant's counters provably cannot pollute another's.

Thread-safety: every structure here is shared between tenant worker threads
and the scheduler; all mutation happens under a per-object lock, and no
blocking call (sleep, send, wait) ever runs while one is held (enforced by
graftcheck's lock-order checker — this file is in its scope).
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence

from . import telemetry, trace_plane

# Decision values a JobRegistry can return.
ADMIT = "admit"
QUEUE = "queue"
REJECT = "reject"


# --- resource envelopes ------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ResourceEnvelope:
    """What one federated job declares it will consume per round.

    ``round_cost`` is in the same relative units as
    :func:`fedml_tpu.core.scheduler.dp_schedule` workloads (client batch
    counts x model cost); ``device_memory_bytes`` is the admission currency:
    params + server opt state (~2x params) + the stacked cohort of client
    updates, the live set a round step holds at aggregation time.
    """

    tenant: str
    cohort_size: int
    model_bytes: int
    rounds: int = 1
    round_cost: float = 1.0
    priority: float = 1.0
    device_memory_bytes: int = 0

    def __post_init__(self):
        if self.cohort_size <= 0:
            raise ValueError(f"cohort_size must be positive, got "
                             f"{self.cohort_size}")
        if self.model_bytes < 0 or self.priority <= 0:
            raise ValueError("model_bytes must be >= 0 and priority > 0")
        if self.device_memory_bytes == 0:
            object.__setattr__(self, "device_memory_bytes",
                               self.estimate_device_memory_bytes(
                                   self.cohort_size, self.model_bytes))

    @staticmethod
    def estimate_device_memory_bytes(cohort_size: int,
                                     model_bytes: int) -> int:
        # params + server state (opt momentum etc., ~2x params) + the
        # stacked per-client update tensor the aggregation step holds
        return int(model_bytes * (3 + cohort_size))

    @classmethod
    def from_workloads(cls, tenant: str, workloads: Sequence[float],
                       model_bytes: int, rounds: int = 1,
                       priority: float = 1.0) -> "ResourceEnvelope":
        """Price a round from per-client workloads (``dp_schedule`` units:
        e.g. batch counts); the round cost is the total batch-work the mesh
        must retire for one round of this job."""
        return cls(
            tenant=str(tenant),
            cohort_size=len(workloads),
            model_bytes=int(model_bytes),
            rounds=int(rounds),
            round_cost=float(sum(workloads)) or 1.0,
            priority=float(priority),
        )


@dataclasses.dataclass(frozen=True)
class AdmissionVerdict:
    """Typed admission outcome — the control plane's answer to "may this
    job run now": ``admit`` (capacity reserved), ``queue`` (wait for a
    release), or ``reject`` (would never fit / queue full)."""

    tenant: str
    decision: str  # ADMIT | QUEUE | REJECT
    reason: str
    requested_bytes: int
    available_bytes: int
    capacity_bytes: int
    queue_position: Optional[int] = None

    @property
    def admitted(self) -> bool:
        return self.decision == ADMIT

    @property
    def queued(self) -> bool:
        return self.decision == QUEUE

    @property
    def rejected(self) -> bool:
        return self.decision == REJECT

    def summary(self) -> str:
        pos = (f" (queue position {self.queue_position})"
               if self.queue_position is not None else "")
        return (f"admission[{self.tenant}]: {self.decision}{pos} — "
                f"{self.reason} (requested {self.requested_bytes}B, "
                f"available {self.available_bytes}B of "
                f"{self.capacity_bytes}B)")


class JobRegistry:
    """Admission control over one device mesh's memory budget.

    ``admit`` reserves envelope bytes against ``capacity_bytes`` and a
    ``max_concurrent`` job slot; jobs that would fit but can't right now
    queue FIFO (up to ``max_queue``); jobs that could NEVER fit — or arrive
    at a full queue — are rejected outright. ``release`` frees a job's
    reservation and promotes queued jobs that now fit, returning their
    fresh ``admit`` verdicts so the caller can start them.
    """

    def __init__(self, capacity_bytes: int, max_concurrent: int = 8,
                 max_queue: int = 16):
        self.capacity_bytes = int(capacity_bytes)
        self.max_concurrent = int(max_concurrent)
        self.max_queue = int(max_queue)
        self._lock = threading.Lock()
        self._active: Dict[str, ResourceEnvelope] = {}
        self._queue: Deque[ResourceEnvelope] = deque()

    # ------------------------------------------------------------- internals

    def _available_locked(self) -> int:
        return self.capacity_bytes - sum(
            e.device_memory_bytes for e in self._active.values())

    def _verdict(self, env: ResourceEnvelope, decision: str, reason: str,
                 available: int, queue_position: Optional[int] = None
                 ) -> AdmissionVerdict:
        v = AdmissionVerdict(
            tenant=env.tenant, decision=decision, reason=reason,
            requested_bytes=env.device_memory_bytes,
            available_bytes=available, capacity_bytes=self.capacity_bytes,
            queue_position=queue_position,
        )
        reg = telemetry.get_registry()
        if reg.enabled:
            reg.counter("fedml_admissions_total", decision=decision,
                        tenant=env.tenant).inc()
            reg.gauge("fedml_admitted_jobs").set(len(self._active))
            reg.gauge("fedml_admission_queue_depth").set(len(self._queue))
        return v

    def _try_admit_locked(self, env: ResourceEnvelope
                          ) -> Optional[AdmissionVerdict]:
        available = self._available_locked()
        if (env.device_memory_bytes <= available
                and len(self._active) < self.max_concurrent):
            self._active[env.tenant] = env
            return self._verdict(
                env, ADMIT, "capacity reserved",
                available - env.device_memory_bytes)
        return None

    # ------------------------------------------------------------- public

    def admit(self, env: ResourceEnvelope) -> AdmissionVerdict:
        with self._lock:
            if env.tenant in self._active or any(
                    q.tenant == env.tenant for q in self._queue):
                return self._verdict(
                    env, REJECT, "tenant already registered",
                    self._available_locked())
            if env.device_memory_bytes > self.capacity_bytes:
                return self._verdict(
                    env, REJECT,
                    "envelope exceeds total mesh capacity — would never fit",
                    self._available_locked())
            v = self._try_admit_locked(env)
            if v is not None:
                return v
            if len(self._queue) >= self.max_queue:
                return self._verdict(
                    env, REJECT, "admission queue full — shed",
                    self._available_locked())
            self._queue.append(env)
            return self._verdict(
                env, QUEUE,
                "insufficient capacity now — queued for a release",
                self._available_locked(),
                queue_position=len(self._queue) - 1)

    def release(self, tenant: str) -> List[AdmissionVerdict]:
        """Free ``tenant``'s reservation; returns admit verdicts for every
        queued job the freed capacity now covers (FIFO, no overtaking)."""
        promoted: List[AdmissionVerdict] = []
        with self._lock:
            self._active.pop(str(tenant), None)
            while self._queue:
                v = self._try_admit_locked(self._queue[0])
                if v is None:
                    break
                self._queue.popleft()
                promoted.append(v)
            reg = telemetry.get_registry()
            if reg.enabled:
                reg.gauge("fedml_admitted_jobs").set(len(self._active))
                reg.gauge("fedml_admission_queue_depth").set(len(self._queue))
        return promoted

    def active_tenants(self) -> List[str]:
        with self._lock:
            return list(self._active)

    def queued_tenants(self) -> List[str]:
        with self._lock:
            return [e.tenant for e in self._queue]

    def available_bytes(self) -> int:
        with self._lock:
            return self._available_locked()


# --- fair scheduling ---------------------------------------------------------


class DeficitRoundRobinScheduler:
    """Deficit round-robin over per-tenant run queues.

    Classic DRR (Shreedhar & Varghese) with the flow cost replaced by the
    tenant's declared per-round cost in ``dp_schedule`` units: each cycle
    visits tenants in rotation, tops each visited deficit up by
    ``quantum * priority``, and serves the first tenant whose deficit covers
    its cost. The caller charges the *measured* cost after the step
    (:meth:`charge`), which both burns the deficit and feeds the over-budget
    detector: a tenant whose measured costs run past
    ``over_budget_factor x declared`` for ``demote_after`` consecutive
    steps has its priority multiplied by ``demote_factor`` (floored), so a
    mis-declared envelope degrades its own service, not its neighbors'.
    """

    def __init__(self, quantum: float = 1.0, demote_factor: float = 0.5,
                 over_budget_factor: float = 2.0, demote_after: int = 3,
                 min_priority: float = 0.05):
        self.quantum = float(quantum)
        self.demote_factor = float(demote_factor)
        self.over_budget_factor = float(over_budget_factor)
        self.demote_after = int(demote_after)
        self.min_priority = float(min_priority)
        self._lock = threading.Lock()
        self._order: Deque[str] = deque()
        self._cost: Dict[str, float] = {}
        self._priority: Dict[str, float] = {}
        self._deficit: Dict[str, float] = {}
        self._served: Dict[str, float] = {}
        self._steps: Dict[str, int] = {}
        self._over_streak: Dict[str, int] = {}
        self._demotions: Dict[str, int] = {}
        # True while the head tenant's once-per-visit quantum top-up has
        # already been applied (cleared when the rotation moves past it)
        self._topped: Dict[str, bool] = {}

    def register(self, tenant: str, round_cost: float,
                 priority: float = 1.0) -> None:
        tenant = str(tenant)
        with self._lock:
            if tenant in self._cost:
                raise ValueError(f"tenant {tenant!r} already registered")
            self._order.append(tenant)
            self._cost[tenant] = max(float(round_cost), 1e-9)
            self._priority[tenant] = float(priority)
            self._deficit[tenant] = 0.0
            self._served.setdefault(tenant, 0.0)
            self._steps.setdefault(tenant, 0)
            self._over_streak[tenant] = 0
            self._topped[tenant] = False

    def unregister(self, tenant: str) -> None:
        tenant = str(tenant)
        with self._lock:
            if tenant in self._cost:
                self._order.remove(tenant)
                del self._cost[tenant]
                del self._priority[tenant]
                del self._deficit[tenant]
                self._topped.pop(tenant, None)

    def next_tenant(self, ready: Optional[Sequence[str]] = None
                    ) -> Optional[str]:
        """Pick the next tenant to grant one round step. ``ready`` (when
        given) restricts the choice to tenants currently able to run —
        others keep their rotation slot but are skipped without a top-up.
        Returns ``None`` when no (ready) tenant is registered."""
        with self._lock:
            if not self._order:
                return None
            ready_set = None if ready is None else {str(t) for t in ready}
            if ready_set is not None and not (ready_set & set(self._order)):
                return None
            # textbook DRR: the head tenant keeps being granted while its
            # deficit covers a round, and its once-per-visit top-up is
            # quantum * priority — so long-run service is proportional to
            # priority and independent of per-round unit cost. Deficits grow
            # every full rotation, so a pick is guaranteed in at most
            # ceil(max cost / (quantum * min priority)) cycles.
            while True:
                for _ in range(len(self._order)):
                    t = self._order[0]
                    if ready_set is None or t in ready_set:
                        if self._deficit[t] >= self._cost[t]:
                            return t  # stay at head: visit not spent yet
                        if not self._topped[t]:
                            self._topped[t] = True
                            self._deficit[t] += (
                                self.quantum * self._priority[t])
                            if self._deficit[t] >= self._cost[t]:
                                return t
                    # visit over (or tenant not ready): move on
                    self._topped[t] = False
                    self._order.rotate(-1)

    def charge(self, tenant: str, measured_cost: float) -> None:
        """Burn ``tenant``'s deficit by the measured step cost and update
        the over-budget streak / demotion state."""
        tenant = str(tenant)
        with self._lock:
            if tenant not in self._cost:
                return
            cost = max(float(measured_cost), 0.0)
            # burn the measured cost, but never let one pathological step
            # push the deficit below one declared round (starvation bound)
            self._deficit[tenant] = max(
                self._deficit[tenant] - cost, -self._cost[tenant])
            self._served[tenant] = self._served.get(tenant, 0.0) + cost
            self._steps[tenant] = self._steps.get(tenant, 0) + 1
            declared = self._cost[tenant]
            if cost > self.over_budget_factor * declared:
                self._over_streak[tenant] += 1
            else:
                self._over_streak[tenant] = 0
            if self._over_streak[tenant] >= self.demote_after:
                self._over_streak[tenant] = 0
                old = self._priority[tenant]
                new = max(old * self.demote_factor, self.min_priority)
                if new < old:
                    self._priority[tenant] = new
                    self._demotions[tenant] = (
                        self._demotions.get(tenant, 0) + 1)
                    reg = telemetry.get_registry()
                    if reg.enabled:
                        reg.counter("fedml_tenant_demotions_total",
                                    tenant=tenant).inc()

    def served(self, tenant: str) -> float:
        with self._lock:
            return self._served.get(str(tenant), 0.0)

    def priority(self, tenant: str) -> float:
        with self._lock:
            return self._priority.get(str(tenant), 0.0)

    def demotions(self, tenant: str) -> int:
        with self._lock:
            return self._demotions.get(str(tenant), 0)

    def stats(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                t: {
                    "served": self._served.get(t, 0.0),
                    "steps": float(self._steps.get(t, 0)),
                    "priority": self._priority.get(t, 0.0),
                    "demotions": float(self._demotions.get(t, 0)),
                }
                for t in sorted(set(self._served) | set(self._cost))
            }


# --- overload: bounded check-in queue ---------------------------------------

# shed reasons: a full queue is backpressure working as designed; an
# inadmissible check-in (a departed/unknown device announcing itself) is a
# registry decision. Operators need the split — `fedml_shed_total{reason=}`
# carries it, and `fedml-tpu telemetry summary` breaks it out.
SHED_QUEUE_FULL = "queue_full"
SHED_INADMISSIBLE = "inadmissible"


class CheckinQueue:
    """Bounded device check-in queue with load shedding.

    ``offer`` is the ingress edge the load generator (and a real gateway)
    hammers: it either enqueues and returns True, or sheds the check-in,
    counts it per tenant (``fedml_checkins_shed_total{tenant=...}``) and per
    reason (``fedml_shed_total{reason=queue_full|inadmissible}``), and
    returns False — so overload produces bounded memory and a visible
    counter instead of an unbounded backlog. ``offer_many`` is the batched
    edge for arrival waves (one lock acquisition per wave). ``poll`` is the
    drain side (the admission/round plane). The depth gauge is updated on
    both edges; its high-water mark is tracked so a drill can assert the
    bound held.

    The serving plane (``fedml_tpu.serving``) rides this same edge:
    inference requests and training check-in frames can share one queue,
    drained deficit-round-robin across tenants — see
    ``cross_silo/loadgen.py``'s mixed-traffic mode.
    """

    def __init__(self, maxsize: int = 1024):
        if maxsize <= 0:
            raise ValueError(f"maxsize must be positive, got {maxsize}")
        self.maxsize = int(maxsize)
        self._lock = threading.Lock()
        self._items: Deque[Any] = deque()
        self._offered = 0
        self._accepted = 0
        self._shed = 0
        self._shed_full = 0
        self._shed_inadmissible = 0
        self._max_depth = 0

    def _record_sheds(self, tenant: Optional[str], depth: int,
                      accepted: int, shed_full: int,
                      shed_inadmissible: int) -> None:
        """Metric writes for one offer batch — called OUTSIDE the queue
        lock (the registry has its own lock and lock-order discipline
        forbids nesting the two)."""
        reg = telemetry.get_registry()
        if not reg.enabled:
            return
        labels = {} if tenant is None else {"tenant": str(tenant)}
        if accepted:
            reg.counter("fedml_checkins_accepted_total",
                        **labels).inc(accepted)
        for reason, n in ((SHED_QUEUE_FULL, shed_full),
                          (SHED_INADMISSIBLE, shed_inadmissible)):
            if not n:
                continue
            reg.counter("fedml_checkins_shed_total", **labels).inc(n)
            reg.counter("fedml_shed_total", reason=reason, **labels).inc(n)
            if trace_plane.active():
                trace_plane.record_instant(
                    "shed", attrs={"tenant": tenant, "reason": reason,
                                   "count": n, "depth": depth})
        reg.gauge("fedml_checkin_queue_depth").set(depth)

    def offer(self, item: Any, tenant: Optional[str] = None,
              admissible: bool = True) -> bool:
        """Offer one check-in. ``admissible=False`` sheds it up front with
        reason ``inadmissible`` (the caller's registry refused the device);
        a full queue sheds with reason ``queue_full``."""
        with self._lock:
            self._offered += 1
            if not admissible:
                self._shed += 1
                self._shed_inadmissible += 1
                shed_full, shed_inad, depth = 0, 1, len(self._items)
            elif len(self._items) >= self.maxsize:
                self._shed += 1
                self._shed_full += 1
                shed_full, shed_inad, depth = 1, 0, len(self._items)
            else:
                self._items.append(item)
                self._accepted += 1
                shed_full, shed_inad, depth = 0, 0, len(self._items)
                if depth > self._max_depth:
                    self._max_depth = depth
        accepted = 1 - shed_full - shed_inad
        self._record_sheds(tenant, depth, accepted, shed_full, shed_inad)
        return accepted == 1

    def offer_many(self, items: Sequence[Any], tenant: Optional[str] = None,
                   admissible: Optional[Sequence[bool]] = None
                   ) -> Dict[str, int]:
        """Batched admission edge: offer one arrival wave under a single
        lock acquisition (the per-offer lock/metric round-trip dominates at
        cross-device rates). ``admissible`` (aligned to ``items``) marks
        check-ins the caller's registry already refused — they shed with
        reason ``inadmissible`` without consuming queue room. Returns the
        wave's accounting: accepted / shed_queue_full / shed_inadmissible.
        """
        accepted = shed_full = shed_inad = 0
        with self._lock:
            self._offered += len(items)
            room = self.maxsize - len(self._items)
            for i, item in enumerate(items):
                if admissible is not None and not admissible[i]:
                    shed_inad += 1
                elif room > 0:
                    self._items.append(item)
                    room -= 1
                    accepted += 1
                else:
                    shed_full += 1
            self._accepted += accepted
            self._shed += shed_full + shed_inad
            self._shed_full += shed_full
            self._shed_inadmissible += shed_inad
            depth = len(self._items)
            if depth > self._max_depth:
                self._max_depth = depth
        self._record_sheds(tenant, depth, accepted, shed_full, shed_inad)
        return {"accepted": accepted, "shed_queue_full": shed_full,
                "shed_inadmissible": shed_inad}

    def poll(self) -> Optional[Any]:
        reg = telemetry.get_registry()
        with self._lock:
            item = self._items.popleft() if self._items else None
            depth = len(self._items)
        if item is not None and reg.enabled:
            reg.gauge("fedml_checkin_queue_depth").set(depth)
        return item

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "offered": self._offered,
                "accepted": self._accepted,
                "shed": self._shed,
                "shed_queue_full": self._shed_full,
                "shed_inadmissible": self._shed_inadmissible,
                "depth": len(self._items),
                "max_depth": self._max_depth,
                "maxsize": self.maxsize,
            }
