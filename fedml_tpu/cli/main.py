"""``fedml_tpu`` command-line interface.

Parity: reference ``python/fedml/cli/cli.py:24`` (click group with
``version``, ``status``, ``logs``, ``build``, ``login``, ``logout``) plus a
``run`` command the reference spreads across example main.py files. The
MLOps-platform network calls are replaced by a local state directory
(``~/.fedml_tpu``): ``login`` records the account binding, ``status``/
``logs`` read local runner state — the agent daemon surface without the
hosted backend (which is gated in this zero-egress build).

Usage: ``python -m fedml_tpu.cli <command>``.
"""

from __future__ import annotations

import json
import os
import time
import zipfile

import click

STATE_DIR = os.path.expanduser(os.environ.get("FEDML_TPU_HOME", "~/.fedml_tpu"))


def _state_path(name: str) -> str:
    os.makedirs(STATE_DIR, exist_ok=True)
    return os.path.join(STATE_DIR, name)


@click.group()
def cli():
    """fedml_tpu: TPU-native federated learning."""


@cli.command("version", help="Display fedml_tpu version.")
def version():
    import fedml_tpu

    click.echo("fedml_tpu version: " + fedml_tpu.__version__)


@cli.command("login", help="Bind this device to an account id (local record).")
@click.argument("account_id")
@click.option("--role", default="client", type=click.Choice(["client", "server"]))
def login(account_id, role):
    with open(_state_path("session.json"), "w") as f:
        json.dump({"account_id": account_id, "role": role, "time": time.time()}, f)
    click.echo(f"bound account {account_id} as {role} (state: {STATE_DIR})")


@cli.command("logout", help="Clear the account binding.")
def logout():
    p = _state_path("session.json")
    if os.path.exists(p):
        os.remove(p)
    click.echo("logged out")


@cli.command("status", help="Display training status.")
def status():
    def _read(name):
        try:
            with open(os.path.join(STATE_DIR, name)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    edge_recs = [
        r for r in (
            _read(f) for f in sorted(os.listdir(STATE_DIR))
            if f.startswith("status_edge") and f.endswith(".json")
        ) if r
    ] if os.path.isdir(STATE_DIR) else []
    local = _read("status.json")
    # status.json without an edge_id came from the `run` command; with one
    # it duplicates a per-edge file (agents write both). Show each source
    # once so stale agent state never masks a live local run or vice versa.
    show_local = local is not None and "edge_id" not in local
    if not edge_recs and not show_local:
        click.echo("Client training status: IDLE")
        return
    if show_local:
        click.echo("Client training status: "
                   + local.get("status", "IDLE").upper())
    for r in edge_recs:
        click.echo(f"Edge {r.get('edge_id', '?')} training status: "
                   + r.get("status", "IDLE").upper())


@cli.command("logs", help="Display recent run logs.")
@click.option("--client", "-c", is_flag=True, help="Client logs.")
@click.option("--server", "-s", is_flag=True, help="Server logs.")
@click.option("--lines", "-n", default=30)
def logs(client, server, lines):
    log_dir = _state_path("logs")
    if not os.path.isdir(log_dir) or not os.listdir(log_dir):
        click.echo("no logs yet")
        return
    newest = max(
        (os.path.join(log_dir, f) for f in os.listdir(log_dir)), key=os.path.getmtime
    )
    with open(newest) as f:
        for line in f.readlines()[-lines:]:
            click.echo(line.rstrip())


@cli.command("build", help="Package entry script + config for distribution.")
@click.option("--type", "-t", "pkg_type", type=click.Choice(["client", "server"]), required=True)
@click.option("--source_folder", "-sf", required=True)
@click.option("--entry_point", "-ep", required=True)
@click.option("--config_folder", "-cf", required=True)
@click.option("--dest_folder", "-df", required=True)
def build(pkg_type, source_folder, entry_point, config_folder, dest_folder):
    """Reference ``fedml build`` (cli.py:351 ``build_mlops_package:434``):
    zips entry + source + config into a deployable package.

    ``--source_folder default`` packages the stock skeleton entries
    (cli/build_package — reference ``cli/build-package/mlops-core``); a
    real directory named ``default`` takes precedence over the sentinel."""
    if source_folder == "default" and not os.path.isdir(source_folder):
        from . import build_package as _bp

        source_folder = _bp.SKELETON_DIR
        entry_point = (_bp.SERVER_ENTRY if pkg_type == "server"
                       else _bp.CLIENT_ENTRY)
        click.echo(f"using stock skeleton source (entry {entry_point})")
    os.makedirs(dest_folder, exist_ok=True)
    out = os.path.join(dest_folder, f"fedml_tpu-{pkg_type}-package.zip")

    def _walk_clean(top):
        # no bytecode, sorted traversal: entry ORDER is deterministic
        # across hosts (readdir order varies). Full byte-reproducibility
        # would also need fixed zip mtimes + dropping built_at.
        for root, dirs, files in os.walk(top):
            dirs[:] = sorted(d for d in dirs if d != "__pycache__")
            for name in sorted(files):
                if not name.endswith((".pyc", ".pyo")):
                    yield os.path.join(root, name)

    with zipfile.ZipFile(out, "w", zipfile.ZIP_DEFLATED) as z:
        for full in _walk_clean(source_folder):
            z.write(full, os.path.join("source", os.path.relpath(full, source_folder)))
        for full in _walk_clean(config_folder):
            z.write(full, os.path.join("config", os.path.relpath(full, config_folder)))
        z.writestr(
            "package.json",
            json.dumps({"type": pkg_type, "entry_point": entry_point,
                        "built_at": time.time()}),
        )
    click.echo(f"package built: {out}")


@cli.command("agent", help="Run the edge agent daemon (serves MLOps jobs).")
@click.option("--edge_id", "-e", default=0, type=int)
@click.option("--broker_dir", "-b", default=None,
              help="FileSystemBroker root shared with the server runner.")
@click.option("--store_dir", "-s", default=None,
              help="FileSystemBlobStore root for package distribution.")
def agent(edge_id, broker_dir, store_dir):
    """Reference ``fedml login`` spawns this daemon (cli.py:152); here it is
    an explicit foreground command (daemonize with your supervisor)."""
    from ..comm.pubsub import FileSystemBroker
    from ..comm.store import FileSystemBlobStore
    from .runner import FedMLEdgeRunner

    broker = FileSystemBroker(root=broker_dir)
    store = FileSystemBlobStore(root=store_dir)
    runner = FedMLEdgeRunner(edge_id, broker, store=store, home_dir=STATE_DIR)
    runner.start()
    click.echo(f"edge agent {edge_id} serving jobs (broker: {broker.root})")
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        runner.stop()
        broker.close()


@cli.command("dispatch", help="Fan a built package out to edge agents and wait.")
@click.option("--package", "-p", required=True, type=click.Path(exists=True))
@click.option("--edge_id", "-e", "edge_ids", multiple=True, type=int, required=True)
@click.option("--run_id", "-r", default="run0")
@click.option("--broker_dir", "-b", default=None)
@click.option("--store_dir", "-s", default=None)
@click.option("--timeout", "-t", default=600.0)
def dispatch(package, edge_ids, run_id, broker_dir, store_dir, timeout):
    """Reference ``server_runner.py:426 send_training_request_to_edges``:
    the server-side MLOps flow the agent daemons serve. Exits 0 when every
    edge reports FINISHED."""
    from ..comm.pubsub import FileSystemBroker
    from ..comm.store import FileSystemBlobStore
    from .runner import FedMLServerRunner

    broker = FileSystemBroker(root=broker_dir)
    store = FileSystemBlobStore(root=store_dir)
    server = FedMLServerRunner(broker, store=store)
    server.send_training_request_to_edges(run_id, list(edge_ids), package)
    statuses = server.wait_for_edges(
        list(edge_ids), timeout=timeout, run_id=run_id)
    click.echo(json.dumps({"run_id": run_id, "statuses": statuses}))
    broker.close()
    if not all(statuses.get(e) == "FINISHED" for e in edge_ids):
        raise SystemExit(1)


@cli.command("analyze",
             help="Run the graftcheck static-analysis suite over fedml_tpu/ "
                  "(jit-purity, determinism, lock-order, config-drift, "
                  "no-print, donation-safety, sharding-consistency, "
                  "host-sync, collective-deadlock, thread-hazard, "
                  "retrace-hazard, wire-protocol, resource-leak). Flags are "
                  "forwarded to the checker driver: --checker ID "
                  "(repeatable), --json, --format {text,json,sarif}, "
                  "--changed-only [REF], --baseline PATH, --no-baseline, "
                  "--write-baseline, --root DIR, --stats, --cache PATH, "
                  "--no-cache. Exits 1 on non-baselined "
                  "findings. See docs/static_analysis.md.",
             context_settings={"ignore_unknown_options": True})
@click.argument("graftcheck_args", nargs=-1, type=click.UNPROCESSED)
def analyze(graftcheck_args):
    from ..analysis import main as graftcheck_main

    raise SystemExit(graftcheck_main(list(graftcheck_args)))


@cli.command("run", help="Run a simulation from a YAML config.")
@click.option("--cf", "config_file", required=True, type=click.Path(exists=True))
@click.option("--backend", default=None, help="sp | TPU (overrides YAML)")
@click.option("--flight-record", is_flag=True,
              help="Arm the flight recorder for this run (equivalent to "
                   "flight_recorder: true in the YAML): crashes, rollbacks "
                   "and SIGTERM dump a black-box bundle under flight_dir.")
def run(config_file, backend, flight_record):
    import fedml_tpu
    from fedml_tpu.arguments import load_arguments

    args_list = ["--cf", config_file]
    args = load_arguments(args_list=args_list)
    if backend:
        args.backend = backend
    if flight_record:
        args.flight_recorder = True
    fedml_tpu.init(args=args)
    with open(_state_path("status.json"), "w") as f:
        json.dump({"status": "RUNNING", "time": time.time()}, f)
    try:
        history = fedml_tpu.run_simulation(args=args)
        final = history[-1] if history else {}
        with open(_state_path("status.json"), "w") as f:
            json.dump({"status": "FINISHED", "final": final, "time": time.time()}, f)
        click.echo(json.dumps(final))
    except Exception:
        with open(_state_path("status.json"), "w") as f:
            json.dump({"status": "FAILED", "time": time.time()}, f)
        raise


@cli.command("chaos-drill",
             help="Run a seeded fault-injection drill over loopback.")
@click.option("--seed", default=7, type=int, help="Fault plan seed.")
@click.option("--rounds", default=3, type=int)
@click.option("--clients", default=3, type=int)
@click.option("--drop-rate", default=0.2, type=float,
              help="Per-message drop probability.")
@click.option("--duplicate-rate", default=0.0, type=float)
@click.option("--fail-send-rate", default=0.0, type=float,
              help="Per-attempt transient send-failure probability.")
@click.option("--crash-rank", default=None, type=int,
              help="Rank to crash (black-hole) mid-run.")
@click.option("--crash-at-round", default=1, type=int)
@click.option("--byzantine-kind", default=None,
              type=click.Choice(["scale", "sign_flip", "gauss", "nan"]),
              help="Corrupt client uploads with this byzantine fault kind.")
@click.option("--byzantine-rate", default=0.3, type=float,
              help="Per-upload corruption probability (byzantine scenario).")
@click.option("--byzantine-scale", default=10.0, type=float,
              help="Boost factor for --byzantine-kind=scale.")
@click.option("--defend/--no-defend", default=True,
              help="Byzantine scenario: run with sanitizer + multi-Krum "
                   "(default) or undefended (shows the damage).")
@click.option("--codec", default=None, metavar="SPEC",
              help="Run the drill with the compressed update plane on "
                   "(comm_codec spec, e.g. 'delta|topk:0.01|q8' or 'q8') — "
                   "proves faults on compressed frames are absorbed.")
@click.option("--timeout", default=120.0, type=float,
              help="Hang bound: the drill fails if the run outlives this.")
@click.option("--tenant", default=None,
              help="Scope the drill's telemetry accounting to this tenant "
                   "(counters land tenant-labeled; deltas filter to them).")
@click.option("--flight-record", is_flag=True,
              help="Arm the flight recorder + span shipping for the drill; "
                   "crashes and rollbacks dump a black-box bundle, and one "
                   "manual bundle is written when the drill ends.")
@click.option("--flight-dir", default="flight_records", type=click.Path(),
              help="Directory flight bundles land in (with --flight-record).")
@click.option("--json", "as_json", is_flag=True,
              help="Emit the drill outcome as one JSON line (the same "
                   "reporter bench.py --chaos uses) instead of the summary.")
@click.option("--straggler", is_flag=True,
              help="Run the straggler drill instead: sync vs buffered-async "
                   "engines under one seeded heavy-tail delay plan; gates "
                   "async goodput >= --min-goodput-ratio x the sync round "
                   "rate at final accuracy within --max-acc-delta.")
@click.option("--leaf-crash", "tier_scenario", flag_value="leaf_crash",
              default=None,
              help="Run the hierarchical-federation drill instead: kill a "
                   "leaf aggregator mid-generation and gate that failover "
                   "commits every surviving client's update exactly once "
                   "within --max-acc-delta of the fault-free run.")
@click.option("--partition", "tier_scenario", flag_value="partition",
              help="Hierarchical drill variant: cut root<->leaf for one "
                   "round window, verify the cut heals and the same "
                   "exactly-once + accuracy gates hold.")
@click.option("--device-churn", "device_churn", is_flag=True,
              help="Run the cross-device fleet drill instead: a simulated "
                   "device day with 30% fleet churn (dropout + rejoin waves, "
                   "permanent departures, one partition window), gated on "
                   "accuracy within --max-acc-delta of the churn-free "
                   "reference, closed shed/drop accounting, and a "
                   "bit-identical replay.")
@click.option("--spill-dir", default=None, type=click.Path(),
              help="Device-churn drill: directory for the client-state "
                   "arena's disk tier (departures reclaim their spill "
                   "files there). Default: a temp dir.")
@click.option("--rollout", is_flag=True,
              help="Run the poisoned-rollout drill instead: corrupt one "
                   "published model version (--byzantine sign_flip/nan/"
                   "scale/gauss) and gate that the serving canary blocks "
                   "the promotion, rolls back to last-good within "
                   "--max-acc-delta of served accuracy, and pins the "
                   "version against re-promotion.")
@click.option("--skew", default=10.0, type=float,
              help="Straggler drill: slowest/fastest client speed ratio.")
@click.option("--buffer-size", default=2, type=int,
              help="Straggler drill: async commit buffer size K.")
@click.option("--min-goodput-ratio", default=3.0, type=float,
              help="Straggler drill: async-goodput / sync-round-rate gate.")
@click.option("--max-acc-delta", default=0.02, type=float,
              help="Straggler drill: max allowed sync-minus-async accuracy.")
def chaos_drill(seed, rounds, clients, drop_rate, duplicate_rate,
                fail_send_rate, crash_rank, crash_at_round, byzantine_kind,
                byzantine_rate, byzantine_scale, defend, codec, timeout,
                tenant, flight_record, flight_dir, as_json, straggler,
                tier_scenario, device_churn, spill_dir, rollout, skew,
                buffer_size, min_goodput_ratio, max_acc_delta):
    """Stand up a full cross-silo deployment (server + clients, real codec,
    real round FSM) under the given fault plan and verify every round still
    closes. Exits 1 if the run hangs or loses rounds — the same check
    ``tests/test_chaos.py`` gates CI with, runnable against any config."""
    from ..cross_silo.chaos import run_chaos_drill

    if tier_scenario is not None:
        from ..cross_silo.chaos import run_tier_drill

        result = run_tier_drill(
            scenario=tier_scenario, max_acc_delta=max_acc_delta,
            random_seed=seed, comm_round=rounds)
        click.echo(json.dumps(result.json_record()) if as_json
                   else result.summary())
        if not result.ok:
            raise SystemExit(1)
        return

    if device_churn:
        import tempfile

        from ..cross_device.device_day import run_device_churn_drill

        result = run_device_churn_drill(
            max_acc_delta=max_acc_delta,
            spill_dir=spill_dir or tempfile.mkdtemp(prefix="device_day_"))
        click.echo(json.dumps(result.json_record()) if as_json
                   else result.summary())
        if not result.ok:
            raise SystemExit(1)
        return

    if rollout:
        from ..cross_silo.chaos import run_rollout_drill

        kw = dict(random_seed=seed, max_acc_delta=max_acc_delta)
        if byzantine_kind is not None:
            kw.update(rollout_poison_kind=byzantine_kind,
                      rollout_poison_scale=byzantine_scale)
        result = run_rollout_drill(**kw)
        click.echo(json.dumps(result.json_record()) if as_json
                   else result.summary())
        if not result.ok:
            raise SystemExit(1)
        return

    if straggler:
        from ..cross_silo.chaos import run_straggler_drill

        result = run_straggler_drill(
            min_goodput_ratio=min_goodput_ratio, max_acc_delta=max_acc_delta,
            random_seed=seed, async_delay_skew=skew,
            async_buffer_size=buffer_size)
        click.echo(json.dumps(result.json_record()) if as_json
                   else result.summary())
        if not result.ok:
            raise SystemExit(1)
        return

    kw = dict(
        fault_seed=seed, comm_round=rounds, client_num_in_total=clients,
        client_num_per_round=clients, fault_drop_rate=drop_rate,
        fault_duplicate_rate=duplicate_rate,
        fault_fail_send_rate=fail_send_rate,
    )
    if crash_rank is not None:
        kw.update(fault_crash_rank=crash_rank,
                  fault_crash_at_round=crash_at_round)
    if byzantine_kind is not None:
        kw.update(fault_byzantine_kind=byzantine_kind,
                  fault_byzantine_rate=byzantine_rate,
                  fault_byzantine_scale=byzantine_scale,
                  local_test_on_all_clients=True)
        if defend:
            kw.update(defense_type="multi_krum", sanitize_updates=True,
                      watchdog_factor=2.0)
    if codec is not None:
        # validate the spec before standing up a whole deployment
        from ..comm.codec import parse_codec_spec

        parse_codec_spec(codec)
        kw.update(comm_codec=codec)
    from ..core import telemetry
    if (codec is not None or tenant is not None or flight_record) \
            and not telemetry.enabled():
        # the codec verdict and tenant scoping read counter deltas
        telemetry.configure(enabled=True)
    if flight_record:
        # through the drill's config (not configure() here): the drill's
        # fedml_tpu.init re-reads the trace-plane family from its args and
        # would reset a pre-set flight_dir back to the default
        kw.update(flight_recorder=True, flight_dir=flight_dir,
                  trace_ship_spans=True)
    result = run_chaos_drill(join_timeout_s=timeout, tenant=tenant, **kw)
    if flight_record:
        from ..core import trace_plane

        bundle = trace_plane.flight_dump("manual", force=True)
        if bundle:
            click.echo(f"flight bundle: {bundle}")
    click.echo(json.dumps(result.json_record()) if as_json
               else result.summary())
    if not result.ok:
        raise SystemExit(1)
    if codec is not None and not result.codec_bytes_wire:
        click.echo("codec drill: FAIL — comm_codec was set but no "
                   "fedml_codec_* traffic was recorded")
        raise SystemExit(1)


@cli.command("serve",
             help="Run N federated jobs multi-tenant over one device mesh.")
@click.option("--job", "-j", "job_specs", multiple=True, required=True,
              metavar="NAME=CONFIG.yaml[:PRIORITY]",
              help="One tenant job: a name, its YAML config, and an optional "
                   "scheduler priority weight (repeat for each tenant).")
@click.option("--capacity-bytes", default=2 << 30, type=int,
              help="Admission budget: total device bytes jobs may reserve.")
@click.option("--max-jobs", default=8, type=int,
              help="Max concurrently admitted jobs.")
@click.option("--max-queue", default=16, type=int,
              help="Admission queue bound (beyond it: reject).")
@click.option("--quantum", default=1.0, type=float,
              help="Deficit-round-robin quantum per scheduling cycle.")
@click.option("--checkpoint-root", default=None, type=click.Path(),
              help="Per-tenant checkpoint namespaces live under this root.")
@click.option("--json", "as_json", is_flag=True,
              help="Emit one JSON line per tenant instead of summaries.")
def serve(job_specs, capacity_bytes, max_jobs, max_queue, quantum,
          checkpoint_root, as_json):
    """Admit each job against the byte budget (admit / queue / reject, typed
    verdicts), then interleave the admitted jobs' round steps fairly over one
    mesh — per-tenant telemetry, checkpoints, and numerics stay isolated
    (each job's history is bit-identical to running it solo). Exits 1 if any
    job is rejected or fails."""
    from ..arguments import SECTION_FAMILIES, load_yaml_config
    from ..core import telemetry
    from ..simulation import MultiTenantSimDriver, TenantJob

    if not telemetry.enabled():
        telemetry.configure(enabled=True)

    def flat(cfg):
        # same section-flattening rule as Arguments.set_attr_from_config
        out = {}
        for section, content in cfg.items():
            if isinstance(content, dict) and (
                    section in SECTION_FAMILIES or section.endswith("_args")):
                out.update(content)
            else:
                out[section] = content
        return out

    jobs = []
    for spec in job_specs:
        name, eq, rest = spec.partition("=")
        if not eq or not name:
            raise click.BadParameter(
                f"--job wants NAME=CONFIG.yaml[:PRIORITY], got '{spec}'")
        path, colon, prio = rest.rpartition(":")
        try:
            priority = float(prio) if colon else 1.0
        except ValueError:
            path, priority = rest, 1.0  # the ':' belonged to the path
        if not colon:
            path = rest
        if not os.path.exists(path):
            raise click.BadParameter(f"--job {name}: no such config '{path}'")
        jobs.append(TenantJob(name, flat(load_yaml_config(path)),
                              priority=priority))

    driver = MultiTenantSimDriver(
        jobs, capacity_bytes=capacity_bytes, max_concurrent=max_jobs,
        max_queue=max_queue, quantum=quantum,
        checkpoint_root=checkpoint_root, log_fn=click.echo)
    results = driver.run()
    ok = True
    for name in sorted(results):
        r = results[name]
        ok = ok and r.ok
        if as_json:
            last = r.history[-1] if r.history else {}
            click.echo(json.dumps({
                "tenant": r.tenant, "decision": r.verdict.decision,
                "ok": r.ok, "rounds": len(r.history),
                "rounds_expected": r.rounds_expected,
                "elapsed_s": round(r.elapsed_s, 3), "error": r.error,
                "final_train_loss": last.get("train_loss"),
            }))
        else:
            click.echo(r.summary())
    if not ok:
        raise SystemExit(1)


@cli.command("loadgen",
             help="Replay device check-in overload against the bounded "
                  "check-in queue and report the throughput/shed frontier.")
@click.option("--duration", default=1.0, type=float,
              help="Drill length in seconds.")
@click.option("--rate", default=0.0, type=float,
              help="Target aggregate check-ins/sec (0 = producers run flat "
                   "out to find the natural ceiling).")
@click.option("--producers", default=2, type=int)
@click.option("--queue-maxsize", default=512, type=int,
              help="Check-in queue bound; overflow is shed, never buffered.")
@click.option("--tenants", default=2, type=int,
              help="Tenant count check-ins round-robin across.")
@click.option("--churn", default=0.1, type=float,
              help="Seeded fraction of devices that vanish mid-announce.")
@click.option("--seed", default=0, type=int)
@click.option("--json", "as_json", is_flag=True,
              help="Emit the frontier as one JSON line.")
def loadgen(duration, rate, producers, queue_maxsize, tenants, churn, seed,
            as_json):
    """Every check-in rides the real message codec; shedding shows up in the
    per-tenant ``fedml_checkins_shed_total`` counters and the queue's depth
    high-water mark can never pass the bound. Exits 1 if the accounting
    doesn't close (offered != accepted + shed) or the bound broke."""
    from ..core import telemetry
    from ..cross_silo.loadgen import run_loadgen

    if not telemetry.enabled():
        telemetry.configure(enabled=True)
    report = run_loadgen(duration_s=duration, target_rate=rate,
                         producers=producers, queue_maxsize=queue_maxsize,
                         tenants=tenants, churn=churn, seed=seed)
    click.echo(json.dumps(report.json_record()) if as_json
               else report.summary())
    if not report.ok:
        raise SystemExit(1)


@cli.group("telemetry", help="Inspect telemetry artifacts.")
def telemetry_group():
    pass


@telemetry_group.command(
    "summary", help="Summarize a telemetry JSONL file (spans + registry).")
@click.argument("jsonl_path", type=click.Path(exists=True))
@click.option("--tenant", default=None,
              help="Restrict to one tenant's spans and series (multi-run "
                   "JSONL files interleave every tenant's records).")
def telemetry_summary(jsonl_path, tenant):
    from ..core import telemetry as _telemetry

    spans = {}
    instants = {}
    snapshot = None
    skipped = 0
    with open(jsonl_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                skipped += 1
                continue
            kind = rec.get("kind")
            if kind == "span":
                if tenant is not None and rec.get("tenant") != tenant:
                    continue
                s = spans.setdefault(
                    rec.get("name", "?"), {"durations": [], "traces": set()})
                s["durations"].append(float(rec.get("duration", 0.0)))
                if rec.get("trace_id"):
                    s["traces"].add(rec["trace_id"])
            elif kind == "instant":
                # point events (commit / quarantine / rollback / shed …) —
                # the same records the Perfetto export renders as ph:"i"
                if tenant is not None and rec.get("tenant") != tenant:
                    continue
                i = instants.setdefault(
                    rec.get("name", "?"), {"count": 0, "rounds": set()})
                i["count"] += 1
                if rec.get("round") is not None:
                    i["rounds"].add(int(rec["round"]))
            elif kind == "registry_snapshot":
                snapshot = rec.get("registry")  # keep the LAST one
    if snapshot is not None and tenant is not None:
        # the same filtering TenantRegistry.snapshot applies in-process
        snapshot = _telemetry.filter_snapshot(snapshot, tenant)
    if spans:
        click.echo("spans:")
        click.echo(f"  {'name':<28}{'count':>7}{'total_s':>10}"
                   f"{'mean_s':>10}{'p95_s':>10}{'traces':>8}")
        for name in sorted(spans):
            ds = sorted(spans[name]["durations"])
            total = sum(ds)
            p95 = ds[min(len(ds) - 1, int(0.95 * (len(ds) - 1)))]
            click.echo(f"  {name:<28}{len(ds):>7}{total:>10.4f}"
                       f"{total / len(ds):>10.5f}{p95:>10.5f}"
                       f"{len(spans[name]['traces']):>8}")
    if instants:
        click.echo("instants:")
        click.echo(f"  {'name':<28}{'count':>7}{'rounds':>8}")
        for name in sorted(instants):
            i = instants[name]
            click.echo(f"  {name:<28}{i['count']:>7}{len(i['rounds']):>8}")
    if snapshot:
        counters = snapshot.get("counters", {})
        dropped = sum(v for k, v in counters.items()
                      if k.startswith("fedml_spans_dropped_total"))
        if dropped:
            click.echo(f"spans dropped (ring evictions): {dropped:g} — "
                       "raise telemetry_span_buffer to keep them")
        if counters:
            click.echo("counters:")
            for key in sorted(counters):
                click.echo(f"  {key} = {counters[key]:g}")
        shed_rows = [(k.split("reason=", 1)[-1].rstrip("}"), v)
                     for k, v in counters.items()
                     if k.startswith("fedml_shed_total{")
                     and "reason=" in k]
        if shed_rows:
            by_reason: dict = {}
            for reason, v in shed_rows:
                reason = reason.split(",", 1)[0]
                by_reason[reason] = by_reason.get(reason, 0.0) + v
            total = sum(by_reason.values()) or 1.0
            click.echo("shed breakdown (by reason):")
            for reason, v in sorted(by_reason.items(), key=lambda kv: -kv[1]):
                click.echo(f"  {reason:<16}{v:>12g}{v / total:>9.1%}")
        hists = snapshot.get("histograms", {})
        phase_rows = []
        if hists:
            click.echo("histograms:")
            for key in sorted(hists):
                h = hists[key]
                n = h.get("count", 0)
                mean = h["sum"] / n if n else 0.0
                click.echo(f"  {key}: count={n:g} mean={mean:.6g}")
                if key.startswith("fedml_round_phase_seconds{"):
                    phase = key.split("phase=", 1)[-1].rstrip("}")
                    phase_rows.append((phase, h["sum"]))
        if phase_rows:
            total = sum(v for _, v in phase_rows) or 1.0
            click.echo("round phase breakdown (share of attributed wall):")
            for phase, v in sorted(phase_rows, key=lambda kv: -kv[1]):
                click.echo(f"  {phase:<12}{v:>12.4f}s{v / total:>9.1%}")
    if not spans and not instants and not snapshot:
        click.echo("no span or registry_snapshot records found")
    if skipped:
        click.echo(f"({skipped} unparseable lines skipped)")


@telemetry_group.command(
    "trace",
    help="Render a telemetry JSONL file or flight-recorder bundle as Chrome "
         "trace-event JSON (open in Perfetto / chrome://tracing): one "
         "process per tenant, one track per rank, phase slices, comm spans, "
         "and instant events, skew-corrected from the handshake exchange.")
@click.argument("source", type=click.Path(exists=True))
@click.option("--out", "out_path", required=True, type=click.Path(),
              help="Output trace file, e.g. round.trace.json.")
@click.option("--tenant", default=None,
              help="Keep only this tenant's records.")
@click.option("--round", "round_idx", default=None, type=int,
              help="Keep only this round's spans/phases/instants.")
def telemetry_trace(source, out_path, tenant, round_idx):
    from ..core import trace_plane

    records = trace_plane.load_records(source)
    doc = trace_plane.export_chrome_trace(
        records, out_path=out_path, tenant=tenant, round_idx=round_idx)
    events = doc["traceEvents"]
    slices = [e for e in events if e.get("ph") == "X"]
    if not slices:
        click.echo(f"no matching trace events in {source} "
                   f"(tenant={tenant!r}, round={round_idx!r}) — wrote an "
                   "empty trace")
    pids = {e["pid"] for e in slices}
    tids = {(e["pid"], e["tid"]) for e in slices}
    instants = sum(1 for e in events if e.get("ph") == "i")
    click.echo(f"wrote {out_path}: {len(slices)} slices, {instants} "
               f"instants across {len(pids)} process(es) / {len(tids)} "
               "track(s)")


def main():
    cli()


if __name__ == "__main__":
    main()
