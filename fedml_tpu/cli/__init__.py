"""CLI / local agent surface (reference ``python/fedml/cli/``, SURVEY.md §2.6).

Note: the click entry lives in ``fedml_tpu.cli.main``; only the group object
is re-exported here so the ``main`` *submodule* name stays importable.
"""

from .main import cli

__all__ = ["cli"]
