"""MLOps agent daemons: edge (client) and server runners.

Parity: reference ``cli/edge_deployment/client_runner.py:38``
(``FedMLClientRunner``: package download ``retrieve_and_unzip_package:129``,
config rewrite ``update_local_fedml_config:147``, train-process fork
``callback_start_train:426``, stop ``callback_stop_train:445``, status FSM
``callback_runner_id_status:619``) and ``cli/server_deployment/
server_runner.py:42`` (``FedMLServerRunner``: fans the training request to
edges ``send_training_request_to_edges:426``).

Redesign: the daemons ride the same pluggable control plane as the MQTT_S3
backend — a ``PubSubBroker`` for job dispatch (filesystem broker needs no
hosted MQTT) and a ``BlobStore`` for package distribution (filesystem store
replaces S3). The job lifecycle is identical: a start message names a built
package; the edge daemon fetches + unzips it, rewrites its YAML config with
the run's dynamic args, forks the training process, and reports the
IDLE/RUNNING/FAILED/FINISHED FSM through MLOpsMetrics and a status file the
CLI ``status`` command reads.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import subprocess
import sys
import threading
import time
import zipfile
from typing import Any, Dict, Optional

import yaml

from ..comm.message import pack_payload, unpack_payload
from ..comm.pubsub import PubSubBroker
from ..comm.store import BlobStore
from ..core.mlops import MetricsSink, MLOpsMetrics

JOB_TOPIC_FMT = "mlops_job_{edge_id}"
STATUS_TOPIC = "mlops_status"

MSG_START_TRAIN = "start_train"
MSG_STOP_TRAIN = "stop_train"


class FedMLEdgeRunner:
    """Edge agent daemon (reference ``FedMLClientRunner:38``)."""

    def __init__(
        self,
        edge_id: int,
        broker: PubSubBroker,
        store: Optional[BlobStore] = None,
        home_dir: Optional[str] = None,
        sink: Optional[MetricsSink] = None,
    ):
        self.edge_id = int(edge_id)
        self.broker = broker
        self.store = store
        self.home = home_dir or os.path.expanduser(
            os.environ.get("FEDML_TPU_HOME", "~/.fedml_tpu")
        )
        os.makedirs(self.home, exist_ok=True)
        self.metrics = MLOpsMetrics(sink=sink)
        self.metrics.edge_id = self.edge_id
        self._proc: Optional[subprocess.Popen] = None
        self._current_run = None
        self._proc_lock = threading.Lock()
        self._running = True
        self._done = threading.Event()
        # terminal job history persists across daemon restarts so replayed
        # job-topic history (subscribe_from_start) never re-executes a run
        # that already finished (reference relies on MQTT QoS for this)
        self._history_path = os.path.join(
            self.home, f"jobs_edge{self.edge_id}.json")
        self._history_lock = threading.Lock()
        self._job_history: Dict[str, str] = self._load_history()
        # serializes status reports: the dispatcher thread (stop/replay) and
        # a watcher thread (process exit) can report concurrently
        self._status_lock = threading.Lock()
        self._report_status(MLOpsMetrics.STATUS_IDLE)

    @classmethod
    def from_binding(cls, broker: PubSubBroker, bind_url: str,
                     account_id: str, http_post=None, **kwargs):
        """Hosted-platform flow (reference ``client_login.py`` →
        ``bind_account_and_device_id``): register this host under the
        account, then run the agent as the returned edge id. The transport
        is injectable; a refused binding raises instead of silently running
        as edge 0."""
        from ..core.mlops import bind_account_and_device_id

        edge_id = bind_account_and_device_id(
            bind_url, account_id, http_post=http_post)
        if not edge_id:
            raise RuntimeError(
                f"device binding refused for account {account_id} at "
                f"{bind_url}")
        return cls(edge_id, broker, **kwargs)

    def _load_history(self) -> Dict[str, str]:
        try:
            with open(self._history_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def _record_terminal(self, run_id, status: str) -> None:
        # watcher thread and poller thread can both reach terminal for the
        # same run (stop racing process exit): lock + atomic replace so a
        # torn write can never wipe the whole replay-protection history
        with self._history_lock:
            self._job_history[str(run_id)] = status
            tmp = self._history_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self._job_history, f)
            os.replace(tmp, self._history_path)

    # --- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        """Subscribe to this edge's job topic and serve jobs until stop().
        Brokers with history replay deliver jobs queued before the daemon
        came up (the reference relies on MQTT retained sessions for this)."""
        topic = JOB_TOPIC_FMT.format(edge_id=self.edge_id)
        subscribe = getattr(self.broker, "subscribe_from_start", self.broker.subscribe)
        subscribe(topic, self._on_job)

    def stop(self) -> None:
        self._running = False
        self.broker.unsubscribe(JOB_TOPIC_FMT.format(edge_id=self.edge_id))
        self._kill_train_process()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until a job reaches a terminal state (test convenience)."""
        return self._done.wait(timeout)

    # --- job handling -------------------------------------------------------
    def _on_job(self, _topic: str, payload: bytes) -> None:
        if not self._running:
            return
        job = unpack_payload(payload)
        kind = job.get("msg")
        if kind == MSG_START_TRAIN:
            self._callback_start_train(job)
        elif kind == MSG_STOP_TRAIN:
            self._callback_stop_train(job)

    def _package_dirs(self, run_id) -> Dict[str, str]:
        base = os.path.join(self.home, "fedml_run", f"run_{run_id}",
                            f"edge_{self.edge_id}")
        return {
            "download": os.path.join(base, "download"),
            "run": os.path.join(base, "package"),
        }

    def retrieve_and_unzip_package(self, run_id, package_ref: str) -> str:
        """Fetch the built package (blob-store key or local path) and unzip
        it into this run's directory (reference ``:129``)."""
        dirs = self._package_dirs(run_id)
        os.makedirs(dirs["download"], exist_ok=True)
        local_zip = os.path.join(dirs["download"], os.path.basename(package_ref))
        if self.store is not None and not os.path.exists(package_ref):
            with open(local_zip, "wb") as f:
                f.write(self.store.get(package_ref))
        else:
            shutil.copyfile(package_ref, local_zip)
        shutil.rmtree(dirs["run"], ignore_errors=True)
        with zipfile.ZipFile(local_zip) as z:
            z.extractall(dirs["run"])
        return dirs["run"]

    def update_local_config(self, package_dir: str, dynamic_args: Dict[str, Any]) -> str:
        """Rewrite the packaged YAML config with the run's dynamic args
        (reference ``update_local_fedml_config:147``). Returns the rewritten
        config path."""
        cfg_dir = os.path.join(package_dir, "config")
        cfg_path = None
        for name in sorted(os.listdir(cfg_dir)):
            if name.endswith((".yaml", ".yml")):
                cfg_path = os.path.join(cfg_dir, name)
                break
        if cfg_path is None:
            raise FileNotFoundError(f"no yaml config inside {cfg_dir}")
        with open(cfg_path) as f:
            cfg = yaml.safe_load(f) or {}
        # dynamic args land in the common_args section family
        common = cfg.setdefault("common_args", {})
        for k, v in (dynamic_args or {}).items():
            common[k] = v
        with open(cfg_path, "w") as f:
            yaml.safe_dump(cfg, f)
        return cfg_path

    def _callback_start_train(self, job: Dict[str, Any]) -> None:
        """Reference ``callback_start_train:426``: package -> config -> fork."""
        run_id = job.get("run_id", 0)
        with self._history_lock:
            prior = self._job_history.get(str(run_id))
        if prior is not None:
            logging.info("edge %d: run %s already terminal (%s), skipping",
                         self.edge_id, run_id, prior)
            return
        with self._proc_lock:
            if (self._proc is not None and self._proc.poll() is None
                    and self._current_run == run_id):
                logging.info("edge %d: run %s already running, ignoring "
                             "duplicate start", self.edge_id, run_id)
                return
            superseded = (self._current_run if self._proc is not None
                          and self._proc.poll() is None else None)
        # a different run supersedes the current one (reference restarts the
        # training process on every start message); record the loser as
        # KILLED here — its watcher bows out once self._proc is reassigned
        if superseded is not None:
            with self._history_lock:
                known = str(superseded) in self._job_history
            if not known:
                self._record_terminal(superseded, MLOpsMetrics.STATUS_KILLED)
        self._kill_train_process()
        self.metrics.run_id = run_id
        self._done.clear()
        try:
            package_dir = self.retrieve_and_unzip_package(run_id, job["package"])
            cfg_path = self.update_local_config(
                package_dir, job.get("dynamic_args", {})
            )
            with open(os.path.join(package_dir, "package.json")) as f:
                entry_point = json.load(f)["entry_point"]
            entry = os.path.join(package_dir, "source", entry_point)
            env = dict(os.environ)
            env.update({str(k): str(v) for k, v in (job.get("env") or {}).items()})
            log_dir = os.path.join(self.home, "logs")
            os.makedirs(log_dir, exist_ok=True)
            log_path = os.path.join(log_dir, f"run_{run_id}_edge_{self.edge_id}.log")
            self._report_status(MLOpsMetrics.STATUS_RUNNING)
            # fork/exec outside the lock — callbacks run on one dispatcher
            # thread, so only the self._proc handoff below needs the lock
            # (the watcher thread compares identity before acting)
            with open(log_path, "w") as log:
                # the child duplicates the log fd; close the parent's copy
                proc = subprocess.Popen(
                    [sys.executable, entry, "--cf", cfg_path],
                    cwd=package_dir, env=env,
                    stdout=log, stderr=subprocess.STDOUT,
                )
            with self._proc_lock:
                self._proc = proc
                self._current_run = run_id
            threading.Thread(target=self._watch_train_process,
                             args=(proc, run_id), daemon=True).start()
        except Exception:
            logging.exception("edge %d: start_train failed", self.edge_id)
            self._record_terminal(run_id, MLOpsMetrics.STATUS_FAILED)
            self._report_status(MLOpsMetrics.STATUS_FAILED)
            self._done.set()

    def _watch_train_process(self, proc: subprocess.Popen, run_id) -> None:
        rc = proc.wait()
        with self._proc_lock:
            if self._proc is not proc:
                return  # superseded by a newer run; its watcher owns status
        if rc == 0:
            status = MLOpsMetrics.STATUS_FINISHED
        elif rc < 0:
            status = MLOpsMetrics.STATUS_KILLED
        else:
            status = MLOpsMetrics.STATUS_FAILED
        self._record_terminal(run_id, status)
        self._report_status(status)
        self._done.set()

    def _callback_stop_train(self, job: Dict[str, Any]) -> None:
        """Reference ``callback_stop_train:445``."""
        run_id = job.get("run_id", self._current_run)
        if run_id is not None:
            with self._history_lock:
                terminal = str(run_id) in self._job_history
            if terminal:
                # replayed stop for an already-terminal run: no spurious KILLED
                return
        if run_id is not None and self._current_run is not None \
                and run_id != self._current_run:
            return  # stop for a run this daemon never started
        self._kill_train_process()
        if run_id is not None:
            self._record_terminal(run_id, MLOpsMetrics.STATUS_KILLED)
        self._report_status(MLOpsMetrics.STATUS_KILLED)
        self._done.set()

    def _kill_train_process(self) -> None:
        with self._proc_lock:
            if self._proc is not None and self._proc.poll() is None:
                self._proc.terminate()
                try:
                    self._proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    self._proc.kill()

    # --- status FSM ---------------------------------------------------------
    def _report_status(self, status: str) -> None:
        """Reference ``callback_runner_id_status:619`` + CLI status file."""
        rec = {"status": status, "edge_id": self.edge_id, "time": time.time(),
               "run_id": getattr(self.metrics, "run_id", None)}
        # attr + status files under one lock: a watcher thread and the
        # dispatcher can report concurrently, and a torn attr/file pair
        # would show two different states to the CLI `status` command.
        # The broker publish stays outside the critical section.
        with self._status_lock:
            self.status = status
            # per-edge file: multiple agents sharing one home dir must not
            # clobber each other's state (plus the legacy shared file the
            # CLI `status` command falls back to)
            with open(os.path.join(self.home,
                                   f"status_edge{self.edge_id}.json"), "w") as f:
                json.dump(rec, f)
            with open(os.path.join(self.home, "status.json"), "w") as f:
                json.dump(rec, f)
        self.metrics.report_client_training_status(self.edge_id, status)
        self.broker.publish(STATUS_TOPIC, pack_payload(rec))


class FedMLServerRunner:
    """Server agent (reference ``FedMLServerRunner:42``): receives a run
    request and fans the training job out to the edges."""

    def __init__(
        self,
        broker: PubSubBroker,
        store: Optional[BlobStore] = None,
        sink: Optional[MetricsSink] = None,
    ):
        self.broker = broker
        self.store = store
        self.metrics = MLOpsMetrics(sink=sink)
        self.edge_status: Dict[int, str] = {}
        self.edge_run: Dict[int, Any] = {}
        self._status_lock = threading.Lock()
        self.broker.subscribe(STATUS_TOPIC, self._on_edge_status)

    def _on_edge_status(self, _topic: str, payload: bytes) -> None:
        rec = unpack_payload(payload)
        with self._status_lock:
            self.edge_status[int(rec["edge_id"])] = rec["status"]
            self.edge_run[int(rec["edge_id"])] = rec.get("run_id")

    def upload_package(self, run_id, package_path: str) -> str:
        """Publish the built package for edges to fetch. With a store, edges
        pull by key; without one they read the local path directly."""
        if self.store is None:
            return package_path
        key = f"package_run{run_id}_{os.path.basename(package_path)}"
        with open(package_path, "rb") as f:
            self.store.put(key, f.read())
        return key

    def send_training_request_to_edges(
        self,
        run_id,
        edge_ids,
        package_path: str,
        dynamic_args: Optional[Dict[str, Any]] = None,
        env: Optional[Dict[str, str]] = None,
    ) -> None:
        """Reference ``send_training_request_to_edges:426``."""
        package_ref = self.upload_package(run_id, package_path)
        self.metrics.report_server_training_status(
            run_id, MLOpsMetrics.STATUS_RUNNING)
        for edge_id in edge_ids:
            job = {
                "msg": MSG_START_TRAIN,
                "run_id": run_id,
                "package": package_ref,
                "dynamic_args": dict(dynamic_args or {}, rank=edge_id),
                "env": env or {},
            }
            self.broker.publish(
                JOB_TOPIC_FMT.format(edge_id=edge_id), pack_payload(job)
            )

    def send_stop_request_to_edges(self, run_id, edge_ids) -> None:
        for edge_id in edge_ids:
            self.broker.publish(
                JOB_TOPIC_FMT.format(edge_id=edge_id),
                pack_payload({"msg": MSG_STOP_TRAIN, "run_id": run_id}),
            )

    def wait_for_edges(self, edge_ids, terminal=("FINISHED", "FAILED", "KILLED"),
                       timeout: float = 300.0, run_id=None) -> Dict[int, str]:
        """Block until every edge reports a terminal status — scoped to
        ``run_id`` when given, so stale FINISHED messages from a previous
        run never satisfy a new dispatch."""
        deadline = time.time() + timeout

        def _done(e):
            if self.edge_status.get(e) not in terminal:
                return False
            return run_id is None or self.edge_run.get(e) == run_id

        while time.time() < deadline:
            with self._status_lock:
                if all(_done(e) for e in edge_ids):
                    break
            time.sleep(0.05)
        with self._status_lock:
            if run_id is None:
                return dict(self.edge_status)
            # scope the RESULT too: a stale status from another run must not
            # read as this run's outcome after a timeout
            return {e: s for e, s in self.edge_status.items()
                    if self.edge_run.get(e) == run_id}
