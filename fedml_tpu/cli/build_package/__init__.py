"""Default build-package entry skeletons (reference ``cli/build-package/``).

``fedml_tpu build`` falls back to this directory as the source folder when
the caller passes ``--source_folder default`` — packaging the stock
client/server entries exactly like the reference platform does when the
user brings only a config.
"""

import os

SKELETON_DIR = os.path.dirname(os.path.abspath(__file__))
CLIENT_ENTRY = "tpu_client.py"
SERVER_ENTRY = "tpu_server.py"
