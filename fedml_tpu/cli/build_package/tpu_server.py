"""Minimal server entry for built packages (reference
``cli/build-package/mlops-core/.../torch_server.py``)."""

import fedml_tpu

if __name__ == "__main__":
    args = fedml_tpu.init()
    fedml_tpu.run_cross_silo_server(args)
