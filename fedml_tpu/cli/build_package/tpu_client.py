"""Minimal client entry for built packages (reference
``cli/build-package/mlops-core/.../torch_client.py`` — a 5-line entry the
platform packages when the user supplies no custom source)."""

import fedml_tpu

if __name__ == "__main__":
    args = fedml_tpu.init()
    fedml_tpu.run_cross_silo_client(args)
