"""Compute ops: attention (fused / ring), losses, pallas kernels.

This layer is where hot ops get TPU-specific implementations; everything else
relies on XLA fusion. Reference has no equivalent (its compute is torch ops);
SURVEY.md §2.7 maps PyTorch ATen/CUDA -> XLA:TPU here.
"""

from .losses import softmax_cross_entropy, masked_softmax_cross_entropy, masked_accuracy
from .attention import multihead_attention

__all__ = [
    "softmax_cross_entropy",
    "masked_softmax_cross_entropy",
    "masked_accuracy",
    "multihead_attention",
]
