"""Multi-weight 2D convolution: the packed-lane conv path.

Why this exists: the packed-lane cohort executor (``simulation/fed_sim.py``)
vmaps the whole local-update over the lane axis, so every conv sees
*per-lane weights*. XLA lowers a weight-batched conv to a grouped
convolution, whose thin per-group channels starve the 128-wide MXU — the
measured penalty on the v5e is ~1.5x at the 32x32x16 stage and ~4.7x at
16x16x64 (``results/lane_sweep_r3.json``). The reference has no analogue
(its clients train sequentially in Python — ``simulation/sp/fedavg/
my_model_trainer_classification.py:15``); this is a TPU-native problem and
gets a TPU-native fix:

- ``conv2d_im2col``: convolution as explicit patch extraction (strided
  slices, no conv primitive) + ``einsum``. Under ``vmap`` with batched
  weights this becomes a *batched matmul* — MXU-native, no grouped-conv
  lowering. The cost is patch materialization in HBM (9x activation
  traffic for 3x3), so it is the fallback, not the fast path.
- ``conv2d_pallas``: a fused pallas kernel that builds the im2col patch
  matrix in VMEM per block and feeds one dense ``[M, kh*kw*Ci] @
  [kh*kw*Ci, Co]`` matmul per grid cell — dense-matmul MXU rates with no
  patch HBM traffic. ``jax.vmap`` of a ``pallas_call`` prepends a grid
  axis, so the lane-batched case IS the batched-multi-weight kernel; a
  ``custom_vjp`` supplies pallas backward kernels (dx = flipped-kernel
  conv reusing the forward kernel; dw = patch^T @ dy with grid
  accumulation).

The ``Conv`` flax module is a drop-in for ``nn.Conv`` (same param name
"kernel", same auto-naming, NHWC, SAME/VALID) that dispatches per
``impl`` and per conv shape. 1x1 convs always take the direct-einsum path
(a 1x1 conv *is* a matmul; under vmap that is a batched matmul, never a
grouped conv).
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from flax import linen as nn

# --- pure-JAX im2col ------------------------------------------------------


def _same_pads(size: int, k: int, s: int) -> Tuple[int, int]:
    out = -(-size // s)  # ceil
    pad = max(0, (out - 1) * s + k - size)
    return pad // 2, pad - pad // 2


def extract_patches(x: jnp.ndarray, kh: int, kw: int, stride: int,
                    padding: str) -> jnp.ndarray:
    """[B, H, W, C] -> [B, Ho, Wo, kh*kw*C] via strided slices + concat.

    Feature order is (dy, dx, ci) — matching ``w.reshape(kh*kw*ci, co)``
    for ``w`` of shape [kh, kw, ci, co]. No convolution primitive is
    involved, so vmapping over a weight axis elsewhere cannot force a
    grouped-conv lowering here.
    """
    b, h, w, c = x.shape
    if padding == "SAME":
        (pt, pb), (pl, pr) = _same_pads(h, kh, stride), _same_pads(w, kw, stride)
        x = jnp.pad(x, ((0, 0), (pt, pb), (pl, pr), (0, 0)))
        h, w = h + pt + pb, w + pl + pr
    elif padding != "VALID":
        raise ValueError(f"padding must be SAME or VALID, got {padding!r}")
    ho, wo = (h - kh) // stride + 1, (w - kw) // stride + 1
    taps = []
    for dy in range(kh):
        for dx in range(kw):
            taps.append(jax.lax.slice(
                x,
                (0, dy, dx, 0),
                (b, dy + (ho - 1) * stride + 1, dx + (wo - 1) * stride + 1, c),
                (1, stride, stride, 1)))
    return jnp.concatenate(taps, axis=-1)


def conv2d_im2col(x: jnp.ndarray, w: jnp.ndarray, stride: int = 1,
                  padding: str = "SAME") -> jnp.ndarray:
    """Conv as patches @ weight-matrix. [B,H,W,Ci] x [kh,kw,Ci,Co]."""
    kh, kw, ci, co = w.shape
    if kh == kw == 1:
        if stride > 1:
            x = x[:, ::stride, ::stride, :]
        return jnp.einsum("bhwc,co->bhwo", x, w[0, 0])
    p = extract_patches(x, kh, kw, stride, padding)
    return jnp.einsum("bhwk,ko->bhwo", p, w.reshape(kh * kw * ci, co))


# --- pallas fused kernel --------------------------------------------------

try:  # pallas import kept lazy-tolerant: CPU test envs lack Mosaic only at trace
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def _pick_block_b(b: int, h: int, w: int, ci: int, kk: int, co: int,
                  itemsize: int = 2) -> int:
    """Largest power-of-two batch block whose working set fits ~8 MB VMEM
    (padded lane estimates: trailing dims round up to 128 lanes).
    ``itemsize`` is the element byte width of the actual dtype — f32 inputs
    have twice the bf16 working set and must pick smaller blocks."""
    def lanes(n):
        return -(-n // 128) * 128

    for bt in (64, 32, 16, 8, 4, 2, 1):
        if bt > b or b % bt:
            continue
        est = itemsize * (
            bt * (h + 2) * (w + 2) * lanes(ci)        # input block
            + bt * h * w * lanes(kk * ci)             # patch matrix
            + bt * h * w * lanes(co)                  # output block
        )
        if est <= 8 * 1024 * 1024:
            return bt
    return 1


def _build_patches(x_ref, p_ref, *, kh, kw, ho, wo, stride):
    """Fill the VMEM patch scratch [Bt*Ho*Wo, kh*kw*Ci] from the padded
    input block via static-offset stores. (A jnp.concatenate over the
    shifted taps is the natural spelling, but Mosaic refuses to concat
    vectors whose sublane offsets differ — each dy shift changes the
    offset — so the patch matrix is materialized through the ref.)"""
    xb = x_ref[...]                      # [Bt, Hp, Wp, Ci]
    bt, _, _, ci = xb.shape
    for dy in range(kh):
        for dx in range(kw):
            t = jax.lax.slice(
                xb,
                (0, dy, dx, 0),
                (bt, dy + (ho - 1) * stride + 1, dx + (wo - 1) * stride + 1, ci),
                (1, stride, stride, 1))
            off = (dy * kw + dx) * ci
            p_ref[:, off:off + ci] = t.reshape(bt * ho * wo, ci)


def _fwd_kernel(x_ref, w_ref, o_ref, p_ref, *, kh, kw, ho, wo, stride,
                out_dtype):
    _build_patches(x_ref, p_ref, kh=kh, kw=kw, ho=ho, wo=wo, stride=stride)
    bt = x_ref.shape[0]
    wm = w_ref[...].reshape(kh * kw * x_ref.shape[3], -1)
    acc = jnp.dot(p_ref[...], wm, preferred_element_type=jnp.float32)
    o_ref[...] = acc.reshape(bt, ho, wo, -1).astype(out_dtype)


def _dw_kernel(x_ref, dy_ref, o_ref, p_ref, *, kh, kw, ho, wo, stride):
    _build_patches(x_ref, p_ref, kh=kh, kw=kw, ho=ho, wo=wo, stride=stride)
    bt = x_ref.shape[0]
    g = dy_ref[...].reshape(bt * ho * wo, -1)
    acc = jnp.dot(p_ref[...].T, g, preferred_element_type=jnp.float32)
    # accumulate across the batch-block grid axis
    @pl.when(pl.program_id(0) == 0)
    def _init():
        o_ref[...] = acc

    @pl.when(pl.program_id(0) != 0)
    def _acc():
        o_ref[...] += acc


def _pad_same(x, kh, kw, stride):
    (pt, pb), (pl_, pr) = _same_pads(x.shape[1], kh, stride), _same_pads(x.shape[2], kw, stride)
    return jnp.pad(x, ((0, 0), (pt, pb), (pl_, pr), (0, 0)))


def _supported(x_shape, w_shape, stride, padding) -> bool:
    if not _HAS_PALLAS or len(w_shape) != 4:
        return False
    kh, kw, _, _ = w_shape
    return (padding == "SAME" and stride == 1 and kh == kw == 3
            and len(x_shape) == 4)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def conv2d_pallas(x: jnp.ndarray, w: jnp.ndarray, stride: int = 1,
                  padding: str = "SAME") -> jnp.ndarray:
    """Fused im2col conv (3x3, stride 1, SAME). See module docstring.

    vmap over a leading weight axis turns this into the batched
    multi-weight kernel (pallas prepends the mapped axis to the grid).
    """
    return _conv2d_pallas_impl(x, w, stride, padding)


def _conv2d_pallas_impl(x, w, stride, padding):
    if not _HAS_PALLAS:
        raise RuntimeError("conv2d_pallas requires jax.experimental.pallas")
    if not _supported(x.shape, w.shape, stride, padding):
        raise ValueError(
            "conv2d_pallas supports only 3x3 kernels, stride 1, SAME padding "
            f"on 4-D NHWC inputs; got w.shape={tuple(w.shape)}, "
            f"stride={stride}, padding={padding!r}, x.ndim={len(x.shape)}. "
            "Use Conv(impl=...) for automatic fallback on unsupported shapes.")
    b, h, ww, ci = x.shape
    kh, kw, _, co = w.shape
    ho, wo = h, ww  # stride-1 SAME
    xp = _pad_same(x, kh, kw, stride)
    bt = _pick_block_b(b, h, ww, ci, kh * kw, co,
                       itemsize=jnp.dtype(x.dtype).itemsize)
    kern = functools.partial(_fwd_kernel, kh=kh, kw=kw, ho=ho, wo=wo,
                             stride=stride, out_dtype=x.dtype)
    return pl.pallas_call(
        kern,
        grid=(b // bt,),
        in_specs=[
            pl.BlockSpec((bt, xp.shape[1], xp.shape[2], ci),
                         lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((kh, kw, ci, co), lambda i: (0, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((bt, ho, wo, co), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, ho, wo, co), x.dtype),
        scratch_shapes=[pltpu.VMEM((bt * ho * wo, kh * kw * ci), x.dtype)],
    )(xp, w)


def _conv2d_pallas_fwd(x, w, stride, padding):
    return _conv2d_pallas_impl(x, w, stride, padding), (x, w)


def _conv2d_pallas_bwd(stride, padding, res, g):
    x, w = res
    b, h, ww, ci = x.shape
    kh, kw, _, co = w.shape
    # dx: conv of g with the spatially-flipped, channel-transposed kernel —
    # reuses the forward kernel (still 3x3 stride-1 SAME)
    w_flip = jnp.flip(w, axis=(0, 1)).transpose(0, 1, 3, 2)
    dx = _conv2d_pallas_impl(g, w_flip, stride, padding).astype(x.dtype)
    # dw: patches(x)^T @ g, accumulated across batch blocks on the grid
    xp = _pad_same(x, kh, kw, stride)
    bt = _pick_block_b(b, h, ww, ci, kh * kw, co,
                       itemsize=jnp.dtype(x.dtype).itemsize)
    kern = functools.partial(_dw_kernel, kh=kh, kw=kw, ho=h, wo=ww,
                             stride=stride)
    dw_flat = pl.pallas_call(
        kern,
        grid=(b // bt,),
        in_specs=[
            pl.BlockSpec((bt, xp.shape[1], xp.shape[2], ci),
                         lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((bt, h, ww, co), lambda i: (i, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((kh * kw * ci, co), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((kh * kw * ci, co), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bt * h * ww, kh * kw * ci), x.dtype)],
    )(xp, g)
    return dx, dw_flat.reshape(kh, kw, ci, co).astype(w.dtype)


conv2d_pallas.defvjp(_conv2d_pallas_fwd, _conv2d_pallas_bwd)


# --- flax module ----------------------------------------------------------


class Conv(nn.Module):
    """Drop-in ``nn.Conv`` subset (NHWC, no dilation) with a selectable
    compute path. Auto-named "Conv_i" like ``nn.Conv`` so param trees are
    identical across impls.

    impl:
      - "xla":    ``lax.conv_general_dilated`` (XLA's native conv; best
                  unvmapped, grouped-conv penalty under weight-vmap)
      - "im2col": patches + einsum (batched matmul under weight-vmap;
                  pays patch HBM traffic)
      - "pallas": fused VMEM im2col kernel for 3x3/s1/SAME (+ the 1x1
                  einsum path); other shapes fall back to im2col
    """

    features: int
    kernel_size: Sequence[int] = (3, 3)
    strides: Union[int, Sequence[int]] = 1
    padding: str = "SAME"
    use_bias: bool = False
    dtype: jnp.dtype = jnp.float32
    impl: str = "xla"

    @nn.compact
    def __call__(self, x):
        kh, kw = self.kernel_size
        if isinstance(self.strides, int):
            s = self.strides
        else:
            if len(set(self.strides)) != 1:
                raise ValueError(
                    f"Conv supports only isotropic strides, got {self.strides}"
                    " — use nn.Conv for rectangular strides")
            s = self.strides[0]
        ci = x.shape[-1]
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (kh, kw, ci, self.features), jnp.float32)
        w = kernel.astype(self.dtype)
        x = x.astype(self.dtype)
        if kh == kw == 1:
            y = conv2d_im2col(x, w, s, self.padding)  # 1x1 == matmul
        elif self.impl == "pallas" and _supported(x.shape, w.shape, s, self.padding):
            y = conv2d_pallas(x, w, s, self.padding)
        elif self.impl in ("im2col", "pallas"):
            y = conv2d_im2col(x, w, s, self.padding)
        else:
            y = jax.lax.conv_general_dilated(
                x, w, (s, s), self.padding,
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), jnp.float32)
            y = y + bias.astype(self.dtype)
        return y
