"""Mixture-of-Experts FFN with expert parallelism.

Absent in the reference (like TP/SP, SURVEY.md §2.8); first-class here
because the ``expert`` mesh axis is part of the parallelism contract. Design:
top-1 gating with capacity factor; dispatch/combine are einsums against a
one-hot routing tensor, so the whole layer is dense linear algebra the MXU
likes; the stacked expert weights (E, D, H) shard over ``AXIS_EXPERT`` and
GSPMD turns the dispatch einsum into the all-to-all. Aux load-balancing loss
follows Shazeer et al. (fraction-routed x mean-gate dot product).
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


def top1_routing(
    gate_logits: jax.Array, num_experts: int, capacity: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(B*T, E) logits -> (dispatch (N, E, C), combine (N, E, C), aux_loss).

    Tokens beyond an expert's capacity are dropped (standard top-1 MoE);
    position-in-expert computed with a cumulative sum, everything static-shape.
    """
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                      # (N,)
    expert_onehot = jax.nn.one_hot(expert_idx, num_experts)      # (N, E)
    # position of each token within its expert's queue
    pos_in_expert = (jnp.cumsum(expert_onehot, axis=0) - 1.0) * expert_onehot
    keep = (pos_in_expert < capacity) * expert_onehot            # (N, E)
    pos = jnp.clip(pos_in_expert.astype(jnp.int32), 0, capacity - 1)
    pos_onehot = jax.nn.one_hot(pos, capacity) * keep[..., None]  # (N, E, C)
    gate = (probs * keep).sum(axis=-1, keepdims=True)            # (N, 1)
    dispatch = pos_onehot
    combine = pos_onehot * gate[..., None]
    # aux load-balance loss: E * <fraction routed, mean gate prob>
    frac = expert_onehot.mean(axis=0)
    mean_prob = probs.mean(axis=0)
    aux = num_experts * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux


class MoEBlock(nn.Module):
    """Top-1 MoE FFN. Input (B, T, D) -> ``(out (B, T, D), aux_loss scalar)``;
    stacked expert kernels (E, D, H)/(E, H, D) are the leaves to shard over
    ``AXIS_EXPERT``. Callers must add ``aux_weight * aux_loss`` (typically
    1e-2) to their objective — without it the router has no balancing
    pressure and can collapse all tokens onto one expert."""

    num_experts: int = 8
    dim: int = 256
    hidden_mult: int = 4
    capacity_factor: float = 1.25
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        B, T, D = x.shape
        N = B * T
        E = self.num_experts
        H = self.dim * self.hidden_mult
        C = max(1, int(self.capacity_factor * N / E))
        tokens = x.reshape(N, D)
        gate_logits = nn.Dense(E, use_bias=False, dtype=self.dtype, name="gate")(tokens)
        dispatch, combine, aux = top1_routing(gate_logits, E, C)

        w_in = self.param("w_in", nn.initializers.lecun_normal(), (E, D, H), self.dtype)
        w_out = self.param("w_out", nn.initializers.lecun_normal(), (E, H, D), self.dtype)
        # dispatch: (N, E, C) x (N, D) -> (E, C, D); per-expert FFN; combine back
        expert_in = jnp.einsum("nec,nd->ecd", dispatch.astype(self.dtype), tokens)
        hidden = jax.nn.gelu(jnp.einsum("ecd,edh->ech", expert_in, w_in))
        expert_out = jnp.einsum("ech,ehd->ecd", hidden, w_out)
        out = jnp.einsum("nec,ecd->nd", combine.astype(self.dtype), expert_out)
        return out.reshape(B, T, D), aux
