"""Mixture-of-Experts FFN with expert parallelism.

Absent in the reference (like TP/SP, SURVEY.md §2.8); first-class here
because the ``expert`` mesh axis is part of the parallelism contract. Design:
top-1 gating with capacity factor; dispatch/combine are einsums against a
one-hot routing tensor, so the whole layer is dense linear algebra the MXU
likes; the stacked expert weights (E, D, H) shard over ``AXIS_EXPERT`` and
GSPMD turns the dispatch einsum into the all-to-all. Aux load-balancing loss
follows Shazeer et al. (fraction-routed x mean-gate dot product).
"""

from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


def _rank_queue(onehot: jax.Array, capacity: int, offset=0.0):
    """One choice-rank's capacity queue: (N, E) routing one-hot ->
    (dispatch slice (N, E, C), keep mask (N, E)). ``offset`` shifts queue
    positions (second choices append after first choices)."""
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot + offset * onehot
    keep = (pos < capacity) * onehot
    p = jnp.clip(pos.astype(jnp.int32), 0, capacity - 1)
    return jax.nn.one_hot(p, capacity) * keep[..., None], keep


def _balance_aux(first_onehot: jax.Array, probs: jax.Array,
                 num_experts: int) -> jax.Array:
    """Shazeer/GShard load-balance loss: E * <fraction routed, mean prob>."""
    return num_experts * jnp.sum(
        first_onehot.mean(axis=0) * probs.mean(axis=0))


def top1_routing(
    gate_logits: jax.Array, num_experts: int, capacity: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(B*T, E) logits -> (dispatch (N, E, C), combine (N, E, C), aux_loss).

    Tokens beyond an expert's capacity are dropped (standard top-1 MoE);
    position-in-expert computed with a cumulative sum, everything static-shape.
    """
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    expert_onehot = jax.nn.one_hot(jnp.argmax(probs, axis=-1), num_experts)
    dispatch, keep = _rank_queue(expert_onehot, capacity)
    gate = (probs * keep).sum(axis=-1, keepdims=True)            # (N, 1)
    combine = dispatch * gate[..., None]
    return dispatch, combine, _balance_aux(expert_onehot, probs, num_experts)


def top2_routing(
    gate_logits: jax.Array, num_experts: int, capacity: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-2 gating (GShard/Switch-v2 style): each token routes to its two
    highest-probability experts, gates renormalized over the kept pair,
    independent capacity queues per choice rank (second choices only use
    capacity left by first choices). Same (dispatch, combine, aux) contract
    as :func:`top1_routing` — everything stays static-shape einsum fodder.
    """
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    oh1 = jax.nn.one_hot(jnp.argmax(probs, axis=-1), num_experts)
    # second choice masked in LOGIT space: with saturated gates the masked
    # probs underflow to an all-zero row and argmax would phantom-route to
    # expert 0, wasting its capacity on zero-gate tokens
    masked_logits = jnp.where(oh1 > 0, -jnp.inf,
                              gate_logits.astype(jnp.float32))
    oh2 = jax.nn.one_hot(jnp.argmax(masked_logits, axis=-1), num_experts)

    # first choices fill the queues first; second choices append after
    d1, _ = _rank_queue(oh1, capacity)
    d2, _ = _rank_queue(oh2, capacity, offset=oh1.sum(axis=0, keepdims=True))
    dispatch = d1 + d2
    # gates renormalized over the two choices; d1/d2 already carry the
    # keep masks, so dropped slots contribute nothing
    g1 = (probs * oh1).sum(-1)
    g2 = (probs * oh2).sum(-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    combine = (d1 * (g1 / denom)[:, None, None]
               + d2 * (g2 / denom)[:, None, None])
    # aux balance loss on FIRST choices (GShard convention)
    return dispatch, combine, _balance_aux(oh1, probs, num_experts)


class MoEBlock(nn.Module):
    """MoE FFN (top-1 or top-2 routing). Input (B, T, D) ->
    ``(out (B, T, D), aux_loss scalar)``; stacked expert kernels
    (E, D, H)/(E, H, D) are the leaves to shard over ``AXIS_EXPERT``.
    Callers must add ``aux_weight * aux_loss`` (typically 1e-2) to their
    objective — without it the router has no balancing pressure and can
    collapse all tokens onto one expert."""

    num_experts: int = 8
    dim: int = 256
    hidden_mult: int = 4
    capacity_factor: float = 1.25
    top_k: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        B, T, D = x.shape
        N = B * T
        E = self.num_experts
        H = self.dim * self.hidden_mult
        # top-2 sends ~2x the tokens through the queues
        C = max(1, int(self.capacity_factor * self.top_k * N / E))
        tokens = x.reshape(N, D)
        gate_logits = nn.Dense(E, use_bias=False, dtype=self.dtype, name="gate")(tokens)
        if self.top_k == 2:
            dispatch, combine, aux = top2_routing(gate_logits, E, C)
        elif self.top_k == 1:
            dispatch, combine, aux = top1_routing(gate_logits, E, C)
        else:
            raise ValueError(f"top_k must be 1 or 2, got {self.top_k}")

        w_in = self.param("w_in", nn.initializers.lecun_normal(), (E, D, H), self.dtype)
        w_out = self.param("w_out", nn.initializers.lecun_normal(), (E, H, D), self.dtype)
        # dispatch: (N, E, C) x (N, D) -> (E, C, D); per-expert FFN; combine back
        expert_in = jnp.einsum("nec,nd->ecd", dispatch.astype(self.dtype), tokens)
        hidden = jax.nn.gelu(jnp.einsum("ecd,edh->ech", expert_in, w_in))
        expert_out = jnp.einsum("ech,ehd->ecd", hidden, w_out)
        out = jnp.einsum("nec,ecd->nd", combine.astype(self.dtype), expert_out)
        return out.reshape(B, T, D), aux


EXPERT_STACKED_LEAVES = ("w_in", "w_out")


def expert_param_shardings(mesh, params):
    """NamedShardings for a ``MoEBlock`` param tree on a mesh with an
    ``AXIS_EXPERT`` axis: the stacked expert kernels shard over the expert
    axis, everything else (gate, norms) replicates. The ONE place the
    expert-stacked leaf names live — used by the EP dryrun plane and the
    expert-parallel tests alike."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import AXIS_EXPERT

    def spec_for(path, leaf):
        names = [str(getattr(p, "key", p)) for p in path]
        which = (P(AXIS_EXPERT) if names[-1] in EXPERT_STACKED_LEAVES
                 else P())
        return NamedSharding(mesh, which)

    return jax.tree_util.tree_map_with_path(spec_for, params)
