"""Losses with padding masks.

Masked variants are load-bearing: the rectangular client packing
(``data/federated.py``) pads small clients with zero rows, and the mask keeps
padding out of both the loss and the gradient — the TPU answer to the
reference's ragged Python loops (SURVEY.md §7 hard parts).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean CE over the batch. logits (..., C), labels (...) int."""
    logz = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logz, labels[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def _broadcast_mask(mask: jax.Array, target_ndim: int) -> jax.Array:
    """Per-example mask -> per-target mask (LM labels add a token dim)."""
    while mask.ndim < target_ndim:
        mask = mask[..., None]
    return mask


def masked_softmax_cross_entropy(
    logits: jax.Array, labels: jax.Array, mask: jax.Array
) -> jax.Array:
    """Sum(CE * mask) / max(sum(mask), 1). Shapes: logits (..., C), labels (...)
    and mask broadcastable to labels (a per-example mask covers per-token
    labels: every token of a padded example is masked)."""
    logz = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logz, labels[..., None], axis=-1)[..., 0]
    m = jnp.broadcast_to(_broadcast_mask(mask, ll.ndim), ll.shape)
    denom = jnp.maximum(m.sum(), 1.0)
    return -(ll * m).sum() / denom


def masked_accuracy(logits: jax.Array, labels: jax.Array, mask: jax.Array):
    """Returns (num_correct, num_valid) so callers can aggregate exactly."""
    pred = jnp.argmax(logits, axis=-1)
    m = jnp.broadcast_to(_broadcast_mask(mask, labels.ndim), labels.shape)
    correct = ((pred == labels) * m).sum()
    return correct, m.sum()


def chunked_lm_cross_entropy(hidden: jax.Array, head_kernel: jax.Array,
                             targets: jax.Array,
                             chunk: int = 256) -> jax.Array:
    """Mean next-token CE WITHOUT materializing the full (B, T, V) f32
    logits tensor — the HBM hog of large-vocab LM training (V=32k at
    T=8k/B=4 is 4 GB in f32, times the bwd copies).

    Computes ``hidden @ head_kernel`` and the log-softmax one sequence
    chunk at a time under ``lax.map``; peak extra memory is
    O(B * chunk * V) and the bwd re-derives each chunk's logits from the
    (tiny) saved hidden chunk. hidden (B, T, D), head_kernel (D, V),
    targets (B, T) int. T must be divisible by ``chunk`` (pad upstream)."""
    B, T, D = hidden.shape
    if T % chunk:
        raise ValueError(f"T={T} not divisible by chunk={chunk}")
    hc = hidden.reshape(B, T // chunk, chunk, D).transpose(1, 0, 2, 3)
    tc = targets.reshape(B, T // chunk, chunk).transpose(1, 0, 2)

    @jax.checkpoint
    def one(args):
        # checkpointed: without it lax.map's backward saves each chunk's
        # softmax intermediates — the full (B, T, V) f32 tensor in
        # disguise. Recomputing the chunk logits from the (tiny) saved
        # hidden chunk is the whole point of this op.
        h, t = args
        logits = (h @ head_kernel).astype(jnp.float32)
        logz = jax.nn.log_softmax(logits, axis=-1)
        return jnp.take_along_axis(logz, t[..., None], axis=-1)[..., 0]

    ll = jax.lax.map(one, (hc, tc))
    return -jnp.mean(ll)


def _bce_with_logits(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Per-cell numerically-stable BCE-with-logits (log-sigmoid form).
    The ONE implementation shared by the training loss and the per-sample
    eval path — any stability/semantics change lands in both."""
    z = logits.astype(jnp.float32)
    t = targets.astype(jnp.float32)
    return jnp.maximum(z, 0.0) - z * t + jnp.log1p(jnp.exp(-jnp.abs(z)))


def masked_sigmoid_bce(logits: jax.Array, targets: jax.Array,
                       mask: jax.Array) -> jax.Array:
    """Multi-label binary cross-entropy: sum(BCE * mask) / max(sum(mask), 1)
    over every (example, label) cell. logits/targets (..., L) with 0/1
    float targets (the CheXpert 14-finding contract — reference
    ``app/fedcv/medical_chest_xray_image_clf/data/chexpert/dataset.py:11``
    label_header; their trainer drives BCEWithLogitsLoss over it)."""
    per = _bce_with_logits(logits, targets)
    m = jnp.broadcast_to(_broadcast_mask(mask, per.ndim), per.shape)
    return (per * m).sum() / jnp.maximum(m.sum(), 1.0)


def masked_multilabel_accuracy(logits: jax.Array, targets: jax.Array,
                               mask: jax.Array):
    """Per-label binary accuracy at threshold 0.5 (logit > 0), riding the
    (num_correct, num_valid) plumbing; valid counts (example, label) cells."""
    pred = (logits > 0.0).astype(jnp.float32)
    t = targets.astype(jnp.float32)
    m = jnp.broadcast_to(_broadcast_mask(mask, t.ndim), t.shape)
    return ((pred == t) * m).sum(), m.sum()


def per_sample_metrics(out: jax.Array, y: jax.Array, mask: jax.Array,
                       loss_kind: str = "ce", tol: float = 0.5):
    """Per-SAMPLE (loss_sum, correct, valid) f32 vectors, shape (B,).

    The segmented per-client evaluator (``FedSimulator.local_test_on_all_
    clients``) needs per-sample values so one compiled pass over mixed-client
    batches can scatter-add each sample's stats into its owner client's
    accumulator. Reductions run over every trailing (e.g. per-token) axis,
    so ``sum(loss_sum)/sum(valid)`` over any grouping equals the masked_*
    aggregate over the same samples — per-client and global numbers agree
    with the reference's sum-of-per-sample-loss / num-samples semantics
    (``/root/reference/python/fedml/simulation/sp/fedavg/fedavg_api.py:233``).
    """
    axes = tuple(range(1, max(y.ndim, mask.ndim)))
    if loss_kind == "bce":
        per = _bce_with_logits(out, y)
        m = jnp.broadcast_to(_broadcast_mask(mask, per.ndim), per.shape)
        lbl_axes = tuple(range(1, per.ndim))
        hit = ((out > 0.0).astype(jnp.float32) == y.astype(jnp.float32))
        return ((per * m).sum(lbl_axes), (hit * m).sum(lbl_axes),
                m.sum(lbl_axes))
    if loss_kind == "mse":
        p = out.astype(jnp.float32)
        if p.ndim == y.ndim + 1 and p.shape[-1] == 1:
            p = p[..., 0]
        err = jnp.square(p - y.astype(jnp.float32))
        m = jnp.broadcast_to(_broadcast_mask(mask, err.ndim), err.shape)
        hit = (jnp.abs(p - y.astype(jnp.float32)) <= tol)
        return ((err * m).sum(axes), (hit * m).sum(axes), m.sum(axes))
    logz = jax.nn.log_softmax(out.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logz, y[..., None], axis=-1)[..., 0]
    m = jnp.broadcast_to(_broadcast_mask(mask, ll.ndim), ll.shape)
    pred = jnp.argmax(out, axis=-1)
    correct = ((pred == y) * m).sum(axes)
    return (-(ll * m).sum(axes), correct, m.sum(axes))


def masked_mse(preds: jax.Array, targets: jax.Array, mask: jax.Array) -> jax.Array:
    """Sum(sq err * mask) / max(sum(mask), 1) — regression tasks (FedGraphNN
    moleculenet property regression). preds (...,) or (..., 1)."""
    p = preds.astype(jnp.float32)
    if p.ndim == targets.ndim + 1 and p.shape[-1] == 1:
        p = p[..., 0]
    err = jnp.square(p - targets.astype(jnp.float32))
    m = jnp.broadcast_to(_broadcast_mask(mask, err.ndim), err.shape)
    return (err * m).sum() / jnp.maximum(m.sum(), 1.0)


def masked_within_tolerance(preds: jax.Array, targets: jax.Array,
                            mask: jax.Array, tol: float = 0.5):
    """Regression 'accuracy': count of predictions within ``tol`` of the
    target (so regression rides the same correct/valid metric plumbing)."""
    p = preds.astype(jnp.float32)
    if p.ndim == targets.ndim + 1 and p.shape[-1] == 1:
        p = p[..., 0]
    hit = (jnp.abs(p - targets.astype(jnp.float32)) <= tol)
    m = jnp.broadcast_to(_broadcast_mask(mask, hit.ndim), hit.shape)
    return (hit * m).sum(), m.sum()
