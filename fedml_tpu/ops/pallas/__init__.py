"""Pallas TPU kernels for the hot ops (see /opt guide; pallas_guide.md)."""

from .flash_attention import flash_attention, flash_shapes_ok, flash_vmem_ok

__all__ = ["flash_attention", "flash_shapes_ok", "flash_vmem_ok"]
