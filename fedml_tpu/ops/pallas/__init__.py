"""Pallas TPU kernels for the hot ops (see /opt guide; pallas_guide.md)."""

from .agg_quant import fused_quantize_pack, quant_shapes_ok
from .agg_robust import fused_gram, robust_shapes_ok
from .flash_attention import flash_attention, flash_shapes_ok, flash_vmem_ok

__all__ = [
    "flash_attention",
    "flash_shapes_ok",
    "flash_vmem_ok",
    "fused_gram",
    "fused_quantize_pack",
    "quant_shapes_ok",
    "robust_shapes_ok",
]
