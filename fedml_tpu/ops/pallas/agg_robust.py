"""Pallas Gram-tile kernel for the fused sanitize+Krum robust-agg path.

The unfused defense pipeline reads the stacked cohort three times: once for
``sanitize_stacked``'s non-finite/norm stats, once to materialize the
where-zeroed "clean" copy, and once for ``pairwise_sq_dists``'s Gram
matmul over that copy. The fused path (``core.robust.fused_sanitize_krum``)
collapses the expensive plane to one pass: each (block_c, block_c) tile of
``Z @ Z.T`` is computed here from the RAW (nan-sanitized) stack — the
clean copy is never materialized — and quarantine masking is applied
algebraically afterwards with exact ``where`` masks: zeroing a row of a
matmul operand cannot change any OTHER element's bits (element (i, j)
reads only rows i and j), so ``sanitize -> zero copy -> Gram`` and
``Gram -> mask`` produce identical distance bits.

The kernel deliberately emits ONLY the Gram plane. An earlier revision
also emitted the per-leaf squared-norm segments from column slices of the
fused row tiles, but XLA's reduction order for a strided row-slice sum is
shape-dependent — a ``sum(square(x[:, 40:64]), axis=1)`` over an (8, 64)
VMEM tile and the oracle's contiguous per-leaf ``(C, 24)`` sum disagreed
by 1 ULP on some widths. Those O(C*D) statistics are therefore computed by
the orchestration layer with the oracle's own expressions on the oracle's
own shapes (structural identity => identical bits on every backend),
while the O(C^2*D) Gram plane — whose cross-form bit-determinism
(vmap row matmul == lax.map row tiles == this kernel's dot_general tiles)
the parity suite pins down — stays fused.

Grid is (C/block_c, C/block_c) with full-D operand tiles (no contraction
tiling — a split-K accumulator would change the reduction order and break
bit parity), so the VMEM guard bounds D; oversized shapes take the
jittable reference, which is the same arithmetic in plain jnp. On non-TPU
backends the default dispatch is the reference too — interpret mode
(``interpret=True``) exists for the parity suite, not production.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_BLOCK_C = 8

# two full-D row tiles resident per program (plus the gram tile output)
_VMEM_BUDGET = 8 * 1024 * 1024

# interpret mode (non-TPU) unrolls every grid step into the jaxpr — fine
# for parity-test shapes, catastrophic for a cohort-scale grid (a 10k
# cohort is 1250^2 steps). Past this many steps the interpret path takes
# the reference instead; the kernel-vs-reference bit parity the tests pin
# makes the switch invisible.
_INTERPRET_GRID_CAP = 4096


def robust_shapes_ok(C: int, D: int) -> bool:
    """True when the Gram kernel's tiling handles a (C, D) cohort stack."""
    if C < 1 or D < 1:
        return False
    return 2 * 4 * _BLOCK_C * D + 4 * _BLOCK_C * _BLOCK_C <= _VMEM_BUDGET


def _gram_kernel(a_ref, b_ref, gram_ref):
    """Grid (C/block_c, C/block_c). a/b are (block_c, D) row tiles of the
    sanitized flat stack; gram tile (i, j) = a @ b.T."""
    gram_ref[...] = jax.lax.dot_general(
        a_ref[...], b_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)


def fused_gram(flat, *, interpret: Optional[bool] = None,
               use_kernel: bool = True) -> jax.Array:
    """(C, C) f32 Gram matrix ``flat @ flat.T`` of a (C, D) cohort stack,
    in (block_c, block_c) Pallas tiles.

    ``flat`` must already be finite (the caller applies ``nan_to_num``,
    mirroring ``pairwise_sq_dists``). Bit-identical to the vmap/tiled
    matmul forms ``pairwise_sq_dists`` lowers to — pinned by the parity
    suite. Cohorts are padded to a block multiple with zero rows (pad
    outputs are sliced away; zero rows cannot perturb real elements'
    bits). Shapes outside :func:`robust_shapes_ok` (or
    ``use_kernel=False``) take the jittable jnp reference.
    """
    flat = jnp.asarray(flat, jnp.float32)
    C, D = flat.shape
    if not (use_kernel and robust_shapes_ok(C, D)):
        return _reference_gram(flat)
    if interpret is None:
        # Non-TPU production dispatch takes the bit-identical jnp reference:
        # interpret mode emulates the kernel step by step and is far slower
        # than plain XLA. The parity suite opts in with interpret=True.
        if jax.default_backend() != "tpu":
            return _reference_gram(flat)
        interpret = False

    cpad = -(-C // _BLOCK_C) * _BLOCK_C
    if interpret and (cpad // _BLOCK_C) ** 2 > _INTERPRET_GRID_CAP:
        return _reference_gram(flat)
    fp = flat if cpad == C else jnp.concatenate(
        [flat, jnp.zeros((cpad - C, D), jnp.float32)], axis=0)
    grid = (cpad // _BLOCK_C, cpad // _BLOCK_C)
    gram = pl.pallas_call(
        _gram_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_C, D), lambda i, j: (i, 0)),
            pl.BlockSpec((_BLOCK_C, D), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((_BLOCK_C, _BLOCK_C), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((cpad, cpad), jnp.float32),
        interpret=interpret,
    )(fp, fp)
    return gram[:C, :C]


def _reference_gram(flat):
    """Jittable jnp reference: ``pairwise_sq_dists``'s exact untiled form."""
    return jax.vmap(lambda r: flat @ r)(flat)
