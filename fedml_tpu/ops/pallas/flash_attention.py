"""Pallas flash attention (TPU): fused QK^T -> online softmax -> V.

The hot op of the transformer stack (FedNLP/Cheetah planes). K-blocked 3-D
grid design (round-3 rewrite): the grid is (batch*head, q-block, k-block)
with the k dimension innermost, so Mosaic's pipeline streams (block_k, Dh)
K/V tiles through VMEM while the online-softmax state (running max,
normalizer, output accumulator) lives in VMEM scratch across the k steps.
Nothing stages the full sequence: VMEM use is O(block_q * Dh + block_k * Dh)
regardless of T — single-chip T is bounded by HBM, not the ~16 MB VMEM
budget that capped the round-2 full-K/V kernel at T~12k. The (T, T) score
matrix never exists in HBM — memory O(T * Dh) — and every matmul is a
(block_q x Dh) x (Dh x block_k) MXU tile.

Causal masking skips fully-masked key blocks via ``pl.when`` (the grid step
still runs but does no FLOPs and no accumulation), and the diagonal block
applies the row>=col mask.

Gradients: custom VJP with the same K-blocked scheme (FlashAttention-2):
dq accumulates over k-blocks on a (bh, qi, ki) grid; dk/dv accumulate over
q-blocks on a (bh, ki, qi) grid. The forward saves per-row logsumexp;
probabilities are recomputed blockwise. Cost is the standard ~one extra
forward of FLOPs.

On non-TPU backends the kernels run in interpret mode so tests validate
numerics everywhere; the compiled path engages on real TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# measured on the v5e (scripts/bench_flash_attention.py block sweep):
# 128x128 grid steps drown in pipeline overhead (slower than dense), 1024
# is the knee (2.4x dense at T=8192), 2048 exceeds scoped VMEM. T=1024
# prefers 512 blocks (diagonal-only work).
MAX_BLOCK = 1024
MIN_BLOCK = 128
NEG_INF = float(jnp.finfo(jnp.float32).min)

# scoped-VMEM budget for one kernel instance's working set, calibrated
# between the measured-good 1024 blocks and the measured-failing 2048
# (both at Dh=64 bf16 on the v5e): the 1536-block working set is the line
_VMEM_BUDGET = (1536 + 2 * 2 * 1536) * 64 * 2 + (2 * 128 + 64) * 1536 * 4


def auto_block(T: int) -> int | None:
    """Largest power-of-two block in [128, 1024] dividing T (every candidate
    is a multiple of 128, as Mosaic's lane dimension requires); at T <= 1024
    prefer T//2 (measured faster — diagonal-only work). None if no
    candidate divides T."""
    if T <= MAX_BLOCK:
        half = T // 2
        if half >= MIN_BLOCK and half % MIN_BLOCK == 0 and T % half == 0:
            return half
    for b in (MAX_BLOCK, 512, 256, MIN_BLOCK):
        if b <= T and T % b == 0:
            return b
    return None


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                  m_scr, l_scr, acc_scr, *, block_k: int, causal: bool,
                  scale: float):
    """Grid (B*H, T//block_q, T//block_k), k innermost. Refs:
    q (1, block_q, Dh), k/v (1, block_k, Dh), o (1, block_q, Dh),
    lse (1, 1, block_q). Scratch (f32): m/l (block_q, 128), acc
    (block_q, Dh) — softmax state persists across the k steps; o/lse are
    written once on the last step (their block index is k-invariant, so
    Mosaic flushes them to HBM only when the q block advances)."""
    block_q = q_ref.shape[1]
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    q_start = qi * block_q
    k_start = ki * block_k

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    # causal: key blocks strictly above the diagonal contribute nothing
    run = (k_start <= q_start + block_q - 1) if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0].astype(jnp.float32) * scale
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (block_q, block_k)
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m = m_scr[:, :1]
        l = l_scr[:, :1]
        blk_max = jnp.max(s, axis=1, keepdims=True)
        new_m = jnp.maximum(m, blk_max)
        p = jnp.exp(s - new_m)
        corr = jnp.exp(m - new_m)
        new_l = l * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(new_m, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(new_l, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        m = m_scr[:, :1]
        l_safe = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = (m + jnp.log(l_safe))[:, 0]


def _bh_layout(t):
    """(B, T, H, Dh) -> (B*H, T, Dh)."""
    B, T, H, Dh = t.shape
    return t.transpose(0, 2, 1, 3).reshape(B * H, T, Dh)


def _flash_forward(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool,
    block_q: int, block_k: int, interpret: bool,
):
    """q/k/v: (B, T, H, Dh) -> (out (B, T, H, Dh), lse (B*H, 1, T) f32).
    lse carries a singleton middle dim so its blocks satisfy Mosaic's
    last-two-dims rule (divisible by (8, 128) or equal to the array dims)."""
    B, T, H, Dh = q.shape
    scale = 1.0 / (Dh ** 0.5)
    qb, kb, vb = _bh_layout(q), _bh_layout(k), _bh_layout(v)
    grid = (B * H, T // block_q, T // block_k)
    out, lse = pl.pallas_call(
        functools.partial(_flash_kernel, block_k=block_k, causal=causal,
                          scale=scale),
        out_shape=(
            jax.ShapeDtypeStruct((B * H, T, Dh), q.dtype),
            jax.ShapeDtypeStruct((B * H, 1, T), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, Dh), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, Dh), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, Dh), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, Dh), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bh, qi, ki: (bh, 0, qi)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, Dh), jnp.float32),
        ],
        interpret=interpret,
    )(qb, kb, vb)
    return out.reshape(B, H, T, Dh).transpose(0, 2, 1, 3), lse


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, block_k: int, causal: bool, scale: float):
    """Grid (B*H, T//block_q, T//block_k), k innermost: one q block
    accumulates dq over the streamed key blocks; p recomputed from
    (q, k, lse)."""
    block_q = q_ref.shape[1]
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    q_start = qi * block_q
    k_start = ki * block_k

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    run = (k_start <= q_start + block_q - 1) if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, None]       # (block_q, 1)
        delta = delta_ref[0, 0][:, None]   # (block_q, 1)
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_scr[...] = dq_scr[...] + scale * jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, block_q: int,
                causal: bool, scale: float):
    """Grid (B*H, T//block_k, T//block_q), q innermost: one key block
    accumulates dk/dv over the streamed query blocks."""
    block_k = k_ref.shape[1]
    ki, qi = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)
    k_start = ki * block_k
    q_start = qi * block_q

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    # causal: q blocks entirely above this key block see none of it
    run = (q_start + block_q - 1 >= k_start) if causal else True

    @pl.when(run)
    def _body():
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)                       # (block_q, block_k)
        dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_scr[...] = dk_scr[...] + scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal, block_q, block_k, interpret):
    """Blockwise dq/dk/dv; q/k/v/out/g (B, T, H, Dh), lse (B*H, 1, T)."""
    B, T, H, Dh = q.shape
    scale = 1.0 / (Dh ** 0.5)
    qb, kb, vb = _bh_layout(q), _bh_layout(k), _bh_layout(v)
    dob = _bh_layout(g)
    # delta_i = sum_d dO_id * O_id — O(T*Dh), plain XLA (fuses into one pass)
    delta = jnp.sum(dob.astype(jnp.float32) * _bh_layout(out).astype(jnp.float32),
                    axis=-1)[:, None, :]  # (B*H, 1, T), lse's layout

    def qblk(blk):
        return pl.BlockSpec((1, blk, Dh), lambda bh, i, j: (bh, i, 0))

    def jblk(blk):
        return pl.BlockSpec((1, blk, Dh), lambda bh, i, j: (bh, j, 0))

    def row_i(blk):
        return pl.BlockSpec((1, 1, blk), lambda bh, i, j: (bh, 0, i))

    def row_j(blk):
        return pl.BlockSpec((1, 1, blk), lambda bh, i, j: (bh, 0, j))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_k=block_k, causal=causal,
                          scale=scale),
        out_shape=jax.ShapeDtypeStruct((B * H, T, Dh), q.dtype),
        grid=(B * H, T // block_q, T // block_k),
        in_specs=[qblk(block_q), jblk(block_k), jblk(block_k), qblk(block_q),
                  row_i(block_q), row_i(block_q)],
        out_specs=qblk(block_q),
        scratch_shapes=[pltpu.VMEM((block_q, Dh), jnp.float32)],
        interpret=interpret,
    )(qb, kb, vb, dob, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_q=block_q, causal=causal,
                          scale=scale),
        out_shape=(jax.ShapeDtypeStruct((B * H, T, Dh), k.dtype),
                   jax.ShapeDtypeStruct((B * H, T, Dh), v.dtype)),
        grid=(B * H, T // block_k, T // block_q),
        in_specs=[jblk(block_q), qblk(block_k), qblk(block_k), jblk(block_q),
                  row_j(block_q), row_j(block_q)],
        out_specs=(qblk(block_k), qblk(block_k)),
        scratch_shapes=[pltpu.VMEM((block_k, Dh), jnp.float32),
                        pltpu.VMEM((block_k, Dh), jnp.float32)],
        interpret=interpret,
    )(qb, kb, vb, dob, lse, delta)

    from_bh = lambda t: t.reshape(B, H, T, Dh).transpose(0, 2, 1, 3)  # noqa: E731
    return from_bh(dq), from_bh(dk), from_bh(dv)


# Measured-fastest (block_q, block_k) per sequence length, from on-chip
# same-process sweeps (scripts/bench_flash_blocks_r5.py ->
# results/flash_blocks_r5.json). Shapes absent here fall back to
# auto_block squares. Rectangular blocks (small q x large k) keep the
# softmax state resident while streaming more K per grid step — the r4
# T=2048 sweep saw (128, 1024) at 1.62x dense (flash_attention_holes_r4
# t2048_block_sweep) pending confirmation under the r5 protocol.
BLOCK_TABLE: dict = {}
# the shape family the sweep measures (q/k/v head dim, element bytes):
# table entries qualify ONLY here — other Dh/itemsize would resolve to
# unmeasured auto blocks. Dispatch (ops/attention.py) and any future
# sweep extension read this, so the qualifying condition lives in one
# place next to the table it scopes.
BLOCK_TABLE_SWEPT_SHAPE = (64, 2)


def _resolve_blocks(T, block_q, block_k, Dh: int = 64, itemsize: int = 2):
    table = BLOCK_TABLE.get(T)
    if block_q is None and block_k is None and table is not None:
        bq, bk = table
        # table entries face the SAME guards the auto path does: lane
        # alignment (Mosaic needs multiples of 128) and scoped VMEM for
        # the larger tile — a mis-adopted (128, 2048) entry must fall
        # back to auto squares, not blow VMEM at chip time
        if ((Dh, itemsize) == BLOCK_TABLE_SWEPT_SHAPE
                and T % bq == 0 and T % bk == 0
                and bq % MIN_BLOCK == 0 and bk % MIN_BLOCK == 0
                and flash_vmem_ok(T, Dh, itemsize, block=max(bq, bk))):
            return bq, bk
    auto = auto_block(T)
    bq = block_q or auto
    bk = block_k or auto
    if bq is None or bk is None or T % bq or T % bk:
        raise ValueError(
            f"flash_attention: T={T} has no block tiling (callers should "
            "gate on flash_shapes_ok and fall back to dense)")
    return bq, bk


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool = False,
    block_q: int | None = None,
    block_k: int | None = None,
) -> jax.Array:
    """Flash attention with K-blocked pallas forward AND backward.
    q/k/v (B, T, H, Dh); block sizes default to the measured-fastest
    tiling for T (auto_block); requires T % block == 0 (callers fall back
    to dense otherwise)."""
    interpret = jax.default_backend() != "tpu"
    block_q, block_k = _resolve_blocks(
        q.shape[1], block_q, block_k, Dh=q.shape[-1],
        itemsize=jnp.dtype(q.dtype).itemsize)
    out, _ = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out


def _fwd(q, k, v, causal, block_q, block_k):
    interpret = jax.default_backend() != "tpu"
    block_q, block_k = _resolve_blocks(
        q.shape[1], block_q, block_k, Dh=q.shape[-1],
        itemsize=jnp.dtype(q.dtype).itemsize)
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _bwd(causal, block_q, block_k, res, g):
    q, k, v, out, lse = res
    interpret = jax.default_backend() != "tpu"
    block_q, block_k = _resolve_blocks(
        q.shape[1], block_q, block_k, Dh=q.shape[-1],
        itemsize=jnp.dtype(q.dtype).itemsize)
    return _flash_backward(q, k, v, out, lse, g, causal, block_q, block_k, interpret)


flash_attention.defvjp(_fwd, _bwd)


def flash_vmem_ok(T: int, Dh: int, itemsize: int = 2,
                  block: int | None = None) -> bool:
    """K-blocked kernels hold only O(block * Dh) in VMEM, independent of T —
    the round-2 full-K/V staging limit (T~12k at Dh=64 bf16) is gone.
    Retained as a guard against configs where the block pipeline plus
    scratch would still exceed scoped VMEM (huge Dh or oversized explicit
    blocks; the measured ceiling on the v5e is 2048 blocks at Dh=64)."""
    block = block or auto_block(T) or MIN_BLOCK
    # q + double-buffered k/v tiles in the input dtype...
    per_block = (block + 2 * 2 * block) * Dh * itemsize
    # ...plus the f32 m/l/acc scratch rows
    scratch = (2 * 128 + Dh) * block * 4
    return per_block + scratch <= _VMEM_BUDGET


def flash_shapes_ok(T: int, Dh: int, block_q: int | None = None,
                    block_k: int | None = None,
                    itemsize: int = 2) -> bool:
    """Static dispatch guard used by ops.attention.multihead_attention: the
    sequence must tile into whole blocks, Dh must fill lanes reasonably,
    and the requested (or auto) blocks must fit scoped VMEM; T itself is
    unbounded on a single chip (HBM is the ceiling)."""
    bq = block_q or auto_block(T)
    bk = block_k or auto_block(T)
    return (bq is not None and bk is not None
            and T % bq == 0 and T % bk == 0
            and bq % MIN_BLOCK == 0 and bk % MIN_BLOCK == 0
            and (Dh % 128 == 0 or Dh == 64)
            and flash_vmem_ok(T, Dh, itemsize, block=max(bq, bk)))
