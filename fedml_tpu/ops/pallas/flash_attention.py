"""Pallas flash attention (TPU): fused QK^T -> online softmax -> V.

The hot op of the transformer stack (FedNLP/Cheetah planes). One kernel
instance handles one (batch*head, q-block): the query block stays in VMEM
while K/V stream through in blocks; softmax is accumulated online (running
max + normalizer) so the (T, T) score matrix never materializes in HBM —
memory O(T * Dh) instead of O(T^2), and the matmuls hit the MXU at
(BLOCK_Q x Dh) x (Dh x BLOCK_K) granularity.

Gradients: ``flash_attention`` carries a custom VJP with *blockwise pallas
backward kernels* (FlashAttention-2 scheme). The forward saves the per-row
logsumexp; the backward recomputes probabilities block-by-block from
(q, k, lse) and accumulates dq in a q-block-parallel kernel and dk/dv in a
k-block-parallel kernel — so the backward, like the forward, never builds
the (T, T) matrix. Cost is the standard ~one extra forward of FLOPs.

On non-TPU backends the kernels run in interpret mode so tests validate
numerics everywhere; the compiled path engages on real TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = float(jnp.finfo(jnp.float32).min)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                  block_k: int, causal: bool, scale: float):
    """Grid: (B*H, T // block_q). Refs (leading grid-block dim of 1):
    q (1, block_q, Dh), k/v (1, T, Dh), o (1, block_q, Dh),
    lse (1, 1, block_q) — the singleton middle dim keeps the block's last
    two dims Mosaic-legal ((1, block_q): dim -2 equals the array dim)."""
    block_q = q_ref.shape[1]
    Dh = q_ref.shape[2]
    T = k_ref.shape[1]
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, Dh), jnp.float32)

    n_kblocks = T // block_k
    # causal: skip key blocks strictly after this query block
    q_start = qi * block_q

    def body(kb, carry):
        m, l, acc = carry
        k_start = kb * block_k
        k_blk = k_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, block_k)
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        blk_max = jnp.max(s, axis=1, keepdims=True)
        new_m = jnp.maximum(m, blk_max)
        p = jnp.exp(s - new_m)
        corr = jnp.exp(m - new_m)
        new_l = l * corr + jnp.sum(p, axis=1, keepdims=True)
        new_acc = acc * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return new_m, new_l, new_acc

    if causal:
        # only key blocks up to and including the diagonal block
        n_iter = jnp.minimum((q_start + block_q + block_k - 1) // block_k, n_kblocks)
        m, l, acc = jax.lax.fori_loop(0, n_iter, body, (m0, l0, acc0))
    else:
        m, l, acc = jax.lax.fori_loop(0, n_kblocks, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0, 0] = (m + jnp.log(l_safe))[:, 0]


def _bh_layout(t):
    """(B, T, H, Dh) -> (B*H, T, Dh)."""
    B, T, H, Dh = t.shape
    return t.transpose(0, 2, 1, 3).reshape(B * H, T, Dh)


def _flash_forward(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool,
    block_q: int, block_k: int, interpret: bool,
):
    """q/k/v: (B, T, H, Dh) -> (out (B, T, H, Dh), lse (B*H, 1, T) f32).
    lse carries a singleton middle dim so its blocks satisfy Mosaic's
    last-two-dims rule (divisible by (8, 128) or equal to the array dims)."""
    B, T, H, Dh = q.shape
    scale = 1.0 / (Dh ** 0.5)
    qb, kb, vb = _bh_layout(q), _bh_layout(k), _bh_layout(v)
    grid = (B * H, T // block_q)
    out, lse = pl.pallas_call(
        functools.partial(_flash_kernel, block_k=block_k, causal=causal, scale=scale),
        out_shape=(
            jax.ShapeDtypeStruct((B * H, T, Dh), q.dtype),
            jax.ShapeDtypeStruct((B * H, 1, T), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, Dh), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, T, Dh), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, T, Dh), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, Dh), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bh, qi: (bh, 0, qi)),
        ),
        interpret=interpret,
    )(qb, kb, vb)
    return out.reshape(B, H, T, Dh).transpose(0, 2, 1, 3), lse


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               block_k: int, causal: bool, scale: float):
    """Grid (B*H, T // block_q): one q block accumulates its dq over all
    (causal: non-masked) key blocks. p is recomputed from (q, k, lse)."""
    block_q = q_ref.shape[1]
    Dh = q_ref.shape[2]
    T = k_ref.shape[1]
    qi = pl.program_id(1)
    q_start = qi * block_q
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0][:, None]       # (block_q, 1)
    delta = delta_ref[0, 0][:, None]   # (block_q, 1)
    n_kblocks = T // block_k

    def body(kb, dq):
        k_start = kb * block_k
        k_blk = k_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)                       # (block_q, block_k)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + scale * jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    dq0 = jnp.zeros((block_q, Dh), jnp.float32)
    if causal:
        n_iter = jnp.minimum((q_start + block_q + block_k - 1) // block_k, n_kblocks)
        dq = jax.lax.fori_loop(0, n_iter, body, dq0)
    else:
        dq = jax.lax.fori_loop(0, n_kblocks, body, dq0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, block_q: int, causal: bool, scale: float):
    """Grid (B*H, T // block_k): one key block accumulates its dk/dv over all
    (causal: at-or-after-diagonal) query blocks."""
    block_k = k_ref.shape[1]
    Dh = k_ref.shape[2]
    T = q_ref.shape[1]
    ki = pl.program_id(1)
    k_start = ki * block_k
    k_blk = k_ref[0].astype(jnp.float32)
    v_blk = v_ref[0].astype(jnp.float32)
    n_qblocks = T // block_q

    def body(qb, carry):
        dk, dv = carry
        q_start = qb * block_q
        q = q_ref[0, pl.ds(q_start, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(q_start, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(q_start, block_q)][:, None]
        delta = delta_ref[0, 0, pl.ds(q_start, block_q)][:, None]
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)                       # (block_q, block_k)
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk = dk + scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((block_k, Dh), jnp.float32)
    dv0 = jnp.zeros((block_k, Dh), jnp.float32)
    if causal:
        # first q block whose rows can reach this key block: rows >= cols
        # needs q_start + block_q - 1 >= k_start  =>  qb >= k_start // block_q
        dk, dv = jax.lax.fori_loop(k_start // block_q, n_qblocks, body, (dk0, dv0))
    else:
        dk, dv = jax.lax.fori_loop(0, n_qblocks, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal, block_q, block_k, interpret):
    """Blockwise dq/dk/dv; q/k/v/out/g (B, T, H, Dh), lse (B*H, 1, T)."""
    B, T, H, Dh = q.shape
    scale = 1.0 / (Dh ** 0.5)
    qb, kb, vb = _bh_layout(q), _bh_layout(k), _bh_layout(v)
    dob = _bh_layout(g)
    # delta_i = sum_d dO_id * O_id — O(T*Dh), plain XLA (fuses into one pass)
    delta = jnp.sum(dob.astype(jnp.float32) * _bh_layout(out).astype(jnp.float32),
                    axis=-1)[:, None, :]  # (B*H, 1, T), lse's layout

    qkv_spec = lambda blk: pl.BlockSpec((1, blk, Dh), lambda bh, i: (bh, i, 0))  # noqa: E731
    full_spec = pl.BlockSpec((1, T, Dh), lambda bh, i: (bh, 0, 0))
    row_spec = lambda blk: pl.BlockSpec((1, 1, blk), lambda bh, i: (bh, 0, i))  # noqa: E731
    full_row = pl.BlockSpec((1, 1, T), lambda bh, i: (bh, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_k=block_k, causal=causal, scale=scale),
        out_shape=jax.ShapeDtypeStruct((B * H, T, Dh), q.dtype),
        grid=(B * H, T // block_q),
        in_specs=[qkv_spec(block_q), full_spec, full_spec, qkv_spec(block_q),
                  row_spec(block_q), row_spec(block_q)],
        out_specs=qkv_spec(block_q),
        interpret=interpret,
    )(qb, kb, vb, dob, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_q=block_q, causal=causal, scale=scale),
        out_shape=(jax.ShapeDtypeStruct((B * H, T, Dh), k.dtype),
                   jax.ShapeDtypeStruct((B * H, T, Dh), v.dtype)),
        grid=(B * H, T // block_k),
        in_specs=[full_spec, qkv_spec(block_k), qkv_spec(block_k), full_spec,
                  full_row, full_row],
        out_specs=(qkv_spec(block_k), qkv_spec(block_k)),
        interpret=interpret,
    )(qb, kb, vb, dob, lse, delta)

    from_bh = lambda t: t.reshape(B, H, T, Dh).transpose(0, 2, 1, 3)  # noqa: E731
    return from_bh(dq), from_bh(dk), from_bh(dv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool = False,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
) -> jax.Array:
    """Flash attention with blockwise pallas forward AND backward.
    q/k/v (B, T, H, Dh); requires T % block sizes == 0 (callers fall back
    to dense otherwise)."""
    interpret = jax.default_backend() != "tpu"
    out, _ = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out


def _fwd(q, k, v, causal, block_q, block_k):
    interpret = jax.default_backend() != "tpu"
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _bwd(causal, block_q, block_k, res, g):
    q, k, v, out, lse = res
    interpret = jax.default_backend() != "tpu"
    return _flash_backward(q, k, v, out, lse, g, causal, block_q, block_k, interpret)


flash_attention.defvjp(_fwd, _bwd)


def flash_vmem_ok(T: int, Dh: int, itemsize: int = 2) -> bool:
    """The kernels stage one head's FULL K/V in VMEM (BlockSpec (1, T, Dh))
    and only block over queries, so T is bounded by the ~16 MB scoped-VMEM
    budget: measured on v5e with Dh=64 bf16, T=12288 compiles and T=16384
    exceeds the limit by 128 KB (~1 KB of scoped VMEM per position at
    itemsize 2 — the staging buffers hold the INPUT dtype, so f32 halves
    the reachable T). A K-blocked 3D-grid kernel lifts this later; beyond
    it, ring/Ulysses sequence parallelism shards T across chips."""
    return T * Dh * itemsize <= 12288 * 64 * 2


def flash_shapes_ok(T: int, Dh: int, block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    itemsize: int = 2) -> bool:
    """Static dispatch guard used by ops.attention.multihead_attention: the
    sequence must tile into whole blocks, Dh must fill lanes reasonably,
    and the full-K/V VMEM staging must fit (see :func:`flash_vmem_ok`)."""
    return (T % block_q == 0 and T % block_k == 0
            and (Dh % 128 == 0 or Dh == 64)
            and flash_vmem_ok(T, Dh, itemsize))
