"""Pallas flash attention (TPU): fused QK^T -> online softmax -> V.

The hot op of the transformer stack (FedNLP/Cheetah planes). One kernel
instance handles one (batch*head, q-block): the query block stays in VMEM
while K/V stream through in blocks; softmax is accumulated online (running
max + normalizer) so the (T, T) score matrix never materializes in HBM —
memory O(T * Dh) instead of O(T^2), and the matmuls hit the MXU at
(BLOCK_Q x Dh) x (Dh x BLOCK_K) granularity.

Gradients: ``flash_attention`` carries a custom VJP whose backward
recomputes attention with the dense XLA path — forward-pass memory/speed
wins (the usual bottleneck for long-context eval/serving), exact gradients,
~1 extra forward of FLOPs in training (the standard recompute trade).

On non-TPU backends the kernel runs in interpret mode so tests validate
numerics everywhere; the compiled path engages on real TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = float(jnp.finfo(jnp.float32).min)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool, scale: float):
    """Grid: (B*H, T // block_q). Refs (leading grid-block dim of 1):
    q (1, block_q, Dh), k/v (1, T, Dh), o (1, block_q, Dh)."""
    block_q = q_ref.shape[1]
    Dh = q_ref.shape[2]
    T = k_ref.shape[1]
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, Dh), jnp.float32)

    n_kblocks = T // block_k
    # causal: skip key blocks strictly after this query block
    q_start = qi * block_q

    def body(kb, carry):
        m, l, acc = carry
        k_start = kb * block_k
        k_blk = k_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, block_k)
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        blk_max = jnp.max(s, axis=1, keepdims=True)
        new_m = jnp.maximum(m, blk_max)
        p = jnp.exp(s - new_m)
        corr = jnp.exp(m - new_m)
        new_l = l * corr + jnp.sum(p, axis=1, keepdims=True)
        new_acc = acc * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return new_m, new_l, new_acc

    if causal:
        # only key blocks up to and including the diagonal block
        n_iter = jnp.minimum((q_start + block_q + block_k - 1) // block_k, n_kblocks)
        m, l, acc = jax.lax.fori_loop(0, n_iter, body, (m0, l0, acc0))
    else:
        m, l, acc = jax.lax.fori_loop(0, n_kblocks, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_forward(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool,
    block_q: int, block_k: int, interpret: bool,
) -> jax.Array:
    """q/k/v: (B, T, H, Dh) -> (B, T, H, Dh)."""
    B, T, H, Dh = q.shape
    scale = 1.0 / (Dh ** 0.5)
    # fold (B, H) into the grid's first axis; layout (BH, T, Dh)
    to_bh = lambda t: t.transpose(0, 2, 1, 3).reshape(B * H, T, Dh)  # noqa: E731
    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    grid = (B * H, T // block_q)
    out = pl.pallas_call(
        functools.partial(_flash_kernel, block_k=block_k, causal=causal, scale=scale),
        out_shape=jax.ShapeDtypeStruct((B * H, T, Dh), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, Dh), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, T, Dh), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, T, Dh), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, Dh), lambda bh, qi: (bh, qi, 0)),
        interpret=interpret,
    )(qb, kb, vb)
    return out.reshape(B, H, T, Dh).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool = False,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
) -> jax.Array:
    """Flash attention with dense-recompute backward. q/k/v (B, T, H, Dh);
    requires T % block sizes == 0 (callers fall back to dense otherwise)."""
    interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _dense_attention(q, k, v, causal):
    Dh = q.shape[-1]
    scale = 1.0 / jnp.sqrt(Dh).astype(jnp.float32)
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if causal:
        T, S = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((T, S), dtype=bool))
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32)).astype(q.dtype)


def _fwd(q, k, v, causal, block_q, block_k):
    out = flash_attention(q, k, v, causal, block_q, block_k)
    return out, (q, k, v)


def _bwd(causal, block_q, block_k, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _dense_attention(q, k, v, causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)


def flash_shapes_ok(T: int, Dh: int, block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K) -> bool:
    """Static dispatch guard used by ops.attention.multihead_attention: the
    sequence must tile into whole blocks and Dh must fill lanes reasonably."""
    return T % block_q == 0 and T % block_k == 0 and (Dh % 128 == 0 or Dh == 64)
