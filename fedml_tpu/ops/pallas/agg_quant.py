"""Pallas fused stochastic-quantize + wire-pack for the codec hot path.

The compressed update plane's q8/q4 stage (comm/codec.py) is three passes
over every compressible leaf: hash-derived uniforms + per-256-chunk pow2
scales + clip/floor (``stochastic_quantize``), a dequantize multiply, and a
separate nibble/byte pack for the wire. All of it is memory-bound
elementwise work on a (C, m) cohort stack — prime fusion territory. This
kernel does the whole stage in ONE pass per (row-block, column-block) tile:
counter-hash uniforms (lowbias32, the exact mixing chain of
``codec._mix32_arr``), chunk absmax -> pow2 scale, stochastic floor,
int8/int4 byte emission, and the decode-side multiply, all while the tile
sits in VMEM. The grid is (C/block_c, mpad/block_m) with every tile
independent (chunk scales never cross a 256 boundary, and block_m is a
multiple of 256), so Mosaic pipelines tiles back-to-back with no carried
scratch.

Bit-exactness is the load-bearing invariant: pow2 scales make every op in
the pipeline exact arithmetic except the single ``floor(v/s + u)``, so the
packed bytes must equal the numpy wire path (``UpdateCodec._encode_leaf``)
byte-for-byte and the decoded stack must equal the unfused XLA path
(``codec._quant_roundtrip_jnp``) bit-for-bit. The kernel computes the
frexp/ldexp scale with pure uint32 exponent arithmetic, matching XLA's
frexp semantics (subnormal absmax -> flushed scale, inf -> 2^-eb, nan/zero
-> 1.0); chunks whose absmax is subnormal are outside the numpy parity
contract (numpy keeps subnormal scales where XLA flushes — a pre-existing
property of the unfused path, pinned by tests).

On non-TPU backends the default dispatch is the jittable jnp reference
(same arithmetic, no Pallas) — interpret mode (``interpret=True``) exists
for the parity suite, which pins kernel == reference bit equality on CPU.
Shapes outside the kernel's tiling take the reference on every backend.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Mirrors codec._QCHUNK / codec._EB — the codec asserts the values agree at
# wiring time so the two modules cannot drift silently.
QCHUNK = 256
_EB = {8: 6, 4: 2}
_BOUND = {8: 127, 4: 7}

# Row-block of 8 (f32 sublane) and a column block of up to 16 chunks keeps
# the per-tile working set (values + uniforms + levels + bytes + decode)
# around 100 KB — far inside VMEM even with double buffering.
_BLOCK_C = 8
_MAX_BLOCK_CHUNKS = 16

# One kernel instance's VMEM working set must stay well under the ~16 MB
# budget; 2 MB of f32 per tile is conservative given Mosaic double-buffers.
_VMEM_TILE_BUDGET = 2 * 1024 * 1024
# interpret mode (non-TPU) unrolls every grid step into the jaxpr — fine
# for parity-test shapes, catastrophic at cohort scale (10k rows = 1250
# row blocks). Past this many steps the interpret path takes the jnp
# reference; kernel/reference bit parity makes the switch invisible.
_INTERPRET_GRID_CAP = 4096


def _mix32(x):
    """lowbias32 finalizer on uint32 arrays — the exact constants of
    ``codec._mix32_arr`` (asserted equal at import of the codec wiring)."""
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> jnp.uint32(15))
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> jnp.uint32(16))
    return x


def _uniform_from_idx(idx_u32, base_u32):
    """Hash (element index XOR row key) -> f32 uniform in [0, 1)."""
    h = _mix32(idx_u32 ^ base_u32)
    return (h >> jnp.uint32(8)).astype(jnp.float32) * jnp.float32(1.0 / (1 << 24))


def _pow2_scale_bits(amax, eb: int):
    """Per-chunk power-of-two scale 2^(frexp_exp(amax) - eb) via uint32
    exponent arithmetic — bit-identical to XLA's frexp/ldexp pair
    (``codec._pow2_scales`` under jnp) without relying on Mosaic support
    for those ops: subnormal absmax takes XLA's frexp exponent of -149 (so
    the ldexp result flushes to 0), inf maps to exponent 0, and zero/nan
    absmax yield scale 1.0."""
    bits = jax.lax.bitcast_convert_type(amax, jnp.uint32)
    be = (bits >> jnp.uint32(23)).astype(jnp.int32)  # biased exp; sign is 0
    ea = jnp.where(be == 255, 0, jnp.where(be == 0, -149, be - 126))
    e2 = ea - eb
    s_norm = jax.lax.bitcast_convert_type(
        ((e2 + 127) << 23).astype(jnp.uint32), jnp.float32)
    s = jnp.where(e2 >= -126, s_norm, jnp.float32(0.0))
    return jnp.where(amax > 0, s, jnp.float32(1.0))


def _quant_tile(v, key_col, col0, bits: int):
    """Shared per-tile arithmetic: (block_c, block_m) f32 values + (block_c,
    1) uint32 row keys -> (levels f32 in [-bound, bound], scales (block_c,
    nchunk))."""
    bc, bm = v.shape
    nchunk = bm // QCHUNK
    idx = col0 + jax.lax.broadcasted_iota(jnp.uint32, (bc, bm), 1)
    u = _uniform_from_idx(idx, key_col)
    blk = v.reshape(bc, nchunk, QCHUNK)
    amax = jnp.max(jnp.abs(blk), axis=-1)
    s = _pow2_scale_bits(amax, _EB[bits])
    bound = jnp.float32(_BOUND[bits])
    q = jnp.clip(jnp.floor(blk / s[..., None] + u.reshape(bc, nchunk, QCHUNK)),
                 -bound, bound)
    return q.reshape(bc, bm), s


def _pack_nibbles(q_i32):
    """int32 levels in [-7, 7] -> two-per-byte uint8 (bias +8, first element
    high nibble) — the byte layout of native ``pack_i4``."""
    bc, bm = q_i32.shape
    b = (q_i32 + 8).reshape(bc, bm // 2, 2)
    return ((b[:, :, 0] << 4) | b[:, :, 1]).astype(jnp.uint8)


def _quantize_pack_kernel(v_ref, h_ref, packed_ref, s_ref, dec_ref, *,
                          bits: int, block_m: int):
    """Grid (C/block_c, mpad/block_m). Refs: v (block_c, block_m) f32,
    h (block_c, 1) uint32 row keys; outputs packed (block_c, block_m [q8
    int8] or block_m/2 [q4 uint8]), s (block_c, block_m/QCHUNK) f32,
    dec (block_c, block_m) f32. Tiles are independent: uniforms come from
    the global element index (col0 offset), scales never cross a chunk
    boundary, so there is no carried state and no init/finalize step."""
    col0 = jnp.uint32(pl.program_id(1) * block_m)
    q, s = _quant_tile(v_ref[...], h_ref[...], col0, bits)
    s_ref[...] = s
    # wire path stores int8 and multiplies back in f32; same values here
    qi = q.astype(jnp.int8)
    dec_ref[...] = qi.astype(jnp.float32) * jnp.repeat(s, QCHUNK, axis=1)
    if bits == 8:
        packed_ref[...] = qi
    else:
        packed_ref[...] = _pack_nibbles(q.astype(jnp.int32))


def _pad_cols(m: int, block_m: int) -> int:
    return -(-m // block_m) * block_m


def _block_m_for(mpad: int) -> int:
    return QCHUNK * min(mpad // QCHUNK, _MAX_BLOCK_CHUNKS)


def quant_shapes_ok(C: int, m: int) -> bool:
    """True when the fused kernel's tiling handles (C, m): at least one
    quant chunk of payload and a per-tile working set inside the VMEM
    budget (~6 f32 planes of block_c x block_m)."""
    if C < 1 or m < 1:
        return False
    block_m = _block_m_for(_pad_cols(m, QCHUNK))
    return 6 * 4 * _BLOCK_C * block_m <= _VMEM_TILE_BUDGET


def row_keys(seed: int, round_u32, cids_u32, leaf_hash: int):
    """Per-row base keys: the ``codec.stochastic_key`` mixing chain with the
    traced round/client ids entering as uint32 arrays (identical to the
    unfused ``codec._quant_roundtrip_jnp`` preamble)."""
    h = jnp.uint32((int(seed) ^ 0x9E3779B9) & 0xFFFFFFFF)
    h = _mix32(h ^ jnp.asarray(round_u32).astype(jnp.uint32))
    h = _mix32(h ^ jnp.asarray(cids_u32).astype(jnp.uint32))
    h = _mix32(h ^ jnp.uint32(leaf_hash))
    return h


def fused_quantize_pack(vals, bits: int, seed: int, round_u32, cids_u32,
                        leaf_hash: int = 0, *,
                        interpret: Optional[bool] = None,
                        use_kernel: bool = True,
                        ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One-pass stochastic quantize + wire pack + decode over a cohort stack.

    ``vals`` is the (C, m) f32 value stack (one row per client),
    ``round_u32``/``cids_u32`` the traced round scalar and (C,) client-id
    vector. Returns ``(packed, scales, dec)``:

    - ``packed`` — the wire bytes, per row: (C, m) int8 for q8, or
      (C, ceil(m/2)) uint8 nibble-packed for q4. Row ``i`` equals the numpy
      wire path's ``rec["q"]`` for client ``cids[i]`` byte-for-byte
      (``pack_i4``'s odd-tail pad nibble falls out for free: a padded zero
      element stochastically floors to level 0 = biased nibble 8).
    - ``scales`` — (C, ceil(m/256)) f32 per-chunk pow2 scales
      (== ``rec["s"]``).
    - ``dec`` — (C, m) f32 decoded values, bit-identical to the unfused
      ``codec._quant_roundtrip_jnp``.

    ``use_kernel=False`` (or shapes outside :func:`quant_shapes_ok`) takes
    the jittable jnp reference — same arithmetic, no Pallas.
    """
    vals = jnp.asarray(vals, jnp.float32)
    C, m = vals.shape
    h = row_keys(seed, round_u32, cids_u32, leaf_hash)
    if not (use_kernel and quant_shapes_ok(C, m)):
        return _reference_quantize_pack(vals, bits, h)
    if interpret is None:
        # Non-TPU production dispatch takes the bit-identical jnp reference:
        # interpret mode emulates the kernel step by step and is far slower
        # than plain XLA. The parity suite opts in with interpret=True.
        if jax.default_backend() != "tpu":
            return _reference_quantize_pack(vals, bits, h)
        interpret = False

    mpad = _pad_cols(m, QCHUNK)
    block_m = _block_m_for(mpad)
    mpad2 = _pad_cols(mpad, block_m)
    cpad = _pad_cols(C, _BLOCK_C)
    grid = (cpad // _BLOCK_C, mpad2 // block_m)
    if interpret and grid[0] * grid[1] > _INTERPRET_GRID_CAP:
        return _reference_quantize_pack(vals, bits, h)
    vp = jnp.zeros((cpad, mpad2), jnp.float32).at[:C, :m].set(vals)
    hp = jnp.zeros((cpad, 1), jnp.uint32).at[:C, 0].set(h)
    packed_dt = jnp.int8 if bits == 8 else jnp.uint8
    packed_bm = block_m if bits == 8 else block_m // 2
    packed_cols = mpad2 if bits == 8 else mpad2 // 2
    nchunk_blk = block_m // QCHUNK
    packed, scales, dec = pl.pallas_call(
        functools.partial(_quantize_pack_kernel, bits=bits, block_m=block_m),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_C, block_m), lambda i, j: (i, j)),
            pl.BlockSpec((_BLOCK_C, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((_BLOCK_C, packed_bm), lambda i, j: (i, j)),
            pl.BlockSpec((_BLOCK_C, nchunk_blk), lambda i, j: (i, j)),
            pl.BlockSpec((_BLOCK_C, block_m), lambda i, j: (i, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((cpad, packed_cols), packed_dt),
            jax.ShapeDtypeStruct((cpad, mpad2 // QCHUNK), jnp.float32),
            jax.ShapeDtypeStruct((cpad, mpad2), jnp.float32),
        ],
        interpret=interpret,
    )(vp, hp)
    nbytes = m if bits == 8 else (m + 1) // 2
    return (packed[:C, :nbytes], scales[:C, :mpad // QCHUNK], dec[:C, :m])


def _reference_quantize_pack(vals, bits: int, h):
    """Jittable jnp reference: identical arithmetic to the kernel (and to
    ``codec._quant_roundtrip_jnp`` on the decode side), one expression per
    stage instead of one VMEM pass."""
    C, m = vals.shape
    mpad = _pad_cols(m, QCHUNK)
    vp = jnp.zeros((C, mpad), jnp.float32).at[:, :m].set(vals)
    q, s = _quant_tile(vp, h[:, None], jnp.uint32(0), bits)
    qi = q.astype(jnp.int8)
    dec = (qi.astype(jnp.float32)
           * jnp.repeat(s, QCHUNK, axis=1))[:, :m]
    if bits == 8:
        return qi[:, :m], s, dec
    packed = _pack_nibbles(q.astype(jnp.int32))
    return packed[:, :(m + 1) // 2], s, dec
