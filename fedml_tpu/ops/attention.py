"""Attention ops: dense multihead attention + ring attention over a seq axis.

Single-chip path is plain XLA (it fuses QK^T -> softmax -> V well on the MXU
for moderate T; a pallas flash kernel is the planned upgrade — see
ops/pallas/). The ring path implements blockwise ring attention
(Liu et al.) with ``lax.ppermute`` over the ``seq`` mesh axis: each shard
holds a query block, K/V blocks rotate around the ring, and softmax is
accumulated online (running max + normalizer), so memory stays O(T/n per
device) and comms ride ICI. This is the long-context capability the task
brief requires (SURVEY.md §5.7: absent in reference, first-class here).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def auto_attention_impl(B: int, H: int, T: int, Dh: int,
                        itemsize: int = 2) -> str:
    """Pick 'flash' vs 'dense' for (B, T, H, Dh) attention.

    Speed: measured crossover (results/flash_attention_bench.json) — XLA's
    fused dense attention holds a slight edge below T=4096 on the v5e
    (0.88-0.99x); from 4096 the K-blocked kernel wins 2x+ and is the only
    option once (T, T) logits stop fitting in HBM.

    Memory: BELOW the speed crossover, dense training saves the
    (B, H, T, T) probabilities for the backward pass PER LAYER — a
    12-layer stack at B=16 H=16 T=2048 pins 26 GB. Prefer flash whenever
    one layer's saved tensor crosses 512 MB (a meaningful slice of 16 GB
    HBM once multiplied by typical depths).

    A BLOCK_TABLE entry for T (ops/pallas/flash_attention.py — populated
    only from confirmed on-chip sweeps, scripts/bench_flash_blocks_r5.py)
    means flash measured at-or-faster than dense at that length with the
    tabled blocks, so it lowers the crossover for exactly that T — but
    only at the SWEPT shape family (Dh=64 bf16): at other Dh/itemsize the
    kernel's guards would reject the tabled blocks and run unmeasured
    auto squares, a config the table says nothing about.
    """
    from .pallas import flash_shapes_ok
    from .pallas.flash_attention import BLOCK_TABLE, BLOCK_TABLE_SWEPT_SHAPE

    dense_saved_bytes = B * H * T * T * itemsize
    want_flash = (T >= 4096 or dense_saved_bytes > 512 * 1024**2
                  or (T in BLOCK_TABLE
                      and (Dh, itemsize) == BLOCK_TABLE_SWEPT_SHAPE))
    if want_flash and flash_shapes_ok(T, Dh, itemsize=itemsize):
        return "flash"
    return "dense"


def multihead_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = False,
    impl: Optional[str] = None,
) -> jax.Array:
    """Attention. q/k/v: (B, T, H, Dh) -> (B, T, H, Dh).

    ``impl``: 'flash' (pallas kernel, ops/pallas/flash_attention.py),
    'dense', or None = auto (flash when shapes tile into whole blocks).
    """
    T, Dh = q.shape[1], q.shape[-1]
    if impl is None:
        itemsize = jnp.dtype(q.dtype).itemsize
        impl = auto_attention_impl(q.shape[0], q.shape[2], T, Dh, itemsize)
        saved_gb = q.shape[0] * q.shape[2] * T * T * itemsize / 2**30
        if impl == "dense" and (T >= 8192 or saved_gb > 0.5):
            # loud, not silent: dense wanted flash (long T, or the
            # per-layer saved probabilities alone cross the memory
            # threshold) but flash was refused (untileable T or
            # lane-unfriendly Dh) — the failure will surface later as a
            # generic HBM allocation error; point at the fix NOW.
            import logging

            logging.warning(
                "attention auto-dispatch: falling back to DENSE O(T^2) "
                "attention at T=%d (flash needs T tileable by 128-blocks "
                "and Dh in {64, k*128}; got Dh=%d) — expect ~%.1f GB of "
                "saved probabilities PER LAYER; pad T/Dh to tileable "
                "sizes or shard the sequence with ring/ulysses attention",
                T, Dh, saved_gb)
    if impl == "flash":
        from .pallas import flash_attention

        return flash_attention(q, k, v, causal)
    scale = 1.0 / jnp.sqrt(Dh).astype(q.dtype)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        S = logits.shape[-1]
        mask = jnp.tril(jnp.ones((T, S), dtype=bool))
        logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
    impl: Optional[str] = None,
) -> jax.Array:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style). Must run
    inside shard_map with ``axis_name`` bound; q/k/v are local sequence
    shards (B, T_local, H, Dh) with ALL heads present.

    Two collectives instead of the ring's n ppermute hops: an all-to-all
    re-shards from sequence to heads (each device gets the FULL sequence
    for H/n heads), full-sequence attention runs locally — flash-kernel
    eligible, unlike the ring's blockwise accumulation — and a reverse
    all-to-all restores sequence sharding. The axis size must divide the
    head count (n | H). Comms volume per device is ~n/2x LOWER than the
    ring's (ring moves 2*B*T*H*Dh per device over its n K/V hops; the
    four all-to-alls here move ~4*B*(T/n)*H*Dh — each device only ever
    holds H/n heads of the full sequence). Prefer Ulysses when H >= n
    and the per-device full-T attention fits memory; the ring remains
    the extreme-context option where O(T/n) activation memory is the
    constraint.
    """
    n = lax.axis_size(axis_name)
    B, Tl, H, Dh = q.shape
    if H % n != 0:
        raise ValueError(
            f"ulysses needs heads ({H}) divisible by the sequence axis ({n})")

    def seq_to_heads(x):
        # (B, Tl, H, Dh) --all_to_all--> (B, n*Tl, H/n, Dh)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = multihead_attention(qh, kh, vh, causal=causal, impl=impl)
    # (B, n*Tl, H/n, Dh) --all_to_all--> (B, Tl, H, Dh)
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
) -> jax.Array:
    """Blockwise ring attention. Must run inside shard_map with ``axis_name``
    bound; q/k/v are the local sequence shards (B, T_local, H, Dh).

    Online-softmax accumulation: for each incoming K/V block keep running
    (max, normalizer, weighted-sum) in f32 and rotate K/V with ppermute.
    For ``causal=True`` blocks are masked by global block position (query
    shard i attends to key shard j fully if j < i, diagonally if j == i).
    """
    n = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    B, T, H, Dh = q.shape
    scale = 1.0 / jnp.sqrt(Dh).astype(jnp.float32)

    qf = q.astype(jnp.float32)
    neg = jnp.finfo(jnp.float32).min
    perm = [(i, (i + 1) % n) for i in range(n)]
    tri = jnp.tril(jnp.ones((T, T), dtype=bool))

    def block_logits(kblk, src_idx):
        logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kblk.astype(jnp.float32)) * scale
        if causal:
            keep_all = src_idx < my_idx
            keep_diag = src_idx == my_idx
            mask = jnp.where(keep_all, True, jnp.where(keep_diag, tri, False))
            logits = jnp.where(mask[None, None], logits, neg)
        return logits

    def step(carry, _):
        kblk, vblk, src_idx, m, l, acc = carry
        logits = block_logits(kblk, src_idx)
        blk_max = jnp.max(logits, axis=-1)            # (B,H,T)
        new_m = jnp.maximum(m, blk_max)
        correction = jnp.exp(m - new_m)
        p = jnp.exp(logits - new_m[..., None])        # (B,H,T,K)
        new_l = l * correction + p.sum(axis=-1)
        new_acc = acc * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, vblk.astype(jnp.float32)
        )
        kblk = lax.ppermute(kblk, axis_name, perm)
        vblk = lax.ppermute(vblk, axis_name, perm)
        src_idx = lax.ppermute(src_idx, axis_name, perm)
        return (kblk, vblk, src_idx, new_m, new_l, new_acc), None

    m0 = jnp.full((B, H, T), neg, jnp.float32)
    l0 = jnp.zeros((B, H, T), jnp.float32)
    acc0 = jnp.zeros((B, H, T, Dh), jnp.float32)
    (k_, v_, _, m, l, acc), _ = lax.scan(
        step, (k, v, my_idx, m0, l0, acc0), None, length=n
    )
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # (B,T,H,Dh)
