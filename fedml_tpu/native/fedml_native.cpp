// Native runtime components for fedml_tpu.
//
// The reference outsources all native code to external libs (SURVEY.md §2.7:
// its cpp/ and rust/ trees are empty placeholders). Here the host-side hot
// paths that sit OUTSIDE XLA get a C++ implementation:
//
//  1. cohort packer — builds the rectangular (clients, cap, feat) training
//     block from ragged per-client sample indices: fused shuffle+gather+pad
//     with one pass per client, no intermediate numpy copies. This is the
//     per-round host work feeding the compiled FL round step.
//  2. fp16/int8 quantization codec — WAN weight compression for the
//     cross-silo plane (2-4x smaller Messages than raw f32).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <cmath>
#include <algorithm>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// 1. cohort packer
//
// x:        (n_samples, feat_size) float32, C-contiguous
// y:        (n_samples, label_size) int32 (label_size>=1; scalar labels = 1)
// idx:      concatenated per-client sample indices (int64)
// offsets:  (n_clients+1) prefix offsets into idx
// perm:     permutation of each client's local order (same layout as idx);
//           pass identity for no shuffle
// cap:      samples per client after padding (num_batches * batch_size)
// outputs:  out_x (n_clients, cap, feat), out_y (n_clients, cap, label),
//           out_mask (n_clients, cap) float32
// ---------------------------------------------------------------------------
void pack_cohort_f32(
    const float* x, const int32_t* y,
    const int64_t* idx, const int64_t* offsets, const int64_t* perm,
    int64_t n_clients, int64_t feat_size, int64_t label_size, int64_t cap,
    float* out_x, int32_t* out_y, float* out_mask, int32_t n_threads)
{
    if (n_threads <= 0) {
        n_threads = (int32_t)std::min<int64_t>(
            n_clients, std::max(1u, std::thread::hardware_concurrency()));
    }
    auto work = [&](int64_t c0, int64_t c1) {
        for (int64_t c = c0; c < c1; ++c) {
            const int64_t lo = offsets[c], hi = offsets[c + 1];
            const int64_t n = std::min(hi - lo, cap);
            float* ox = out_x + c * cap * feat_size;
            int32_t* oy = out_y + c * cap * label_size;
            float* om = out_mask + c * cap;
            for (int64_t i = 0; i < n; ++i) {
                const int64_t src = idx[lo + perm[lo + i]];
                std::memcpy(ox + i * feat_size, x + src * feat_size,
                            sizeof(float) * (size_t)feat_size);
                std::memcpy(oy + i * label_size, y + src * label_size,
                            sizeof(int32_t) * (size_t)label_size);
                om[i] = 1.0f;
            }
            // zero the padded tail
            std::memset(ox + n * feat_size, 0,
                        sizeof(float) * (size_t)((cap - n) * feat_size));
            std::memset(oy + n * label_size, 0,
                        sizeof(int32_t) * (size_t)((cap - n) * label_size));
            std::memset(om + n, 0, sizeof(float) * (size_t)(cap - n));
        }
    };
    if (n_threads == 1 || n_clients == 1) {
        work(0, n_clients);
        return;
    }
    std::vector<std::thread> threads;
    const int64_t chunk = (n_clients + n_threads - 1) / n_threads;
    for (int64_t t = 0; t < n_threads; ++t) {
        const int64_t c0 = t * chunk, c1 = std::min(n_clients, c0 + chunk);
        if (c0 >= c1) break;
        threads.emplace_back(work, c0, c1);
    }
    for (auto& th : threads) th.join();
}

// ---------------------------------------------------------------------------
// 1b. lane-row gather — assembles the packed schedule's (n_slots, bs) lane
//     index tensor from the cohort index rectangle in one pass.
//
// rows:    (n_rows, bs) int32 — per-client batch rows (last row all-zero pad)
// srcmap:  (n_slots) int64 — source row per lane slot
// out:     (n_slots, bs) int32
// ---------------------------------------------------------------------------
void pack_lane_rows_i32(
    const int32_t* rows, const int64_t* srcmap,
    int64_t n_slots, int64_t bs, int32_t* out, int32_t n_threads)
{
    if (n_threads <= 0) {
        n_threads = (int32_t)std::min<int64_t>(
            std::max<int64_t>(n_slots / 4096, 1),
            std::max(1u, std::thread::hardware_concurrency()));
    }
    auto work = [&](int64_t s0, int64_t s1) {
        for (int64_t s = s0; s < s1; ++s) {
            std::memcpy(out + s * bs, rows + srcmap[s] * bs,
                        sizeof(int32_t) * (size_t)bs);
        }
    };
    if (n_threads == 1 || n_slots <= 1) {
        work(0, n_slots);
        return;
    }
    std::vector<std::thread> threads;
    const int64_t chunk = (n_slots + n_threads - 1) / n_threads;
    for (int64_t t = 0; t < n_threads; ++t) {
        const int64_t s0 = t * chunk, s1 = std::min(n_slots, s0 + chunk);
        if (s0 >= s1) break;
        threads.emplace_back(work, s0, s1);
    }
    for (auto& th : threads) th.join();
}

// ---------------------------------------------------------------------------
// 2. quantization codec: f32 <-> int8 with per-chunk absmax scales
//    (chunk = 256 values; scales stored f32). Ratio ~3.9x vs f32.
// ---------------------------------------------------------------------------
static const int64_t QCHUNK = 256;

int64_t quant_i8_bound(int64_t n) {  // bytes needed for payload
    const int64_t n_chunks = (n + QCHUNK - 1) / QCHUNK;
    return n + n_chunks * (int64_t)sizeof(float);
}

void quantize_i8(const float* src, int64_t n, int8_t* dst_q, float* dst_scales) {
    const int64_t n_chunks = (n + QCHUNK - 1) / QCHUNK;
    for (int64_t c = 0; c < n_chunks; ++c) {
        const int64_t lo = c * QCHUNK, hi = std::min(n, lo + QCHUNK);
        float amax = 0.0f;
        for (int64_t i = lo; i < hi; ++i) amax = std::max(amax, std::fabs(src[i]));
        const float scale = amax > 0 ? amax / 127.0f : 1.0f;
        dst_scales[c] = scale;
        const float inv = 1.0f / scale;
        for (int64_t i = lo; i < hi; ++i) {
            dst_q[i] = (int8_t)std::lrintf(src[i] * inv);
        }
    }
}

void dequantize_i8(const int8_t* q, const float* scales, int64_t n, float* dst) {
    const int64_t n_chunks = (n + QCHUNK - 1) / QCHUNK;
    for (int64_t c = 0; c < n_chunks; ++c) {
        const int64_t lo = c * QCHUNK, hi = std::min(n, lo + QCHUNK);
        const float s = scales[c];
        for (int64_t i = lo; i < hi; ++i) dst[i] = (float)q[i] * s;
    }
}

// ---------------------------------------------------------------------------
// 3. int4 nibble packing: int8 levels in [-7, 7] biased by +8 into the
//    high/low nibbles of one byte (odd tails pad with the zero level).
//    Quantization math stays in Python (shared with the JAX path) — the
//    native layer only does the byte shuffling.
// ---------------------------------------------------------------------------

void pack_i4(const int8_t* q, int64_t n, uint8_t* dst) {
    const int64_t pairs = n / 2;
    for (int64_t p = 0; p < pairs; ++p) {
        const uint8_t hi = (uint8_t)(q[2 * p] + 8);
        const uint8_t lo = (uint8_t)(q[2 * p + 1] + 8);
        dst[p] = (uint8_t)((hi << 4) | (lo & 0x0F));
    }
    if (n % 2) {
        const uint8_t hi = (uint8_t)(q[n - 1] + 8);
        dst[pairs] = (uint8_t)((hi << 4) | 8);  // pad nibble = zero level
    }
}

void unpack_i4(const uint8_t* packed, int64_t n, int8_t* dst) {
    for (int64_t i = 0; i < n; ++i) {
        const uint8_t b = packed[i / 2];
        const uint8_t nib = (i % 2 == 0) ? (uint8_t)(b >> 4) : (uint8_t)(b & 0x0F);
        dst[i] = (int8_t)((int)nib - 8);
    }
}

// ---------------------------------------------------------------------------
// 4. build provenance: the Makefile bakes a truncated sha256 of this file
//    into the binary so the loader (and `make check`) can detect a stale .so
//    even when filesystem mtimes lie (fresh checkouts, copied build trees).
//    The "FEDML_SRC_HASH=" prefix makes the hash greppable from the binary.
// ---------------------------------------------------------------------------

#ifndef FEDML_NATIVE_SRC_HASH
#define FEDML_NATIVE_SRC_HASH "unknown"
#endif

const char* fedml_native_src_hash(void) {
    return "FEDML_SRC_HASH=" FEDML_NATIVE_SRC_HASH;
}

}  // extern "C"
