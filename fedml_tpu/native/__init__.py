"""ctypes bindings for the native runtime (fedml_native.cpp).

Builds ``libfedml_native.so`` with g++ on first import (cached next to the
source); every entry point has a pure-numpy fallback so the package works
without a toolchain. pybind11 is not in this image — the C ABI + ctypes is
the binding layer (task brief, Environment notes).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libfedml_native.so")
_lib: Optional[ctypes.CDLL] = None
_tried = False
_hash_warned = False


def _src_hash() -> str:
    """Truncated sha256 of fedml_native.cpp — the provenance token the
    Makefile bakes into the binary (see fedml_native_src_hash)."""
    import hashlib

    with open(os.path.join(_HERE, "fedml_native.cpp"), "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()[:16]


def _hash_ok(lib: ctypes.CDLL) -> bool:
    """Compare the binary's embedded source hash against the on-disk source.

    The mtime guard (:func:`_fresh`) misses staleness when timestamps lie —
    fresh checkouts, copied build trees, prebuilt artifacts — so the loaded
    binary itself is the authority: on mismatch (or a pre-hash binary) warn
    once and refuse the .so, engaging the numpy fallback.
    """
    global _hash_warned
    embedded = None
    try:
        fn = lib.fedml_native_src_hash
        fn.restype = ctypes.c_char_p
        raw = fn()
        if raw:
            embedded = raw.decode("ascii", "replace").split("=", 1)[-1]
    except AttributeError:
        pass  # binary predates the hash scheme: stale by definition
    expect = _src_hash()
    if embedded == expect:
        return True
    if not _hash_warned:
        _hash_warned = True
        logging.warning(
            "libfedml_native.so was built from different sources (embedded "
            "hash %s, source %s); numpy fallback engaged — rebuild with "
            "`make -C fedml_tpu/native`", embedded, expect)
    return False


def _fresh() -> bool:
    """True when the existing .so is at least as new as its inputs."""
    if not os.path.exists(_SO):
        return False
    so_mtime = os.path.getmtime(_SO)
    for src in ("fedml_native.cpp", "Makefile"):
        p = os.path.join(_HERE, src)
        if os.path.exists(p) and os.path.getmtime(p) > so_mtime:
            return False
    return True


def _build() -> str:
    """'ok' | 'no-toolchain' | 'failed' — callers must not load a stale .so
    after a *failed* rebuild (the source no longer matches the binary).
    Serialized across processes with a lock file so concurrent first imports
    never compile/link the same output simultaneously."""
    lock_path = os.path.join(_HERE, ".build.lock")
    try:
        import fcntl

        lock = open(lock_path, "w")
        fcntl.flock(lock, fcntl.LOCK_EX)
    except Exception:
        lock = None
    try:
        if _fresh():  # another process built it while we waited on the lock
            return "ok"
        subprocess.run(
            ["make", "-s", "-C", _HERE, "libfedml_native.so"],
            check=True, capture_output=True, timeout=120,
        )
        return "ok" if os.path.exists(_SO) else "failed"
    except FileNotFoundError as e:  # make itself missing
        logging.debug("native toolchain unavailable: %s", e)
        return "no-toolchain"
    except Exception as e:
        logging.warning("native build failed (numpy fallback engaged): %s", e)
        return "failed"
    finally:
        if lock is not None:
            lock.close()


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    # fast path: a .so at least as new as the sources loads without touching
    # make (also what keeps concurrent processes from racing a rebuild);
    # otherwise rebuild under a lock — and never load a binary STALER than
    # the source after a failed rebuild
    if not _fresh():
        status = _build()
        if status == "failed":
            return None  # stale .so would shadow the (broken/newer) source
        if status == "no-toolchain" and not os.path.exists(_SO):
            return None
        # no-toolchain with a prebuilt .so present: best available option
    try:
        lib = ctypes.CDLL(_SO)
        if not _hash_ok(lib):
            return None  # stale binary: numpy fallback (warned once above)
        lib.pack_cohort_f32.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int32,
        ]
        lib.pack_lane_rows_i32.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int32,
        ]
        lib.quant_i8_bound.argtypes = [ctypes.c_int64]
        lib.quant_i8_bound.restype = ctypes.c_int64
        lib.quantize_i8.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p
        ]
        lib.dequantize_i8.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p
        ]
        try:  # absent from pre-int4 prebuilt .so (no-toolchain path)
            lib.pack_i4.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p
            ]
            lib.unpack_i4.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p
            ]
        except AttributeError:
            logging.debug("native lib predates int4 pack; numpy fallback")
        _lib = lib
    except OSError as e:
        logging.debug("native load failed: %s", e)
    return _lib


def native_available() -> bool:
    return get_lib() is not None


_QCHUNK = 256


def quantize_i8(arr: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """f32 array -> (int8 values, f32 per-256-chunk scales)."""
    flat = np.ascontiguousarray(arr, np.float32).ravel()
    n = flat.size
    n_chunks = -(-n // _QCHUNK) if n else 0
    q = np.empty(n, np.int8)
    scales = np.empty(n_chunks, np.float32)
    lib = get_lib()
    if lib is not None and n:
        lib.quantize_i8(
            flat.ctypes.data, n, q.ctypes.data, scales.ctypes.data
        )
        return q, scales
    # numpy fallback
    for c in range(n_chunks):
        blk = flat[c * _QCHUNK : (c + 1) * _QCHUNK]
        amax = np.abs(blk).max() if blk.size else 0.0
        s = amax / 127.0 if amax > 0 else 1.0
        scales[c] = s
        q[c * _QCHUNK : (c + 1) * _QCHUNK] = np.rint(blk / s).astype(np.int8)
    return q, scales


def dequantize_i8(q: np.ndarray, scales: np.ndarray, shape) -> np.ndarray:
    n = int(np.prod(shape)) if shape else q.size
    out = np.empty(n, np.float32)
    lib = get_lib()
    if lib is not None and n:
        lib.dequantize_i8(
            np.ascontiguousarray(q).ctypes.data,
            np.ascontiguousarray(scales).ctypes.data, n, out.ctypes.data,
        )
    else:
        for c in range(len(scales)):
            blk = q[c * _QCHUNK : (c + 1) * _QCHUNK].astype(np.float32)
            out[c * _QCHUNK : (c + 1) * _QCHUNK] = blk * scales[c]
    return out.reshape(shape)


def pack_lane_rows(rows: np.ndarray, srcmap: np.ndarray,
                   n_threads: int = 0) -> np.ndarray:
    """Gather (n_rows, bs) int32 batch rows into the packed schedule's lane
    tensor via a slot -> row map (see pack_lane_rows_i32). srcmap may have
    any leading shape; the output matches it with a trailing bs axis."""
    rows = np.ascontiguousarray(rows, np.int32)
    sm = np.ascontiguousarray(srcmap, np.int64)
    bs = rows.shape[-1]
    out_shape = sm.shape + (bs,)
    lib = get_lib()
    if lib is None:
        return rows[sm.ravel()].reshape(out_shape)
    out = np.empty((sm.size, bs), np.int32)
    lib.pack_lane_rows_i32(
        rows.ctypes.data, sm.ctypes.data, sm.size, bs, out.ctypes.data,
        int(n_threads),
    )
    return out.reshape(out_shape)


def pack_cohort(
    x: np.ndarray,
    y: np.ndarray,
    client_indices: list,
    cap: int,
    perms: Optional[list] = None,
    n_threads: int = 0,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Fused shuffle+gather+pad across a cohort (see fedml_native.cpp).

    x (N, *feat) f32, y (N, *label) int; client_indices: list of int arrays.
    Returns (out_x (C, cap, *feat), out_y (C, cap, *label), mask (C, cap)).
    """
    C = len(client_indices)
    feat_shape = x.shape[1:]
    label_shape = y.shape[1:]
    feat_size = int(np.prod(feat_shape)) if feat_shape else 1
    label_size = int(np.prod(label_shape)) if label_shape else 1
    lib = get_lib()
    x2 = np.ascontiguousarray(x, np.float32).reshape(len(x), feat_size)
    y2 = np.ascontiguousarray(y, np.int32).reshape(len(y), label_size)
    out_x = np.empty((C, cap, feat_size), np.float32)
    out_y = np.empty((C, cap, label_size), np.int32)
    out_m = np.empty((C, cap), np.float32)
    if lib is not None:
        idx = np.concatenate([np.asarray(ci, np.int64) for ci in client_indices]) \
            if C else np.zeros(0, np.int64)
        offsets = np.zeros(C + 1, np.int64)
        np.cumsum([len(ci) for ci in client_indices], out=offsets[1:])
        if perms is None:
            perm = np.concatenate([
                np.arange(len(ci), dtype=np.int64) for ci in client_indices
            ]) if C else np.zeros(0, np.int64)
        else:
            perm = np.concatenate([np.asarray(p, np.int64) for p in perms])
        lib.pack_cohort_f32(
            x2.ctypes.data, y2.ctypes.data, idx.ctypes.data,
            offsets.ctypes.data, perm.ctypes.data,
            C, feat_size, label_size, cap,
            out_x.ctypes.data, out_y.ctypes.data, out_m.ctypes.data,
            int(n_threads),
        )
    else:
        out_x[:] = 0; out_y[:] = 0; out_m[:] = 0
        for c, ci in enumerate(client_indices):
            ci = np.asarray(ci, np.int64)
            order = perms[c] if perms is not None else np.arange(len(ci))
            take = ci[np.asarray(order)][:cap]
            n = len(take)
            out_x[c, :n] = x2[take]
            out_y[c, :n] = y2[take]
            out_m[c, :n] = 1.0
    return (
        out_x.reshape((C, cap) + feat_shape),
        out_y.reshape((C, cap) + label_shape),
        out_m,
    )
