"""Centralized trainer: non-federated baseline runs over the same data plane.

Parity: reference ``python/fedml/centralized/centralized_trainer.py:9``
(``CentralizedTrainer`` — "train federated non-IID dataset in a centralized
way"; consumes the positional dataset tuple, runs plain epoch SGD, evals per
epoch). Redesign: the centralized baseline is the FL engine degenerated to
one client holding everything — ``data.load(centralized=True)`` puts every
sample on client 0 and one "round" of the compiled simulator is exactly one
centralized epoch, so the baseline shares the jitted hot loop, eval, and
metric plumbing instead of duplicating them.
"""

from __future__ import annotations

from typing import List


class CentralizedTrainer:
    """Reference-named facade over the one-client simulator."""

    def __init__(self, dataset=None, model=None, device=None, args=None):
        import copy
        import dataclasses

        from .simulation import build_simulator

        assert args is not None, "args required (fedml_tpu.init output)"
        # work on a copy — the caller's args must stay valid for federated
        # runs (and repeated centralized ones)
        args = copy.copy(args)
        args.centralized = True
        args.client_num_in_total = 1
        args.client_num_per_round = 1
        # one round == one epoch over the full dataset: epochs stays the
        # per-round epoch count (1), comm_round carries args.epochs
        epochs = int(getattr(args, "epochs", 1) or 1)
        args.comm_round = epochs
        args.epochs = 1
        self.args = args
        self.sim, self.apply_fn = build_simulator(args, fed_data=dataset,
                                                  model=model)
        # every "round" (= epoch) evaluates, like the reference's per-epoch
        # eval loop (centralized_trainer.py train/eval cadence)
        self.sim.cfg = dataclasses.replace(self.sim.cfg,
                                           frequency_of_the_test=1)

    def train(self) -> List[dict]:
        """Run the centralized epochs; returns per-epoch history records
        with train/test loss + accuracy."""
        return self.sim.run(self.apply_fn)

    @property
    def params(self):
        return self.sim.params


def run_centralized(args) -> List[dict]:
    """One-call centralized baseline (dataset/model from the factories)."""
    return CentralizedTrainer(args=args).train()
