"""Benchmark: FedAvg CIFAR-10 ResNet-56 rounds/sec (BASELINE.json north star).

Setup mirrors the reference MPI benchmark config (BENCHMARK_MPI.md: 100-client
pool, 10 clients/round, batch 64) with 1 local epoch per round. The reference
publishes no wall-clock numbers (BASELINE.md), so ``vs_baseline`` is reported
against a fixed denominator of 1.0 round/sec — a conservative stand-in for the
reference NCCL simulator per-round wall-clock at this workload — until a
reproduced reference run provides a real one.

Precision: bf16 compute / f32 params + f32 aggregation (standard TPU mixed
precision; the MXU natively runs bf16). Measured on the single v-chip:
fp32 0.685 rounds/sec -> bf16 3.40 rounds/sec (4.96x), with matching loss
trajectories at this scale.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json


def main() -> None:
    import jax

    import fedml_tpu
    from fedml_tpu.simulation import build_simulator

    rounds_timed = 5
    args = fedml_tpu.init(config=dict(
        dataset="cifar10", model="resnet56", partition_method="hetero",
        partition_alpha=0.5, client_num_in_total=100, client_num_per_round=10,
        comm_round=1 + rounds_timed, learning_rate=0.01, epochs=1,
        batch_size=64, frequency_of_the_test=10_000, random_seed=0,
        use_bf16=True,
    ))
    sim, apply_fn = build_simulator(args)

    # run all rounds; per-round wall-clock is recorded in history
    hist = sim.run(apply_fn=None, log_fn=None)
    # drop round 0 (compile) and average steady-state
    steady = [h["round_time"] for h in hist[1:]]
    rounds_per_sec = len(steady) / sum(steady)

    baseline_rounds_per_sec = 1.0  # see module docstring
    print(json.dumps({
        "metric": "fedavg_cifar10_resnet56_rounds_per_sec",
        "value": round(rounds_per_sec, 4),
        "unit": "rounds/sec (10 clients x 1 epoch x bs64 per round)",
        "vs_baseline": round(rounds_per_sec / baseline_rounds_per_sec, 4),
    }))


if __name__ == "__main__":
    main()
