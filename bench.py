"""Benchmark: FedAvg CIFAR-10 ResNet-56 rounds/sec (BASELINE.json north star).

Setup mirrors the reference MPI benchmark config (BENCHMARK_MPI.md: 100-client
pool, 10 clients/round, batch 64) with 1 local epoch per round.

Measurement protocol:
- a warm run over the SAME round range as a timed block pays compile +
  device-data upload (discarded) — sampling is round-indexed, so the warm
  run compiles exactly the cohort shapes the timed blocks will replay,
- then 5 independent timed runs ("blocks") of N rounds each (after one
  discarded burn-in block), measured
  WALL-TO-WALL around sim.run(): run() ends by materializing the final
  round's metric vector, whose value requires every dispatched executable
  to have retired — so the wall time is honest even on backends where
  block_until_ready is unreliable (the tunneled axon chip). The reported
  value is the MEDIAN block rate; the spread (max-min) is printed on stderr
  so one-shot flukes are visible.
- before timing, the forward computation is lowered and asserted to contain
  bf16 ops (mixed precision actually engaged, not just requested).

Availability: the tunneled backend can be transiently UNAVAILABLE (it was
at round-4 bench time, costing that round its number). Before importing
jax in this process, the backend is probed via
``fedml_tpu.utils.chip_probe`` (fresh subprocess per attempt — a failed
in-process init is cached by xla_bridge and unrecoverable; a CPU fallback
counts as failure so the bench never silently measures CPU). On final
failure the JSON line is still printed with an "error" field (value null)
so the driver artifact always parses.

Baseline denominator: the reference publishes no wall-clock numbers
(BASELINE.md). If ``BASELINE_LOCAL.json`` exists (produced by
``scripts/measure_reference_baseline.py`` — the reference's torch hot loop
timed on THIS machine's CPU at the same workload and extrapolated to a
round), its rounds/sec is used and the basis is echoed in the output line.
Otherwise vs_baseline falls back to a denominator of 1.0 round/sec with
basis "undocumented-1.0" — explicitly a placeholder, not a measurement.
"""

from __future__ import annotations

import json
import os
import sys
import time


def emit(value, vs_baseline, basis, error=None, candidate_errors=None,
         host_pack=None, telemetry=None) -> None:
    line = {
        "metric": "fedavg_cifar10_resnet56_rounds_per_sec",
        "value": value,
        "unit": ("rounds/sec (10 clients x 1 epoch x bs64 per round; "
                 f"baseline basis: {basis})"),
        "vs_baseline": vs_baseline,
    }
    if error is not None:
        line["error"] = error
    if candidate_errors:
        # a one-executor run is a DEGRADED measurement, not a clean A/B
        # win — automation must be able to tell them apart
        line["candidate_errors"] = {
            ("flat" if k else "tree"): v for k, v in candidate_errors.items()
        }
    if host_pack:
        # per-round host-packing attribution from the final timed block
        # (pack_time = build cost wherever it ran, pack_wait = round-loop
        # stall, overlap = fraction hidden behind earlier device work)
        line["host_pack"] = host_pack
    if telemetry:
        # phase breakdown + metrics-registry snapshot of the final timed
        # block (fedml_tpu.core.telemetry) — where the round wall went
        line["telemetry"] = telemetry
    print(json.dumps(line), flush=True)


def _host_pack_stats(history) -> dict:
    recs = [r for r in history if "pack_time" in r]
    if not recs:
        return {}
    mean = lambda k: sum(r[k] for r in recs) / len(recs)  # noqa: E731
    return {
        "pack_time_mean_s": round(mean("pack_time"), 6),
        "pack_wait_mean_s": round(mean("pack_wait"), 6),
        "overlap_mean": round(mean("overlap"), 4),
    }


def _phase_stats(history) -> dict:
    """Mean per-round phase attribution over a run's history: where the
    round wall-clock went (device wait vs dispatch vs eval vs host slack)
    and how much of it the named phases cover (coverage_frac ~1.0 — the
    accumulator is drained at the same stamp round_time is taken)."""
    recs = [r for r in history if r.get("phases")]
    if not recs:
        return {}
    acc: dict = {}
    for r in recs:
        for k, v in r["phases"].items():
            acc[k] = acc.get(k, 0.0) + v
    n = len(recs)
    round_mean = sum(r["round_time"] for r in recs) / n
    covered = sum(acc.values()) / n
    return {
        "round_time_mean_s": round(round_mean, 6),
        "coverage_frac": round(covered / round_mean, 4) if round_mean else None,
        "phase_breakdown_s": {k: round(v / n, 6) for k, v in acc.items()},
    }


def load_baseline() -> tuple[float, str]:
    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "BASELINE_LOCAL.json")
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            base = json.load(f)
        return float(base["rounds_per_sec"]), base.get("basis",
                                                       "BASELINE_LOCAL.json")
    return 1.0, "undocumented-1.0"


# one block = one sim.run() = this many rounds; _build's comm_round and
# the timed-block rate numerator must be THIS constant or the metric
# silently corrupts (the rate divides ROUNDS_PER_BLOCK by a run's wall)
ROUNDS_PER_BLOCK = 6


def _build(flat: bool):
    import jax
    import jax.numpy as jnp

    import fedml_tpu
    from fedml_tpu.simulation import build_simulator

    # Lane count pinned from on-chip sweeps (results/lane_sweep_r4.json,
    # superseding r3's grouped-conv theory): per-step cost scales ~linearly
    # with lane count under TREE carry (~2.2 ms per lane per step — per-op
    # latency across ~250+ small-shape ops dominates). Flat carry removes
    # the per-leaf cost, re-swept in results/lane_sweep_r5.json. Override
    # with FEDML_BENCH_LANES.
    lanes_env = os.environ.get("FEDML_BENCH_LANES", "2")
    args = fedml_tpu.init(config=dict(
        dataset="cifar10", model="resnet56", partition_method="hetero",
        partition_alpha=0.5, client_num_in_total=100, client_num_per_round=10,
        comm_round=ROUNDS_PER_BLOCK, learning_rate=0.01, epochs=1,
        batch_size=64, frequency_of_the_test=10_000, random_seed=0,
        use_bf16=True,
        packed_lanes=int(lanes_env) if lanes_env else None,
        packed_flat_carry=flat,
    ))
    sim, apply_fn = build_simulator(args)
    assert sim._use_device_data, "device-resident data path must engage"
    # Dirichlet alpha=0.5 client sizes are heavily skewed: the auto cohort
    # schedule must pick the packed-lane path (one program per round,
    # clients back-to-back in balanced lanes — 2.1x over bucketed)
    assert sim._packed, "packed cohort schedule must engage on skewed data"

    # mixed precision must actually engage: the lowered forward has bf16 ops
    x_probe = jnp.zeros((8, 32, 32, 3), jnp.float32)
    hlo = jax.jit(
        lambda p, x: apply_fn(p, x, train=True)
    ).lower(sim.params, x_probe).as_text()
    assert "bf16" in hlo, "bf16 requested but absent from lowered HLO"
    return sim


def _timed_block(sim, rounds_per_block: int) -> float:
    sim.history.clear()
    t0 = time.perf_counter()
    sim.run(apply_fn=None, log_fn=None)
    return rounds_per_block / (time.perf_counter() - t0)


def run_bench() -> tuple[float, dict, dict]:
    blocks, rounds_per_block = 5, ROUNDS_PER_BLOCK
    # Carry selection: flat carry (lane scan state as ONE ravelled vector)
    # won the on-chip per-step microbench 1.6x (results/lane_sweep_r4.json)
    # and is parity-exact vs tree (tests/test_packed_schedule.py), but the
    # end-to-end winner is measured, not assumed: warm both executors and
    # keep the faster one for the timed blocks. Schedule choice is the
    # framework's job — the metric is achievable rounds/sec.
    # FEDML_BENCH_FLAT={0,1} pins a carry and skips the A/B.
    forced = os.environ.get("FEDML_BENCH_FLAT", "")
    flats = ((forced == "1",) if forced in ("0", "1") else (True, False))
    cands, warm, errors = {}, {}, {}
    for flat in flats:
        # a candidate that fails to build/compile/run must not cost the
        # round its number while the other executor works — record the
        # error and measure the survivor (flat was chip-unvalidated when
        # this A/B landed; see results/chip_outage_r5.json)
        try:
            sim = _build(flat)
            sim.run(apply_fn=None, log_fn=None)   # compile + upload
            _timed_block(sim, rounds_per_block)   # burn-in (discarded)
            # decide on a MEDIAN of 3 warm blocks — one-shot block rates
            # fluke (that is why the timed phase prints its spread)
            rates = sorted(_timed_block(sim, rounds_per_block)
                           for _ in range(3))
        except Exception as e:  # noqa: BLE001
            errors[flat] = f"{type(e).__name__}: {e}"
            print(f"carry candidate flat={flat} FAILED: {errors[flat]}",
                  file=sys.stderr, flush=True)
            continue
        cands[flat] = sim
        warm[flat] = rates[1]
        print(f"warm blocks: flat={flat} {[round(r, 3) for r in rates]} "
              f"median={warm[flat]:.4f} r/s", file=sys.stderr, flush=True)
    if not cands:
        raise RuntimeError(f"every carry candidate failed: {errors}")
    flat = max(warm, key=warm.get)
    sim = cands.pop(flat)
    cands.clear()  # drop the loser's device-resident data before timing
    print(f"carry selected: {'flat' if flat else 'tree'}",
          file=sys.stderr, flush=True)

    from fedml_tpu.core import telemetry as _telemetry

    _telemetry.get_registry().reset()  # snapshot covers the timed blocks only
    block_rates = sorted(
        _timed_block(sim, rounds_per_block) for _ in range(blocks))
    rounds_per_sec = block_rates[len(block_rates) // 2]
    spread = block_rates[-1] - block_rates[0]
    print(
        f"block rates: {[round(r, 3) for r in block_rates]} "
        f"median={rounds_per_sec:.4f} spread={spread:.4f}",
        file=sys.stderr,
    )
    telemetry_stats = {
        **_phase_stats(sim.history),
        "registry": _telemetry.get_registry().snapshot(),
    }
    # history of the LAST timed block (each block clears it first)
    return (rounds_per_sec, errors, _host_pack_stats(sim.history),
            telemetry_stats)


def main() -> int:
    from fedml_tpu.utils.chip_probe import wait_for_chip

    try:
        baseline, basis = load_baseline()
        if baseline <= 0:
            raise ValueError(f"non-positive baseline {baseline}")
    except Exception as e:  # noqa: BLE001 — never lose the JSON line
        baseline, basis = 1.0, f"undocumented-1.0 (baseline unreadable: {e})"
    ok, detail = wait_for_chip(
        attempts=5, sleep_s=90.0,
        log=lambda m: print(f"bench {m}", file=sys.stderr, flush=True))
    if not ok:
        emit(None, None, basis,
             error=f"backend unavailable after bounded retries ({detail})")
        return 1
    try:
        rounds_per_sec, candidate_errors, host_pack, telem = run_bench()
    except Exception as e:  # noqa: BLE001 — driver artifact must parse
        emit(None, None, basis, error=f"{type(e).__name__}: {e}")
        return 1
    emit(round(rounds_per_sec, 4), round(rounds_per_sec / baseline, 4), basis,
         candidate_errors=candidate_errors, host_pack=host_pack,
         telemetry=telem)
    return 0


def host_pack_bench(rounds: int = 20) -> int:
    """``--host-pack``: CPU-only micro-mode isolating the per-round HOST
    packing cost of the packed schedule (100-client Dirichlet cohort, full
    participation). Times the vectorized builder (cohort-level pack + cached
    lane plan + native row gather) against the pre-pipeline per-client loop
    on identical inputs — the builders are bit-exact (tests/test_prefetch.py)
    so this is a pure like-for-like host cost A/B. No chip probe: the win is
    measurable wherever python runs, which is the point (the device never
    waits on a host that packs ahead). Also runs a short prefetch-on block
    and reports the recorded overlap fraction."""
    import numpy as np

    import fedml_tpu
    from fedml_tpu.simulation import build_simulator
    from fedml_tpu.simulation.fed_sim import reference_client_sampling

    args = fedml_tpu.init(config=dict(
        dataset="cifar10", model="lr", partition_method="hetero",
        partition_alpha=0.5, client_num_in_total=100,
        client_num_per_round=100, comm_round=4, learning_rate=0.05,
        epochs=1, batch_size=16, frequency_of_the_test=10_000,
        random_seed=0, debug_small_data=True, cohort_schedule="packed",
    ))
    sim, _ = build_simulator(args)
    assert sim._packed, "packed cohort schedule must engage"
    cfg = sim.cfg
    cohorts = [
        np.asarray(reference_client_sampling(
            r, cfg.client_num_in_total, cfg.client_num_per_round))
        for r in range(rounds)
    ]
    # steady state on both sides: lane-plan cache warm for the new builder
    # (the loop has no cache to warm — it redoes everything every round)
    sim._build_packed_inputs(cohorts[0], 0, None)
    t_new, t_old = [], []
    for r, ci in enumerate(cohorts):
        t0 = time.perf_counter()
        sim._build_packed_inputs(ci, r, None)
        t_new.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        sim._build_packed_inputs_loop(ci, r, None)
        t_old.append(time.perf_counter() - t0)
    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    new_s, old_s = med(t_new), med(t_old)
    hist = sim.run(apply_fn=None, log_fn=None)  # prefetch defaults on
    overlap = _host_pack_stats(hist)
    line = {
        "metric": "host_pack_packed_round_build_seconds",
        "unit": ("median s/round host packing, 100-client Dirichlet(0.5) "
                 "cohort, packed schedule, full participation"),
        "value": round(new_s, 6),
        "loop_baseline": round(old_s, 6),
        "speedup": round(old_s / new_s, 2) if new_s > 0 else None,
        **({"host_pack": overlap} if overlap else {}),
    }
    print(json.dumps(line), flush=True)
    ok = new_s > 0 and old_s / new_s >= 2.0 and \
        overlap.get("overlap_mean", 0.0) > 0.0
    print(f"host-pack: new={new_s * 1e3:.2f}ms loop={old_s * 1e3:.2f}ms "
          f"speedup={old_s / new_s:.2f}x "
          f"overlap_mean={overlap.get('overlap_mean')} "
          f"{'OK' if ok else 'BELOW TARGET'}", file=sys.stderr, flush=True)
    return 0 if ok else 1


def telemetry_overhead_bench(rounds: int = 20, trials: int = 3,
                             threshold: float = 0.01) -> int:
    """``--telemetry-overhead``: CPU-only guard for the telemetry cost
    budget (ISSUE: enabled-vs-disabled delta < 1% of round wall-clock).
    One simulator, interleaved enabled/disabled 20-round blocks (interleaving
    cancels thermal/allocator drift), compared on MIN wall per arm — min is
    the noise-robust estimator for a lower-bounded cost. Also asserts the
    per-round phase breakdown covers round_time within 5%."""
    import fedml_tpu
    from fedml_tpu.core import telemetry, trace_plane
    from fedml_tpu.simulation import build_simulator

    args = fedml_tpu.init(config=dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=20, client_num_per_round=10, comm_round=rounds,
        learning_rate=0.1, epochs=1, batch_size=8,
        frequency_of_the_test=10_000, random_seed=0,
    ))
    sim, _ = build_simulator(args)
    sim.run(apply_fn=None, log_fn=None)  # compile warm-up (discarded)

    def _block(enabled: bool) -> float:
        telemetry.configure(enabled=enabled)
        # the <1% budget must hold with the full trace plane armed, not
        # just the PR 2 metrics layer (ISSUE 10 acceptance)
        trace_plane.configure(ship_spans=enabled, anomaly_detection=enabled,
                              flight_recorder=enabled)
        sim.history.clear()
        t0 = time.perf_counter()
        sim.run(apply_fn=None, log_fn=None)
        return time.perf_counter() - t0

    walls = {True: [], False: []}
    for _ in range(trials):
        for enabled in (True, False):
            walls[enabled].append(_block(enabled))
    on, off = min(walls[True]), min(walls[False])
    overhead = (on - off) / off if off > 0 else 0.0
    # phase coverage from the last ENABLED block's history
    telemetry.configure(enabled=True)
    trace_plane.configure(ship_spans=True, anomaly_detection=True,
                          flight_recorder=True)
    sim.history.clear()
    sim.run(apply_fn=None, log_fn=None)
    trace_plane.reset()
    phases = _phase_stats(sim.history)
    cov = phases.get("coverage_frac") or 0.0
    cov_ok = abs(cov - 1.0) <= 0.05
    ok = overhead < threshold and cov_ok
    line = {
        "metric": "telemetry_overhead_frac",
        "unit": (f"(min wall enabled - disabled)/disabled over {trials}x"
                 f"{rounds}-round interleaved CPU blocks; budget <"
                 f" {threshold}"),
        "value": round(overhead, 5),
        "wall_enabled_s": round(on, 4),
        "wall_disabled_s": round(off, 4),
        "phase_coverage_frac": cov,
        "telemetry": phases,
    }
    print(json.dumps(line), flush=True)
    print(f"telemetry-overhead: {overhead * 100:.3f}% (budget "
          f"{threshold * 100:.0f}%) phase_coverage={cov} "
          f"{'OK' if ok else 'OVER BUDGET' if cov_ok else 'COVERAGE GAP'}",
          file=sys.stderr, flush=True)
    return 0 if ok else 1


def cohort_sweep_bench(sizes=(10, 100, 1000, 10000), pool: int = 20000,
                       warmup_rounds: int = 2, measured_rounds: int = 3) -> int:
    """``--cohort-sweep``: CPU-only scaling sweep of the arena-backed round
    loop over sampled cohort sizes (10/100/1k/10k from a 20k-client pool,
    SCAFFOLD so every round exercises the client-state gather/scatter path).
    Synthetic separable 2-class blobs keep the per-client work constant so
    the sweep isolates cohort-axis scaling: per size it reports rounds/sec
    plus the per-round phase breakdown (state_gather / state_scatter now
    attributed) and checks the named phases + host_other sum to round_time.
    Gate: the 10k-cohort sampled round must clear 1 round/sec."""
    import math

    import numpy as np

    import fedml_tpu
    from fedml_tpu.data.federated import ArrayPair, build_federated_data
    from fedml_tpu.simulation import build_simulator

    spc, dim, class_num = 8, 16, 2
    rng = np.random.default_rng(0)
    n = pool * spc
    y = (np.arange(n) % class_num).astype(np.int64)
    x = rng.normal(size=(n, dim)).astype(np.float32) \
        + 2.0 * y[:, None].astype(np.float32)
    net_map = {c: list(range(c * spc, (c + 1) * spc)) for c in range(pool)}
    fed = build_federated_data(
        ArrayPair(x, y), ArrayPair(x[:64], y[:64]), net_map, class_num)

    results = []
    for per_round in sizes:
        args = fedml_tpu.init(config=dict(
            dataset="synthetic_blobs", model="lr",
            client_num_in_total=pool, client_num_per_round=int(per_round),
            comm_round=warmup_rounds + measured_rounds,
            learning_rate=0.1, epochs=1, batch_size=spc,
            frequency_of_the_test=10_000, random_seed=0,
            federated_optimizer="SCAFFOLD",
            # synchronous rounds: with the prefetch pipeline on, round r+1's
            # host work lands in round r's drain window and the per-round
            # phase breakdown can exceed that round's wall; sync mode keeps
            # every phase inside its own round so the sum is exact
            prefetch=False,
        ))
        sim, _ = build_simulator(args, fed_data=fed)
        assert sim._arena is not None, "sweep must run the arena backend"
        hist = sim.run(apply_fn=None, log_fn=None)
        recs = hist[warmup_rounds:]
        wall = sum(r["round_time"] for r in recs)
        acc: dict = {}
        sums_ok = True
        for r in recs:
            ps = r["phases"]
            # host_other is computed as the exact remainder at drain time,
            # so the breakdown must reproduce round_time to float precision
            sums_ok = sums_ok and math.isclose(
                sum(ps.values()), r["round_time"],
                rel_tol=1e-6, abs_tol=1e-9)
            for k, v in ps.items():
                acc[k] = acc.get(k, 0.0) + v
        results.append({
            "cohort": int(per_round),
            "rounds_per_sec": round(measured_rounds / wall, 4) if wall else None,
            "phase_breakdown_s": {
                k: round(v / measured_rounds, 6) for k, v in sorted(acc.items())},
            "phase_sum_equals_round_time": bool(sums_ok),
            "state_phases_present": bool(
                "state_gather" in acc and "state_scatter" in acc),
        })
        print(f"cohort-sweep: cohort={per_round} "
              f"rounds_per_sec={results[-1]['rounds_per_sec']}",
              file=sys.stderr, flush=True)
    by_cohort = {r["cohort"]: r for r in results}
    pass_10k = (by_cohort.get(10000, {}).get("rounds_per_sec") or 0.0) > 1.0
    all_sums = all(r["phase_sum_equals_round_time"] for r in results)
    all_state = all(r["state_phases_present"] for r in results)
    line = {
        "metric": "cohort_sweep_rounds_per_sec",
        "unit": (f"rounds/sec per sampled cohort size, SCAFFOLD lr on "
                 f"synthetic blobs ({pool}-client pool, {spc} samples x "
                 f"dim {dim} each), arena client-state backend, CPU"),
        "results": results,
        "pass_10k_above_1rps": bool(pass_10k),
        "phase_sums_exact": bool(all_sums),
    }
    print(json.dumps(line), flush=True)
    ok = pass_10k and all_sums and all_state
    print(f"cohort-sweep: 10k>1r/s={pass_10k} phase_sums_exact={all_sums} "
          f"state_phases={all_state} {'OK' if ok else 'BELOW TARGET'}",
          file=sys.stderr, flush=True)
    return 0 if ok else 1


def agg_sweep_bench(cohorts=(1000, 10000), codecs=("none", "q4"),
                    defenses=("krum",), pool: int = 12000,
                    warmup_rounds: int = 1, measured_rounds: int = 2) -> int:
    """``--agg-sweep``: robust-aggregation frontier — defense x codec x
    cohort, each cell run with ``agg_kernels`` off (the unfused programs)
    and on (the fused quantize+pack / sanitize+Krum hot path), reporting
    rounds/sec, the exact per-phase attribution, and the codec's wire
    bytes per round (``spec_wire_nbytes`` x cohort). A second block
    measures the double-buffered arena movement: the residual
    ``state_gather + state_scatter`` cost under the prefetch pipeline
    (where ``put_take`` fuses scatter-back with the next round's gather,
    stamped ``state_move``) against the unoverlapped cost of the
    synchronous path.

    Gates: every phase breakdown must sum exactly to its round's wall
    time; the overlapped gather+scatter residual must be <= 20% of the
    unoverlapped cost; and on TPU (where the Pallas kernels engage — on
    CPU they fall back to the bit-identical jnp references, so the fused
    path's arithmetic is the same XLA code) the 10k-cohort krum+q4 cell
    must clear 2x the unfused rounds/sec."""
    import math

    import numpy as np

    import jax
    import fedml_tpu
    from fedml_tpu.comm.codec import spec_wire_nbytes
    from fedml_tpu.data.federated import ArrayPair, build_federated_data
    from fedml_tpu.simulation import build_simulator

    # dim 64 keeps the lr weight leaf above the codec's _MIN_LEAF
    # compressibility floor, so the wire-byte column actually shrinks
    # under q4 instead of every leaf riding raw
    spc, dim, class_num = 8, 64, 2
    rng = np.random.default_rng(0)
    n = pool * spc
    y = (np.arange(n) % class_num).astype(np.int64)
    x = rng.normal(size=(n, dim)).astype(np.float32) \
        + 2.0 * y[:, None].astype(np.float32)
    net_map = {c: list(range(c * spc, (c + 1) * spc)) for c in range(pool)}
    fed = build_federated_data(
        ArrayPair(x, y), ArrayPair(x[:64], y[:64]), net_map, class_num)

    def _run_cell(per_round, defense, codec, kernels):
        cfg = dict(
            dataset="synthetic_blobs", model="lr",
            client_num_in_total=pool, client_num_per_round=int(per_round),
            comm_round=warmup_rounds + measured_rounds,
            learning_rate=0.1, epochs=1, batch_size=spc,
            frequency_of_the_test=10_000, random_seed=0,
            federated_optimizer="FedAvg",
            defense_type=defense, byzantine_n=2,
            sanitize_updates=True,
            agg_kernels=bool(kernels),
            # synchronous rounds keep every phase inside its own round so
            # the breakdown sums are exact (see cohort_sweep_bench)
            prefetch=False,
        )
        if codec != "none":
            cfg["comm_codec"] = codec
        args = fedml_tpu.init(config=cfg)
        sim, _ = build_simulator(args, fed_data=fed)
        # shape/dtype template for the wire-byte estimate — the live params
        # are donated into the round step, so snapshot before run()
        params = jax.tree_util.tree_map(
            lambda l: np.zeros(l.shape, l.dtype), sim.params)
        hist = sim.run(apply_fn=None, log_fn=None)
        recs = hist[warmup_rounds:]
        wall = sum(r["round_time"] for r in recs)
        acc, sums_ok = {}, True
        for r in recs:
            ps = r["phases"]
            sums_ok = sums_ok and math.isclose(
                sum(ps.values()), r["round_time"],
                rel_tol=1e-6, abs_tol=1e-9)
            for k, v in ps.items():
                acc[k] = acc.get(k, 0.0) + v
        return params, {
            "rounds_per_sec": round(measured_rounds / wall, 4) if wall else None,
            "phase_breakdown_s": {
                k: round(v / measured_rounds, 6) for k, v in sorted(acc.items())},
            "phase_sum_equals_round_time": bool(sums_ok),
        }

    results = []
    for per_round in cohorts:
        for defense in defenses:
            for codec in codecs:
                params, unfused = _run_cell(per_round, defense, codec, False)
                _, fused = _run_cell(per_round, defense, codec, True)
                raw_pc, coded_pc = (
                    spec_wire_nbytes(codec, params) if codec != "none"
                    else ((lambda b: (b, b))(sum(
                        np.asarray(l).nbytes
                        for l in jax.tree_util.tree_leaves(params)))))
                ru, rf = unfused["rounds_per_sec"], fused["rounds_per_sec"]
                cell = {
                    "cohort": int(per_round), "defense": defense,
                    "codec": codec,
                    "wire_bytes_per_round": int(coded_pc) * int(per_round),
                    "raw_bytes_per_round": int(raw_pc) * int(per_round),
                    "unfused": unfused, "fused": fused,
                    "speedup_fused_over_unfused": (
                        round(rf / ru, 3) if ru and rf else None),
                }
                results.append(cell)
                print(f"agg-sweep: cohort={per_round} defense={defense} "
                      f"codec={codec} unfused={ru} fused={rf} r/s",
                      file=sys.stderr, flush=True)

    # --- double-buffered state movement: residual gather+scatter under the
    # prefetch pipeline vs the unoverlapped synchronous cost (SCAFFOLD so
    # every round moves real per-client arena state)
    state_cohort = min(1000, pool)

    def _state_run(prefetch, rounds=10):
        args = fedml_tpu.init(config=dict(
            dataset="synthetic_blobs", model="lr",
            client_num_in_total=pool, client_num_per_round=state_cohort,
            comm_round=rounds, learning_rate=0.1, epochs=1, batch_size=spc,
            frequency_of_the_test=10_000, random_seed=0,
            federated_optimizer="SCAFFOLD", prefetch=bool(prefetch),
            # full-pool capacity isolates the overlap mechanism from the
            # eviction policy: under capacity pressure put_take protect-
            # aborts (by design) and the run degenerates to the sync path
            client_state_capacity=pool,
        ))
        sim, _ = build_simulator(args, fed_data=fed)
        hist = sim.run(apply_fn=None, log_fn=None)
        # Window: skip the compile-heavy first rounds AND the last TWO
        # records — the final round has no successor so it scatters
        # synchronously, and under the deferred-readback attribution that
        # scatter lands in the second-to-last record. Per-phase MEDIAN, not
        # mean: a peek-miss round falls back to the sync scatter, and its
        # first use mid-run pays a one-time compile spike that would
        # otherwise dominate a short window; the gate is about the
        # recurring steady-state residual.
        recs = hist[3:-2]
        keys = {k for r in recs for k in r["phases"]}
        med = {}
        for k in keys:
            vals = sorted(r["phases"].get(k, 0.0) for r in recs)
            med[k] = vals[len(vals) // 2] if vals else 0.0
        engaged = sum(1 for r in recs if r["phases"].get("state_move", 0.0) > 0)
        return med, engaged, len(recs)

    sync_ph, _, _ = _state_run(False)
    pipe_ph, engaged_rounds, window_rounds = _state_run(True)
    unoverlapped = sync_ph.get("state_gather", 0.0) \
        + sync_ph.get("state_scatter", 0.0)
    residual = pipe_ph.get("state_gather", 0.0) \
        + pipe_ph.get("state_scatter", 0.0)
    ratio = (residual / unoverlapped) if unoverlapped > 0 else None
    overlap_pass = (ratio is not None and ratio <= 0.20
                    and engaged_rounds > 0)
    state_move = {
        "cohort": state_cohort,
        "unoverlapped_gather_scatter_s": round(unoverlapped, 6),
        "overlapped_residual_s": round(residual, 6),
        "state_move_s": round(pipe_ph.get("state_move", 0.0), 6),
        "engaged_rounds": f"{engaged_rounds}/{window_rounds}",
        "residual_ratio": round(ratio, 4) if ratio is not None else None,
        "pass_le_20pct": bool(overlap_pass),
    }

    backend = jax.default_backend()
    target = next((c for c in results
                   if c["cohort"] == 10000 and c["defense"] == "krum"
                   and c["codec"] == "q4"), None)
    speedup = (target or {}).get("speedup_fused_over_unfused")
    speedup_pass = speedup is not None and speedup >= 2.0
    all_sums = all(c["unfused"]["phase_sum_equals_round_time"]
                   and c["fused"]["phase_sum_equals_round_time"]
                   for c in results)
    line = {
        "metric": "agg_sweep_robust_frontier",
        "unit": (f"rounds/sec per (defense, codec, cohort) cell, FedAvg lr "
                 f"on synthetic blobs ({pool}-client pool, {spc} samples x "
                 f"dim {dim}), sanitizer on, agg_kernels off vs on, "
                 f"sync rounds; state-move block: SCAFFOLD cohort 1000, "
                 f"prefetch off vs on"),
        "backend": backend,
        "results": results,
        "state_move_overlap": state_move,
        "speedup_10k_krum_q4": speedup,
        "pass_10k_krum_q4_2x": bool(speedup_pass),
        "phase_sums_exact": bool(all_sums),
    }
    print(json.dumps(line), flush=True)
    # the 2x gate is a TPU gate: on CPU the Pallas kernels deliberately
    # fall back to the bit-identical jnp references (interpret mode exists
    # for parity testing, not speed), so fused == unfused arithmetic there
    ok = all_sums and overlap_pass and (speedup_pass or backend != "tpu")
    print(f"agg-sweep: phase_sums_exact={all_sums} "
          f"overlap_ratio={state_move['residual_ratio']} "
          f"(pass<=20%={overlap_pass}) 10k-krum-q4-speedup={speedup} "
          f"(backend={backend}) {'OK' if ok else 'BELOW TARGET'}",
          file=sys.stderr, flush=True)
    return 0 if ok else 1


def round_scan_bench(cohorts=(1000, 10000), scan_rs=(1, 2, 8, 32),
                     pool: int = 12000, measured_blocks: int = 2,
                     out_path: str = "BENCH_r09.json") -> int:
    """``--round-scan``: compiled multi-round dispatch sweep — rounds/sec
    per (cohort, rounds_per_dispatch) cell on the BENCH_r07 10k workload
    (FedAvg lr on synthetic blobs, sanitizer on, krum cell config), with
    the exact per-phase attribution asserted per round. The R=1 cell runs
    the classic per-round engine with prefetch off — the same protocol
    BENCH_r07's 6.92 r/s sync krum/none baseline used — so the speedup
    column is like for like.

    Two findings ride in the JSON: ``glue_s_per_round`` (pack_wait +
    scan_pack + host_other, the host-orchestration cost the scan
    amortizes — ~137 ms/round in BENCH_r07, sub-millisecond at R>=8) and
    a note that r07's ~50 us ``device`` phase was an async-dispatch
    measurement artifact: with the host glue gone, the round's genuine
    XLA compute (local-training GEMMs + gather + sanitize) is exposed as
    the new floor, so single-core CPU speedup saturates well below the
    glue-amortization factor."""
    import math

    import numpy as np

    import jax
    import fedml_tpu
    from fedml_tpu.data.federated import ArrayPair, build_federated_data
    from fedml_tpu.simulation import build_simulator

    spc, dim, class_num = 8, 64, 2
    rng = np.random.default_rng(0)
    n = pool * spc
    y = (np.arange(n) % class_num).astype(np.int64)
    x = rng.normal(size=(n, dim)).astype(np.float32) \
        + 2.0 * y[:, None].astype(np.float32)
    net_map = {c: list(range(c * spc, (c + 1) * spc)) for c in range(pool)}
    fed = build_federated_data(
        ArrayPair(x, y), ArrayPair(x[:64], y[:64]), net_map, class_num)

    def _run_cell(per_round, scan_r):
        # no apply_fn and an out-of-range eval frequency → no hook cuts, so
        # the plan is pure R-blocks; a round count that is an exact multiple
        # of R avoids a short tail block (which would compile a second
        # program inside the measured window). Skip the first block — it
        # carries the one compile — and measure the steady-state blocks.
        warmup = scan_r
        rounds = scan_r * (1 + measured_blocks)
        args = fedml_tpu.init(config=dict(
            dataset="synthetic_blobs", model="lr",
            client_num_in_total=pool, client_num_per_round=int(per_round),
            comm_round=rounds, learning_rate=0.1, epochs=1, batch_size=spc,
            frequency_of_the_test=10_000, random_seed=0,
            federated_optimizer="FedAvg",
            defense_type="krum", byzantine_n=2,
            sanitize_updates=True,
            rounds_per_dispatch=int(scan_r),
            # R=1 replays BENCH_r07's sync protocol exactly; fused blocks
            # run with the block prefetcher engaged (its intended mode)
            prefetch=scan_r > 1,
        ))
        sim, _ = build_simulator(args, fed_data=fed)
        hist = sim.run(apply_fn=None, log_fn=None)
        recs = hist[warmup:]
        wall = sum(r["round_time"] for r in recs)
        acc, sums_ok = {}, True
        for r in recs:
            ps = r["phases"]
            sums_ok = sums_ok and math.isclose(
                sum(ps.values()), r["round_time"],
                rel_tol=1e-6, abs_tol=1e-9)
            for k, v in ps.items():
                acc[k] = acc.get(k, 0.0) + v
        per = {k: v / len(recs) for k, v in acc.items()}
        glue = per.get("pack_wait", 0.0) + per.get("scan_pack", 0.0) \
            + per.get("host_other", 0.0)
        return {
            "cohort": int(per_round),
            "rounds_per_dispatch": int(scan_r),
            "measured_rounds": len(recs),
            "rounds_per_sec": round(len(recs) / wall, 4) if wall else None,
            "glue_s_per_round": round(glue, 6),
            "phase_breakdown_s": {k: round(v, 6)
                                  for k, v in sorted(per.items())},
            "phase_sum_equals_round_time": bool(sums_ok),
        }

    try:
        with open("BENCH_r07.json") as f:
            r07 = json.load(f)
        base = next(c["unfused"]["rounds_per_sec"] for c in r07["results"]
                    if c["cohort"] == 10000 and c["defense"] == "krum"
                    and c["codec"] == "none")
    except Exception:  # noqa: BLE001 — missing artifact must not kill the run
        base = None

    results = []
    for per_round in cohorts:
        for scan_r in scan_rs:
            cell = _run_cell(per_round, scan_r)
            results.append(cell)
            print(f"round-scan: cohort={per_round} R={scan_r} "
                  f"{cell['rounds_per_sec']} r/s "
                  f"glue={cell['glue_s_per_round'] * 1e3:.2f} ms/round "
                  f"sums_exact={cell['phase_sum_equals_round_time']}",
                  file=sys.stderr, flush=True)

    all_sums = all(c["phase_sum_equals_round_time"] for c in results)
    best_10k = max((c["rounds_per_sec"] or 0.0) for c in results
                   if c["cohort"] == 10000 and c["rounds_per_dispatch"] >= 8)
    speedup = round(best_10k / base, 3) if base else None
    r1_10k = next((c for c in results if c["cohort"] == 10000
                   and c["rounds_per_dispatch"] == 1), None)
    line = {
        "metric": "round_scan_dispatch",
        "unit": (f"rounds/sec per (cohort, rounds_per_dispatch) cell, "
                 f"FedAvg lr on synthetic blobs ({pool}-client pool, "
                 f"{spc} samples x dim {dim}), sanitizer on, BENCH_r07 "
                 f"krum/none cell protocol; R=1 sync prefetch-off"),
        "backend": jax.default_backend(),
        "results": results,
        "baseline_r07_10k_rounds_per_sec": base,
        "speedup_10k_scan_vs_r07": speedup,
        "glue_amortized_10k_s": (r1_10k or {}).get("glue_s_per_round"),
        "phase_sums_exact": bool(all_sums),
        "note": ("BENCH_r07's ~50us 'device' phase was an async-dispatch "
                 "artifact: XLA round compute hid inside pack_wait's "
                 "timeslices. With packing device-side and host glue "
                 "amortized over the block, the genuine per-round XLA "
                 "compute (local-update GEMMs + data gather + sanitize) "
                 "is the exposed floor, so rounds/sec saturates at that "
                 "floor on a single-core CPU host."),
    }
    print(json.dumps(line), flush=True)
    try:
        with open(out_path, "w") as f:
            json.dump(line, f, indent=1)
            f.write("\n")
    except OSError as e:
        print(f"round-scan: could not write {out_path}: {e}",
              file=sys.stderr, flush=True)
    print(f"round-scan: phase_sums_exact={all_sums} "
          f"best_10k_scan={best_10k} r/s vs r07 {base} "
          f"(speedup={speedup}) -> {out_path}",
          file=sys.stderr, flush=True)
    return 0 if all_sums else 1


def model_sweep_bench(model_axes=(1, 2, 4), rounds: int = 3) -> int:
    """``--model-sweep``: CPU-only memory-scaling sweep of the 2-D federated
    mesh — the same SCAFFOLD mnist/lr round loop on a fixed client axis (2)
    while the model axis grows 1 → 2 → 4. Per mesh it reports the per-device
    peak HBM from ``device.memory_stats()`` when the backend provides it
    (TPU), falling back to the per-device RESIDENT bytes of the persistent
    round state (params + server opt-state + client-state arena + EF
    residuals, summed over ``addressable_shards``) on backends that return
    None (CPU). Gate: peak per-device footprint must scale ≈1/model_axis
    (within 25% — small replicated-fallback leaves dilute the ratio)."""
    import numpy as np

    import jax
    import fedml_tpu
    from fedml_tpu.parallel.mesh import (AXIS_CLIENT, AXIS_MODEL, MeshConfig,
                                         create_mesh)
    from fedml_tpu.simulation import build_simulator

    devs = jax.devices()
    results = []
    for m in model_axes:
        if 2 * m > len(devs):
            print(f"model-sweep: skipping model_axis={m} "
                  f"(needs {2 * m} devices, have {len(devs)})",
                  file=sys.stderr, flush=True)
            continue
        axes = ((AXIS_CLIENT, 2),)
        if m > 1:
            axes += ((AXIS_MODEL, m),)
        mesh = create_mesh(MeshConfig(axes=axes), devices=devs[:2 * m])
        args = fedml_tpu.init(config=dict(
            dataset="mnist", model="lr", debug_small_data=True,
            client_num_in_total=12, client_num_per_round=4,
            comm_round=rounds, learning_rate=0.1, epochs=1, batch_size=32,
            frequency_of_the_test=10_000, random_seed=0,
            federated_optimizer="SCAFFOLD", prefetch=False,
        ))
        sim, _ = build_simulator(args, mesh=mesh)
        t0 = time.perf_counter()
        sim.run(apply_fn=None, log_fn=None)
        wall = time.perf_counter() - t0
        # resident persistent state per device: every leaf the round loop
        # keeps alive between rounds, attributed to the device holding each
        # shard — this is the footprint the model axis divides
        trees = [sim.params, sim.server_state]
        if sim._arena is not None:
            trees.append(list(sim._arena._leaves))
        if sim._codec_arena is not None:
            trees.append(list(sim._codec_arena._leaves))
        resident = {}
        for leaf in jax.tree.leaves(trees):
            for shd in leaf.addressable_shards:
                key = str(shd.device)
                resident[key] = resident.get(key, 0) + int(shd.data.nbytes)
        peaks, source = {}, "memory_stats.peak_bytes_in_use"
        for d in mesh.devices.flat:
            try:
                stats = d.memory_stats() or {}
            except Exception:
                stats = {}
            pk = stats.get("peak_bytes_in_use")
            if pk is not None:
                peaks[str(d)] = int(pk)
        if not peaks:
            # CPU backend: memory_stats() is None — fall back to the
            # resident-state accounting so the sweep stays meaningful
            peaks, source = dict(resident), "resident_state_bytes"
        results.append({
            "model_axis": int(m),
            "devices": int(2 * m),
            "rounds_per_sec": round(rounds / wall, 4) if wall else None,
            "hbm_source": source,
            "peak_bytes_per_device": {k: peaks[k] for k in sorted(peaks)},
            "peak_bytes_max": int(max(peaks.values())),
            "resident_state_bytes_max": int(max(resident.values())),
        })
        print(f"model-sweep: model_axis={m} "
              f"peak_max={results[-1]['peak_bytes_max']}B "
              f"({source})", file=sys.stderr, flush=True)
    by_axis = {r["model_axis"]: r for r in results}
    base = by_axis.get(1)
    scaling_ok = base is not None
    for r in results:
        if base is None or r["model_axis"] == 1:
            continue
        want = base["resident_state_bytes_max"] / r["model_axis"]
        got = r["resident_state_bytes_max"]
        r["scaling_vs_model_axis_1"] = round(
            base["resident_state_bytes_max"] / got, 3) if got else None
        if not (got <= want * 1.25):
            scaling_ok = False
    line = {
        "metric": "model_sweep_peak_hbm_bytes",
        "unit": ("peak per-device bytes vs model-axis size (client axis 2, "
                 "SCAFFOLD mnist/lr, arena client-state backend; hbm_source "
                 "says whether the backend reported memory_stats or the "
                 "resident-state fallback was used)"),
        "results": results,
        "pass_scales_inverse_model_axis": bool(scaling_ok),
    }
    print(json.dumps(line), flush=True)
    print(f"model-sweep: inverse-scaling={'OK' if scaling_ok else 'FAIL'}",
          file=sys.stderr, flush=True)
    return 0 if scaling_ok else 1


def chaos_bench(seed: int = 7) -> int:
    """``--chaos``: CPU-only robustness gate — a full loopback cross-silo
    deployment under a seeded fault plan (message drops + injected transient
    send failures + one client crash) must still complete every round. Same
    drill as ``fedml-tpu chaos-drill`` / tests/test_chaos.py; the JSON line
    reports rounds completed, wall time, and resilience-plane counters."""
    from fedml_tpu.cross_silo.chaos import run_chaos_drill

    result = run_chaos_drill(
        fault_seed=seed, fault_drop_rate=0.2, fault_fail_send_rate=0.2,
        fault_crash_rank=3, fault_crash_at_round=1,
    )
    line = {
        "metric": "chaos_drill_rounds_completed",
        "unit": (f"rounds closed under seeded faults (seed={seed}, drop 20%, "
                 "fail-send 20%, rank-3 crash at round 1) / rounds expected"),
        **result.json_record(),
    }
    print(json.dumps(line), flush=True)
    print(result.summary(), file=sys.stderr, flush=True)
    if not result.ok:
        return 1

    # second scenario: byzantine NaN uploads against the self-healing plane —
    # the sanitizer must quarantine the corrupted silo every round and the
    # run must stay finite and close every round
    byz = run_chaos_drill(
        fault_seed=seed, fault_byzantine_kind="nan",
        fault_byzantine_ranks=[2], sanitize_updates=True,
        local_test_on_all_clients=True, fault_drop_rate=0.0,
    )
    last_loss = (byz.history[-1].get("local_train_loss")
                 if byz.history else None)
    finite = last_loss is not None and last_loss == last_loss  # not NaN
    byz_ok = byz.ok and byz.quarantined > 0 and finite
    line = {
        "metric": "chaos_byzantine_quarantined",
        "unit": (f"sanitizer quarantine hits under NaN uploads from rank 2 "
                 f"(seed={seed}); run must close finite"),
        **byz.json_record(),
        "final_local_train_loss": (round(last_loss, 4)
                                   if finite else "non-finite"),
    }
    print(json.dumps(line), flush=True)
    print(byz.summary(), file=sys.stderr, flush=True)
    if not byz_ok:
        return 1

    # third + fourth scenarios: the hierarchical-federation failure domain —
    # a leaf aggregator killed mid-generation (its shard rehydrates on a
    # survivor) and a root<->leaf partition that heals after one round
    # window. Both gate exactly-once commits and accuracy against the
    # fault-free single-process reference.
    from fedml_tpu.cross_silo.chaos import run_tier_drill

    rc = 0
    for scenario in ("leaf_crash", "partition"):
        tier = run_tier_drill(scenario=scenario, random_seed=seed)
        line = {
            "metric": f"chaos_tier_{scenario}",
            "unit": ("client updates committed exactly once under a "
                     f"{scenario.replace('_', ' ')} (seed={seed}); accuracy "
                     "gated against the fault-free reference"),
            **tier.json_record(),
        }
        print(json.dumps(line), flush=True)
        print(tier.summary(), file=sys.stderr, flush=True)
        if not tier.ok:
            rc = 1
    return rc


def codec_sweep_bench(specs=("q8", "delta|topk:0.05|q8", "delta|topk:0.01|q8"),
                      rounds: int = 6) -> int:
    """``--codec-sweep``: accuracy-vs-bytes frontier of the compressed
    update plane. Per spec: one clean (fault-free) loopback cross-silo run
    reports final accuracy plus uplink raw/wire bytes (``fedml_codec_*``
    counter deltas); then one simulator run with the strongest spec checks
    the codec cost is attributed as its own phase and the phase breakdown
    still sums to round_time. Gates: uplink wire bytes strictly drop along
    the spec list (each spec is a strictly stronger compressor) and the
    phase sums stay exact."""
    import math

    import fedml_tpu
    from fedml_tpu.core import telemetry
    from fedml_tpu.cross_silo.chaos import run_chaos_drill
    from fedml_tpu.simulation import SimulatorSingleProcess

    telemetry.configure(enabled=True)
    common = dict(comm_round=rounds, fault_drop_rate=0.0, fault_seed=0)

    def final_acc(history):
        for rec in reversed(history):
            if "test_acc" in rec:
                return float(rec["test_acc"])
        return None

    base = run_chaos_drill(**common)
    results = [{
        "spec": None,
        "final_test_acc": final_acc(base.history),
        "uplink_wire_bytes": None,  # uncompressed: wire == raw
        "uplink_ratio": 1.0,
    }]
    wire_seq = []
    for spec in specs:
        r = run_chaos_drill(comm_codec=spec, **common)
        if not (r.ok and r.codec_bytes_wire.get("uplink")):
            print(f"codec-sweep: FAIL — spec '{spec}' run did not close "
                  "cleanly or recorded no uplink codec traffic",
                  file=sys.stderr, flush=True)
            return 1
        wire = r.codec_bytes_wire["uplink"]
        wire_seq.append(wire)
        results.append({
            "spec": spec,
            "final_test_acc": final_acc(r.history),
            "uplink_raw_bytes": int(r.codec_bytes_raw["uplink"]),
            "uplink_wire_bytes": int(wire),
            "uplink_ratio": round(r.codec_ratio("uplink"), 2),
        })
        print(f"codec-sweep: spec={spec!r} "
              f"acc={results[-1]['final_test_acc']} "
              f"ratio={results[-1]['uplink_ratio']}x",
              file=sys.stderr, flush=True)
    # uncompressed bytes basis: encode's nbytes_in is exactly the tree the
    # uncompressed run ships, so every compressed run reports the same raw
    results[0]["uplink_wire_bytes"] = results[1]["uplink_raw_bytes"]
    monotonic = all(a > b for a, b in zip(wire_seq, wire_seq[1:]))

    # simulator leg: same codec applied inside the compiled round step must
    # surface as its own "codec" phase and keep the breakdown exact
    args = fedml_tpu.init(config=dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=3, client_num_per_round=3, comm_round=3,
        learning_rate=0.1, batch_size=8, frequency_of_the_test=10_000,
        random_seed=0, prefetch=False, comm_codec=specs[-1],
    ))
    sim = SimulatorSingleProcess(args)
    hist = sim.run()
    # NOTE: deferred metric readback can drain one round's codec stamp into
    # the neighboring record, so the codec phase is asserted on the run
    # total, while the sum-to-round_time identity must hold per round
    phase_ok = True
    codec_phase = 0.0
    for rec in hist:
        ps = rec.get("phases", {})
        codec_phase += ps.get("codec", 0.0)
        phase_ok = phase_ok and math.isclose(
            sum(ps.values()), rec["round_time"], rel_tol=1e-6, abs_tol=1e-9)
    phase_ok = phase_ok and codec_phase > 0.0

    line = {
        "metric": "codec_sweep_accuracy_vs_bytes",
        "unit": (f"final test accuracy vs uplink bytes per codec spec, "
                 f"{rounds}-round clean loopback cross-silo drill (mnist lr, "
                 "3 silos) + simulator phase-attribution leg, CPU"),
        "results": results,
        "wire_bytes_monotonic_drop": bool(monotonic),
        "sim_codec_phase_s_per_round": round(codec_phase / max(len(hist), 1), 6),
        "sim_phase_sums_exact": bool(phase_ok),
    }
    print(json.dumps(line), flush=True)
    ok = monotonic and phase_ok
    print(f"codec-sweep: monotonic_bytes={monotonic} "
          f"sim_phases_exact={phase_ok} {'OK' if ok else 'FAIL'}",
          file=sys.stderr, flush=True)
    return 0 if ok else 1


def async_sweep_bench(buffer_sizes=(1, 2, 4, None), skew: float = 10.0,
                      rounds: int = 6) -> int:
    """``--async-sweep``: the sync-vs-async frontier of buffered-async
    aggregation. Per buffer size K (None = full cohort, the lockstep
    fallback): one sync and one async run of the simulation engine over the
    SAME seeded heavy-tail delay plan (slowest client ``skew``× the
    fastest), comparing committed-update goodput on the shared virtual
    clock against the barrier's round rate, plus final accuracy.

    Gates: every buffered K (< cohort) must clear goodput >= 3x the sync
    round rate at final accuracy within 2% of sync; the K == cohort run
    must replay the sync engine bit-for-bit (params equality); and every
    async commit record's phase breakdown must sum exactly to its
    round_time (the ``commit`` phase is attributed, not leaked into
    host_other)."""
    import math

    import jax
    import numpy as np

    import fedml_tpu
    from fedml_tpu.cross_silo.chaos import STRAGGLER_DEFAULTS
    from fedml_tpu.simulation import build_simulator
    from fedml_tpu.simulation.async_engine import sync_virtual_seconds
    from fedml_tpu.comm.resilience import ClientDelayPlan

    cfg = dict(STRAGGLER_DEFAULTS, comm_round=rounds, async_delay_skew=skew)
    cohort = int(cfg["client_num_per_round"])
    plan = ClientDelayPlan(
        seed=int(cfg["random_seed"]), base_s=float(cfg["async_delay_base_s"]),
        skew=skew, jitter=float(cfg["async_delay_jitter"]))
    sync_vs = sync_virtual_seconds(
        plan, float(cfg["async_delay_base_s"]), range(cohort), rounds)
    sync_round_rate = rounds / sync_vs

    def _run(extra):
        args = fedml_tpu.init(config=dict(cfg, **extra))
        sim, apply_fn = build_simulator(args)
        history = sim.run(apply_fn, log_fn=None)
        return sim, history

    def _acc(history):
        accs = [r["test_acc"] for r in history if "test_acc" in r]
        return float(accs[-1]) if accs else float("nan")

    sync_sim, sync_hist = _run({"async_mode": False})
    sync_acc = _acc(sync_hist)

    results = []
    gates_ok = True
    phase_ok = True
    lockstep_exact = None
    for k in buffer_sizes:
        k_eff = cohort if k is None else int(k)
        sim, hist = _run({"async_mode": True, "async_buffer_size": k_eff})
        stats = sim.async_stats()
        acc = _acc(hist)
        ratio = (stats["goodput_updates_per_s"] / sync_round_rate
                 if sync_round_rate > 0 else 0.0)
        for rec in hist:
            if "phases" in rec and not math.isclose(
                    sum(rec["phases"].values()), rec["round_time"],
                    rel_tol=1e-6, abs_tol=1e-9):
                phase_ok = False
        row = {
            "buffer_size": k_eff,
            "lockstep": k_eff == cohort,
            "commits": int(stats["version"]),
            "committed_updates": int(stats["committed_updates"]),
            "shed_updates": int(stats["shed_updates"]),
            "virtual_time_s": round(stats["virtual_time_s"], 4),
            "goodput_updates_per_vs": round(
                stats["goodput_updates_per_s"], 4),
            "goodput_over_sync_round_rate": round(ratio, 3),
            "final_acc": round(acc, 6),
            "acc_delta_vs_sync": round(sync_acc - acc, 6),
            "staleness_max": max(
                (int(r.get("staleness_max", 0)) for r in hist), default=0),
        }
        if k_eff == cohort:
            eq = jax.tree.map(
                lambda a, b: bool((np.asarray(a) == np.asarray(b)).all()),
                sync_sim.params, sim.params)
            lockstep_exact = all(jax.tree_util.tree_leaves(eq)) and all(
                s.get("test_acc") == a.get("test_acc")
                for s, a in zip(sync_hist, hist) if "test_acc" in s)
            row["bit_exact_vs_sync"] = bool(lockstep_exact)
        else:
            row_ok = ratio >= 3.0 and (sync_acc - acc) <= 0.02
            row["pass_goodput_and_acc"] = bool(row_ok)
            gates_ok = gates_ok and row_ok
        results.append(row)
        print(f"async-sweep: K={k_eff} ratio={ratio:.1f}x acc={acc:.4f} "
              f"(sync {sync_acc:.4f})", file=sys.stderr, flush=True)

    ok = gates_ok and phase_ok and bool(lockstep_exact)
    line = {
        "metric": "async_sweep_goodput_frontier",
        "unit": (f"committed-update goodput vs sync round rate on the shared "
                 f"virtual clock ({skew:g}x seeded speed skew, digits/lr "
                 f"homo, cohort {cohort}, {rounds} rounds), per async "
                 "buffer size; lockstep row replays the sync engine"),
        "backend": "cpu",
        "sync_rounds_per_vs": round(sync_round_rate, 4),
        "sync_final_acc": round(sync_acc, 6),
        "results": results,
        "pass_goodput_3x_within_2pct": bool(gates_ok),
        "pass_lockstep_bit_exact": bool(lockstep_exact),
        "pass_phase_sums_exact": bool(phase_ok),
    }
    print(json.dumps(line), flush=True)
    print(f"async-sweep: {'OK' if ok else 'FAIL'} (goodput={gates_ok} "
          f"lockstep={lockstep_exact} phases={phase_ok})",
          file=sys.stderr, flush=True)
    return 0 if ok else 1


def loadgen_bench(duration_s: float = 2.0, seed: int = 0) -> int:
    """``--loadgen``: overload gate for the tenancy control plane — the
    check-in load generator must sustain >=10k offered check-ins/sec through
    the real message codec against a bounded queue, with shedding visible in
    the per-tenant counters and the queue depth never passing its bound. The
    JSON line records the throughput/shed frontier."""
    from fedml_tpu.core import telemetry
    from fedml_tpu.cross_silo.loadgen import run_loadgen

    telemetry.configure(enabled=True)
    report = run_loadgen(duration_s=duration_s, producers=2,
                         queue_maxsize=512, tenants=2, churn=0.1, seed=seed)
    rate_ok = report.offered_rate >= 10_000.0
    shed_visible = (report.shed == 0
                    or sum(report.per_tenant_shed.values()) > 0)
    line = {
        "metric": "loadgen_checkins_per_sec",
        "unit": (f"offered device check-ins/sec over {duration_s:.0f}s "
                 f"(2 producers, 2 tenants, 10% seeded churn, seed={seed}, "
                 "512-deep bounded queue), real msgpack codec both ways, CPU"),
        **report.json_record(),
        "pass_10k_per_sec": bool(rate_ok),
        "shed_visible_in_telemetry": bool(shed_visible),
    }
    print(json.dumps(line), flush=True)
    print(report.summary(), file=sys.stderr, flush=True)
    return 0 if (report.ok and rate_ok and shed_visible) else 1


def device_day_bench(seed: int = 0, budget_mb: float = 1536.0) -> int:
    """``--device-day``: the cross-device fleet gate. One full simulated day
    over a 1M-client registry on CPU: seeded diurnal arrivals through the
    bounded admission edge, cohorts folded through the tier-plane fan-in,
    per-device optimizer state tiered device->host->disk by the client-state
    arena.

    Gates: >= 50k offered check-ins/s of wall time at the admission edge
    itself; peak-RSS growth under ``budget_mb`` (the arena's spill tier, not
    RAM, absorbs the fleet's state); the disk tier actually engaged; closed
    shed/drop accounting with zero ledger duplicates; and the whole day
    byte-identical across two runs (history and params digests)."""
    import dataclasses
    import resource
    import tempfile

    from fedml_tpu.core import telemetry
    from fedml_tpu.cross_device.device_day import (DeviceDayConfig,
                                                   run_device_day)

    telemetry.configure(enabled=True)
    spill_root = tempfile.mkdtemp(prefix="device_day_bench_")
    cfg = DeviceDayConfig(
        registry_size=1_000_000, day_s=86_400.0, tick_s=300.0,
        num_classes=4, cohort=128, queue_maxsize=8192, peak_rate=6.0,
        max_commits_per_tick=1, arena_capacity=2048, host_capacity=16384,
        spill_dir=os.path.join(spill_root, "run1"), seed=seed)
    os.makedirs(cfg.spill_dir, exist_ok=True)
    rss_before_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    r1 = run_device_day(cfg)
    rss_after_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    rss_delta_mb = max(0.0, (rss_after_kb - rss_before_kb) / 1024.0)
    spill_files = len(os.listdir(cfg.spill_dir))
    cfg2 = dataclasses.replace(
        cfg, spill_dir=os.path.join(spill_root, "run2"))
    os.makedirs(cfg2.spill_dir, exist_ok=True)
    r2 = run_device_day(cfg2)

    pass_rate = r1.offered_per_s >= 50_000.0
    pass_rss = rss_delta_mb <= float(budget_mb)
    pass_spill = (spill_files > 0
                  and r1.arena_resident <= cfg.arena_capacity)
    pass_deterministic = (r1.history_digest == r2.history_digest
                          and r1.params_digest == r2.params_digest)
    line = {
        "metric": "device_day",
        "unit": ("one simulated 86400s day over a 1,000,000-device registry "
                 f"(288 ticks, seeded diurnal arrivals, seed={seed}), "
                 "bounded admission edge + DRR, cohort=128 tier-plane "
                 "fan-in, arena spill device->host->disk, CPU"),
        **r1.json_record(),
        "rss_delta_mb": round(rss_delta_mb, 1),
        "rss_budget_mb": float(budget_mb),
        "spill_files": spill_files,
        "pass_50k_per_sec_at_edge": bool(pass_rate),
        "pass_rss_budget": bool(pass_rss),
        "pass_spill_engaged": bool(pass_spill),
        "pass_deterministic_day": bool(pass_deterministic),
    }
    print(json.dumps(line), flush=True)
    print(r1.summary(), file=sys.stderr, flush=True)
    print(f"rss delta {rss_delta_mb:.0f}MB (budget {budget_mb:.0f}MB), "
          f"{spill_files} spill files, deterministic="
          f"{pass_deterministic}", file=sys.stderr, flush=True)
    return 0 if (r1.ok and r2.ok and pass_rate and pass_rss and pass_spill
                 and pass_deterministic) else 1


def serve_bench(rounds: int = 30, producers: int = 2,
                target_rate: float = 40_000.0, seed: int = 0) -> int:
    """``--serve``: the train/serve overlap gate. A real simulator trains
    (mnist/lr, debug data, every round committing a version through the
    canary-gated serving plane) while producer threads hammer the inference
    server; the serving window opens at the FIRST published version and
    stays open through every hot-swap until training ends and the queue
    drains.

    Gates: >= 10k requests/s served on CPU while training commits
    underneath; zero admitted requests dropped; >= 5 hot-swaps observed;
    and — the BENCH_r07 artifact fix — the per-round phase sums (stamped
    with ``bench_sync_device_phase``, which blocks on the committed params
    before the completion timestamp) must re-add to the round_time sum
    within 2%, with the ``device`` and ``publish`` phases both attributed
    instead of leaking into host_other."""
    import threading

    import jax
    import numpy as np

    import fedml_tpu
    from fedml_tpu.core import telemetry
    from fedml_tpu.cross_silo.chaos import TIER_DEFAULTS
    from fedml_tpu.serving import (InferenceServer, ServeConfig,
                                   held_out_batches)
    from fedml_tpu.simulation import build_simulator

    telemetry.configure(enabled=True)
    base = {k: v for k, v in TIER_DEFAULTS.items()
            if not k.startswith(("hier_", "group_", "lease_"))}
    args = fedml_tpu.init(config=dict(
        base, comm_round=rounds, random_seed=seed, frequency_of_the_test=1,
        prefetch=False, bench_sync_device_phase=True, serve_enabled=True))
    sim, apply_fn = build_simulator(args)
    cfg = ServeConfig.from_args(args)

    # fixed-shape jitted predict: every batch pads to batch_max so the
    # serve path compiles ONCE and a drain chunk of any size reuses it
    jpred = jax.jit(lambda p, x: apply_fn(p, x, train=False))
    bm = int(cfg.batch_max)

    def predict(params, x):
        x = np.asarray(x)
        n = int(x.shape[0])
        if n == bm:
            return np.asarray(jpred(params, x))
        xp = np.zeros((bm,) + tuple(x.shape[1:]), x.dtype)
        xp[:n] = x
        return np.asarray(jpred(params, xp))[:n]

    test = sim.fed.test_data_global
    server = InferenceServer(
        predict, cfg,
        eval_batches=held_out_batches(test.x, test.y, cfg.canary))
    first_pub = threading.Event()

    def publish(version, params):
        status = server.publish(version, params)
        first_pub.set()
        return status

    sim.attach_publisher(publish)

    x_pool = np.asarray(test.x)
    stop = threading.Event()
    per_rate = float(target_rate) / max(1, int(producers))

    def produce(worker: int) -> None:
        t0 = time.perf_counter()
        i = 0
        n_pool = len(x_pool)
        while not stop.is_set():
            server.submit(x_pool[(worker + i) % n_pool],
                          request_id=(worker, i))
            i += 1
            if i % 64 == 0:
                ahead = i / per_rate - (time.perf_counter() - t0)
                if ahead > 0.001:
                    time.sleep(min(ahead, 0.05))

    trainer = threading.Thread(target=lambda: sim.run(apply_fn, log_fn=None),
                               daemon=True, name="serve-bench-train")
    server.start()
    trainer.start()
    first_pub.wait(timeout=120.0)
    threads = [threading.Thread(target=produce, args=(w,), daemon=True,
                                name=f"serve-bench-p{w}")
               for w in range(max(1, int(producers)))]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    trainer.join()
    stop.set()
    for t in threads:
        t.join(timeout=10.0)
    server.stop(drain=True)
    elapsed = time.perf_counter() - t0

    st = server.stats()
    served_rate = st["served"] / elapsed if elapsed > 0 else 0.0
    dropped = st["admitted"] - st["served"]
    swaps = st["store"]["swaps"]

    # corrected phase attribution (satellite of the serving PR): with
    # bench_sync_device_phase the completion stamp waits on the committed
    # params, so device time stops leaking into host_other
    hist = sim.history
    phase_sums = {}
    for rec in hist:
        for k, v in (rec.get("phases") or {}).items():
            phase_sums[k] = phase_sums.get(k, 0.0) + float(v)
    round_time_sum = sum(float(r.get("round_time", 0.0)) for r in hist)
    phase_total = sum(phase_sums.values())
    phase_drift = (abs(phase_total - round_time_sum) / round_time_sum
                   if round_time_sum > 0 else 1.0)

    rate_ok = served_rate >= 10_000.0
    drop_ok = dropped == 0 and st["served"] == st["admitted"]
    swap_ok = swaps >= 5
    phase_ok = (phase_drift <= 0.02 and phase_sums.get("device", 0.0) > 0
                and phase_sums.get("publish", 0.0) > 0)
    ok = rate_ok and drop_ok and swap_ok and phase_ok

    line = {
        "metric": "serve_requests_per_sec_under_training",
        "unit": (f"inference requests/s served while {rounds} training "
                 f"rounds commit versions through the canary gate "
                 f"(mnist/lr debug data, {producers} producers, "
                 f"batch_max {bm}, seed={seed}), CPU"),
        "elapsed_s": round(elapsed, 4),
        "served": st["served"],
        "served_per_sec": round(served_rate, 1),
        "admitted": st["admitted"],
        "submitted": st["submitted"],
        "shed": st["submitted"] - st["admitted"],
        "dropped": dropped,
        "canary_served": st["canary_served"],
        "swaps": swaps,
        "rollbacks": st["store"]["rollbacks"],
        "versions_served": len(st["served_by_version"]),
        "max_queue_depth": st["queue"]["max_depth"],
        "queue_maxsize": st["queue"]["maxsize"],
        "phase_sums_s": {k: round(v, 4)
                         for k, v in sorted(phase_sums.items())},
        "round_time_sum_s": round(round_time_sum, 4),
        "phase_drift_fraction": round(phase_drift, 4),
        "pass_10k_per_sec": bool(rate_ok),
        "pass_zero_dropped": bool(drop_ok),
        "pass_5_hot_swaps": bool(swap_ok),
        "pass_phase_sums_within_2pct": bool(phase_ok),
        "ok": bool(ok),
    }
    print(json.dumps(line), flush=True)
    print(f"serve: {'OK' if ok else 'FAIL'} — {served_rate:,.0f} req/s, "
          f"{swaps} swaps, dropped {dropped}, phase drift "
          f"{phase_drift:.2%}", file=sys.stderr, flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    if "--host-pack" in sys.argv:
        # host-side measurement only — never wait on (or measure) the chip
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        sys.exit(host_pack_bench())
    if "--telemetry-overhead" in sys.argv:
        # host-side guard only — never wait on (or measure) the chip
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        sys.exit(telemetry_overhead_bench())
    if "--cohort-sweep" in sys.argv:
        # cohort-axis scaling measurement — host + CPU backend only
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        sys.exit(cohort_sweep_bench())
    if "--agg-sweep" in sys.argv:
        # robust-aggregation frontier — CPU backend (kernels engage on TPU;
        # CPU runs the bit-identical reference fallbacks)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        sys.exit(agg_sweep_bench())
    if "--model-sweep" in sys.argv:
        # model-axis memory scaling — CPU backend with virtual devices; the
        # flag must land before the first backend init to take effect
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        sys.exit(model_sweep_bench())
    if "--chaos" in sys.argv:
        # protocol-level drill — loopback only, never touches the chip
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        sys.exit(chaos_bench())
    if "--codec-sweep" in sys.argv:
        # compression frontier — loopback + CPU simulator only
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        sys.exit(codec_sweep_bench())
    if "--async-sweep" in sys.argv:
        # buffered-async frontier — simulation engine on the CPU backend,
        # goodput measured on the seeded virtual clock (deterministic)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        sys.exit(async_sweep_bench())
    if "--loadgen" in sys.argv:
        # check-in overload drill — host threads + codec only, no chip
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        sys.exit(loadgen_bench())
    if "--device-day" in sys.argv:
        # cross-device fleet day — registry + admission edge + arena spill
        # are all host-side; the fold math runs on the CPU backend
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        sys.exit(device_day_bench())
    if "--serve" in sys.argv:
        # train/serve overlap gate — CPU simulator + host serving threads
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        sys.exit(serve_bench())
    if "--round-scan" in sys.argv:
        # compiled multi-round dispatch frontier — CPU backend; exits
        # nonzero if any round's phase breakdown fails the exactness check
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        sys.exit(round_scan_bench())
    sys.exit(main())
