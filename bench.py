"""Benchmark: FedAvg CIFAR-10 ResNet-56 rounds/sec (BASELINE.json north star).

Setup mirrors the reference MPI benchmark config (BENCHMARK_MPI.md: 100-client
pool, 10 clients/round, batch 64) with 1 local epoch per round.

Measurement protocol:
- a warm run over the SAME round range as a timed block pays compile +
  device-data upload (discarded) — sampling is round-indexed, so the warm
  run compiles exactly the cohort shapes the timed blocks will replay,
- then 5 independent timed runs ("blocks") of N rounds each (after one
  discarded burn-in block), measured
  WALL-TO-WALL around sim.run(): run() ends by materializing the final
  round's metric vector, whose value requires every dispatched executable
  to have retired — so the wall time is honest even on backends where
  block_until_ready is unreliable (the tunneled axon chip). The reported
  value is the MEDIAN block rate; the spread (max-min) is printed on stderr
  so one-shot flukes are visible.
- before timing, the forward computation is lowered and asserted to contain
  bf16 ops (mixed precision actually engaged, not just requested).

Baseline denominator: the reference publishes no wall-clock numbers
(BASELINE.md). If ``BASELINE_LOCAL.json`` exists (produced by
``scripts/measure_reference_baseline.py`` — the reference's torch hot loop
timed on THIS machine's CPU at the same workload and extrapolated to a
round), its rounds/sec is used and the basis is echoed in the output line.
Otherwise vs_baseline falls back to a denominator of 1.0 round/sec with
basis "undocumented-1.0" — explicitly a placeholder, not a measurement.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import sys


def main() -> None:
    import jax
    import jax.numpy as jnp

    import fedml_tpu
    from fedml_tpu.simulation import build_simulator

    blocks, rounds_per_block = 5, 6
    # Lane count pinned from on-chip sweeps (results/lane_sweep_r4.json,
    # superseding r3's grouped-conv theory): per-step cost scales ~linearly
    # with lane count (~2.2 ms per lane per step — per-op latency across
    # ~250+ small-shape ops dominates, not MXU or HBM), so few, long lanes
    # win. Override with FEDML_BENCH_LANES.
    lanes_env = os.environ.get("FEDML_BENCH_LANES", "2")
    args = fedml_tpu.init(config=dict(
        dataset="cifar10", model="resnet56", partition_method="hetero",
        partition_alpha=0.5, client_num_in_total=100, client_num_per_round=10,
        comm_round=6, learning_rate=0.01, epochs=1,
        batch_size=64, frequency_of_the_test=10_000, random_seed=0,
        use_bf16=True,
        packed_lanes=int(lanes_env) if lanes_env else None,
        # flat-carry packed executor (results/lane_sweep_r4.json): 1.6x
        # faster per step in the on-chip microbench, parity-exact on CPU;
        # opt-in here until validated end-to-end on the chip
        # (FEDML_BENCH_FLAT=1)
        packed_flat_carry=os.environ.get("FEDML_BENCH_FLAT", "") == "1",
    ))
    sim, apply_fn = build_simulator(args)
    assert sim._use_device_data, "device-resident data path must engage"
    # Dirichlet alpha=0.5 client sizes are heavily skewed: the auto cohort
    # schedule must pick the packed-lane path (one program per round,
    # clients back-to-back in balanced lanes — 2.1x over bucketed)
    assert sim._packed, "packed cohort schedule must engage on skewed data"

    # mixed precision must actually engage: the lowered forward has bf16 ops
    x_probe = jnp.zeros((8, 32, 32, 3), jnp.float32)
    hlo = jax.jit(
        lambda p, x: apply_fn(p, x, train=True)
    ).lower(sim.params, x_probe).as_text()
    assert "bf16" in hlo, "bf16 requested but absent from lowered HLO"

    import time

    # warm: compile every cohort shape the timed blocks will replay
    # (comm_round == rounds_per_block) + device-data upload; then one
    # discarded burn-in block — the first post-compile block consistently
    # runs ~20% slow (tunnel/chip warmup) and would skew a 3-block median
    assert args.comm_round == rounds_per_block
    sim.run(apply_fn=None, log_fn=None)
    sim.history.clear()
    sim.run(apply_fn=None, log_fn=None)
    block_rates = []
    for _ in range(blocks):
        sim.history.clear()
        t0 = time.perf_counter()
        sim.run(apply_fn=None, log_fn=None)
        block_rates.append(rounds_per_block / (time.perf_counter() - t0))
    block_rates.sort()
    rounds_per_sec = block_rates[len(block_rates) // 2]
    spread = block_rates[-1] - block_rates[0]
    print(
        f"block rates: {[round(r, 3) for r in block_rates]} "
        f"median={rounds_per_sec:.4f} spread={spread:.4f}",
        file=sys.stderr,
    )

    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "BASELINE_LOCAL.json")
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            base = json.load(f)
        baseline_rounds_per_sec = float(base["rounds_per_sec"])
        basis = base.get("basis", "BASELINE_LOCAL.json")
    else:
        baseline_rounds_per_sec = 1.0
        basis = "undocumented-1.0"
    print(json.dumps({
        "metric": "fedavg_cifar10_resnet56_rounds_per_sec",
        "value": round(rounds_per_sec, 4),
        "unit": ("rounds/sec (10 clients x 1 epoch x bs64 per round; "
                 f"baseline basis: {basis})"),
        "vs_baseline": round(rounds_per_sec / baseline_rounds_per_sec, 4),
    }))


if __name__ == "__main__":
    main()
