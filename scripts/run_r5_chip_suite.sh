#!/usr/bin/env bash
# Round-5 chip measurement suite: runs every staged on-chip task in
# dependency order, one JAX process at a time (the tunnel wedges under
# concurrent holders). Safe to re-run; each stage logs to results/.
#
#   ./scripts/run_r5_chip_suite.sh [probe_attempts] [probe_sleep_s]
#
# Order:
#   1. availability probe (bounded)
#   2. flash block confirmation  -> results/flash_blocks_r5.json
#      (bench_lm_attribution auto-adopts its table_adopt output)
#   3. LM step op attribution    -> results/lm_mfu_bench_r5.json
#   4. flat-carry validation + lane re-sweep -> results/lane_sweep_r5.json
#   5. the flagship bench        -> one JSON line on stdout
set -uo pipefail
cd "$(dirname "$0")/.."

ATTEMPTS=${1:-3}
SLEEP=${2:-120}

echo "[suite] probing chip (${ATTEMPTS} attempts)..."
if ! python scripts/probe_chip.py "$ATTEMPTS" "$SLEEP"; then
    echo "[suite] chip unavailable; aborting (re-run when the tunnel is up)"
    exit 1
fi

FAILED=0
run_stage() {
    local name=$1; shift
    echo "[suite] === $name ==="
    if ! timeout 3600 "$@" 2>&1 | tee "results/${name}.log"; then
        echo "[suite] $name FAILED (continuing — stages are independent)"
        FAILED=1
    fi
    # post-kill settle: a failed/killed JAX process wedges the tunnel
    # claim for minutes
    sleep 60
}

run_stage flash_blocks_r5      python -u scripts/bench_flash_blocks_r5.py
run_stage lm_attribution_r5    python -u scripts/bench_lm_attribution_r5.py
run_stage lane_sweep_r5        python -u scripts/lane_sweep_r5.py
run_stage bench_r5             python bench.py
if [ "$FAILED" -ne 0 ]; then
    echo "[suite] done WITH FAILURES — check results/*.log"
    exit 1
fi
echo "[suite] done; artifacts under results/"
