#!/usr/bin/env python
"""Loopback bit-identity smoke for compiled multi-round dispatch.

Runs the same tiny SCAFFOLD + sanitizer federation twice — once on the
classic per-round engine, once with ``rounds_per_dispatch=4`` — and
demands bitwise-equal final parameters and an identical round history
(timing fields aside). This is the cheap CI tripwire for the invariant
the full parity suite (tests/test_round_scan.py) checks exhaustively:
fusing rounds into one ``lax.scan`` region must never change a single
bit of the training trajectory.

Exits 0 on bitwise identity, 1 on any mismatch.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

TIMING_KEYS = {"round_time", "dispatch_time", "pack_time", "pack_wait",
               "overlap", "phases", "scan_rounds"}


def _run(rounds_per_dispatch: int):
    import numpy as np

    import jax

    import fedml_tpu
    from fedml_tpu.simulation import build_simulator

    args = fedml_tpu.init(config=dict(
        dataset="cifar10", model="lr", partition_method="hetero",
        partition_alpha=0.3, debug_small_data=True,
        client_num_in_total=10, client_num_per_round=5, comm_round=6,
        learning_rate=0.05, epochs=1, batch_size=16,
        frequency_of_the_test=100, random_seed=0,
        federated_optimizer="SCAFFOLD", sanitize_updates=True,
        rounds_per_dispatch=rounds_per_dispatch,
    ))
    sim, apply_fn = build_simulator(args)
    hist = sim.run(apply_fn, log_fn=None)
    flat = np.concatenate([np.asarray(l, np.float64).ravel()
                           for l in jax.tree.leaves(sim.params)])
    stripped = [{k: v for k, v in r.items() if k not in TIMING_KEYS}
                for r in hist]
    return flat, stripped, hist


def main() -> int:
    import numpy as np

    p1, h1, _ = _run(1)
    p4, h4, raw4 = _run(4)
    fused = sum(1 for r in raw4 if "scan_rounds" in r)
    ok = True
    if not fused:
        print("scan_smoke: FAIL — no round ran on the fused path",
              file=sys.stderr)
        ok = False
    if not np.array_equal(p1, p4):
        bad = int(np.sum(p1 != p4))
        print(f"scan_smoke: FAIL — {bad}/{p1.size} final parameter "
              f"entries differ between R=1 and R=4", file=sys.stderr)
        ok = False
    if h1 != h4:
        diff = [r["round"] for a, b in zip(h1, h4) if a != b
                for r in (a,)] or ["length"]
        print(f"scan_smoke: FAIL — history diverges at round(s) {diff}",
              file=sys.stderr)
        ok = False
    if ok:
        print(f"scan_smoke: OK — R=4 bit-identical to per-round over "
              f"{len(h1)} rounds ({fused} fused)", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
