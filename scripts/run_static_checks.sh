#!/usr/bin/env bash
# Run every first-party static check; nonzero exit if any fails.
#
#   scripts/run_static_checks.sh
#
# Intended as the CI / pre-commit gate (see devops/README.md):
#   1. graftcheck — the fedml_tpu.analysis checker suite (jit-purity,
#      determinism, lock-order, config-drift, no-print, donation-safety,
#      sharding-consistency, host-sync, collective-deadlock,
#      thread-hazard); exits 1 on any finding not grandfathered in
#      scripts/graftcheck_baseline.json. Pre-commit can pass
#      "--changed-only" through for the <5s loop; CI runs the full scan
#      (optionally with "--format sarif" for PR annotation).
#   2. gen_config_reference --check — fails if docs/config_reference.md
#      is stale relative to the config keys the code actually reads.
#
# Both checks are pure-AST and run in seconds on CPU; no JAX devices,
# network, or model downloads are involved.
set -u

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO_ROOT"
PY="${PYTHON:-python}"

rc=0

echo "== graftcheck (fedml_tpu static-analysis suite) =="
"$PY" scripts/graftcheck.py "$@" || rc=1

echo "== config reference freshness =="
"$PY" scripts/gen_config_reference.py --check || rc=1

if [ "$rc" -ne 0 ]; then
    echo "static checks FAILED (see above)" >&2
fi
exit "$rc"
