#!/usr/bin/env bash
# Run every first-party static check; nonzero exit if any fails.
#
#   scripts/run_static_checks.sh
#
# Intended as the CI / pre-commit gate (see devops/README.md):
#   1. graftcheck — the fedml_tpu.analysis checker suite (jit-purity,
#      determinism, lock-order, config-drift, no-print, donation-safety,
#      sharding-consistency, host-sync, collective-deadlock,
#      thread-hazard, retrace-hazard, wire-protocol, resource-leak);
#      exits 1 on any finding not grandfathered in
#      scripts/graftcheck_baseline.json. Pre-commit can pass
#      "--changed-only" through for the fast loop; CI runs the full scan.
#      Every gate run also emits results/graftcheck.sarif for PR
#      annotation and fails if the scan exceeds its wall-clock budget
#      (GRAFTCHECK_BUDGET_S, default 60s — warm cache runs finish in
#      well under a second).
#   2. gen_config_reference --check — fails if docs/config_reference.md
#      is stale relative to the config keys the code actually reads.
#   3. make -C fedml_tpu/native check — rebuilds libfedml_native.so if
#      mtime-stale, then verifies the source hash baked into the binary
#      matches fedml_native.cpp (skipped when no toolchain; the runtime
#      falls back to numpy there anyway).
#   4. tier_smoke — a tiny 1-root + 2-leaf loopback hierarchy run that
#      must be bit-identical to the single-process reference with an
#      exact commit ledger; the cheapest end-to-end probe of the tier
#      wire protocol.
#   5. scan_smoke — the same loopback federation on the classic
#      per-round engine vs rounds_per_dispatch=4; final parameters and
#      history must be bitwise identical (the fused-lax.scan invariant).
#   6. serve_smoke — the debug federation with the inference server
#      attached: every round publishes + canary-promotes, live requests
#      all serve with zero drops, a NaN publish rolls back and pins,
#      and training params stay bitwise-equal to the serving-off run.
#   7. device_day_smoke — a 10k-device registry through a 2-minute
#      simulated day with the full churn drill (dropout + rejoin waves,
#      permanent departures reclaiming arena spill files, one partition
#      window); gates closed shed/drop accounting, accuracy vs the
#      churn-free reference, and a bit-identical replay.
#
# Checks 1-3 are pure-AST / host-compile; checks 4-7 run JAX on CPU
# (debug-small dataset, a few seconds each). No network or model
# downloads are involved.
set -u

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO_ROOT"
PY="${PYTHON:-python}"

rc=0

echo "== graftcheck (fedml_tpu static-analysis suite) =="
GRAFTCHECK_BUDGET_S="${GRAFTCHECK_BUDGET_S:-60}"
gc_start=$(date +%s)
"$PY" scripts/graftcheck.py "$@" || rc=1
gc_elapsed=$(( $(date +%s) - gc_start ))
if [ "$gc_elapsed" -gt "$GRAFTCHECK_BUDGET_S" ]; then
    echo "graftcheck exceeded its ${GRAFTCHECK_BUDGET_S}s wall-clock budget (took ${gc_elapsed}s)" >&2
    rc=1
fi
# SARIF artifact on every gate run, for CI PR annotation; findings also
# fail above via the text run, so the artifact itself never masks a red
mkdir -p results
"$PY" scripts/graftcheck.py --format sarif "$@" > results/graftcheck.sarif || true

echo "== config reference freshness =="
"$PY" scripts/gen_config_reference.py --check || rc=1

echo "== native library source hash =="
if command -v make >/dev/null 2>&1 && command -v "${CXX:-g++}" >/dev/null 2>&1; then
    make -s -C fedml_tpu/native check || rc=1
else
    # no toolchain: the runtime warns once and uses the numpy fallback, so
    # a stale .so cannot silently serve wrong code — skip rather than fail
    echo "(skipped: native toolchain unavailable)"
fi

echo "== tiered federation loopback smoke =="
JAX_PLATFORMS=cpu "$PY" scripts/tier_smoke.py || rc=1

echo "== multi-round scan bit-identity smoke =="
JAX_PLATFORMS=cpu "$PY" scripts/scan_smoke.py || rc=1

echo "== serving-plane rollout smoke =="
JAX_PLATFORMS=cpu "$PY" scripts/serve_smoke.py || rc=1

echo "== cross-device fleet churn smoke =="
JAX_PLATFORMS=cpu "$PY" scripts/device_day_smoke.py || rc=1

if [ "$rc" -ne 0 ]; then
    echo "static checks FAILED (see above)" >&2
fi
exit "$rc"
