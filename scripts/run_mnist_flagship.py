"""Reference flagship run: MNIST + LR FedAvg, 1000 clients / 10 per round /
200 rounds / lr 0.03 (doc/en/simulation/benchmark/BENCHMARK_simulation.md:5,
target 81.9% test acc).

With real LEAF MNIST present in --data_cache_dir (the reference's MNIST.zip
extracted: train/ + test/ json dirs), this reproduces the benchmark with the
natural per-user partition and the result is directly comparable to 81.9%.
In a zero-egress image the loader falls back to the synthetic stand-in —
still the full 1000-client/200-round protocol at scale, but the accuracy is
then NOT comparable to the reference table (the history json records which
data path ran).

Usage: python scripts/run_mnist_flagship.py [--data_cache_dir DIR] [--rounds N]
Writes results/mnist_lr_flagship_history.json.
"""

from __future__ import annotations

import argparse
import json
import os
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data_cache_dir", default=None)
    ap.add_argument("--rounds", type=int, default=200)
    opts = ap.parse_args()

    import fedml_tpu
    from fedml_tpu.simulation import build_simulator

    args = fedml_tpu.init(config=dict(
        dataset="mnist", model="lr", data_cache_dir=opts.data_cache_dir,
        partition_method="hetero", partition_alpha=0.5,
        client_num_in_total=1000, client_num_per_round=10,
        comm_round=opts.rounds, learning_rate=0.03, epochs=1, batch_size=10,
        frequency_of_the_test=25, random_seed=0,
    ))
    sim, apply_fn = build_simulator(args)
    from fedml_tpu.data import leaf

    real = bool(opts.data_cache_dir) and (
        leaf.leaf_json_dirs(opts.data_cache_dir) is not None
        or os.path.exists(os.path.join(opts.data_cache_dir, "mnist.npz"))
        or os.path.exists(
            os.path.join(opts.data_cache_dir, "train-images-idx3-ubyte")
        )
    )
    t0 = time.time()
    hist = sim.run(apply_fn)
    out = {
        "config": {
            "dataset": "mnist", "model": "lr", "client_num_in_total": 1000,
            "client_num_per_round": 10, "comm_round": opts.rounds,
            "learning_rate": 0.03, "batch_size": 10,
        },
        "data_path": "real" if real else "synthetic-standin",
        "reference_target_acc": 0.819,
        "final_test_acc": hist[-1].get("test_acc"),
        "wall_seconds": time.time() - t0,
        "history": hist,
    }
    os.makedirs("results", exist_ok=True)
    path = os.path.join("results", "mnist_lr_flagship_history.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: v for k, v in out.items() if k != "history"}, indent=2))


if __name__ == "__main__":
    main()
