"""Confirm-and-adopt pass for flash block shapes (VERDICT r4 #4).

The r4 sweep (results/flash_attention_holes_r4.json t2048_block_sweep)
saw (block_q=128, block_k=1024) at 1.62x dense at T=2048 — UNCONFIRMED
single reading. This script re-measures the short-T regime with repeated
independent trials in ONE process (cross-process numbers vary up to 3x
on the tunneled chip) and emits:

- per-T winners -> the BLOCK_TABLE entries to adopt in
  ops/pallas/flash_attention.py,
- a dense-vs-best-flash verdict per T -> whether the auto-dispatch
  crossover in ops/attention.py can drop below 4096.

Confirmation rule: a candidate must beat dense in >= 2 of 3 trials AND
its median must beat dense's median — sub-5 ms single readings on this
tunnel must never drive retunes (r4 lesson, recorded in
flash_attention_holes_r4.json).

Protocol per reading: marginal fwd+bwd from two chained-scan lengths,
all three grads feeding the carry, device-computed scalar readback.
Run alone on the real chip. Writes results/flash_blocks_r5.json.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")
from fedml_tpu.ops.attention import multihead_attention  # noqa: E402
from fedml_tpu.ops.pallas.flash_attention import flash_attention  # noqa: E402

N1, N2 = 4, 36
TRIALS = 3
SHAPES = ((1024, 4, 8), (2048, 4, 8), (4096, 4, 8))
# candidates per T: auto square, the r4 rectangular winner family, and
# the transposed rectangle as a control
CANDS = {
    1024: ((512, 512), (128, 1024), (1024, 128), (128, 512), (256, 256)),
    2048: ((1024, 1024), (128, 1024), (1024, 128), (128, 2048), (256, 1024),
           (128, 512)),
    4096: ((1024, 1024), (128, 1024), (256, 1024), (128, 2048)),
}

if "--smoke" in sys.argv:  # CPU interpret-mode plumbing check only
    N1, N2, TRIALS = 1, 3, 2
    SHAPES = ((256, 1, 2),)
    CANDS = {256: ((128, 128), (128, 256))}


def timed_train(fn, q, k, v):
    grad = jax.grad(lambda q, k, v: jnp.sum(
        fn(q, k, v).astype(jnp.float32) ** 2), argnums=(0, 1, 2))
    res = {}
    for n in (N1, N2):
        @jax.jit
        def loop(q, k, v):
            def body(c, _):
                dq, dk, dv = grad(c, k, v)
                return c + 1e-12 * (dq + dk + dv), None
            c, _ = jax.lax.scan(body, q, None, length=n)
            return jnp.sum(c.astype(jnp.float32))
        float(loop(q, k, v))  # compile
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            float(loop(q, k, v))
            ts.append(time.perf_counter() - t0)
        res[n] = min(ts)
    return (res[N2] - res[N1]) / (N2 - N1)


def qkv(T, B, H, Dh=64):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return tuple(jax.random.normal(k, (B, T, H, Dh), jnp.bfloat16) * 0.3
                 for k in ks)


def median(xs):
    s = sorted(xs)
    return s[len(s) // 2]


def main():
    print("devices:", jax.devices(), flush=True)
    out = {
        "protocol": (f"marginal fwd+bwd from chained-scan lengths {N1}/{N2}"
                     f", min of 3 walls per length, {TRIALS} independent "
                     "trials per config interleaved with dense, "
                     "median-of-trials decides"),
        "dtype": "bf16", "Dh": 64, "points": [],
        "table_adopt": {}, "crossover": {},
    }
    for T, B, H in SHAPES:
        q, k, v = qkv(T, B, H)
        pt = {"T": T, "B": B, "H": H, "dense_ms": [], "cands": {}}
        # interleave trials: dense, then each candidate, repeated — a slow
        # tunnel phase hits all configs equally instead of one
        for _trial in range(TRIALS):
            md = timed_train(lambda q, k, v: multihead_attention(
                q, k, v, causal=True, impl="dense"), q, k, v)
            pt["dense_ms"].append(round(md * 1e3, 3))
            for bq, bk in CANDS[T]:
                # per-candidate LIST always; failures append a sentinel so
                # a transient tunnel error neither crashes the sweep nor
                # overwrites good readings (review finding)
                readings = pt["cands"].setdefault(f"{bq}x{bk}", [])
                try:
                    m = timed_train(lambda q, k, v: flash_attention(
                        q, k, v, causal=True, block_q=bq, block_k=bk),
                        q, k, v)
                    readings.append(round(m * 1e3, 3))
                except Exception as e:
                    readings.append(f"failed: {repr(e)[:120]}")
            print(f"T={T} trial done: dense={pt['dense_ms'][-1]} ms",
                  flush=True)
        dmed = median(pt["dense_ms"])
        best_key, best_med = None, None
        for key, ms in pt["cands"].items():
            good = [m for m in ms if isinstance(m, (int, float))]
            if len(good) < TRIALS:
                # record WHY it's out — 'lost' and 'not fully measured'
                # must be distinguishable in the artifact (review finding)
                pt.setdefault("verdicts", {})[key] = {
                    "trials_ok": len(good), "excluded": True,
                    "confirmed": False,
                }
                continue
            wins = sum(m < pt["dense_ms"][i] for i, m in enumerate(good))
            cmed = median(good)
            pt.setdefault("verdicts", {})[key] = {
                "median_ms": cmed, "wins_vs_dense": wins,
                "vs_dense": round(dmed / cmed, 3),
                "confirmed": wins >= 2 and cmed < dmed,
            }
            if best_med is None or cmed < best_med:
                best_key, best_med = key, cmed
        pt["dense_median_ms"] = dmed
        pt["best"] = best_key
        out["points"].append(pt)
        if best_key and pt["verdicts"][best_key]["confirmed"]:
            bq, bk = (int(x) for x in best_key.split("x"))
            out["table_adopt"][T] = [bq, bk]
            out["crossover"][T] = "flash"
        else:
            out["crossover"][T] = "dense"
        print(json.dumps(pt), flush=True)

    out["recommendation"] = (
        "adopt table_adopt into BLOCK_TABLE; lower auto_attention_impl "
        "crossover to the smallest T whose crossover says 'flash' (only "
        "if contiguous up to 4096)")
    with open("results/flash_blocks_r5.json", "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print("wrote results/flash_blocks_r5.json", flush=True)


if __name__ == "__main__":
    main()
