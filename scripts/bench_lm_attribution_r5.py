"""Op-level attribution of the flagship LM step vs the MEASURED chip
ceiling (VERDICT r4 #3).

The r4 headline (48-51% of nominal 197 TF/s) leaves ~75% of the chip's
measured ~400 TF/s bf16 dense ceiling unexplained. This script breaks the
dim-1024/12-layer flagship step into op groups, times each with the
corrected protocol (chained-scan marginals, device-computed scalar
readbacks, same-process comparisons only), and pulls the levers found:

measured groups per (T, B):
  full_step      fwd + bwd + AdamW (best config: remat + chunked CE)
  fwd_bwd        loss grad only            -> opt = full_step - fwd_bwd
  fwd_only       loss value only           -> bwd = fwd_bwd - fwd_only
  attention      12x flash fwd+bwd at the model's (B, T, 16, 64)
  ce_chunked     chunked CE fwd+bwd on (B, T, D) hidden + (D, V) head
  adamw_only     opt.update + apply over a fixed grad tree
  matmul_core    the step's big matmuls (qkv/proj/mlp/head) fwd+bwd
  hbm_bw         elementwise-pass GB/s (memory-bound denominator)

Each group records FLOPs, a bytes-moved estimate, achieved TF/s, and a
bound verdict: compute-bound (time ~ flops/400TF) vs memory-bound
(time ~ bytes/measured-BW). levers: mu_dtype=bf16, batch growth, and the
flash BLOCK_TABLE from results/flash_blocks_r5.json when present.

Run alone on the real chip. Writes results/lm_mfu_bench_r5.json.
CPU plumbing check: --smoke (tiny shapes, numbers meaningless).
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import optax

sys.path.insert(0, ".")
from fedml_tpu.models.transformer import TransformerLM  # noqa: E402
from fedml_tpu.ops.losses import chunked_lm_cross_entropy  # noqa: E402
from fedml_tpu.ops.pallas.flash_attention import (  # noqa: E402
    BLOCK_TABLE, flash_attention)

NOMINAL_TF = 197.0
MEASURED_TF = 400.0
VOCAB, DIM, LAYERS, HEADS = 32000, 1024, 12, 16
DH = DIM // HEADS
N1, N2 = 3, 23
POINTS = ((2048, 8), (2048, 16), (8192, 4))
SMOKE = "--smoke" in sys.argv
if SMOKE:
    VOCAB, DIM, LAYERS, HEADS = 256, 64, 2, 4
    DH = DIM // HEADS
    N1, N2 = 1, 3
    POINTS = ((256, 2),)


def marginal(build_loop) -> float:
    """build_loop(n) -> jitted fn returning a scalar; marginal sec/step."""
    res = {}
    for n in (N1, N2):
        f = build_loop(n)
        float(f())  # compile + warm
        ts = []
        for _ in range(4):
            t0 = time.perf_counter()
            float(f())
            ts.append(time.perf_counter() - t0)
        res[n] = min(ts)
    return (res[N2] - res[N1]) / (N2 - N1)


def scan_loop(step_fn, carry_init):
    """Standard chained-scan harness: step_fn(carry) -> carry."""
    def build(n):
        @jax.jit
        def run():
            def body(c, _):
                return step_fn(c), None
            c, _ = jax.lax.scan(body, carry_init, None, length=n)
            return jax.tree_util.tree_reduce(
                lambda a, l: a + l.astype(jnp.float32).sum() * 1e-12,
                jax.tree_util.tree_leaves(c), 0.0)
        return run
    return build


def bound_verdict(sec, flops, bytes_moved, bw_gbs):
    t_flops = flops / (MEASURED_TF * 1e12)
    t_mem = bytes_moved / (bw_gbs * 1e9) if bw_gbs else 0.0
    pred = max(t_flops, t_mem)
    return {
        "tflops_per_sec": round(flops / sec / 1e12, 1),
        "pct_of_measured_ceiling": round(100 * flops / sec / 1e12
                                         / MEASURED_TF, 1),
        "compute_floor_ms": round(t_flops * 1e3, 3),
        "memory_floor_ms": round(t_mem * 1e3, 3),
        "measured_ms": round(sec * 1e3, 3),
        "bound": ("memory" if t_mem > t_flops else "compute"),
        "efficiency_vs_floor": round(pred / sec, 2) if sec > 0 else None,
    }


def main():
    print("devices:", jax.devices(), flush=True)
    out = {"model": {"vocab": VOCAB, "dim": DIM, "layers": LAYERS,
                     "heads": HEADS},
           "protocol": (f"chained-scan marginal {N1}/{N2}, min of 4 walls, "
                        "scalar readback; same-process comparisons only"),
           "denominators": {"nominal_tf": NOMINAL_TF,
                           "measured_ceiling_tf": MEASURED_TF},
           "points": []}

    # adopt confirmed flash blocks if the r5 sweep artifact exists
    fb = "results/flash_blocks_r5.json"
    if os.path.exists(fb):
        adopt = json.load(open(fb)).get("table_adopt", {})
        for tt, (bq, bk) in adopt.items():
            BLOCK_TABLE[int(tt)] = (bq, bk)
        out["flash_block_table"] = {int(t): v for t, v in adopt.items()}

    # --- HBM bandwidth denominator --------------------------------------
    nbytes = 1 << 28 if not SMOKE else 1 << 20  # 256 MB bf16 elements
    big = jnp.ones(nbytes // 2, jnp.bfloat16)
    sec = marginal(scan_loop(lambda x: x * 1.000001, big))
    bw_gbs = 2 * nbytes / sec / 1e9  # one read + one write per pass
    out["hbm_bw_gbs"] = round(bw_gbs, 1)
    print(f"hbm bw: {bw_gbs:.0f} GB/s", flush=True)

    for T, B in POINTS:
        pt = {"T": T, "B": B, "groups": {}}
        model = TransformerLM(vocab_size=VOCAB, dim=DIM, num_heads=HEADS,
                              num_layers=LAYERS, max_len=max(T, 2048),
                              dtype=jnp.bfloat16, remat=True)
        rng = jax.random.PRNGKey(0)
        tokens = jax.random.randint(rng, (B, T), 0, VOCAB)
        params = model.init(rng, tokens[:, :8])
        n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
        n_active = n_params - (VOCAB * DIM + max(T, 2048) * DIM)
        ce_chunk = 256 if T % 256 == 0 else T // 4

        def make_loss_fn(m):
            def loss_fn(p, toks):
                hid = m.apply(p, toks, train=True, return_hidden=True)
                head = p["params"]["head"]["kernel"].astype(hid.dtype)
                return chunked_lm_cross_entropy(hid, head,
                                                jnp.roll(toks, -1, axis=1),
                                                chunk=ce_chunk)
            return loss_fn

        loss_fn = make_loss_fn(model)
        grad_fn = jax.value_and_grad(loss_fn)

        def steps_for(opt, gfn=None):
            gfn = gfn or grad_fn
            st = opt.init(params)

            def full(c):
                p, s, toks = c
                _, g = gfn(p, toks)
                up, s = opt.update(g, s, p)
                return (optax.apply_updates(p, up), s,
                        jnp.roll(toks, 1, axis=0))
            return full, st

        opt = optax.adamw(3e-4, weight_decay=0.01)
        full, opt_state = steps_for(opt)

        # FLOP accounting (PaLM convention)
        toks_step = B * T
        attn_flops = 2 * 2 * 2 * LAYERS * (T * T / 2) * DIM * B
        fwd_flops = 2 * n_active * toks_step + attn_flops
        train_flops = 3 * fwd_flops
        pbytes = 4 * n_params  # f32 params

        # 1. full step
        sec = marginal(scan_loop(full, (params, opt_state, tokens)))
        pt["groups"]["full_step"] = dict(
            bound_verdict(sec, train_flops,
                          # params read+write, mu/nu read+write, grads
                          bytes_moved=pbytes * 6,
                          bw_gbs=bw_gbs),
            tokens_per_sec=int(toks_step / sec))
        full_sec = sec

        # 2. fwd+bwd only
        def fwd_bwd(c):
            p, toks = c
            l, g = grad_fn(p, toks)
            scale = 1e-12 * l
            p2 = jax.tree.map(lambda a, b: a + scale * b.astype(a.dtype)
                              if a.dtype.kind == "f" else a, p, g)
            return (p2, jnp.roll(toks, 1, axis=0))
        sec_fb = marginal(scan_loop(fwd_bwd, (params, tokens)))
        pt["groups"]["fwd_bwd"] = bound_verdict(
            sec_fb, train_flops, pbytes * 3, bw_gbs)

        # 3. fwd only
        def fwd_only(c):
            p, toks, acc = c
            return (p, jnp.roll(toks, 1, axis=0), acc + loss_fn(p, toks))
        sec_f = marginal(scan_loop(fwd_only, (params, tokens, 0.0)))
        pt["groups"]["fwd_only"] = bound_verdict(
            sec_f, fwd_flops, pbytes, bw_gbs)

        # derived splits
        pt["derived"] = {
            "bwd_ms": round((sec_fb - sec_f) * 1e3, 2),
            "optimizer_ms": round((full_sec - sec_fb) * 1e3, 2),
        }

        # 4. attention isolated (12 layers' worth)
        qkv = tuple(jax.random.normal(k, (B, T, HEADS, DH), jnp.bfloat16) * .3
                    for k in jax.random.split(rng, 3))
        ag = jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, True).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2))

        def attn_step(c):
            q = c
            dq, dk, dv = ag(q, *qkv[1:])
            return q + 1e-12 * (dq + dk + dv)
        sec_a = marginal(scan_loop(attn_step, qkv[0]))
        pt["groups"]["attention_x12"] = bound_verdict(
            LAYERS * sec_a, 3 * attn_flops,
            LAYERS * 3 * (3 * B * T * HEADS * DH * 2), bw_gbs)

        # 5. chunked CE isolated
        hid0 = jax.random.normal(rng, (B, T, DIM), jnp.bfloat16) * 0.3
        head0 = params["params"]["head"]["kernel"].astype(jnp.bfloat16)
        tgt = jnp.roll(tokens, -1, axis=1)
        cg = jax.grad(lambda h: chunked_lm_cross_entropy(
            h, head0, tgt, chunk=ce_chunk))

        def ce_step(c):
            return c + 1e-3 * cg(c)
        sec_c = marginal(scan_loop(ce_step, hid0))
        ce_flops = 3 * 2 * B * T * DIM * VOCAB
        pt["groups"]["ce_chunked"] = bound_verdict(
            sec_c, ce_flops, 3 * DIM * VOCAB * 2, bw_gbs)

        # 6. AdamW isolated (fixed grads)
        g0 = jax.tree.map(jnp.ones_like, params)

        def adamw_step(c):
            p, s = c
            up, s = opt.update(g0, s, p)
            return (optax.apply_updates(p, up), s)
        sec_o = marginal(scan_loop(adamw_step, (params, opt.init(params))))
        pt["groups"]["adamw_only"] = bound_verdict(
            sec_o, 10 * n_params, pbytes * 6, bw_gbs)

        # 7. matmul core: the step's big matmuls fwd+bwd (qkv, proj,
        # mlp x2 per layer + head), as plain dense matmuls
        x0 = jax.random.normal(rng, (B * T, DIM), jnp.bfloat16) * 0.3
        shapes = {"qkv": (DIM, 3 * DIM), "proj": (DIM, DIM),
                  "up": (DIM, 4 * DIM), "down": (4 * DIM, DIM),
                  "head": (DIM, VOCAB)}
        wm = {k: jax.random.normal(jax.random.PRNGKey(i), s, jnp.bfloat16)
              * 0.02 for i, (k, s) in enumerate(shapes.items())}

        def mm_loss(x):
            # chain the step's big matmuls per layer so none is DCE-able
            h = x
            acc = jnp.float32(0)
            for _ in range(LAYERS):
                qkv = h @ wm["qkv"]
                acc += jnp.sum(qkv.astype(jnp.float32) ** 2) * 1e-9
                h = h @ wm["proj"]
                u = h @ wm["up"]
                h = (u @ wm["down"]) * 0.01 + h
            logits = h @ wm["head"]
            return acc + jnp.sum(logits.astype(jnp.float32) ** 2) * 1e-9
        mg = jax.grad(mm_loss)
        ws = ([shapes["qkv"], shapes["proj"], shapes["up"], shapes["down"]]
              * LAYERS + [shapes["head"]])

        def mm_step(c):
            return c + 1e-12 * mg(c)
        sec_m = marginal(scan_loop(mm_step, x0))
        mm_flops = 3 * sum(2 * B * T * a * b for a, b in ws)
        pt["groups"]["matmul_core"] = bound_verdict(
            sec_m, mm_flops, sum(a * b for a, b in ws) * 2 * 3, bw_gbs)

        # --- levers (same process) --------------------------------------
        levers = {}
        opt_bf = optax.adamw(3e-4, weight_decay=0.01,
                             mu_dtype=jnp.bfloat16)
        full_bf, st_bf = steps_for(opt_bf)
        sec_bf = marginal(scan_loop(full_bf, (params, st_bf, tokens)))
        levers["mu_dtype_bf16"] = {
            "step_ms": round(sec_bf * 1e3, 2),
            "vs_f32_mu": round(full_sec / sec_bf, 3),
        }
        # remat="dots": save matmul outputs, recompute only elementwise —
        # reclaims most of full remat's ~1.3x recompute FLOPs if the
        # extra saved activations still fit HBM at this (T, B)
        sec_d = None
        try:
            model_d = TransformerLM(
                vocab_size=VOCAB, dim=DIM, num_heads=HEADS,
                num_layers=LAYERS, max_len=max(T, 2048),
                dtype=jnp.bfloat16, remat="dots")
            gd = jax.value_and_grad(make_loss_fn(model_d))
            full_d, st_d = steps_for(opt, gfn=gd)
            sec_d = marginal(scan_loop(full_d, (params, st_d, tokens)))
            levers["remat_dots"] = {
                "step_ms": round(sec_d * 1e3, 2),
                "vs_full_remat": round(full_sec / sec_d, 3),
            }
        except Exception as e:  # OOM at long T is an expected outcome
            levers["remat_dots"] = f"failed: {repr(e)[:120]}"
        pt["levers"] = levers
        best = min(s for s in (full_sec, sec_bf, sec_d) if s is not None)
        pt["headline"] = {
            "best_step_ms": round(best * 1e3, 2),
            "train_tflops_per_sec": round(train_flops / best / 1e12, 1),
            "mfu_vs_nominal": round(
                train_flops / best / 1e12 / NOMINAL_TF, 3),
            "mfu_vs_measured_ceiling": round(
                train_flops / best / 1e12 / MEASURED_TF, 3),
        }
        out["points"].append(pt)
        print(json.dumps(pt), flush=True)

    with open("results/lm_mfu_bench_r5.json", "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print("wrote results/lm_mfu_bench_r5.json", flush=True)


if __name__ == "__main__":
    main()
