"""Transport microbenchmark: loopback / gRPC / TRPC round-trip + throughput.

Parity: reference ``test/grpc_benchmark/`` (standalone gRPC throughput bench
with its own proto and multi-machine launcher — no committed results). Here
one script covers every in-repo point-to-point backend, measures median
round-trip latency and payload throughput for model-sized tensors, and
prints ONE JSON line per backend so results can be committed.

Usage:  python scripts/bench_transport.py [--sizes 1000,1000000] [--repeats 5]
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

import numpy as np

sys.path.insert(0, __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__))))

from fedml_tpu.comm.message import Message  # noqa: E402


class _Collector:
    def __init__(self):
        self.event = threading.Event()
        self.payload = None

    def receive_message(self, msg_type, msg):
        self.payload = msg.get("tensor")
        self.event.set()


def _bench_pair(send_mgr, recv_mgr, sizes, repeats):
    col = _Collector()
    recv_mgr.add_observer(col)
    loop = threading.Thread(target=recv_mgr.handle_receive_message, daemon=True)
    loop.start()
    out = {}
    for n in sizes:
        payload = np.arange(n, dtype=np.float32)
        times = []
        for _ in range(repeats):
            col.event.clear()
            msg = Message(type="bench", sender_id=0, receiver_id=1)
            msg.add_params("tensor", payload)
            t0 = time.perf_counter()
            send_mgr.send_message(msg)
            assert col.event.wait(timeout=60), "delivery timed out"
            times.append(time.perf_counter() - t0)
            np.testing.assert_array_equal(col.payload, payload)
        times.sort()
        median = times[len(times) // 2]
        out[n] = {
            "latency_ms": round(median * 1e3, 3),
            "throughput_MBps": round(payload.nbytes / median / 1e6, 1),
        }
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="1000,100000,10000000")
    ap.add_argument("--repeats", type=int, default=5)
    args = ap.parse_args()
    sizes = [int(s) for s in args.sizes.split(",")]

    results = {}

    from fedml_tpu.comm.loopback import LoopbackCommManager, LoopbackHub

    hub = LoopbackHub()
    lb0 = LoopbackCommManager(rank=0, size=2, hub=hub)
    lb1 = LoopbackCommManager(rank=1, size=2, hub=hub)
    results["LOOPBACK"] = _bench_pair(lb0, lb1, sizes, args.repeats)
    lb0.stop_receive_message(), lb1.stop_receive_message()

    from fedml_tpu.comm.trpc_backend import TRPCCommManager

    t0m = TRPCCommManager(rank=0, size=2, base_port=23890)
    t1m = TRPCCommManager(rank=1, size=2, base_port=23890)
    results["TRPC"] = _bench_pair(t0m, t1m, sizes, args.repeats)
    t0m.stop_receive_message(), t1m.stop_receive_message()

    try:
        from fedml_tpu.comm.grpc_backend import GRPCCommManager

        g0 = GRPCCommManager(rank=0, size=2, base_port=23990)
        g1 = GRPCCommManager(rank=1, size=2, base_port=23990)
        results["GRPC"] = _bench_pair(g0, g1, sizes, args.repeats)
        g0.stop_receive_message(), g1.stop_receive_message()
    except ImportError:
        results["GRPC"] = "grpcio unavailable"

    for backend, r in results.items():
        print(json.dumps({"backend": backend, "results": r}))


if __name__ == "__main__":
    main()
