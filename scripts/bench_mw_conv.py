"""Microbench: multi-weight conv impls at the ResNet-56 packed-lane shapes.

Measures marginal ms/step via the two-chained-scan-lengths protocol (fixed
dispatch overhead cancels; forced np.asarray readback — block_until_ready is
unreliable on the tunneled chip). Writes results/mw_conv_bench.json.

Run alone on the real chip: `python scripts/bench_mw_conv.py` (default env
dials the axon TPU; do not run concurrently with any other JAX process).
"""

from __future__ import annotations

import functools
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
from fedml_tpu.ops.conv import conv2d_im2col, conv2d_pallas  # noqa: E402

L, B = 6, 64          # lanes x per-lane batch (lane_sweep_r3 configuration)
STAGES = [(32, 32, 16), (16, 16, 64), (8, 8, 128)]
N1, N2 = 10, 510   # 500-step delta: tunnel jitter (±30-60 ms/invocation)
                   # needs ≥50 ms of marginal compute to resolve sub-0.1ms ops
DTYPE = jnp.bfloat16


def run_case(make_step, init_carry, flops_per_step):
    """Returns marginal seconds/step and TFLOP/s.

    The loop returns a device-computed SCALAR — the readback that forces
    retirement must be 4 bytes, not the full carry (a multi-MB tunnel
    transfer whose jitter would swamp the marginal)."""
    results = {}
    for n in (N1, N2):
        def loop(carry):
            def body(c, _):
                return make_step(c), None
            c, _ = jax.lax.scan(body, carry, None, length=n)
            leaves = jax.tree_util.tree_leaves(c)
            return sum(l.astype(jnp.float32).sum() for l in leaves)
        loop_j = jax.jit(loop)
        float(loop_j(init_carry))            # compile + warm
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            float(loop_j(init_carry))        # scalar readback retires all
            ts.append(time.perf_counter() - t0)
        results[n] = min(ts)
    marginal = (results[N2] - results[N1]) / (N2 - N1)
    return marginal, flops_per_step / marginal / 1e12


def conv_xla(x, w):
    return jax.lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))


def main():
    print("devices:", jax.devices())
    out = {"config": {"L": L, "B": B, "dtype": "bf16", "protocol":
           f"marginal from scan lengths {N1}/{N2}, min of 3, forced readback"},
           "cases": {}}

    for (h, w, c) in STAGES:
        key = f"{h}x{w}x{c}"
        out["cases"][key] = {}
        rng = np.random.RandomState(0)
        xs = jnp.asarray(rng.randn(L, B, h, w, c), DTYPE) * 0.1
        ws = jnp.asarray(rng.randn(L, 3, 3, c, c), DTYPE) * 0.05
        x1 = xs.reshape(L * B, h, w, c)
        w1 = ws[0]
        # FLOPs: fwd = 2*M*K*N per lane; fwd+bwd = 3x
        flops_fwd = 2 * (L * B * h * w) * (9 * c) * c

        # ---- forward-only ----
        fwd_impls = {
            "shared_xla": (lambda xc: (conv_xla(xc, w1) * 0.1).astype(DTYPE), x1),
            "vmap_xla_grouped": (lambda xc: (jax.vmap(conv_xla)(xc, ws) * 0.1).astype(DTYPE), xs),
            "vmap_im2col": (lambda xc: (jax.vmap(
                functools.partial(conv2d_im2col, stride=1, padding="SAME"))(xc, ws) * 0.1).astype(DTYPE), xs),
            "vmap_pallas": (lambda xc: (jax.vmap(
                functools.partial(conv2d_pallas, stride=1, padding="SAME"))(xc, ws) * 0.1).astype(DTYPE), xs),
        }
        for name, (step, init) in fwd_impls.items():
            try:
                m, tf = run_case(step, init, flops_fwd)
                out["cases"][key][f"fwd_{name}"] = {
                    "ms_per_step": round(m * 1e3, 4), "tflops": round(tf, 2)}
                print(f"{key} fwd {name}: {m*1e3:.3f} ms  {tf:.1f} TF/s", flush=True)
            except Exception as e:
                out["cases"][key][f"fwd_{name}"] = {"error": repr(e)[:300]}
                print(f"{key} fwd {name}: FAILED {repr(e)[:200]}", flush=True)

        # ---- fwd+bwd (x and w grads; carry both to chain iterations) ----
        def make_train(conv_fn, vmapped):
            def loss(xc, wc):
                y = (jax.vmap(conv_fn)(xc, wc) if vmapped else conv_fn(xc, wc))
                return (y.astype(jnp.float32) ** 2).mean()

            def step(carry):
                xc, wc = carry
                dx, dw = jax.grad(loss, argnums=(0, 1))(xc, wc)
                return ((xc + dx.astype(DTYPE) * 0.01).astype(DTYPE),
                        (wc - dw.astype(DTYPE) * 0.01).astype(DTYPE))
            return step

        bwd_impls = {
            "shared_xla": (make_train(conv_xla, False), (x1, w1)),
            "vmap_xla_grouped": (make_train(conv_xla, True), (xs, ws)),
            "vmap_im2col": (make_train(
                functools.partial(conv2d_im2col, stride=1, padding="SAME"), True), (xs, ws)),
            "vmap_pallas": (make_train(
                functools.partial(conv2d_pallas, stride=1, padding="SAME"), True), (xs, ws)),
        }
        for name, (step, init) in bwd_impls.items():
            try:
                m, tf = run_case(step, init, 3 * flops_fwd)
                out["cases"][key][f"train_{name}"] = {
                    "ms_per_step": round(m * 1e3, 4), "tflops": round(tf, 2)}
                print(f"{key} train {name}: {m*1e3:.3f} ms  {tf:.1f} TF/s", flush=True)
            except Exception as e:
                out["cases"][key][f"train_{name}"] = {"error": repr(e)[:300]}
                print(f"{key} train {name}: FAILED {repr(e)[:200]}", flush=True)

    with open("results/mw_conv_bench.json", "w") as f:
        json.dump(out, f, indent=1)
    print("wrote results/mw_conv_bench.json")


if __name__ == "__main__":
    main()
