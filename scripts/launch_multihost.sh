#!/usr/bin/env bash
# Multi-host launcher (reference parity: cross_silo/hierarchical/
# dist_trainer_launcher.py:23 uses pdsh + torchrun; on TPU pods the
# coordination service replaces the rendezvous backend).
#
# Usage:
#   ./launch_multihost.sh <coordinator_ip:port> <num_hosts> <host_id> <entry.py> [args...]
#
# Each host of a pod slice runs this with its own host_id (0..num_hosts-1);
# fedml_tpu.init() picks the env vars up via
# parallel/mesh.py:maybe_initialize_distributed -> jax.distributed.initialize.
set -euo pipefail

COORD=${1:?coordinator ip:port}
NUM=${2:?num hosts}
ID=${3:?host id}
ENTRY=${4:?entry script}
shift 4

export JAX_COORDINATOR_ADDRESS="$COORD"
export JAX_NUM_PROCESSES="$NUM"
export JAX_PROCESS_ID="$ID"

exec python "$ENTRY" "$@"
