"""Cross-framework parity: the jitted engine vs the reference torch hot loop.

The strongest accuracy-parity evidence available in a zero-egress image
(VERDICT r2 weak #4): run the reference framework's FedAvg semantics —
replicated here in torch, on this machine's CPU — and the fedml_tpu jitted
engine on *identical* data, *identical* client sampling, *identical*
per-client batch permutations, *identical* initial weights, and assert the
per-round train-loss curves and the final global parameters agree to f32
tolerance.

Reference semantics replicated on the torch side:
- client sampling: the engine's pure per-round sampler
  (``fedml_tpu.simulation.sampling.sample_clients`` — a
  ``default_rng([seed, round])`` no-replacement draw; the reference's
  global ``np.random.seed(round_idx)`` stream survives as
  ``reference_client_sampling`` for the cross-silo server, but the
  simulation engines no longer consume it)
- local training: ``simulation/sp/fedavg/my_model_trainer_classification.py:15``
  (plain SGD, mean-reduction CE on logits, fixed batch order, ``epochs`` passes)
- aggregation: ``fedavg_api.py:156-171`` (sample-count weighted mean over the
  full weight set)

Determinism bridge: both sides consume the engine's per-client shuffle
streams ``np.random.default_rng([seed, round, client_id])`` (the engine's
``FedSimulator._client_perms``; the reference's DataLoader shuffle is an
unseeded torch generator, so batch ORDER is the one free variable — pinning
it to the same deterministic stream on both sides is what makes bitwise-level
comparison possible). The torch models mirror the flax modules exactly
(flatten in NHWC order) so initial weights transfer by transpose alone.

Usage: python scripts/parity_vs_reference.py
Writes results/parity_vs_reference.json.
"""

from __future__ import annotations

import copy
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BS = 16


# --- synthetic data (identical arrays feed both frameworks) ---------------

def make_synth(n_clients, sizes, feat_shape, n_classes, seed,
               test_per_client=24):
    rng = np.random.default_rng(seed)
    total = sum(sizes)
    # class-dependent means so the loss visibly falls
    y = rng.integers(0, n_classes, size=total).astype(np.int64)
    centers = rng.normal(0.0, 1.0, size=(n_classes,) + tuple(feat_shape))
    x = (centers[y] + rng.normal(0.0, 1.0, size=(total,) + tuple(feat_shape))
         ).astype(np.float32)
    idx_map, start = {}, 0
    for c, n in enumerate(sizes):
        idx_map[c] = list(range(start, start + n))
        start += n
    # per-client local TEST splits (same generative process) so the
    # _local_test_on_all_clients comparison exercises distinct local sets
    n_test = test_per_client * n_clients
    ty = rng.integers(0, n_classes, size=n_test).astype(np.int64)
    tx = (centers[ty]
          + rng.normal(0.0, 1.0, size=(n_test,) + tuple(feat_shape))
          ).astype(np.float32)
    test_idx_map = {
        c: list(range(c * test_per_client, (c + 1) * test_per_client))
        for c in range(n_clients)
    }
    return x, y, idx_map, tx, ty, test_idx_map


# --- engine side ----------------------------------------------------------

def run_engine(model_name, x, y, idx_map, n_classes, per_round, rounds,
               epochs, lr, seed, tx, ty, test_idx_map):
    import jax

    # Parity is about ALGORITHM semantics, so pin true-f32 math: on TPU the
    # default matmul/conv precision decomposes f32 into bf16 passes, which
    # drifts past the tolerance over rounds (measured: cnn 0.057 loss diff
    # at default vs ~1e-4 at highest). CPU is unaffected.
    jax.config.update("jax_default_matmul_precision", "highest")

    import fedml_tpu
    from fedml_tpu.data.federated import ArrayPair, build_federated_data
    from fedml_tpu.simulation import build_simulator

    fed = build_federated_data(
        ArrayPair(x, y.astype(np.int32)), ArrayPair(tx, ty.astype(np.int32)),
        idx_map, n_classes, test_idx_map=test_idx_map,
    )
    args = fedml_tpu.init(config=dict(
        dataset="synthetic_parity", model=model_name,
        client_num_in_total=len(idx_map), client_num_per_round=per_round,
        comm_round=rounds, learning_rate=lr, epochs=epochs, batch_size=BS,
        frequency_of_the_test=1, random_seed=seed,
        cohort_schedule="even", local_test_on_all_clients=True,
    ))
    sim, apply_fn = build_simulator(args, fed_data=fed)
    # real copies, not views: the round step donates the params buffers
    init_params = jax.tree.map(lambda a: np.array(a, copy=True), sim.params)
    hist = sim.run(apply_fn=apply_fn, log_fn=None)
    final_params = jax.tree.map(np.asarray, sim.params)
    losses = [h["train_loss"] for h in hist]
    local_metrics = [
        {k: h[k] for k in ("local_train_acc", "local_train_loss",
                           "local_test_acc", "local_test_loss")}
        for h in hist
    ]
    return init_params, final_params, losses, local_metrics


# --- reference-semantics torch side --------------------------------------

def _torch_models(model_name, flax_params, n_classes, feat_shape):
    """Build the torch mirror and load the flax initial weights into it."""
    import torch
    import torch.nn as nn

    p = flax_params["params"]
    if model_name == "lr":
        d = int(np.prod(feat_shape))

        class LR(nn.Module):
            def __init__(self):
                super().__init__()
                self.linear = nn.Linear(d, n_classes)

            def forward(self, x):
                return self.linear(x.flatten(1))

        m = LR()
        with torch.no_grad():
            m.linear.weight.copy_(torch.from_numpy(np.asarray(p["linear"]["kernel"]).T))
            m.linear.bias.copy_(torch.from_numpy(np.asarray(p["linear"]["bias"])))
        return m

    if model_name == "cnn_fedavg":
        # mirror of models/cnn.py CNNOriginalFedAvg; flattens in NHWC order so
        # flax dense kernels transfer by plain transpose
        class CNN(nn.Module):
            def __init__(self):
                super().__init__()
                self.c1 = nn.Conv2d(feat_shape[-1], 32, 5, padding=2)
                self.c2 = nn.Conv2d(32, 64, 5, padding=2)
                self.d1 = nn.Linear(64 * (feat_shape[0] // 4) * (feat_shape[1] // 4), 512)
                self.d2 = nn.Linear(512, n_classes)
                self.pool = nn.MaxPool2d(2, 2)

            def forward(self, x):
                x = x.permute(0, 3, 1, 2)  # NHWC input -> NCHW convs
                x = self.pool(torch.relu(self.c1(x)))
                x = self.pool(torch.relu(self.c2(x)))
                x = x.permute(0, 2, 3, 1).flatten(1)  # NHWC flatten = flax
                return self.d2(torch.relu(self.d1(x)))

        m = CNN()
        with torch.no_grad():
            for tmod, fkey in ((m.c1, "Conv_0"), (m.c2, "Conv_1")):
                k = np.asarray(p[fkey]["kernel"])  # (H, W, Cin, Cout)
                tmod.weight.copy_(torch.from_numpy(k.transpose(3, 2, 0, 1).copy()))
                tmod.bias.copy_(torch.from_numpy(np.asarray(p[fkey]["bias"])))
            for tmod, fkey in ((m.d1, "Dense_0"), (m.d2, "Dense_1")):
                k = np.asarray(p[fkey]["kernel"])  # (in, out)
                tmod.weight.copy_(torch.from_numpy(k.T.copy()))
                tmod.bias.copy_(torch.from_numpy(np.asarray(p[fkey]["bias"])))
        return m

    raise ValueError(model_name)


def run_torch_reference(model_name, flax_init, x, y, idx_map, n_classes,
                        per_round, rounds, epochs, lr, seed, feat_shape,
                        tx, ty, test_idx_map):
    import torch
    import torch.nn as nn

    torch.manual_seed(0)
    model = _torch_models(model_name, flax_init, n_classes, feat_shape)
    criterion = nn.CrossEntropyLoss()
    n_total = len(idx_map)
    w_global = copy.deepcopy(model.state_dict())
    losses_per_round = []
    local_metrics_per_round = []

    def local_test_on_all_clients():
        """fedavg_api.py:188-246 + my_model_trainer_classification.local_test
        (sum-of-per-sample-loss accumulation): weighted aggregates over
        every client's local train and test split under w_global."""
        model.load_state_dict(w_global)
        model.eval()
        sum_crit = nn.CrossEntropyLoss(reduction="sum")
        out = {}
        for split, data, split_map in (
            ("train", (x, y), idx_map), ("test", (tx, ty), test_idx_map)
        ):
            n_corr = n_samp = loss_sum = 0.0
            with torch.no_grad():
                for cid in range(n_total):
                    rows = np.asarray(split_map[int(cid)])
                    bx = torch.from_numpy(data[0][rows])
                    by = torch.from_numpy(data[1][rows])
                    logits = model(bx)
                    loss_sum += float(sum_crit(logits, by).item())
                    n_corr += float((logits.argmax(-1) == by).sum().item())
                    n_samp += len(rows)
            key = "local_train" if split == "train" else "local_test"
            out[f"{key}_acc"] = n_corr / n_samp
            out[f"{key}_loss"] = loss_sum / n_samp
        return out

    for round_idx in range(rounds):
        # lockstep with the engine's pure per-round sampler (the engine
        # moved off the reference's global np.random.seed(round_idx) stream;
        # parity means drawing the SAME cohorts the engine draws)
        from fedml_tpu.simulation.sampling import sample_clients

        cohort = np.asarray(
            sample_clients(seed, round_idx, n_total, per_round))
        w_locals, client_losses = [], []
        for cid in cohort:
            model.load_state_dict(copy.deepcopy(w_global))
            model.train()
            opt = torch.optim.SGD(model.parameters(), lr=lr)
            rows = np.asarray(idx_map[int(cid)])
            # the engine's deterministic local-epoch shuffle
            perm = np.random.default_rng(
                [seed, round_idx, int(cid)]).permutation(len(rows))
            order = rows[perm]
            nb = len(order) // BS
            batch_losses = []
            for _ in range(epochs):
                for b in range(nb):
                    sel = order[b * BS:(b + 1) * BS]
                    bx = torch.from_numpy(x[sel])
                    by = torch.from_numpy(y[sel])
                    model.zero_grad()
                    loss = criterion(model(bx), by)
                    loss.backward()
                    opt.step()
                    batch_losses.append(loss.item())
            client_losses.append(float(np.mean(batch_losses)))
            w_locals.append((len(rows), copy.deepcopy(model.state_dict())))
        # fedavg_api.py:156-171 sample-weighted aggregation
        training_num = sum(n for n, _ in w_locals)
        agg = {}
        for k in w_locals[0][1]:
            agg[k] = sum((n / training_num) * w[k] for n, w in w_locals)
        w_global = agg
        losses_per_round.append(float(np.mean(client_losses)))
        local_metrics_per_round.append(local_test_on_all_clients())

    model.load_state_dict(w_global)
    return model, losses_per_round, local_metrics_per_round


def _flax_to_flat(model_name, flax_params):
    """Flax params -> {torch_key: np.ndarray} for comparison."""
    p = flax_params["params"]
    if model_name == "lr":
        return {"linear.weight": np.asarray(p["linear"]["kernel"]).T,
                "linear.bias": np.asarray(p["linear"]["bias"])}
    out = {}
    for tkey, fkey in (("c1", "Conv_0"), ("c2", "Conv_1")):
        out[f"{tkey}.weight"] = np.asarray(
            p[fkey]["kernel"]).transpose(3, 2, 0, 1)
        out[f"{tkey}.bias"] = np.asarray(p[fkey]["bias"])
    for tkey, fkey in (("d1", "Dense_0"), ("d2", "Dense_1")):
        out[f"{tkey}.weight"] = np.asarray(p[fkey]["kernel"]).T
        out[f"{tkey}.bias"] = np.asarray(p[fkey]["bias"])
    return out


def run_parity(model_name, feat_shape, n_classes, sizes, per_round, rounds,
               epochs, lr, seed=3):
    x, y, idx_map, tx, ty, test_idx_map = make_synth(
        len(sizes), sizes, feat_shape, n_classes, seed)
    flax_init, flax_final, engine_losses, engine_local = run_engine(
        model_name, x, y, idx_map, n_classes, per_round, rounds, epochs, lr,
        seed, tx, ty, test_idx_map)
    torch_model, torch_losses, torch_local = run_torch_reference(
        model_name, flax_init, x, y, idx_map, n_classes, per_round, rounds,
        epochs, lr, seed, feat_shape, tx, ty, test_idx_map)

    loss_diffs = [abs(a - b) for a, b in zip(engine_losses, torch_losses)]
    # per-round _local_test_on_all_clients METRIC VALUES must match too —
    # the reference's reported numbers, not just the final params
    local_keys = ("local_train_acc", "local_train_loss",
                  "local_test_acc", "local_test_loss")
    local_diffs = [
        abs(e[k] - t[k])
        for e, t in zip(engine_local, torch_local) for k in local_keys
    ]
    flat = _flax_to_flat(model_name, flax_final)
    sd = torch_model.state_dict()
    param_diff = max(
        float(np.max(np.abs(flat[k] - sd[k].numpy()))) for k in flat
    )
    return {
        "model": model_name,
        "rounds": rounds,
        "engine_losses": engine_losses,
        "reference_losses": torch_losses,
        "engine_local_metrics": engine_local,
        "reference_local_metrics": torch_local,
        "max_abs_loss_diff": max(loss_diffs),
        "max_abs_local_metric_diff": max(local_diffs),
        "max_abs_param_diff": param_diff,
        "loss_tol": 2e-3,
        "param_tol": 2e-3,
        "pass": (max(loss_diffs) < 2e-3 and param_diff < 2e-3
                 and max(local_diffs) < 2e-3),
    }


def main():
    import jax

    results = {
        "engine_backend": (
            f"{jax.default_backend()} (jax_default_matmul_precision=highest "
            "is pinned by the harness: TPU's default precision decomposes "
            "f32 matmuls/convs into bf16 passes — a hardware numeric mode, "
            "not an algorithm-semantics difference, and it drifts the CNN "
            "case past tolerance over rounds)"),
        "basis": (
            "reference FedAvg semantics (engine sample_clients cohorts, "
            "trainer my_model_trainer_classification.py:15, aggregation "
            "fedavg_api.py:156-171) replicated in torch on this CPU vs the "
            "fedml_tpu jitted engine; identical data/init/sampling/batch "
            "permutations, f32 both sides"
        ),
        "cases": [
            run_parity("lr", (32,), 5, sizes=[64, 48, 32, 64, 48, 32, 64, 64],
                       per_round=4, rounds=6, epochs=2, lr=0.1),
            run_parity("cnn_fedavg", (28, 28, 1), 10,
                       sizes=[32, 32, 48, 32, 48, 32],
                       per_round=3, rounds=4, epochs=1, lr=0.05),
        ],
    }
    results["pass"] = all(c["pass"] for c in results["cases"])
    out = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                       "results", "parity_vs_reference.json")
    with open(out, "w") as f:
        json.dump(results, f, indent=2)
        f.write("\n")
    print(json.dumps(results, indent=2))
    if not results["pass"]:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
