"""MFU flagship: realistically-sized LM trainer throughput on one chip.

VERDICT r3 #2: the repo needs at least one number of the form "X% MFU at
realistic model size". Config: decoder-only LM, dim 1024, 12 layers, 16
heads, 32k vocab, bf16, AdamW, causal flash attention via the auto
dispatcher (ops/attention.py), T in {2048, 8192}.

Measurement: marginal step time from two chained-scan lengths (fixed
dispatch overhead cancels) with a device-computed scalar readback (see
results/lane_sweep_r4.json protocol_fix — full-array readbacks over the
tunnel swamp the signal). MFU denominators: the v5e's NOMINAL 197 TF/s
bf16 spec AND the chip's measured dense-matmul ceiling (~400+ TF/s on this
tunnel image, results/lane_sweep_r4.json), reported separately so neither
flatters.

FLOP accounting (per training step, the standard PaLM convention):
  fwd = 2 * n_active_params * tokens + 2 * 2 * L * T^2/2 * d * B  (attn QK+AV, causal)
  train = 3x fwd (bwd = 2x fwd)
Embedding-table lookups are excluded from n_active_params; the tied/untied
LM head matmul is included.

Writes results/lm_mfu_bench.json. Run alone on the real chip.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, ".")
from fedml_tpu.models.transformer import TransformerLM  # noqa: E402

NOMINAL_TF = 197.0   # v5e spec bf16
MEASURED_TF = 400.0  # dense-matmul ceiling measured on this tunnel chip

VOCAB, DIM, LAYERS, HEADS = 32000, 1024, 12, 16
N1, N2 = 3, 23


def measure(T: int, B: int, remat: bool = False,
            chunked_ce: bool = False) -> dict:
    model = TransformerLM(vocab_size=VOCAB, dim=DIM, num_heads=HEADS,
                          num_layers=LAYERS, max_len=max(T, 2048),
                          dtype=jnp.bfloat16, remat=remat)
    rng = jax.random.PRNGKey(0)
    tokens = jax.random.randint(rng, (B, T), 0, VOCAB)
    params = model.init(rng, tokens[:, :8])
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    # active matmul params: everything except wte/wpe embeds (head included)
    n_embed = VOCAB * DIM + max(T, 2048) * DIM
    n_active = n_params - n_embed

    opt = optax.adamw(3e-4, weight_decay=0.01)
    opt_state = opt.init(params)

    from fedml_tpu.ops.losses import chunked_lm_cross_entropy

    def loss_fn(p, toks):
        if chunked_ce:
            # full (B,T,V) f32 logits never materialize: hidden out of the
            # model, head matmul + log-softmax per sequence chunk. Targets
            # wrap (roll) so T stays chunk-divisible — throughput-identical.
            hid = model.apply(p, toks, train=True, return_hidden=True)
            head = p["params"]["head"]["kernel"].astype(hid.dtype)
            return chunked_lm_cross_entropy(hid, head,
                                            jnp.roll(toks, -1, axis=1))
        logits = model.apply(p, toks[:, :-1], train=True).astype(jnp.float32)
        tgt = toks[:, 1:]
        logz = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logz, tgt[..., None], -1))

    def step(carry, _):
        p, s, toks = carry
        loss, g = jax.value_and_grad(loss_fn)(p, toks)
        up, s = opt.update(g, s, p)
        p = optax.apply_updates(p, up)
        # cheap token permutation so iterations stay data-dependent
        toks = jnp.roll(toks, 1, axis=0)
        return (p, s, toks), loss

    def loop(n):
        def run(p, s, toks):
            (p, s, _), losses = jax.lax.scan(step, (p, s, toks), None, length=n)
            return losses[-1] + jax.tree_util.tree_reduce(
                lambda a, l: a + l.astype(jnp.float32).sum() * 0,
                jax.tree_util.tree_leaves(p), 0.0)
        return jax.jit(run)

    res = {}
    for n in (N1, N2):
        f = loop(n)
        float(f(params, opt_state, tokens))          # compile + warm
        ts = []
        for _ in range(4):
            t0 = time.perf_counter()
            float(f(params, opt_state, tokens))
            ts.append(time.perf_counter() - t0)
        res[n] = min(ts)
    sec_per_step = (res[N2] - res[N1]) / (N2 - N1)

    toks_per_step = B * T if chunked_ce else B * (T - 1)
    # QK^T + AV: 2 matmuls x 2 flops x (T^2/2 causal) x d, per layer/batch
    attn_flops = 2 * 2 * 2 * LAYERS * (T * T / 2) * DIM * B
    fwd = 2 * n_active * toks_per_step + attn_flops
    train_flops = 3 * fwd
    tf = train_flops / sec_per_step / 1e12
    return {
        "seq_len": T, "batch": B, "remat": remat, "chunked_ce": chunked_ce,
        "params_total_M": round(n_params / 1e6, 1),
        "params_active_M": round(n_active / 1e6, 1),
        "step_time_ms": round(sec_per_step * 1e3, 2),
        "tokens_per_sec": int(toks_per_step / sec_per_step),
        "train_tflops_per_sec": round(tf, 1),
        "mfu_vs_nominal_197tf": round(tf / NOMINAL_TF, 3),
        "mfu_vs_measured_400tf": round(tf / MEASURED_TF, 3),
    }


def main():
    print("devices:", jax.devices())
    out = {
        "model": f"decoder-only LM dim={DIM} L={LAYERS} heads={HEADS} vocab={VOCAB} bf16 AdamW",
        "protocol": f"marginal step time from scan lengths {N1}/{N2}, min of 4, scalar readback",
        "denominators": {"nominal_tf": NOMINAL_TF, "measured_ceiling_tf": MEASURED_TF},
        "points": [],
    }
    # (2048, 4, plain) is the naive-formulation baseline (dense attention,
    # full f32 logits — batch capped by the saved dense probabilities);
    # the chunked-CE points engage the memory-aware attention auto-dispatch
    # (flash once one layer's saved dense probs exceed 512 MB), which is
    # what unlocks the larger batches that reach target MFU
    for T, B, remat, chunked in ((2048, 4, False, False),
                                 (2048, 16, False, True),
                                 (8192, 2, False, True),
                                 (16384, 1, False, True),
                                 (32768, 1, True, True)):
        try:
            r = measure(T, B, remat, chunked)
        except Exception as e:
            r = {"seq_len": T, "batch": B, "remat": remat,
                 "chunked_ce": chunked, "error": repr(e)[:200]}
        print(r, flush=True)
        out["points"].append(r)
    with open("results/lm_mfu_bench.json", "w") as f:
        json.dump(out, f, indent=1)
    print("wrote results/lm_mfu_bench.json")


if __name__ == "__main__":
    main()
