#!/usr/bin/env python
"""Fast loopback hierarchy smoke for the static-check gate.

Runs the tiered federation twice on a tiny CPU config — once as the
single-process reference driver, once as 1 root + 2 leaf-aggregator
actors over the loopback backend — and fails unless the final global
parameters are bit-identical and the commit ledger is exact (every
chunk committed once, zero duplicates). This is the cheapest end-to-end
probe of the tier wire protocol: a chunk-boundary, rng-lane, or fold
-order regression shows up as a byte diff here long before the full
tier-1 suite runs.

    JAX_PLATFORMS=cpu python scripts/tier_smoke.py
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import numpy as np  # noqa: E402

import fedml_tpu  # noqa: E402
from fedml_tpu.cross_silo.chaos import TIER_DEFAULTS  # noqa: E402
from fedml_tpu.simulation.federation import (  # noqa: E402
    build_tiered_simulator, run_tiered_federation)


def main() -> int:
    cfg = dict(TIER_DEFAULTS)
    cfg["comm_round"] = 2

    ref_sim, ref_apply = build_tiered_simulator(fedml_tpu.init(config=cfg))
    ref_sim.run(ref_apply, log_fn=None)

    root = run_tiered_federation(fedml_tpu.init(config=cfg))

    rounds = int(cfg["comm_round"])
    if len(root.history) != rounds:
        print(f"tier smoke: FAILED — {len(root.history)}/{rounds} rounds "
              "completed", file=sys.stderr)
        return 1

    ledger = root.state.ledger
    # the ledger records (round, client) pairs — one per cohort member
    expected = rounds * int(cfg["client_num_per_round"])
    if int(ledger.total_commits) != expected or int(ledger.duplicates) != 0:
        print(f"tier smoke: FAILED — ledger commits="
              f"{int(ledger.total_commits)}/{expected} "
              f"duplicates={int(ledger.duplicates)}", file=sys.stderr)
        return 1

    ref_leaves = [np.asarray(x) for x in
                  jax.tree_util.tree_leaves(ref_sim.params)]
    tier_leaves = [np.asarray(x) for x in
                   jax.tree_util.tree_leaves(root.sim.params)]
    for i, (a, b) in enumerate(zip(ref_leaves, tier_leaves)):
        if a.shape != b.shape or not np.array_equal(a, b):
            print(f"tier smoke: FAILED — param leaf {i} differs from the "
                  "single-process reference (bit-identity contract broken)",
                  file=sys.stderr)
            return 1

    print(f"tier smoke: OK — {rounds} rounds over loopback bit-identical to "
          f"the single-process reference ({expected} client commits, "
          "0 duplicates)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
