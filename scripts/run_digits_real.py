"""Real-data federated accuracy artifact for a zero-egress environment.

sklearn's bundled handwritten-digits set (1797 real 8x8 images — the one
genuinely real vision dataset available without network egress) federated
across 10 clients, LR FedAvg. Unlike the synthetic stand-ins, the resulting
accuracy is a real generalization number; the history JSON records it for
the record (results/digits_real_history.json).

Usage: python scripts/run_digits_real.py [--rounds N] [--hetero]
"""

from __future__ import annotations

import argparse
import json
import os
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=60)
    ap.add_argument("--hetero", action="store_true",
                    help="Dirichlet(0.5) non-IID partition instead of IID")
    opts = ap.parse_args()

    import fedml_tpu
    from fedml_tpu.simulation import build_simulator

    args = fedml_tpu.init(config=dict(
        dataset="digits", model="lr",
        partition_method="hetero" if opts.hetero else "homo",
        partition_alpha=0.5,
        client_num_in_total=10, client_num_per_round=10,
        comm_round=opts.rounds, learning_rate=0.3, epochs=1, batch_size=32,
        frequency_of_the_test=10, random_seed=0,
    ))
    sim, apply_fn = build_simulator(args)
    t0 = time.time()
    hist = sim.run(apply_fn)
    out = {
        "dataset": "sklearn digits (REAL data, 1797 samples, 8x8)",
        "partition": "dirichlet-0.5" if opts.hetero else "iid",
        "config": {"clients": 10, "rounds": opts.rounds, "model": "lr",
                   "lr": 0.3, "batch_size": 32},
        "final_test_acc": hist[-1].get("test_acc"),
        "wall_seconds": time.time() - t0,
        "history": hist,
    }
    os.makedirs("results", exist_ok=True)
    path = os.path.join(
        "results",
        f"digits_real_{'hetero' if opts.hetero else 'iid'}_history.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: v for k, v in out.items() if k != "history"}))


if __name__ == "__main__":
    main()
