"""Generate tiny committed fixtures for the medical real-format parsers.

- chexpert/: CheXpert-v1.0-small layout (train.csv/valid.csv + image trees,
  path column formatted exactly like the real CSV incl. the two stripped
  leading components; labels with blanks and -1 uncertain entries).
- fets2021/: partitioning CSV + three subjects — two as .npz bundles, one
  as a BraTS-style dir of .nii.gz volumes (written by a minimal NIfTI-1
  writer so read_nifti's header/endianness/Fortran-order path is exercised
  against independently-constructed files).

Run once: python scripts/make_medical_fixtures.py
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

FIX = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "tests", "fixtures", "real_formats")


def write_nifti(path: str, vol: np.ndarray) -> None:
    """Minimal NIfTI-1 writer (little-endian, no scaling/affine)."""
    codes = {np.dtype(np.uint8): (2, 8), np.dtype(np.int16): (4, 16),
             np.dtype(np.int32): (8, 32), np.dtype(np.float32): (16, 32)}
    code, bitpix = codes[vol.dtype]
    hdr = bytearray(352)
    struct.pack_into("<i", hdr, 0, 348)                    # sizeof_hdr
    dims = [vol.ndim] + list(vol.shape) + [1] * (7 - vol.ndim)
    struct.pack_into("<8h", hdr, 40, *dims)                # dim
    struct.pack_into("<h", hdr, 70, code)                  # datatype
    struct.pack_into("<h", hdr, 72, bitpix)                # bitpix
    struct.pack_into("<f", hdr, 108, 352.0)                # vox_offset
    hdr[344:348] = b"n+1\x00"                              # magic
    payload = bytes(hdr) + np.asfortranarray(vol).tobytes(order="F")
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "wb") as f:
        f.write(payload)


def make_chexpert() -> None:
    from PIL import Image

    root = os.path.join(FIX, "chexpert")
    rng = np.random.default_rng(7)
    header = (
        "Path,Sex,Age,Frontal/Lateral,AP/PA,No Finding,"
        "Enlarged Cardiomediastinum,Cardiomegaly,Lung Opacity,Lung Lesion,"
        "Edema,Consolidation,Pneumonia,Atelectasis,Pneumothorax,"
        "Pleural Effusion,Pleural Other,Fracture,Support Devices")
    for split, n in (("train", 12), ("valid", 4)):
        rows = [header]
        for i in range(n):
            rel = f"patient{i:05d}/study1/view1_frontal.jpg"
            img_path = os.path.join(root, split, rel)
            os.makedirs(os.path.dirname(img_path), exist_ok=True)
            # label-correlated brightness so learning/parsing is checkable
            lbl = (rng.random(14) < 0.25).astype(int)
            base = 60 + 120 * lbl[2]  # Cardiomegaly brightens the image
            arr = rng.integers(0, 40, (32, 32), np.uint8) + base
            Image.fromarray(arr.astype(np.uint8), "L").save(img_path)
            cells = []
            for j, v in enumerate(lbl):
                if j == 5 and i % 4 == 1:
                    cells.append("")          # blank -> policy fill
                elif j == 7 and i % 4 == 2:
                    cells.append("-1.0")      # uncertain -> policy fill
                else:
                    cells.append(f"{float(v):.1f}")
            rows.append(
                f"CheXpert-v1.0-small/{split}/{rel},Female,60,Frontal,AP,"
                + ",".join(cells))
        with open(os.path.join(root, f"{split}.csv"), "w") as f:
            f.write("\n".join(rows) + "\n")


def make_fets() -> None:
    root = os.path.join(FIX, "fets2021")
    os.makedirs(root, exist_ok=True)
    rng = np.random.default_rng(11)
    h = w = 24
    d = 12
    subjects = {
        "1": ["FeTS21_Training_001", "FeTS21_Training_002"],
        "2": ["FeTS21_Training_003"],
    }
    with open(os.path.join(root, "partitioning_1.csv"), "w") as f:
        f.write("Partition_ID,Subject_ID\n")
        for pid, subs in subjects.items():
            for s in subs:
                f.write(f"{pid},{s}\n")

    def make_subject(seed):
        r = np.random.default_rng(seed)
        mods = r.normal(0, 1, (h, w, d, 4)).astype(np.float32)
        seg = np.zeros((h, w, d), np.int32)
        r0, c0, z0 = r.integers(2, h - 8), r.integers(2, w - 8), d // 2 - 2
        for cls, off in ((1, 0), (2, 2), (4, 4)):  # BraTS labels {1,2,4}
            seg[r0 + off:r0 + off + 3, c0:c0 + 3, z0:z0 + 4] = cls
        mods[..., 0] += (seg > 0) * 2.0  # tumor visible in flair
        return mods, seg

    # subjects 1-2 as npz bundles
    for i, subject in enumerate(subjects["1"]):
        mods, seg = make_subject(20 + i)
        np.savez_compressed(
            os.path.join(root, f"{subject}.npz"),
            flair=mods[..., 0], t1=mods[..., 1], t1ce=mods[..., 2],
            t2=mods[..., 3], seg=seg)
    # subject 3 as a BraTS dir of .nii.gz volumes (int16 seg exercises the
    # dtype table; float32 modalities the common path)
    subject = subjects["2"][0]
    mods, seg = make_subject(30)
    sdir = os.path.join(root, subject)
    os.makedirs(sdir, exist_ok=True)
    for mi, m in enumerate(("flair", "t1", "t1ce", "t2")):
        write_nifti(os.path.join(sdir, f"{subject}_{m}.nii.gz"),
                    mods[..., mi].astype(np.float32))
    write_nifti(os.path.join(sdir, f"{subject}_seg.nii.gz"),
                seg.astype(np.int16))


if __name__ == "__main__":
    make_chexpert()
    make_fets()
    print(f"fixtures written under {FIX}")
