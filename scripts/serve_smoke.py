#!/usr/bin/env python
"""Serving-plane smoke: publish/promote/serve/rollback in one minute.

Runs the tiny debug federation with an inference server attached
(inline canary — no worker thread, so every verdict is deterministic)
and gates the four serving invariants end to end:

  1. every training round published a version and the final active
     version is the last round's commit, canary-promoted;
  2. live requests submitted against the store are all served, none
     dropped, and are attributed to the version that served them;
  3. a poisoned (NaN) publish is rolled back before serving a single
     request, and re-publishing that version is refused as pinned;
  4. the identical run with serving disabled produces bitwise-equal
     final parameters — the training path cannot feel the server.

This is the cheap CI tripwire for the invariants tests/test_serving.py
checks exhaustively. Exits 0 when all four hold, 1 otherwise.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

BASE = dict(
    dataset="mnist", model="lr", partition_method="hetero",
    partition_alpha=0.5, debug_small_data=True,
    client_num_in_total=6, client_num_per_round=4, comm_round=4,
    learning_rate=0.1, epochs=1, batch_size=8,
    frequency_of_the_test=1, random_seed=0, prefetch=False,
)


def _run(serve: bool):
    import fedml_tpu
    from fedml_tpu import serving
    from fedml_tpu.simulation import build_simulator

    cfg = dict(BASE)
    if serve:
        cfg.update(serve_enabled=True, canary_batches=2,
                   canary_batch_size=32)
    args = fedml_tpu.init(config=cfg)
    sim, apply_fn = build_simulator(args)
    server = serving.build_inference_server(args, sim, apply_fn)
    sim.run(apply_fn, log_fn=None)
    return sim, server


def main() -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp

    sim, server = _run(serve=True)
    rounds = BASE["comm_round"]
    ok = True

    store_stats = server.store.stats()
    if store_stats["active_version"] != rounds:
        print(f"serve_smoke: FAIL — active version "
              f"{store_stats['active_version']} != {rounds} after "
              f"{rounds} rounds", file=sys.stderr)
        ok = False

    # 2. live traffic: submit against the promoted model, pump inline
    x = np.asarray(sim.fed.test_data_global.x[:96], np.float32)
    for i in range(96):
        server.submit(x[i])
    server.pump()
    st = server.stats()
    if st["served"] != 96 or st["dropped"] != 0:
        print(f"serve_smoke: FAIL — served {st['served']}/96, "
              f"dropped {st['dropped']}", file=sys.stderr)
        ok = False
    if sum(st["served_by_version"].values()) != st["served"]:
        print("serve_smoke: FAIL — served_by_version does not account "
              "for every request", file=sys.stderr)
        ok = False

    # 3. poisoned publish: NaN params must roll back, then pin
    poison = jax.tree.map(lambda l: jnp.full_like(l, jnp.nan), sim.params)
    status = server.publish(rounds + 1, poison)
    repub = server.publish(rounds + 1, sim.params)
    active_after = server.store.stats()["active_version"]
    if (status, repub, active_after) != ("rolled_back", "pinned", rounds):
        print(f"serve_smoke: FAIL — poison publish gave "
              f"({status}, {repub}, active={active_after}), expected "
              f"(rolled_back, pinned, active={rounds})", file=sys.stderr)
        ok = False

    # 4. serving must not perturb training: bitwise-equal params
    ref, _ = _run(serve=False)
    for a, b in zip(jax.tree.leaves(sim.params), jax.tree.leaves(ref.params)):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            print("serve_smoke: FAIL — final params differ between "
                  "serving-enabled and serving-disabled runs",
                  file=sys.stderr)
            ok = False
            break

    if ok:
        print(f"serve_smoke: OK — {rounds} versions promoted, 96 served / "
              f"0 dropped, NaN rollout rolled back + pinned, training "
              f"bit-identical with serving off", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
