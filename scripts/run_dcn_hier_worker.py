"""One silo-process of a hierarchical FL round whose GLOBAL aggregation
crosses the process boundary (the DCN axis).

Launch 2 of these under jax.distributed (coordinator on localhost; see
tests/test_multihost_dcn.py). Each process is one GROUP/silo: it runs
``--group-rounds`` of local FedAvg over its own clients entirely
in-process (the ICI tier), then the two groups' models are combined by a
sample-weighted mean computed AS A CROSS-PROCESS MESH COLLECTIVE — a jit
over a global mesh whose devices span both processes, so the reduction
traffic rides the distributed runtime exactly where a TPU pod would use
DCN. Both processes must end with bit-identical global params.

Parity: reference ``cross_silo/hierarchical`` topology (torch DDP process
groups + MPI server tier, dist_trainer_launcher.py:23) collapsed to
jax.distributed + one sharded program.

Usage: run_dcn_hier_worker.py --out OUT.json [--group-rounds N]
"""

from __future__ import annotations

import argparse
import functools
import json


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True)
    ap.add_argument("--group-rounds", type=int, default=2)
    opts = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.flatten_util import ravel_pytree
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import fedml_tpu
    from fedml_tpu.simulation import build_simulator

    # fedml_tpu.init runs maybe_initialize_distributed (the coordinator
    # env vars) — the world only exists after it
    # --- group tier: local FedAvg rounds, one group per process ----------
    # group data differs per process (disjoint client populations); seeds
    # are deterministic so the test harness can recompute the expectation
    import os

    pid = int(os.environ.get("JAX_PROCESS_ID", 0))
    args = fedml_tpu.init(config=dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=6, client_num_per_round=3,
        comm_round=opts.group_rounds, learning_rate=0.1, epochs=1,
        batch_size=10, frequency_of_the_test=10_000,
        random_seed=100 + pid,  # group-specific data AND init
    ))
    assert jax.process_count() == 2, "expects a 2-process jax.distributed world"
    assert pid == jax.process_index()
    sim, apply_fn = build_simulator(args)
    sim.run(apply_fn=None, log_fn=None)
    flat, unravel = ravel_pytree(sim.params)
    weight = float(sim.fed.train_data_num)

    # --- global tier: weighted mean over the DCN axis --------------------
    # one "silo" mesh axis spanning every global device (2 per process);
    # each process contributes its group's (weighted) vector on its OWN
    # local devices, and the jitted mean reduces ACROSS processes
    devs = np.array(jax.devices()).reshape(-1, 1)
    mesh = Mesh(devs, ("silo", "model"))
    row_sh = NamedSharding(mesh, P("silo", "model"))
    n_rows = len(jax.devices())
    flat_np = np.asarray(flat, np.float32)
    local_rows = [
        jax.device_put(flat_np[None, :], d) for d in jax.local_devices()
    ]
    stacked = jax.make_array_from_single_device_arrays(
        (n_rows, flat_np.shape[0]), row_sh, local_rows)
    w_np = np.full(len(jax.local_devices()), weight / len(jax.local_devices()),
                   np.float32)
    w_rows = [jax.device_put(w_np[None, i], d)
              for i, d in enumerate(jax.local_devices())]
    w_global = jax.make_array_from_single_device_arrays(
        (n_rows,), NamedSharding(mesh, P("silo")), w_rows)

    @functools.partial(
        jax.jit, out_shardings=NamedSharding(mesh, P()))
    def global_mean(rows, w):
        # executes over the global mesh: the sum crosses the process
        # boundary (DCN); output REPLICATED so every process holds a full
        # addressable copy to read back locally
        return (w[:, None] * rows).sum(0) / w.sum()

    merged = global_mean(stacked, w_global)
    merged_vec = np.asarray(merged.addressable_data(0))
    global_params = unravel(jnp.asarray(merged_vec))

    # evaluate the MERGED model on this group's test split (proves the
    # cross-process result is a usable model, not just bytes)
    sim.params = global_params
    metrics = sim.evaluate(apply_fn)

    with open(opts.out, "w") as f:
        json.dump({
            "process": pid,
            "global_devices": len(jax.devices()),
            "local_devices": len(jax.local_devices()),
            "group_weight": weight,
            "group_vec_l2": float(np.linalg.norm(flat_np)),
            "merged_digest": float(np.abs(merged_vec).sum()),
            "merged_first8": [float(v) for v in merged_vec[:8]],
            "test_acc": metrics.get("test_acc"),
        }, f)


if __name__ == "__main__":
    main()
