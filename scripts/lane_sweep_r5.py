"""Flat-carry validation + lane re-sweep on the flagship bench workload
(VERDICT r4 #1b/#1c).

Round 4 attributed the packed-step cost to per-leaf update/flush/reset
ops over ~173 tensors and built the flat-carry executor (one ravelled
vector per lane; 5.08 -> 3.16 ms/step in the 2-lane microbench) — but
the tunnel died before end-to-end chip validation, and the lane count
was never re-swept under flat carry (with the per-leaf cost gone, more
lanes may win: padded-work reduction returns as the dominant term).

This script, run alone on the real chip:
1. parity: 3 bench rounds flat vs tree carry — params must agree to
   bf16-accumulation tolerance (the CPU parity tests are exact; this
   guards the TPU compilation path).
2. rate A/B at lanes=2: tree vs flat end-to-end rounds/sec (the bench.py
   protocol: wall around sim.run over compiled-shape-warm blocks).
3. lane sweep under flat carry: lanes in {1, 2, 4, 8} (pow2: compiled
   (G, L_pad) shape reuse round-to-round — see packed-lane notes),
   median block rate each.

Writes results/lane_sweep_r5.json; prints the winning (carry, lanes)
combo to adopt as bench.py defaults.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")


SMOKE = "--smoke" in sys.argv  # CPU plumbing check: tiny model/data


def build(lanes: int, flat: bool, rounds: int = 6):
    import fedml_tpu
    from fedml_tpu.simulation import build_simulator

    cfg = dict(
        dataset="cifar10", model="resnet56", partition_method="hetero",
        partition_alpha=0.5, client_num_in_total=100,
        client_num_per_round=10, comm_round=rounds, learning_rate=0.01,
        epochs=1, batch_size=64, frequency_of_the_test=10_000,
        random_seed=0, use_bf16=True, packed_lanes=lanes,
        packed_flat_carry=flat,
    )
    if SMOKE:
        cfg.update(model="resnet8", debug_small_data=True, batch_size=8,
                   client_num_in_total=20, client_num_per_round=4,
                   cohort_schedule="packed")
    args = fedml_tpu.init(config=cfg)
    sim, apply_fn = build_simulator(args)
    assert sim._packed
    return sim


def timed_rate(sim, blocks: int = 3, rounds: int = 6) -> list:
    sim.run(apply_fn=None, log_fn=None)   # compile + upload
    sim.history.clear()
    sim.run(apply_fn=None, log_fn=None)   # burn-in block
    rates = []
    for _ in range(blocks):
        sim.history.clear()
        t0 = time.perf_counter()
        sim.run(apply_fn=None, log_fn=None)
        rates.append(rounds / (time.perf_counter() - t0))
    return sorted(rates)


def flat_params(sim):
    import jax

    return np.concatenate([
        np.asarray(x, np.float32).ravel()
        for x in jax.tree_util.tree_leaves(sim.params)])


def main():
    import jax

    print("devices:", jax.devices(), flush=True)
    out = {"workload": "bench.py flagship (FedAvg CIFAR-10 ResNet-56, "
                       "10 clients x bs64, packed)",
           "protocol": "wall around sim.run, warm + burn-in block, "
                       "median of 3 blocks of 6 rounds"}

    # 1. on-chip parity flat vs tree (3 rounds)
    p = {}
    for flat in (False, True):
        sim = build(2, flat, rounds=3)
        sim.run(apply_fn=None, log_fn=None)
        p["flat" if flat else "tree"] = flat_params(sim)
    d = np.abs(p["flat"] - p["tree"])
    denom = np.maximum(np.abs(p["tree"]), 1e-6)
    out["parity_3rounds"] = {
        "max_abs_diff": float(d.max()),
        "max_rel_diff": float((d / denom).max()),
        # bf16 accumulation: chaotic divergence is possible over many
        # steps; 3 rounds should stay within loose tolerance
        "pass": bool(float((d / denom).max()) < 0.05
                     or float(d.max()) < 5e-3),
    }
    print("parity:", out["parity_3rounds"], flush=True)

    # 2. A/B at lanes=2
    ab = {}
    for flat in (False, True):
        sim = build(2, flat)
        rates = timed_rate(sim)
        ab["flat" if flat else "tree"] = {
            "block_rates": [round(r, 3) for r in rates],
            "median_rps": round(rates[len(rates) // 2], 4),
        }
        print(f"lanes=2 flat={flat}: {ab['flat' if flat else 'tree']}",
              flush=True)
    ab["speedup"] = round(
        ab["flat"]["median_rps"] / ab["tree"]["median_rps"], 3)
    out["ab_lanes2"] = ab

    # 3. lane sweep under flat carry
    sweep = {}
    for lanes in ((1, 2) if SMOKE else (1, 2, 4, 8)):
        sim = build(lanes, True)
        rates = timed_rate(sim)
        sweep[lanes] = {
            "block_rates": [round(r, 3) for r in rates],
            "median_rps": round(rates[len(rates) // 2], 4),
            "packed_shape": list(getattr(sim, "_last_packed_shape", ())),
        }
        print(f"flat lanes={lanes}: {sweep[lanes]}", flush=True)
    out["flat_lane_sweep"] = sweep
    best_lanes = max(sweep, key=lambda k: sweep[k]["median_rps"])
    out["winner"] = {
        "carry": ("flat" if ab["flat"]["median_rps"]
                  >= ab["tree"]["median_rps"] else "tree"),
        "lanes": best_lanes,
        "median_rps": sweep[best_lanes]["median_rps"],
    }
    print("winner:", out["winner"], flush=True)

    with open("results/lane_sweep_r5.json", "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print("wrote results/lane_sweep_r5.json", flush=True)


if __name__ == "__main__":
    main()
