"""Generate tiny committed fixtures in the reference's on-disk formats.

Run once; outputs live in tests/fixtures/ and are committed so the loader
tests always exercise the real-format parse paths (VERDICT r1 #4). Contents
are synthetic; only the FORMATS are real:

- LEAF JSON (reference data/MNIST/data_loader.py:32 read_data)
- TFF h5 fed_shakespeare (data/fed_shakespeare/data_loader.py)
- TFF h5 FederatedEMNIST (data/FederatedEMNIST/data_loader.py)
- TFF h5 stackoverflow_nwp + word_count file (data/stackoverflow_nwp/)
"""

from __future__ import annotations

import json
import os

import h5py
import numpy as np

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "tests", "fixtures")


def make_leaf_mnist() -> None:
    rng = np.random.default_rng(0)
    base = os.path.join(OUT, "leaf_mnist")
    for split, n_lo, n_hi in (("train", 6, 12), ("test", 2, 4)):
        users, num_samples, user_data = [], [], {}
        for u in range(3):
            uid = f"f_{u:05d}"
            n = int(rng.integers(n_lo, n_hi))
            users.append(uid)
            num_samples.append(n)
            user_data[uid] = {
                "x": rng.random((n, 784)).round(4).tolist(),
                "y": rng.integers(0, 10, n).tolist(),
            }
        d = os.path.join(base, split)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "all_data_0.json"), "w") as f:
            json.dump(
                {"users": users, "num_samples": num_samples, "user_data": user_data}, f
            )


SNIPPETS = {
    "THE_FOOL": [
        "Have more than thou showest, speak less than thou knowest.",
        "Lend less than thou owest.",
    ],
    "KENT": ["This is nothing, fool."],
}


def make_fed_shakespeare() -> None:
    for split in ("train", "test"):
        path = os.path.join(OUT, f"shakespeare_{split}.h5")
        with h5py.File(path, "w") as h5:
            g = h5.create_group("examples.md")
            for client, snippets in SNIPPETS.items():
                cg = g.create_group(client)
                sel = snippets if split == "train" else snippets[:1]
                cg.create_dataset(
                    "snippets", data=np.array([s.encode() for s in sel])
                )


def make_femnist() -> None:
    rng = np.random.default_rng(1)
    for split, n in (("train", 8), ("test", 3)):
        path = os.path.join(OUT, f"fed_emnist_{split}.h5")
        with h5py.File(path, "w") as h5:
            g = h5.create_group("examples.md")
            for u in range(2):
                cg = g.create_group(f"f{u:04d}_00")
                cg.create_dataset(
                    "pixels", data=rng.random((n, 28, 28)).astype(np.float32)
                )
                cg.create_dataset("label", data=rng.integers(0, 62, n))


SO_SENTENCES = {
    "user_a": ["how do i sort a list in python", "what is a pointer"],
    "user_b": ["why does my code segfault"],
}
SO_WORDS = ("a i in is what how do my why list sort python pointer code does "
            "segfault the to of and").split()


def make_stackoverflow() -> None:
    with open(os.path.join(OUT, "stackoverflow.word_count"), "w") as f:
        for i, w in enumerate(SO_WORDS):
            f.write(f"{w} {1000 - i}\n")
    for split in ("train", "test"):
        path = os.path.join(OUT, f"stackoverflow_{split}.h5")
        with h5py.File(path, "w") as h5:
            g = h5.create_group("examples.md")
            for client, sents in SO_SENTENCES.items():
                cg = g.create_group(client)
                sel = sents if split == "train" else sents[:1]
                cg.create_dataset("tokens", data=np.array([s.encode() for s in sel]))


if __name__ == "__main__":
    os.makedirs(OUT, exist_ok=True)
    make_leaf_mnist()
    make_fed_shakespeare()
    make_femnist()
    make_stackoverflow()
    print("fixtures written to", OUT)
