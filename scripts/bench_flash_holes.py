"""Close the r3 flash-attention measurement holes (VERDICT r3 #3):

1. T=16384: dense comparator (at B1H4 where dense fits; B4H8 dense is
   memory-infeasible — the bf16 logits alone are 17 GB vs 15.75 GB HBM).
2. T=32768: fwd+bwd (r3 had forward-only); dense attempted, OOM recorded.
3. T=2048: (block_q, block_k) sweep to close or explain the 0.88x gap
   vs dense below the auto-dispatch crossover.

Protocol: chained passes per dispatch (scan), marginal over two chain
lengths, device-computed scalar readback (results/lane_sweep_r4.json
protocol_fix). Writes results/flash_attention_holes_r4.json.
Run alone on the real chip.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
from fedml_tpu.ops.attention import multihead_attention  # noqa: E402
from fedml_tpu.ops.pallas.flash_attention import flash_attention  # noqa: E402

N1, N2 = 2, 22
PEAK_TF = 400e12   # measured dense-matmul ceiling: plausibility floor for
                   # marginals (tunnel noise can produce negative/absurd
                   # values; a marginal below 25% of the at-peak time for
                   # the op's FLOPs is physically impossible -> rejected)


def attn_train_flops(T, B, H, Dh=64, causal=True):
    # QK^T + AV fwd (x2 matmuls), ~2x more in bwd; causal halves T^2
    per = 2 * 2 * B * H * (T * T / (2 if causal else 1)) * Dh
    return 3 * per


def timed_train(fn, q, k, v):
    """Marginal seconds per fwd+bwd pass via two chained-scan lengths."""
    grad = jax.grad(lambda q, k, v: jnp.sum(
        fn(q, k, v).astype(jnp.float32) ** 2), argnums=(0, 1, 2))
    res = {}
    for n in (N1, N2):
        @jax.jit
        def loop(q, k, v):
            def body(c, _):
                dq, dk, dv = grad(c, k, v)
                # ALL three grads must feed the carry or XLA dead-code-
                # eliminates the dK/dV backward (review catch: the
                # eliminated fraction differs per impl, poisoning ratios)
                return c + 1e-12 * (dq + dk + dv), None
            c, _ = jax.lax.scan(body, q, None, length=n)
            return jnp.sum(c.astype(jnp.float32))
        float(loop(q, k, v))
        ts = []
        for _ in range(4):
            t0 = time.perf_counter()
            float(loop(q, k, v))
            ts.append(time.perf_counter() - t0)
        res[n] = min(ts)
    return (res[N2] - res[N1]) / (N2 - N1)


def qkv(T, B, H, Dh=64):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    shape = (B, T, H, Dh)
    return tuple(jax.random.normal(k, shape, jnp.bfloat16) * 0.3 for k in ks)


def main():
    print("devices:", jax.devices())
    out = {"protocol": f"marginal fwd+bwd pass from chained-scan lengths {N1}/{N2}, min of 4, scalar readback",
           "dtype": "bf16", "Dh": 64}

    # --- 1+2: long-T fwd+bwd with dense comparators where feasible ------
    long_pts = []
    for T, B, H in ((16384, 1, 4), (32768, 1, 4)):
        q, k, v = qkv(T, B, H)
        pt = {"T": T, "B": B, "H": H}
        m = timed_train(lambda q, k, v: flash_attention(q, k, v, causal=True), q, k, v)
        # no dense comparator exists here to derive a floor from, so use
        # the FLOPs-based one: a marginal under 25% of the at-peak time is
        # tunnel noise, not a measurement
        floor_lc = 0.25 * attn_train_flops(T, B, H) / PEAK_TF
        if m < floor_lc:
            pt["flash_train"] = (f"rejected: marginal {m*1e3:.2f} ms below "
                                 f"plausibility floor {floor_lc*1e3:.2f} ms")
        else:
            pt["flash_train_ms"] = round(m * 1e3, 2)
        # the dense comparator is independent of the flash reading —
        # measure it regardless so a flash fluke can't lose the point
        try:
            md = timed_train(lambda q, k, v: multihead_attention(
                q, k, v, causal=True, impl="dense"), q, k, v)
            pt["dense_train_ms"] = round(md * 1e3, 2)
            if "flash_train_ms" in pt:
                pt["speedup"] = round(md / m, 2)
        except Exception as e:
            pt["dense_train"] = f"infeasible: {repr(e)[:160]}"
        print(pt, flush=True)
        long_pts.append(pt)
    out["long_context_fwd_bwd"] = long_pts
    out["dense_B4H8_note"] = ("dense comparator at the r3 benchmark shape "
                              "B4H8 is memory-infeasible at T>=16384: bf16 "
                              "logits alone are B*H*T^2*2 = 17.2 GB vs "
                              "15.75 GB HBM; comparators above use B1H4 "
                              "for both impls")

    # --- 3: T=2048 block sweep ------------------------------------------
    T, B, H = 2048, 4, 8
    q, k, v = qkv(T, B, H)
    md = timed_train(lambda q, k, v: multihead_attention(
        q, k, v, causal=True, impl="dense"), q, k, v)
    # flash does the same matmul FLOPs as dense and saves only O(T^2) HBM
    # traffic, so >4x-than-dense readings are physically impossible here —
    # tunnel-noise flukes, rejected
    floor = md / 4
    sweep = {"dense_train_ms": round(md * 1e3, 2), "grid": [],
             "plausibility_floor_ms": round(floor * 1e3, 3)}
    cands = []
    for bq in (128, 256, 512, 1024, 2048):
        for bk in (128, 256, 512, 1024, 2048):
            try:
                m = timed_train(lambda q, k, v: flash_attention(
                    q, k, v, causal=True, block_q=bq, block_k=bk), q, k, v)
                rec = {"block_q": bq, "block_k": bk,
                       "train_ms": round(m * 1e3, 2),
                       "vs_dense": round(md / m, 2)}
                if m < floor:
                    rec["rejected"] = "below plausibility floor (noise)"
                else:
                    cands.append((m, bq, bk))
                sweep["grid"].append(rec)
                print(rec, flush=True)
            except Exception as e:
                sweep["grid"].append({"block_q": bq, "block_k": bk,
                                      "error": repr(e)[:120]})
                print(f"bq={bq} bk={bk} FAIL", flush=True)
    # single sweep passes are still noisy: re-measure the 4 fastest
    # plausible candidates twice more and rank by median of 3
    finals = []
    for m0, bq, bk in sorted(cands)[:4]:
        ms = [m0]
        for _ in range(2):
            ms.append(timed_train(lambda q, k, v: flash_attention(
                q, k, v, causal=True, block_q=bq, block_k=bk), q, k, v))
        med = sorted(ms)[1]
        if med < floor:   # the floor applies to re-measures too
            print(f"re-measure bq={bq} bk={bk}: median {med*1e3:.2f} ms "
                  "below plausibility floor, rejected", flush=True)
            continue
        finals.append({"block_q": bq, "block_k": bk,
                       "train_ms_median3": round(med * 1e3, 2),
                       "vs_dense": round(md / med, 2)})
        print("re-measure:", finals[-1], flush=True)
    if finals:
        sweep["best"] = min(finals, key=lambda r: r["train_ms_median3"])
        sweep["finalists"] = finals
    out["t2048_block_sweep"] = sweep
    print("best @2048:", sweep.get("best"), flush=True)

    # interpretation computed from THIS run's measurements, so a re-run
    # always produces a self-consistent artifact
    interp = []
    for pt in long_pts:
        flash_desc = (f"flash {pt['flash_train_ms']} ms"
                      if "flash_train_ms" in pt
                      else f"flash reading {pt.get('flash_train', 'absent')}")
        if "speedup" in pt:
            interp.append(
                f"T={pt['T']} fwd+bwd: flash {pt['speedup']}x dense "
                f"({pt['flash_train_ms']} vs {pt['dense_train_ms']} ms at "
                f"B{pt['B']}H{pt['H']}); single-run magnitude — tunnel "
                "load drifts cross-run readings, direction is the claim.")
        elif "dense_train_ms" in pt:
            interp.append(
                f"T={pt['T']} fwd+bwd: dense {pt['dense_train_ms']} ms; "
                f"{flash_desc}.")
        else:
            # dense raised: report the recorded error verbatim — it may be
            # a memory-infeasibility (expected at 32k: bf16 logits alone
            # are B*H*T^2*2 bytes vs 15.75 GB HBM) or a transient tunnel
            # failure; the raw record distinguishes them
            interp.append(
                f"T={pt['T']} fwd+bwd: {flash_desc}; dense comparator "
                f"unavailable this run ({pt.get('dense_train', '?')[:80]}).")
    if sweep.get("best"):
        interp.append(
            f"T=2048: best plausible blocks {sweep['best']['block_q']}/"
            f"{sweep['best']['block_k']} measure {sweep['best']['vs_dense']}x "
            "dense (median of 3) this run — flash is not slower than dense "
            "at 2048 under this protocol. The auto-dispatch crossover at "
            "4096 stays (never worse); sub-5ms op readings on this tunnel "
            "should not drive retunes.")
    interp.append(
        "Protocol: marginal from chained-scan lengths "
        f"{N1}/{N2}, all grads fed to the carry (no DCE), scalar readback, "
        "plausibility floors (dense/4 at 2048; FLOPs-based at long T). "
        "Cross-run history lives in git, not in this file.")
    out["interpretation"] = interp

    with open("results/flash_attention_holes_r4.json", "w") as f:
        json.dump(out, f, indent=1)
    print("wrote results/flash_attention_holes_r4.json")


if __name__ == "__main__":
    main()
