"""Measure the reference framework's per-round wall-clock on THIS machine.

The reference publishes no throughput numbers (SURVEY.md §6), so the bench's
``vs_baseline`` denominator has to be produced locally. This script times the
reference's actual hot loop — the per-client SGD epoch of
``simulation/sp/fedavg/my_model_trainer_classification.py:15`` (forward, CE
loss, backward, step) on its flagship CIFAR-10 ResNet-56
(``model/cv/resnet.py:257``, imported from the reference tree at runtime, not
copied) — and extrapolates to the bench workload: 10 clients/round x 500
samples/client x batch 64 = 80 batches/round.

Torch here is CPU-only, so this is a CPU-scaled denominator; the basis string
recorded in BASELINE_LOCAL.json says so explicitly, and bench.py echoes it in
its output line so the vs_baseline ratio is never mistaken for a same-hardware
comparison.

Usage: python scripts/measure_reference_baseline.py [n_batches]
Writes BASELINE_LOCAL.json at the repo root.
"""

from __future__ import annotations

import importlib.util
import json
import os
import sys
import time

REF_RESNET = "/root/reference/python/fedml/model/cv/resnet.py"
BATCHES_PER_ROUND = 80  # 10 clients x ceil(500/64) = 8 batches, bench workload
BATCH_SIZE = 64


def load_reference_resnet56():
    spec = importlib.util.spec_from_file_location("ref_resnet", REF_RESNET)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.resnet56(class_num=10)


def main() -> None:
    import torch
    import torch.nn as nn

    n_batches = int(sys.argv[1]) if len(sys.argv) > 1 else 10
    torch.manual_seed(0)
    model = load_reference_resnet56()
    model.train()
    criterion = nn.CrossEntropyLoss()
    optimizer = torch.optim.SGD(model.parameters(), lr=0.01)
    x = torch.randn(BATCH_SIZE, 3, 32, 32)
    y = torch.randint(0, 10, (BATCH_SIZE,))

    # one warmup batch (allocator, thread pool spin-up)
    optimizer.zero_grad(); criterion(model(x), y).backward(); optimizer.step()

    t0 = time.perf_counter()
    for _ in range(n_batches):
        optimizer.zero_grad()
        loss = criterion(model(x), y)
        loss.backward()
        optimizer.step()
    per_batch = (time.perf_counter() - t0) / n_batches

    seconds_per_round = per_batch * BATCHES_PER_ROUND
    result = {
        "rounds_per_sec": 1.0 / seconds_per_round,
        "seconds_per_round": seconds_per_round,
        "seconds_per_batch": per_batch,
        "batches_timed": n_batches,
        "basis": (
            "reference torch hot loop (my_model_trainer_classification.py:15"
            " semantics, resnet56 bs64) timed on this machine's CPU, "
            f"extrapolated to {BATCHES_PER_ROUND} batches/round — CPU-scaled,"
            " not same-hardware"
        ),
        "torch_version": torch.__version__,
        "cpu_count": os.cpu_count(),
    }
    out = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                       "BASELINE_LOCAL.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(json.dumps(result, indent=2))


if __name__ == "__main__":
    main()
