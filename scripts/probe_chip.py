"""Patient TPU availability probe: retries backend init with backoff.

Thin operator-facing CLI over ``fedml_tpu.utils.chip_probe`` (fresh
subprocess per attempt; CPU fallback counts as UNAVAILABLE). Exits 0 on
first accelerator success, 1 after exhausting attempts.

Usage: python scripts/probe_chip.py [attempts] [sleep_seconds]
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fedml_tpu.utils.chip_probe import wait_for_chip  # noqa: E402


def main() -> int:
    attempts = int(sys.argv[1]) if len(sys.argv) > 1 else 10
    sleep_s = float(sys.argv[2]) if len(sys.argv) > 2 else 120.0
    ok, detail = wait_for_chip(
        attempts=attempts, sleep_s=sleep_s, probe_timeout=180.0,
        log=lambda m: print(f"[{time.strftime('%H:%M:%S')}] {m}", flush=True))
    print("CHIP AVAILABLE" if ok else f"CHIP UNAVAILABLE ({detail})",
          flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
