"""Benchmark pallas flash attention vs XLA dense on the real TPU chip.

Measurement protocol per the repo's axon rules: block_until_ready does not
drain dispatched work on the tunneled chip, so each timed sample chains
PASSES passes per dispatch and stops the clock on a forced np.asarray
readback of a scalar derived from the result. Median of 5 after warmup.

Writes results/flash_attention_bench.json.
Run with the DEFAULT env (the chip), one process at a time:
    python scripts/bench_flash_attention.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

B, H, Dh = 4, 8, 64
PASSES = 10
REPS = 5


def make_fn(impl: str, causal: bool = True):
    import jax
    import jax.numpy as jnp

    from fedml_tpu.ops.attention import multihead_attention

    def loss(q, k, v):
        return jnp.sum(
            multihead_attention(q, k, v, causal=causal, impl=impl)
            .astype(jnp.float32) ** 2)

    grad = jax.grad(loss, argnums=(0, 1, 2))

    @jax.jit
    def chained(q, k, v):
        def body(carry, _):
            dq, dk, dv = grad(carry, k, v)
            # feed a tiny function of the grads back in so XLA cannot hoist
            # any pass out of the chain
            return carry + 1e-12 * dq + 1e-12 * dk + 1e-12 * dv, None

        q_out, _ = jax.lax.scan(body, q, None, length=PASSES)
        return jnp.sum(q_out.astype(jnp.float32))

    return chained


def time_impl(impl: str, T: int):
    import jax
    import jax.numpy as jnp

    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, T, H, Dh), jnp.bfloat16)
    k = jax.random.normal(kk, (B, T, H, Dh), jnp.bfloat16)
    v = jax.random.normal(kv, (B, T, H, Dh), jnp.bfloat16)
    fn = make_fn(impl)
    np.asarray(fn(q, k, v))  # compile + warm
    times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        np.asarray(fn(q, k, v))  # forced readback = honest drain
        times.append((time.perf_counter() - t0) / PASSES)
    return float(np.median(times) * 1e3)  # ms per fwd+bwd pass


def main() -> None:
    import jax

    assert jax.default_backend() == "tpu", (
        f"bench needs the real chip, got {jax.default_backend()}")
    points = []
    for T in (1024, 2048, 4096, 8192, 16384):
        flash_ms = time_impl("flash", T)
        # dense at T=16384: f32 (T,T) logits per (B,H) = 4*8*16384^2*4 = 34 GB
        dense_ms = time_impl("dense", T) if T <= 8192 else None
        rec = {"T": T, "flash_ms": round(flash_ms, 2)}
        if dense_ms is not None:
            rec["dense_ms"] = round(dense_ms, 2)
            rec["speedup"] = round(dense_ms / flash_ms, 2)
        points.append(rec)
        print(rec, flush=True)
    # long-context single-chip reach (flash only, smaller B to fit activations)
    import jax.numpy as jnp

    from fedml_tpu.ops.attention import multihead_attention

    for T in (32768, 65536):
        try:
            q = jax.random.normal(jax.random.PRNGKey(1), (1, T, 4, Dh),
                                  jnp.bfloat16)
            fn = jax.jit(lambda q: jnp.sum(multihead_attention(
                q, q, q, causal=True, impl="flash").astype(jnp.float32)))
            np.asarray(fn(q))
            t0 = time.perf_counter()
            np.asarray(fn(q))
            ms = (time.perf_counter() - t0) * 1e3
            points.append({"T": T, "flash_fwd_only_ms_B1H4": round(ms, 2)})
            print(points[-1], flush=True)
        except Exception as exc:  # noqa: BLE001 — record the limit honestly
            points.append({"T": T, "flash_fwd_only_error": str(exc)[:200]})
            print(points[-1], flush=True)
            break

    result = {
        "workload": (
            f"causal self-attention fwd+bwd (jit grad), B={B} H={H} Dh={Dh}, "
            f"bf16; per-pass time from {PASSES} chained passes per dispatch"),
        "hardware": "1 TPU chip (tunneled); median of 5 after warm, forced "
                    "readback drain",
        "kernel": "K-blocked 3D-grid pallas (round 3); VMEM O(block*Dh), "
                  "T-independent",
        "points": points,
    }
    out = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                       "results", "flash_attention_bench.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
