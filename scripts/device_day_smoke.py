#!/usr/bin/env python
"""Fast cross-device fleet smoke for the static-check gate.

Runs a 10k-device registry through a 2-minute simulated day with the full
churn drill (30% fleet dropout + rejoin waves, a permanent-departure
subset, one partition window) and fails unless:

- the churn-free reference, the churned day, and the churned replay all
  close their accounting (every arrival blackholed/accepted/shed by
  reason, every cohort slot committed or dropped, zero ledger duplicates);
- churned accuracy lands within the drill tolerance of the reference;
- the churned day replays BYTE-identically (history digest) — the
  determinism contract every device_day drill rests on;
- permanent departures reclaim their arena spill files from the disk tier.

This is the cheapest end-to-end probe of the cross-device plane: an
admission-edge, lifecycle, or seeding regression shows up here as a digest
diff or an accounting gap long before the full tier-1 suite runs.

    JAX_PLATFORMS=cpu python scripts/device_day_smoke.py
"""

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fedml_tpu.cross_device.device_day import (  # noqa: E402
    DeviceDayConfig, run_device_churn_drill)


def main() -> int:
    cfg = DeviceDayConfig(
        registry_size=10_000, day_s=120.0, tick_s=5.0, num_classes=4,
        cohort=32, queue_maxsize=256, peak_rate=80.0, dropout_rate=0.05,
        max_commits_per_tick=2, arena_capacity=128, host_capacity=256,
        spill_dir=tempfile.mkdtemp(prefix="device_day_smoke_"),
        eval_every_ticks=4, seed=0,
        churn_fraction=0.3, churn_rejoin_ticks=2,
        churn_permanent_fraction=0.2, churn_partition_classes=1,
        churn_partition_ticks=3)
    res = run_device_churn_drill(cfg)
    print(res.summary(), file=sys.stderr)

    failures = []
    if not res.reference.ok:
        failures.append("reference accounting did not close")
    if not res.churned.ok:
        failures.append("churned accounting did not close")
    if res.acc_delta > res.max_acc_delta:
        failures.append(f"acc delta {res.acc_delta:.4f} > "
                        f"{res.max_acc_delta}")
    if not res.replay_identical:
        failures.append("churned day did not replay bit-identically")
    if res.churned.departures == 0:
        failures.append("no permanent departures exercised")
    if res.churned.rejoins == 0:
        failures.append("no rejoin wave exercised")
    if res.churned.partition_blackholed == 0:
        failures.append("partition window blackholed nothing")
    if res.churned.reclaimed_spill_files == 0:
        failures.append("departures reclaimed no spill files (arena "
                        "disk-tier lifecycle regression)")
    if failures:
        for f in failures:
            print(f"device-day smoke: FAILED — {f}", file=sys.stderr)
        return 1
    print("device-day smoke: OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
