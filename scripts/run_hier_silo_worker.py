"""One process of a multi-process hierarchical cross-silo silo.

Launch P of these (one per host/process; see ``scripts/launch_multihost.sh``)
with JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID set.
Process 0 runs the FL server plus the silo's ClientMasterManager; processes
1..P-1 run ClientSlaveManager. The silo's local update is one jitted program
whose batch axis is sharded over a Mesh spanning every process.

Parity: reference ``cross_silo/hierarchical/dist_trainer_launcher.py:23``
(pdsh+torchrun entry) and the master/slave managers it launches.

Usage: python scripts/run_hier_silo_worker.py --out OUT.json [--rounds N]
"""

from __future__ import annotations

import argparse
import json
import threading


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True)
    ap.add_argument("--rounds", type=int, default=2)
    opts = ap.parse_args()

    import jax

    import fedml_tpu
    from fedml_tpu.cross_silo import (
        ClientMasterManager,
        ClientSlaveManager,
        FedMLAggregator,
        FedMLServerManager,
        FedMLTrainer,
        SlaveSync,
        assemble_silo,
    )
    from fedml_tpu.parallel import AXIS_DATA, MeshConfig, create_mesh

    args = fedml_tpu.init(config=dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=1, client_num_per_round=1, comm_round=opts.rounds,
        learning_rate=0.1, epochs=1, batch_size=16,
        frequency_of_the_test=1, random_seed=0,
    ))
    n_dev = len(jax.devices())
    assert jax.process_count() > 1, "this worker expects a jax.distributed world"
    mesh = create_mesh(MeshConfig(axes=((AXIS_DATA, n_dev),)),
                       devices=jax.devices())

    # assemble ONCE; both the server actor (proc 0) and the trainer share it
    fed_data, variables, apply_fn, local_update = assemble_silo(args)
    trainer = FedMLTrainer(
        client_index=0, fed_data=fed_data, model_params=variables,
        local_update=local_update, args=args, mesh=mesh,
    )

    if jax.process_index() == 0:
        from fedml_tpu.comm import LoopbackHub

        hub = LoopbackHub()
        aggregator = FedMLAggregator(
            fed_data.test_data_global, fed_data.train_data_global,
            fed_data.train_data_num, 1, args, variables, apply_fn=apply_fn,
        )
        server = FedMLServerManager(
            args, aggregator, rank=0, client_num=1, backend="LOOPBACK", hub=hub,
        )
        master = ClientMasterManager(
            args, trainer, rank=1, size=2, backend="LOOPBACK", hub=hub,
            slave_sync=SlaveSync(variables),
        )
        t = threading.Thread(target=master.run, daemon=True)
        t.start()
        server.start()
        server.run()
        t.join(timeout=120)
        with open(opts.out, "w") as f:
            json.dump({
                "history": server.history,
                "process_count": jax.process_count(),
                "global_devices": n_dev,
                "local_devices": len(jax.local_devices()),
            }, f)
    else:
        slave = ClientSlaveManager(trainer)
        slave.run()
        with open(opts.out, "w") as f:
            json.dump({
                "process_count": jax.process_count(),
                "global_devices": n_dev,
                "local_devices": len(jax.local_devices()),
                "slave": True,
            }, f)


if __name__ == "__main__":
    main()
