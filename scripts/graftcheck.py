#!/usr/bin/env python
"""Thin launcher for the fedml_tpu static-analysis suite.

Equivalent to ``python -m fedml_tpu.cli analyze``; exists so CI and
pre-commit hooks can run the checks without the click dependency chain.
See docs/static_analysis.md for the checker catalogue, the
``# graftcheck: disable=<id>`` suppression syntax, and the baseline
workflow (scripts/graftcheck_baseline.json).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fedml_tpu.analysis import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
