#!/usr/bin/env python
"""Lint: forbid bare ``print(...)`` calls in ``fedml_tpu/`` library code.

Library output must go through ``logging`` or the telemetry sinks
(``fedml_tpu/core/telemetry.py``) so deployments can route/silence it —
a stray print in a hot path is invisible to log collectors and can stall
under redirected stdout. AST-based: only CALLS of the builtin name
``print`` are flagged, so passing ``print`` as a callback default
(e.g. ``log_fn=print``) stays legal.

Allowlist: ``fedml_tpu/utils/chip_probe.py`` (child-process probe protocol
speaks over stdout by design) and ``fedml_tpu/cli/`` (a CLI's job is to
print). Top-level tools (bench.py, scripts/) are out of scope.

Run as a tier-1 check via tests/test_no_print.py, or directly:
``python scripts/check_no_print.py`` (exit 1 on violations).
"""

from __future__ import annotations

import ast
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIBRARY_DIR = os.path.join(REPO_ROOT, "fedml_tpu")
ALLOWLIST_FILES = {os.path.join("fedml_tpu", "utils", "chip_probe.py")}
ALLOWLIST_DIRS = {os.path.join("fedml_tpu", "cli")}


def _allowed(relpath: str) -> bool:
    if relpath in ALLOWLIST_FILES:
        return True
    return any(relpath.startswith(d + os.sep) for d in ALLOWLIST_DIRS)


def find_print_calls(path: str) -> list:
    """(lineno, source-line) for every bare ``print(...)`` call."""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    tree = ast.parse(src, filename=path)
    lines = src.splitlines()
    hits = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
        ):
            text = lines[node.lineno - 1].strip() if node.lineno <= len(lines) else ""
            hits.append((node.lineno, text))
    return hits


def main() -> int:
    violations = []
    for dirpath, _dirnames, filenames in os.walk(LIBRARY_DIR):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, REPO_ROOT)
            if _allowed(rel):
                continue
            for lineno, text in find_print_calls(path):
                violations.append(f"{rel}:{lineno}: {text}")
    if violations:
        print("bare print() calls in library code (use logging or the "
              "telemetry sinks; see scripts/check_no_print.py):",
              file=sys.stderr)
        for v in violations:
            print(f"  {v}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
