#!/usr/bin/env python
"""Lint: forbid bare ``print(...)`` calls in ``fedml_tpu/`` library code.

Library output must go through ``logging`` or the telemetry sinks
(``fedml_tpu/core/telemetry.py``) so deployments can route/silence it —
a stray print in a hot path is invisible to log collectors and can stall
under redirected stdout.

The check itself now lives in the graftcheck suite as the ``no-print``
checker (``fedml_tpu/analysis/no_print.py``; run all checkers with
``python -m fedml_tpu.cli analyze``). This script is kept as a thin
compatibility shim: ``python scripts/check_no_print.py`` still exits 1 on
violations, and ``find_print_calls`` keeps its old import surface for
tests/test_no_print.py.
"""

from __future__ import annotations

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from fedml_tpu.analysis.no_print import find_print_calls  # noqa: E402,F401


def main() -> int:
    from fedml_tpu.analysis.core import run_checkers
    from fedml_tpu.analysis.no_print import NoPrintChecker

    package_dir = os.path.join(REPO_ROOT, "fedml_tpu")
    findings = run_checkers([NoPrintChecker], package_dir, REPO_ROOT)
    if findings:
        print("bare print() calls in library code (use logging or the "
              "telemetry sinks; see scripts/check_no_print.py):",
              file=sys.stderr)
        for f in findings:
            print(f"  {f.render()}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
