#!/usr/bin/env python
"""Lint: forbid bare ``print(...)`` calls in ``fedml_tpu/`` library code.

Library output must go through ``logging`` or the telemetry sinks
(``fedml_tpu/core/telemetry.py``) so deployments can route/silence it —
a stray print in a hot path is invisible to log collectors and can stall
under redirected stdout.

The check itself lives in the graftcheck suite as the ``no-print``
checker (``fedml_tpu/analysis/no_print.py``; run all checkers with
``python -m fedml_tpu.cli analyze``). This script is a thin compatibility
shim that delegates straight to the graftcheck frontend restricted to
``no-print`` — one driver, one suppression/baseline semantics — and keeps
``find_print_calls`` importable for tests/test_no_print.py.
"""

from __future__ import annotations

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from fedml_tpu.analysis.no_print import find_print_calls  # noqa: E402,F401


def main() -> int:
    from fedml_tpu.analysis.core import main as graftcheck_main

    # --no-baseline matches the shim's historical behaviour (it predates
    # the baseline) and keeps other checkers' entries from showing as stale
    return graftcheck_main(["--checker", "no-print", "--no-baseline"])


if __name__ == "__main__":
    sys.exit(main())
