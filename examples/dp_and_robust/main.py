"""Privacy + robustness in one run: example-level DP-SGD on the clients,
robust aggregation on the server, attack harness for evaluation.

The reference stubs both core/dp and core/security; both are functional
here (algorithms/local_sgd.py dp_* knobs, core/dp accountant,
core/security attacks, core/robust defenses).

    python main.py                 # DP-SGD federated LR + epsilon report
    python main.py --attack scale  # + model-replacement attacker, median agg
"""

import argparse

import fedml_tpu
from fedml_tpu.core import epsilon_for_training
from fedml_tpu.simulation import build_simulator

if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--rounds", type=int, default=30)
    p.add_argument("--noise", type=float, default=0.1)
    p.add_argument("--clip", type=float, default=2.0)
    p.add_argument("--attack", default=None, choices=[None, "scale", "sign_flip"])
    opts = p.parse_args()

    cfg = dict(
        dataset="digits", model="lr", partition_method="hetero",
        partition_alpha=0.5, client_num_in_total=10, client_num_per_round=10,
        comm_round=opts.rounds, learning_rate=0.3, epochs=1, batch_size=32,
        frequency_of_the_test=10, random_seed=0,
        dp_l2_clip=opts.clip, dp_noise_multiplier=opts.noise,
    )
    if opts.attack:
        # inject real attackers into aggregation + median defense
        cfg.update(attack_type=opts.attack, attacker_ratio=0.2,
                   attack_boost=50.0,
                   federated_optimizer="FedAvg_robust",
                   defense_type="coordinate_median")
    args = fedml_tpu.init(config=cfg)
    sim, apply_fn = build_simulator(args)
    hist = sim.run(apply_fn)
    eps = epsilon_for_training(opts.noise, opts.rounds, sim.num_local_batches)
    print(f"final test_acc={hist[-1].get('test_acc'):.4f}  "
          f"eps(conservative, delta=1e-5)={eps:.1f}")
