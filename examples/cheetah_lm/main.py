"""Cheetah distributed LM training: dp x sp x tp over one mesh.

On a v4-8: dp=2, sp=2, tp=2. Ring attention handles the seq axis, Megatron
param shardings the model axis; XLA inserts all collectives.

    python main.py --dp 2 --sp 2 --tp 2 --steps 100
"""

import argparse

import numpy as np

from fedml_tpu.parallel.trainer import DistTrainConfig, DistributedLMTrainer


def data_iter(vocab, B, T, seed=0):
    rng = np.random.default_rng(seed)
    while True:
        start = rng.integers(0, vocab, (B, 1))
        seq = (start + np.arange(T + 1)) % vocab
        yield seq[:, :-1].astype(np.int32), seq[:, 1:].astype(np.int32)


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--dp", type=int, default=2)
    p.add_argument("--sp", type=int, default=2)
    p.add_argument("--tp", type=int, default=2)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--dim", type=int, default=512)
    p.add_argument("--layers", type=int, default=8)
    p.add_argument("--seq_len", type=int, default=2048)
    p.add_argument("--batch", type=int, default=8)
    a = p.parse_args()

    trainer = DistributedLMTrainer(
        DistTrainConfig(dp=a.dp, tp=a.tp, sp=a.sp, lr=3e-4),
        vocab_size=32000, dim=a.dim, num_heads=8, num_layers=a.layers,
        max_len=a.seq_len,
    )
    trainer.train(data_iter(32000, a.batch, a.seq_len), steps=a.steps)
