"""Cheetah distributed LM training: dp x sp x tp over one mesh.

On a v4-8: dp=2, sp=2, tp=2. Ring attention handles the seq axis, Megatron
param shardings the model axis; XLA inserts all collectives.

    python main.py --dp 2 --sp 2 --tp 2 --steps 100
"""

import argparse

import numpy as np

from fedml_tpu.parallel.trainer import DistTrainConfig, DistributedLMTrainer


def data_iter(vocab, B, T, seed=0):
    rng = np.random.default_rng(seed)
    while True:
        start = rng.integers(0, vocab, (B, 1))
        seq = (start + np.arange(T + 1)) % vocab
        yield seq[:, :-1].astype(np.int32), seq[:, 1:].astype(np.int32)


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--dp", type=int, default=2)
    p.add_argument("--sp", type=int, default=2)
    p.add_argument("--tp", type=int, default=2)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--dim", type=int, default=512)
    p.add_argument("--layers", type=int, default=8)
    p.add_argument("--seq_len", type=int, default=2048)
    p.add_argument("--batch", type=int, default=8)
    # memory levers (see results/lm_mfu_bench.json for their measured
    # effect): per-block remat and chunked cross-entropy
    p.add_argument("--no_remat", action="store_true")
    p.add_argument("--ce_chunk", type=int, default=256,
                   help="0 = full-logit CE; else sequence-chunk size "
                        "(seq_len must be divisible by it)")
    p.add_argument("--mu_dtype", default=None, choices=[None, "bfloat16"],
                   help="AdamW first-moment dtype; bfloat16 halves mu's "
                        "HBM footprint and optimizer-stage traffic")
    p.add_argument("--remat_policy", default="full", choices=["full", "dots"],
                   help="'dots' saves matmul outputs and recomputes only "
                        "elementwise ops in bwd (less recompute, more "
                        "activation HBM than 'full')")
    a = p.parse_args()
    if a.ce_chunk and a.seq_len % a.ce_chunk:
        # fall back rather than crash on the first step: chunked CE needs
        # seq_len % chunk == 0
        print(f"seq_len {a.seq_len} not divisible by ce_chunk {a.ce_chunk}; "
              "using full-logit CE")
        a.ce_chunk = 0

    trainer = DistributedLMTrainer(
        DistTrainConfig(dp=a.dp, tp=a.tp, sp=a.sp, lr=3e-4,
                        use_remat=not a.no_remat, ce_chunk=a.ce_chunk,
                        mu_dtype=a.mu_dtype, remat_policy=a.remat_policy),
        vocab_size=32000, dim=a.dim, num_heads=8, num_layers=a.layers,
        max_len=a.seq_len,
    )
    trainer.train(data_iter(32000, a.batch, a.seq_len), steps=a.steps)
