"""GPipe pipeline-parallel LM training: dp x pp over one mesh.

On a v4-8: dp=2, pp=4 — each device owns 1/4 of the decoder stack, four
microbatches stream through per step (fill/drain schedule compiled into one
XLA program; ppermute carries the stage-to-stage activations over ICI).

    python main.py --dp 2 --pp 4 --microbatches 4 --steps 50
"""

import argparse

import numpy as np

from fedml_tpu.parallel import PipelineConfig, PipelinedLMTrainer


def data_iter(vocab, B, T, seed=0):
    rng = np.random.default_rng(seed)
    while True:
        start = rng.integers(0, vocab, (B, 1))
        seq = (start + np.arange(T + 1)) % vocab
        yield seq[:, :-1].astype(np.int32), seq[:, 1:].astype(np.int32)


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--dp", type=int, default=2)
    p.add_argument("--pp", type=int, default=4)
    p.add_argument("--microbatches", type=int, default=4)
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--dim", type=int, default=256)
    p.add_argument("--layers", type=int, default=8)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--seq", type=int, default=128)
    opts = p.parse_args()

    cfg = PipelineConfig(pp=opts.pp, dp=opts.dp,
                         microbatches=opts.microbatches, lr=1e-3)
    trainer = PipelinedLMTrainer(
        cfg, vocab_size=1024, dim=opts.dim, num_heads=8,
        num_layers=opts.layers, max_len=opts.seq,
    )
    it = data_iter(1024, opts.batch, opts.seq)
    for step in range(opts.steps):
        toks, tgt = next(it)
        loss = trainer.step(toks, tgt)
        if step % 10 == 0:
            print(f"step {step}: loss {loss:.4f}")
