"""FedCV object detection example (reference app/fedcv/object_detection).

Federated training of the anchor-free grid detector on the synthetic
shapes-detection dataset, then IoU-scored detections on held-out images.
"""

import numpy as np

import jax
import jax.numpy as jnp

import fedml_tpu
from fedml_tpu import data as data_mod
from fedml_tpu.algorithms.fedcv_detection import get_detection_algorithm
from fedml_tpu.models.detection import GridDetector, box_iou, decode_boxes
from fedml_tpu.simulation.fed_sim import FedSimulator, SimConfig


def main():
    args = fedml_tpu.init(config=dict(
        dataset="object_detection", client_num_in_total=8,
        client_num_per_round=4, partition_method="hetero",
        partition_alpha=0.5, random_seed=0))
    fed, _ = data_mod.load(args)
    model = GridDetector(num_classes=2, width=32)

    def apply_fn(params, x, train=False, rngs=None):
        return model.apply(params, x, train=train)

    sample = jnp.asarray(fed.train_data_global.x[:1])
    variables = model.init(jax.random.PRNGKey(0), sample, train=False)
    alg = get_detection_algorithm(apply_fn, lr=2e-3, epochs=2)
    sim = FedSimulator(
        fed, alg, variables,
        SimConfig(comm_round=30, client_num_in_total=8, client_num_per_round=4,
                  batch_size=32, frequency_of_the_test=1000),
    )
    sim.run(apply_fn=None)

    test = fed.test_data_global
    S = test.y.shape[1]
    n = min(len(test.x), 128)
    preds = np.asarray(apply_fn(sim.params, jnp.asarray(test.x[:n])))
    matched = total = 0
    for i in range(n):
        gt = test.y[i]
        pb, pc, _ = decode_boxes(preds[i], obj_threshold=0.5)
        for y, x in zip(*np.nonzero(gt[..., 0] > 0)):
            total += 1
            gt_box = np.array([(x + gt[y, x, 2]) / S, (y + gt[y, x, 3]) / S,
                               gt[y, x, 4], gt[y, x, 5]])
            best = max((box_iou(gt_box, b) for b, c in zip(pb, pc)
                        if c == int(gt[y, x, 1])), default=0.0)
            matched += best >= 0.5
    print(f"IoU>=0.5 class-matched recall: {matched / max(total, 1):.3f} "
          f"({matched}/{total})")


if __name__ == "__main__":
    main()
