"""FedCV object detection — multi-scale anchor detector variant.

The deep path (reference app/fedcv/object_detection vendors YOLOv5):
FPN neck, 3-anchor heads at strides 8/16/32, CIoU loss, jit-side
class-aware NMS. Compare examples/fedcv_object_detection/main.py for the
compact anchor-free grid detector.
"""

import os
import sys

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from fedml_tpu.algorithms.fedcv_detection import get_yolo_algorithm
from fedml_tpu.data.federated import ArrayPair, build_federated_data
from fedml_tpu.models.yolo import (
    YoloLiteDetector,
    detect,
    rasterize_multiscale,
)
from fedml_tpu.simulation.fed_sim import FedSimulator, SimConfig

IMG = 64


def synth(n, seed):
    """Bright squares (class 0 small, class 1 large) on noise."""
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 0.05, (n, IMG, IMG, 1)).astype(np.float32)
    ys, truths = [], []
    for i in range(n):
        big = int(rng.integers(0, 2))
        w = 0.4 if big else 0.12
        cx, cy = rng.uniform(0.25, 0.75, 2)
        px, py, half = int(cx * IMG), int(cy * IMG), int(w * IMG / 2)
        x[i, max(0, py - half):py + half, max(0, px - half):px + half, 0] += 1.0
        ys.append(rasterize_multiscale(
            np.array([[cx, cy, w, w]], np.float32),
            np.array([big], np.int32), IMG, 2))
        truths.append((cx, cy, w, big))
    return x, np.stack(ys), truths


def main():
    x, y, _ = synth(384, seed=0)
    idx_map = {c: list(range(c * 48, (c + 1) * 48)) for c in range(8)}
    fed = build_federated_data(ArrayPair(x, y), ArrayPair(x[:48], y[:48]),
                               idx_map, 2)
    model = YoloLiteDetector(num_classes=2, width=16)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x[:1]),
                           train=False)

    def apply_fn(v, xx, train=False, rngs=None, mutable=False):
        return model.apply(v, xx, train=train)

    alg = get_yolo_algorithm(apply_fn, IMG, 2, lr=2e-3, epochs=2)
    sim = FedSimulator(fed, alg, variables,
                       SimConfig(comm_round=20, client_num_in_total=8,
                                 client_num_per_round=4, batch_size=16,
                                 frequency_of_the_test=1000))
    sim.run(apply_fn=None)

    test_x, _, truths = synth(16, seed=9)
    outs = apply_fn(sim.params, jnp.asarray(test_x), train=False)
    found = 0
    for i in range(16):
        boxes, scores, classes, valid = detect(
            [o[i] for o in outs], IMG, score_threshold=0.1, max_out=8)
        if float(valid.sum()):
            found += 1
            cx, cy, w, big = truths[i]
            j = int(np.argmax(np.asarray(scores)))
            print(f"img {i}: truth cls={big} ({cx:.2f},{cy:.2f},{w:.2f}) -> "
                  f"pred cls={int(classes[j])} box={np.asarray(boxes[j]).round(2)}"
                  f" score={float(scores[j]):.2f}")
    print(f"[example] detections on {found}/16 held-out images")


if __name__ == "__main__":
    main()
