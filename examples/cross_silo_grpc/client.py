import fedml_tpu

if __name__ == "__main__":
    args = fedml_tpu.init()
    fedml_tpu.run_cross_silo_client(args)
