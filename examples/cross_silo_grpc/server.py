import fedml_tpu

if __name__ == "__main__":
    args = fedml_tpu.init()
    args.rank = 0
    fedml_tpu.run_cross_silo_server(args)
