"""FedNLP pretrained fine-tune: HF BERT checkpoint -> federated training.

Reference flow: ``app/fednlp/text_classification/model/bert_model.py`` wraps
a pretrained HuggingFace BertForSequenceClassification and fine-tunes it
federated. Here the checkpoint file (any torch state_dict of that model) is
imported into the flax BERT via ``utils/torch_import`` and fine-tuned with
the jitted engine.

Usage:
    python run.py [checkpoint.pt]

Without a checkpoint argument, a tiny randomly-initialized HF BERT is
constructed in-process (zero egress) and saved first, so the example runs
end-to-end anywhere; with one, bring your own pretrained weights.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from fedml_tpu.algorithms import LocalTrainConfig, get_algorithm
from fedml_tpu.data.federated import ArrayPair, build_federated_data
from fedml_tpu.models.bert import BertConfig, BertForSequenceClassification
from fedml_tpu.simulation.fed_sim import FedSimulator, SimConfig
from fedml_tpu.utils.torch_import import import_bert_classifier

CFG = BertConfig(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                 num_attention_heads=4, intermediate_size=128,
                 max_position_embeddings=32, num_labels=4)


def make_checkpoint(path: str) -> None:
    import torch
    import transformers

    hf = transformers.BertForSequenceClassification(transformers.BertConfig(
        vocab_size=CFG.vocab_size, hidden_size=CFG.hidden_size,
        num_hidden_layers=CFG.num_hidden_layers,
        num_attention_heads=CFG.num_attention_heads,
        intermediate_size=CFG.intermediate_size,
        max_position_embeddings=CFG.max_position_embeddings,
        num_labels=CFG.num_labels, hidden_act="gelu"))
    torch.save(hf.state_dict(), path)
    print(f"[example] wrote fresh checkpoint {path}")


def main() -> None:
    ckpt = sys.argv[1] if len(sys.argv) > 1 else "/tmp/bert_tiny_example.pt"
    if len(sys.argv) <= 1:
        make_checkpoint(ckpt)
    variables = import_bert_classifier(ckpt, CFG)
    print(f"[example] imported {ckpt} into flax BERT "
          f"({CFG.num_hidden_layers} layers, d={CFG.hidden_size})")

    # synthetic topic-classification stand-in (zero-egress image)
    rng = np.random.default_rng(0)
    n, T = 512, 24
    x = rng.integers(0, CFG.vocab_size, size=(n, T)).astype(np.int32)
    y = (x[:, :4].sum(axis=1) % CFG.num_labels).astype(np.int32)
    idx_map = {c: list(range(c * 64, (c + 1) * 64)) for c in range(8)}
    fed = build_federated_data(ArrayPair(x, y), ArrayPair(x[-128:], y[-128:]),
                               idx_map, CFG.num_labels)

    model = BertForSequenceClassification(CFG)

    def apply_fn(v, xx, train=False, rngs=None, mutable=False):
        # forward train + dropout rngs: fine-tune runs with HF's 0.1 dropout
        return model.apply(v, xx, train=train, rngs=rngs)

    alg = get_algorithm("FedAvg", apply_fn,
                        LocalTrainConfig(lr=1e-3, epochs=1,
                                         client_optimizer="adam"),
                        needs_dropout=True)
    sim = FedSimulator(fed, alg, variables,
                       SimConfig(comm_round=10, client_num_in_total=8,
                                 client_num_per_round=4, batch_size=16,
                                 frequency_of_the_test=5))
    sim.run(apply_fn=apply_fn)


if __name__ == "__main__":
    main()
