"""python main.py --cf fedml_config.yaml (reference example entry parity)."""

import fedml_tpu

if __name__ == "__main__":
    args = fedml_tpu.init()
    history = fedml_tpu.run_simulation(args=args)
    print("final:", history[-1])
