"""Native C++ layer: cohort packer parity, int8 codec, comm compression."""

import numpy as np
import pytest

from fedml_tpu import native
from fedml_tpu.comm import Message, compress_tree, decompress_tree, is_compressed


def test_native_builds_and_loads():
    # g++ is in the image; the lib must build (fallback is for other envs)
    assert native.native_available()


def test_pack_cohort_matches_numpy_fallback():
    rng = np.random.default_rng(0)
    N, F = 100, 12
    x = rng.normal(size=(N, F)).astype(np.float32)
    y = rng.integers(0, 10, N).astype(np.int32)
    idx_lists = [rng.choice(N, size=n, replace=False) for n in (30, 7, 19)]
    cap = 32
    ox, oy, om = native.pack_cohort(x, y, idx_lists, cap)
    assert ox.shape == (3, cap, F) and om.shape == (3, cap)
    for c, ci in enumerate(idx_lists):
        n = len(ci)
        np.testing.assert_array_equal(ox[c, :n], x[ci])
        np.testing.assert_array_equal(oy[c, :n], y[ci])
        assert om[c, :n].all() and not om[c, n:].any()
        assert not ox[c, n:].any()


def test_pack_cohort_with_permutation():
    x = np.arange(20, dtype=np.float32).reshape(10, 2)
    y = np.arange(10, dtype=np.int32)
    idx = [np.array([1, 3, 5, 7])]
    perm = [np.array([3, 0, 2, 1])]
    ox, oy, om = native.pack_cohort(x, y, idx, cap=4, perms=perm)
    np.testing.assert_array_equal(oy[0], [7, 1, 5, 3])


def test_quantize_roundtrip_accuracy():
    rng = np.random.default_rng(1)
    arr = rng.normal(0, 0.1, (1000,)).astype(np.float32)
    q, s = native.quantize_i8(arr)
    out = native.dequantize_i8(q, s, arr.shape)
    # int8 absmax per 256-chunk: error bounded by scale/2 ~ amax/254
    assert np.abs(out - arr).max() < np.abs(arr).max() / 100
    # and real compression: int8 + 1 scale per 256 values
    assert q.nbytes + s.nbytes < arr.nbytes / 3.5


def test_compress_tree_through_message_codec():
    tree = {
        "layer": {"kernel": np.random.randn(64, 32).astype(np.float32),
                  "bias": np.random.randn(32).astype(np.float32)},
        "step": np.int32(7),
    }
    payload = compress_tree(tree)
    assert is_compressed(payload)
    msg = Message(3, 1, 0)
    msg.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, payload)
    out = Message.from_bytes(msg.to_bytes()).get(Message.MSG_ARG_KEY_MODEL_PARAMS)
    rec = decompress_tree(out)
    np.testing.assert_allclose(rec["layer"]["kernel"], tree["layer"]["kernel"], atol=0.05)
    np.testing.assert_array_equal(rec["step"], tree["step"])


def test_cross_silo_quantized_run():
    import threading

    import fedml_tpu
    from fedml_tpu.comm import LoopbackHub
    from fedml_tpu.cross_silo import FedML_Horizontal

    args = fedml_tpu.init(config=dict(
        dataset="mnist", model="lr", debug_small_data=True,
        client_num_in_total=2, client_num_per_round=2, comm_round=2,
        learning_rate=0.1, batch_size=8, frequency_of_the_test=1,
        random_seed=0, comm_quantize=True,
    ))
    hub = LoopbackHub()
    server = FedML_Horizontal(args, 0, 2, backend="LOOPBACK", hub=hub)
    clients = [FedML_Horizontal(args, r, 2, backend="LOOPBACK", hub=hub) for r in (1, 2)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.start()
    server.run()
    for t in threads:
        t.join(timeout=30)
    assert len(server.history) == 2
    assert server.history[-1]["test_acc"] > 0.4
