"""Multi-scale anchor detector (models/yolo.py): assignment, CIoU, NMS,
and federated learning with IoU-scored detections.

Reference parity class: app/fedcv/object_detection's vendored YOLOv5
(anchors at strides 8/16/32, FPN neck, CIoU loss, NMS)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fedml_tpu.models.yolo import (
    A,
    ANCHORS,
    YoloLiteDetector,
    batched_nms,
    ciou,
    detect,
    level_grids,
    rasterize_multiscale,
    unpack_targets,
)

IMG = 64


def test_rasterize_assigns_best_anchor_level():
    # a small box goes to the stride-8 level, a huge one to stride-32
    boxes = np.array([[0.30, 0.40, 0.05, 0.06], [0.70, 0.60, 0.50, 0.55]],
                     np.float32)
    classes = np.array([1, 0], np.int32)
    packed = rasterize_multiscale(boxes, classes, IMG, num_classes=2)
    levels = unpack_targets(jnp.asarray(packed), IMG)
    g8, g16, g32 = level_grids(IMG)
    lv0, lv1, lv2 = (np.asarray(t) for t in levels)
    assert lv0.shape == (g8, g8, A, 6)
    # small box: stride-8 cell containing (0.3, 0.4)
    gy, gx = int(0.40 * g8), int(0.30 * g8)
    assert lv0[gy, gx, :, 0].sum() == 1.0
    ai = int(np.argmax(lv0[gy, gx, :, 0]))
    assert lv0[gy, gx, ai, 1] == 1.0  # class
    np.testing.assert_allclose(lv0[gy, gx, ai, 4:6], [0.05, 0.06], atol=1e-6)
    # big box: only the stride-32 level fires
    assert lv1[..., 0].sum() == 0 and lv2[..., 0].sum() == 1.0


def test_ciou_properties():
    same = jnp.asarray([0.5, 0.5, 0.2, 0.2])
    assert float(ciou(same, same)) == pytest.approx(1.0, abs=1e-5)
    far = jnp.asarray([0.1, 0.1, 0.05, 0.05])
    assert float(ciou(far, same)) < 0.0  # disjoint + center penalty
    near = jnp.asarray([0.52, 0.5, 0.2, 0.2])
    assert float(ciou(near, same)) > float(ciou(far, same))


def test_batched_nms_matches_numpy_greedy():
    rng = np.random.default_rng(0)
    boxes = np.concatenate([
        rng.uniform(0.2, 0.8, (30, 2)), rng.uniform(0.05, 0.3, (30, 2))
    ], axis=1).astype(np.float32)
    scores = rng.uniform(0.1, 1.0, 30).astype(np.float32)

    def np_iou(a, b):
        ax1, ay1 = a[0] - a[2] / 2, a[1] - a[3] / 2
        ax2, ay2 = a[0] + a[2] / 2, a[1] + a[3] / 2
        bx1, by1 = b[0] - b[2] / 2, b[1] - b[3] / 2
        bx2, by2 = b[0] + b[2] / 2, b[1] + b[3] / 2
        ix = max(0.0, min(ax2, bx2) - max(ax1, bx1))
        iy = max(0.0, min(ay2, by2) - max(ay1, by1))
        inter = ix * iy
        return inter / (a[2] * a[3] + b[2] * b[3] - inter + 1e-12)

    live = np.ones(30, bool)
    ref = []
    while live.any() and len(ref) < 10:
        i = int(np.argmax(np.where(live, scores, -np.inf)))
        ref.append(i)
        for j in range(30):
            if live[j] and np_iou(boxes[i], boxes[j]) > 0.5:
                live[j] = False
        live[i] = False

    keep, kvalid = jax.jit(batched_nms, static_argnums=(2, 3))(
        jnp.asarray(boxes), jnp.asarray(scores), 0.5, 10)
    got = [int(k) for k, v in zip(np.asarray(keep), np.asarray(kvalid)) if v]
    assert got == ref


def _synth_detection(n, seed):
    """One bright square per image; class 0 = small box, class 1 = large."""
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 0.05, (n, IMG, IMG, 1)).astype(np.float32)
    ys = []
    for i in range(n):
        big = rng.integers(0, 2)
        w = 0.4 if big else 0.12
        cx, cy = rng.uniform(0.25, 0.75, 2)
        px, py = int(cx * IMG), int(cy * IMG)
        half = int(w * IMG / 2)
        x[i, max(0, py - half):py + half, max(0, px - half):px + half, 0] += 1.0
        ys.append(rasterize_multiscale(
            np.array([[cx, cy, w, w]], np.float32),
            np.array([big], np.int32), IMG, 2))
    return x, np.stack(ys)


@pytest.mark.slow
def test_yolo_federated_learns_and_detects():
    from fedml_tpu.algorithms.fedcv_detection import get_yolo_algorithm
    from fedml_tpu.data.federated import ArrayPair, build_federated_data
    from fedml_tpu.simulation.fed_sim import FedSimulator, SimConfig

    x, y = _synth_detection(192, seed=0)
    idx_map = {c: list(range(c * 48, (c + 1) * 48)) for c in range(4)}
    fed = build_federated_data(ArrayPair(x, y), ArrayPair(x[:32], y[:32]),
                               idx_map, 2)
    model = YoloLiteDetector(num_classes=2, width=8)
    variables = model.init(jax.random.PRNGKey(0), x[:1], train=False)

    def apply_fn(v, xx, train=False, rngs=None, mutable=False):
        return model.apply(v, xx, train=train)

    alg = get_yolo_algorithm(apply_fn, IMG, 2, lr=2e-3, epochs=2)
    sim = FedSimulator(fed, alg, variables,
                       SimConfig(comm_round=8, client_num_in_total=4,
                                 client_num_per_round=4, batch_size=16,
                                 frequency_of_the_test=1000, seed=0))
    hist = sim.run(apply_fn=None, log_fn=None)
    assert hist[-1]["train_loss"] < hist[0]["train_loss"]

    # IoU-scored detections on held-out images via the jit-side NMS
    test_x, _ = _synth_detection(16, seed=9)
    outs = apply_fn(sim.params, jnp.asarray(test_x), train=False)
    hits = 0
    for i in range(16):
        per_img = [o[i] for o in outs]
        boxes, scores, classes, valid = detect(
            per_img, IMG, score_threshold=0.1, max_out=8)
        if float(valid.sum()) >= 1:
            hits += 1
    assert hits >= 12, f"only {hits}/16 images produced detections"